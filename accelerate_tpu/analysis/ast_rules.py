"""AST rule engine: the fast repo-wide half of graft-lint.

Where the jaxpr auditor sees the traced program, this engine sees the
source — the two halves cover each other's blind spots.  Caller-side
donated-buffer reuse (GL201, the PR 2 async-checkpoint race shape) happens
*after* the jitted call returns, so no jaxpr contains it; ``time.time()``
inside a jitted function (GL204) leaves no trace at all — the trace bakes
the first call's value silently.

**Jit contexts.**  GL202/GL204 only fire inside code that runs under trace.
A function is a jit context when it (a) is decorated with ``jax.jit`` /
``jax.pmap`` (bare, called, or via ``partial``), (b) is passed by name to a
``jax.jit(...)`` call anywhere in the module, (c) is lexically nested
inside a jit context, or (d) is called by bare name from inside one (the
call graph is closed transitively — ``pinned_step_fn -> step_fn ->
compute_grads`` in the accelerator all count).

**Donated-reuse (GL201).**  The engine records every ``name = jax.jit(fn,
donate_argnums=...)`` binding in the module, then at each call of such a
name treats the bare-``Name`` arguments in donated positions as dead: a
later *load* of that name in the same scope is a finding, unless a
rebinding (``state, m = jitted(state, batch)``) or ``del`` intervenes.
Known miss (documented in docs/static_analysis.md): reuse across loop
iterations with no textual load after the call line.

Suppression: the shared inline marker (see :mod:`.report`) on the flagged
line or the line above.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, Optional, Sequence

from .report import Finding, Report, apply_suppressions, parse_marker
from .rules import RULES

# path substrings every repo-wide run skips: intentionally-buggy lint fixtures
DEFAULT_EXCLUDES = ("tests/analysis_fixtures",)

# directory names pruned from directory sweeps (matched as whole path
# components, so `venv/` is skipped but `myvenv_utils.py` is not):
# vendored/generated trees whose findings are never actionable here
DEFAULT_EXCLUDE_DIRS = frozenset({
    "__pycache__", ".git", ".venv", "venv", ".eggs", ".tox", "build",
    "dist", "node_modules", "site-packages",
})

_HOST_SYNC_METHODS = frozenset({"item", "tolist"})
_HOST_SYNC_NP_FUNCS = frozenset({"asarray", "array"})
_IMPURE_TIME_FUNCS = frozenset({"time", "perf_counter", "monotonic", "time_ns", "process_time"})


def _finding(rule_id: str, message: str, path: str, line: int) -> Finding:
    r = RULES[rule_id]
    return Finding(
        rule=rule_id, severity=r.severity, message=message, fix_hint=r.fix_hint,
        path=path, line=line, engine="ast",
    )


def _dotted(node) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _ModuleIndex:
    """One pass of bookkeeping the rules share: import aliases, function
    defs with nesting, jit-context closure, donated-jit bindings."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        # local name -> canonical dotted name ("np" -> "numpy")
        self.aliases: dict[str, str] = {}
        self.functions: list[ast.FunctionDef] = []
        self._parent: dict[int, Optional[ast.AST]] = {}
        # function name -> donated positional indices, for jax.jit bindings
        self.donated_callables: dict[str, tuple[int, ...]] = {}
        # every name bound to a jax.jit/jax.pmap wrapper (donating or not) —
        # calls of these are "jitted calls" for the timing rule (GL109)
        self.jit_bound_names: set[str] = set()
        self._index()
        self.jit_contexts = self._close_jit_contexts()

    # -- construction ------------------------------------------------------

    def _index(self):
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parent[id(child)] = node
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append(node)
            elif isinstance(node, ast.Assign) or isinstance(node, ast.AnnAssign):
                self._record_donated_binding(node)

    def canonical(self, node) -> Optional[str]:
        """Dotted name with the leading import alias resolved."""
        dotted = _dotted(node)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        head = self.aliases.get(head, head)
        return f"{head}.{rest}" if rest else head

    def _is_jit_call(self, node) -> bool:
        return (
            isinstance(node, ast.Call)
            and self.canonical(node.func) in ("jax.jit", "jax.pmap")
        )

    def _record_donated_binding(self, assign):
        targets = assign.targets if isinstance(assign, ast.Assign) else [assign.target]
        value = assign.value
        if not (self._is_jit_call(value) and len(targets) == 1
                and isinstance(targets[0], ast.Name)):
            return
        self.jit_bound_names.add(targets[0].id)
        donated = _donate_positions(value)
        if donated:
            self.donated_callables[targets[0].id] = donated

    # -- jit-context closure ----------------------------------------------

    def enclosing_function(self, node) -> Optional[ast.AST]:
        cur = self._parent.get(id(node))
        while cur is not None and not isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cur = self._parent.get(id(cur))
        return cur

    def _decorated_as_jit(self, fn) -> bool:
        for dec in fn.decorator_list:
            target = dec
            if isinstance(dec, ast.Call):
                # @jax.jit(...) or @partial(jax.jit, ...)
                if self.canonical(dec.func) in ("jax.jit", "jax.pmap"):
                    return True
                if (self.canonical(dec.func) in ("functools.partial", "partial")
                        and dec.args
                        and self.canonical(dec.args[0]) in ("jax.jit", "jax.pmap")):
                    return True
                continue
            if self.canonical(target) in ("jax.jit", "jax.pmap"):
                return True
        return False

    def _close_jit_contexts(self) -> set:
        by_name: dict[str, list] = {}
        for fn in self.functions:
            by_name.setdefault(fn.name, []).append(fn)
        seeds: set = set()
        for fn in self.functions:
            if self._decorated_as_jit(fn):
                seeds.add(id(fn))
        # functions passed by name into jax.jit(...)
        for node in ast.walk(self.tree):
            if self._is_jit_call(node) and node.args:
                name = _dotted(node.args[0])
                for fn in by_name.get(name or "", []):
                    seeds.add(id(fn))
        # transitive closure over lexical nesting + bare-name calls
        contexts = set(seeds)
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if id(fn) in contexts:
                    continue
                parent = self.enclosing_function(fn)
                if parent is not None and id(parent) in contexts:
                    contexts.add(id(fn))
                    changed = True
            for fn in self.functions:
                if id(fn) not in contexts:
                    continue
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                        for callee in by_name.get(node.func.id, []):
                            if id(callee) not in contexts:
                                contexts.add(id(callee))
                                changed = True
        return contexts

    def in_jit_context(self, node) -> bool:
        fn = self.enclosing_function(node)
        return fn is not None and id(fn) in self.jit_contexts


def _donate_positions(jit_call: ast.Call) -> tuple[int, ...]:
    """Literal donate_argnums of a jax.jit(...) call; a non-literal value
    conservatively reads as ``(0,)`` (the overwhelmingly common case —
    the accelerator's ``donate_argnums=(0,) if donate_state else ()``)."""
    for kw in jit_call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            if not v.elts:
                return ()  # explicit empty literal: donates nothing
            out = tuple(
                e.value for e in v.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)
            )
            return out or (0,)
        return (0,)
    return ()


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def _rule_donated_reuse(index: _ModuleIndex, path: str) -> list[Finding]:
    """GL201: a donated name loaded after the donating call in its scope."""
    findings = []
    scopes: list = [index.tree] + list(index.functions)
    for scope in scopes:
        own = (
            lambda n: index.enclosing_function(n) is scope
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef))
            else index.enclosing_function(n) is None
        )
        calls = []  # (call node, donated arg names)
        for node in ast.walk(scope):
            if not (isinstance(node, ast.Call) and own(node)):
                continue
            donated: tuple[int, ...] = ()
            if isinstance(node.func, ast.Name) and node.func.id in index.donated_callables:
                donated = index.donated_callables[node.func.id]
            elif isinstance(node.func, ast.Call) and index._is_jit_call(node.func):
                donated = _donate_positions(node.func)  # jax.jit(f, ...)(x)
            names = [
                node.args[i].id
                for i in donated
                if i < len(node.args) and isinstance(node.args[i], ast.Name)
            ]
            if names:
                calls.append((node, names))
        if not calls:
            continue
        name_events: dict[str, list] = {}
        for node in ast.walk(scope):
            if isinstance(node, ast.Name) and own(node):
                name_events.setdefault(node.id, []).append(node)
        for call, names in calls:
            call_end = getattr(call, "end_lineno", call.lineno) or call.lineno
            for name in names:
                for ev in sorted(name_events.get(name, []),
                                 key=lambda n: (n.lineno, n.col_offset)):
                    if ev.lineno < call.lineno:
                        continue
                    aug = isinstance(index._parent.get(id(ev)), ast.AugAssign)
                    if isinstance(ev.ctx, (ast.Store, ast.Del)) and not aug:
                        # rebound/deleted at or after the call (the canonical
                        # `state, m = jitted(state, b)`): the donated buffer
                        # is dead under this name.  An AugAssign target is
                        # NOT safe — `state += 1` reads the donated buffer
                        # before writing it.
                        break
                    if not aug and ev.lineno <= call_end:
                        continue  # the call's own argument load
                    findings.append(
                        _finding(
                            "GL201",
                            f"`{name}` was donated at line {call.lineno} "
                            "(donate_argnums) but is read again here — its "
                            "buffer may already be overwritten in place",
                            path, ev.lineno,
                        )
                    )
                    break
    return findings


def _rule_host_sync(index: _ModuleIndex, path: str) -> list[Finding]:
    """GL202: host-synchronizing calls inside jit contexts."""
    findings = []
    for node in ast.walk(index.tree):
        if not (isinstance(node, ast.Call) and index.in_jit_context(node)):
            continue
        msg = None
        if isinstance(node.func, ast.Attribute) and node.func.attr in _HOST_SYNC_METHODS:
            msg = f".{node.func.attr}() forces a device->host sync"
        else:
            canon = index.canonical(node.func)
            if canon in {f"numpy.{f}" for f in _HOST_SYNC_NP_FUNCS}:
                msg = f"{canon}() materializes a traced value on host"
            elif canon in ("float", "int", "bool") and node.args:
                arg = node.args[0]
                fn = index.enclosing_function(node)
                params = set()
                if fn is not None:
                    a = fn.args
                    params = {p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)}
                if isinstance(arg, ast.Name) and arg.id in params:
                    msg = f"{canon}() on traced argument `{arg.id}` concretizes it"
        if msg:
            findings.append(_finding("GL202", f"{msg} inside jitted code", path, node.lineno))
    return findings


def _rule_shard_map_compat(index: _ModuleIndex, path: str) -> list[Finding]:
    """GL203: jax.experimental.shard_map outside the ImportError fallback."""

    def in_import_error_handler(node) -> bool:
        cur = index._parent.get(id(node))
        while cur is not None:
            if isinstance(cur, ast.ExceptHandler):
                names = []
                t = cur.type
                for e in t.elts if isinstance(t, ast.Tuple) else ([t] if t else []):
                    names.append(_dotted(e))
                if any(n in ("ImportError", "ModuleNotFoundError") for n in names):
                    return True
            cur = index._parent.get(id(cur))
        return False

    findings = []
    for node in ast.walk(index.tree):
        hit = None
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.startswith("jax.experimental.shard_map"):
            hit = f"from {node.module} import ..."
        elif isinstance(node, ast.Import) and any(
                a.name.startswith("jax.experimental.shard_map") for a in node.names):
            hit = "import jax.experimental.shard_map"
        elif isinstance(node, ast.Attribute) and \
                _dotted(node) == "jax.experimental.shard_map":
            hit = "jax.experimental.shard_map"
        if hit and not in_import_error_handler(node):
            findings.append(
                _finding(
                    "GL203",
                    f"{hit} outside an `except ImportError` compat fallback",
                    path, node.lineno,
                )
            )
    return findings


def _rule_impure_in_jit(index: _ModuleIndex, path: str) -> list[Finding]:
    """GL204: wall-clock / stdlib-random calls inside jit contexts."""
    findings = []
    for node in ast.walk(index.tree):
        if not (isinstance(node, ast.Call) and index.in_jit_context(node)):
            continue
        canon = index.canonical(node.func)
        if canon is None:
            continue
        hit = None
        if canon in {f"time.{f}" for f in _IMPURE_TIME_FUNCS}:
            hit = f"{canon}() is baked in at trace time"
        elif canon.startswith("random.") or canon.startswith("numpy.random."):
            hit = f"{canon}() draws host randomness once, at trace time"
        if hit:
            findings.append(_finding("GL204", f"{hit} inside jitted code", path, node.lineno))
    return findings


# GL205(a): write-call shapes whose path operand we inspect for live
# checkpoint-directory literals
_WRITE_METHODS = frozenset({"write_text", "write_bytes"})
_WRITE_FUNCS = frozenset({"pickle.dump", "json.dump", "numpy.save", "numpy.savez"})
_ATOMIC_PUBLISH_CALLS = frozenset({
    "os.replace", "os.rename", "shutil.move",
})
_CKPT_PATH_SCOPE = ("resilience", "checkpoint")  # GL205(b) file-path scope


def _string_constants(node) -> Iterable[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def _statics_of_jit_call(jit_call: ast.Call, fn) -> set:
    """Parameter names of ``fn`` marked static by a ``jax.jit(...)`` call's
    literal ``static_argnums`` / ``static_argnames``."""
    a = fn.args
    positional = [p.arg for p in (*a.posonlyargs, *a.args)]
    names: set = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            idxs = []
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                idxs = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                idxs = [
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                ]
            names.update(positional[i] for i in idxs if 0 <= i < len(positional))
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = [v] if isinstance(v, ast.Constant) else (
                v.elts if isinstance(v, (ast.Tuple, ast.List)) else []
            )
            names.update(
                e.value for e in elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
    return names


# shape-constructing calls GL305 watches: a traced-shape value flowing into
# one of these re-specializes the program per input shape
_SHAPE_CONSUMER_FUNCS = frozenset({
    "jax.numpy.arange", "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full",
    "jax.numpy.broadcast_to", "jax.lax.iota",
})
_SHAPE_CONSUMER_METHODS = frozenset({"reshape", "broadcast_to"})


def _rule_shape_dependent_trace(index: _ModuleIndex, path: str) -> list[Finding]:
    """GL305: ``arg.shape[i]`` of a non-static jit argument flowing directly
    into a shape-constructing call inside jitted code — every distinct input
    shape is a fresh compile (the mid-traffic recompile cause the serving
    bucket ladder exists to remove).  Only the DIRECT flow is flagged: a
    shape read bound to a local first is the documented miss (and routing
    the width through a pinned bucket constant is the fix either way)."""
    # parameter names each function has marked static, from its decorator
    # or any jax.jit(fn_name, static_...) binding in the module
    statics: dict[int, set] = {}
    for fn in index.functions:
        s: set = set()
        for dec in fn.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            if index.canonical(dec.func) in ("jax.jit", "jax.pmap"):
                s |= _statics_of_jit_call(dec, fn)
            elif (index.canonical(dec.func) in ("functools.partial", "partial")
                    and dec.args
                    and index.canonical(dec.args[0]) in ("jax.jit", "jax.pmap")):
                s |= _statics_of_jit_call(dec, fn)
        statics[id(fn)] = s
    by_name: dict[str, list] = {}
    for fn in index.functions:
        by_name.setdefault(fn.name, []).append(fn)
    for node in ast.walk(index.tree):
        if index._is_jit_call(node) and node.args:
            for fn in by_name.get(_dotted(node.args[0]) or "", []):
                statics[id(fn)].update(_statics_of_jit_call(node, fn))

    findings = []
    for node in ast.walk(index.tree):
        if not (isinstance(node, ast.Call) and index.in_jit_context(node)):
            continue
        canon = index.canonical(node.func)
        is_method = (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SHAPE_CONSUMER_METHODS
        )
        if canon not in _SHAPE_CONSUMER_FUNCS and not is_method:
            continue
        fn = index.enclosing_function(node)
        a = fn.args
        params = {p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)}
        fn_statics = statics.get(id(fn), set())
        flagged = False
        for arg in (*node.args, *[kw.value for kw in node.keywords]):
            if flagged:
                break
            for sub in ast.walk(arg):
                if (
                    isinstance(sub, ast.Subscript)
                    and isinstance(sub.value, ast.Attribute)
                    and sub.value.attr == "shape"
                    and isinstance(sub.value.value, ast.Name)
                    and sub.value.value.id in params
                    and sub.value.value.id not in fn_statics
                ):
                    target = canon if canon in _SHAPE_CONSUMER_FUNCS else (
                        f".{node.func.attr}()"
                    )
                    findings.append(
                        _finding(
                            "GL305",
                            f"`{sub.value.value.id}.shape[...]` flows into "
                            f"{target} inside jitted code and "
                            f"`{sub.value.value.id}` is not static: the "
                            "program re-specializes (recompiles) per input "
                            "shape",
                            path, node.lineno,
                        )
                    )
                    flagged = True
                    break
    return findings


def _walk_same_frame(root):
    """``ast.walk`` that does not descend into nested function/lambda
    bodies: their code runs when the function is CALLED, not where it is
    defined, so a statement inside one is not executed by the enclosing
    loop iteration."""
    frame_nodes = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    yield root
    stack = [] if isinstance(root, frame_nodes) else [root]
    while stack:
        for child in ast.iter_child_nodes(stack.pop()):
            yield child
            if not isinstance(child, frame_nodes):
                stack.append(child)


def _rule_jit_in_hot_loop(index: _ModuleIndex, path: str) -> list[Finding]:
    """GL306: a ``jax.jit(...)`` call expression constructed inside a
    ``for``/``while`` body — a fresh wrapper (and jit cache) every
    iteration.  Loop ``else`` blocks run once and stay quiet; a ``while``
    test is evaluated per iteration and counts.  A jit inside a function
    merely *defined* in the loop runs at call time, not per iteration, and
    stays quiet."""
    findings = []
    seen: set = set()
    for node in ast.walk(index.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            continue
        roots = list(node.body)
        if isinstance(node, ast.While):
            roots.append(node.test)
        for root in roots:
            for sub in _walk_same_frame(root):
                if (
                    isinstance(sub, ast.Call)
                    and index.canonical(sub.func) in ("jax.jit", "jax.pmap")
                    and id(sub) not in seen
                ):
                    seen.add(id(sub))
                    findings.append(
                        _finding(
                            "GL306",
                            f"{index.canonical(sub.func)}(...) constructed "
                            "inside a loop body: a fresh jit wrapper (and "
                            "cache) every iteration — the program recompiles "
                            "per pass",
                            path, sub.lineno,
                        )
                    )
    return findings


def _rule_checkpoint_atomicity(index: _ModuleIndex, path: str) -> list[Finding]:
    """GL205: non-atomic checkpoint writes + swallowed exceptions on the
    save/restore spine.

    (a) A write call — ``open(p, "w"/"wb"/"a"...)``, ``*.write_text``/
    ``write_bytes``, ``pickle.dump``/``json.dump``/``np.save*`` — whose
    *path expression* names a live checkpoint directory (a string literal
    containing ``checkpoint_`` without ``.tmp``, directly or through a
    one-hop local assignment) is flagged unless the enclosing function also
    performs an atomic publish (``os.replace``/``os.rename``/
    ``shutil.move``).  The write-into-tmp-then-replace idiom
    (``checkpointing._finalize_checkpoint``) passes both ways.

    (b) ``except``/``except Exception``/``except BaseException`` whose body
    is exactly ``pass``, in modules whose path mentions resilience or
    checkpoint: on this spine a swallowed failure *is* data loss.
    """
    findings: list[Finding] = []

    # -- (a) non-atomic writes into live checkpoint paths -------------------
    def has_live_ckpt_literal(expr, scope) -> bool:
        def live(s: str) -> bool:
            return "checkpoint_" in s and ".tmp" not in s

        if any(live(s) for s in _string_constants(expr)):
            return True
        # one-hop resolution: `d = f".../checkpoint_{i}"; open(d / "x", "wb")`
        names = {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}
        if not names:
            return False
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id in names \
                    and any(live(s) for s in _string_constants(node.value)):
                return True
        return False

    def publishes_atomically(scope) -> bool:
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                canon = index.canonical(node.func)
                if canon in _ATOMIC_PUBLISH_CALLS:
                    return True
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("replace", "rename")
                    and not isinstance(node.func.value, ast.Constant)
                    and len(node.args) == 1
                    and not node.keywords
                ):
                    # Path.replace(target) / Path.rename(target): exactly one
                    # positional argument — which also keeps the 2-argument
                    # str.replace(old, new) path-mangling idiom from reading
                    # as an atomic publish
                    return True
        return False

    for node in ast.walk(index.tree):
        if not isinstance(node, ast.Call):
            continue
        path_expr = None
        canon = index.canonical(node.func)
        if canon == "open" and node.args:
            mode = ""
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if any(m in mode for m in ("w", "a", "x", "+")):
                path_expr = node.args[0]
        elif isinstance(node.func, ast.Attribute) and node.func.attr in _WRITE_METHODS:
            path_expr = node.func.value
        elif canon in _WRITE_FUNCS and len(node.args) >= 2:
            path_expr = node.args[1] if canon in ("pickle.dump", "json.dump") else node.args[0]
        if path_expr is None:
            continue
        scope = index.enclosing_function(node) or index.tree
        if has_live_ckpt_literal(path_expr, scope) and not publishes_atomically(scope):
            findings.append(
                _finding(
                    "GL205",
                    "write into a live `checkpoint_*` path with no atomic "
                    "publish (os.replace) in scope — a crash mid-write "
                    "leaves a directory that looks like a checkpoint",
                    path, node.lineno,
                )
            )

    # -- (b) swallowed exceptions on the resilience/checkpoint spine --------
    posix = path.replace("\\", "/").lower()
    if any(tok in posix for tok in _CKPT_PATH_SCOPE):
        for node in ast.walk(index.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or _dotted(node.type) in (
                "Exception", "BaseException",
            )
            body_is_pass = len(node.body) == 1 and isinstance(node.body[0], ast.Pass)
            if broad and body_is_pass:
                findings.append(
                    _finding(
                        "GL205",
                        "bare `except"
                        + (f" {_dotted(node.type)}" if node.type is not None else "")
                        + ": pass` on the checkpoint/resilience spine — a "
                        "swallowed save/restore failure reads as success",
                        path, node.lineno,
                    )
                )
    return findings


# GL109: host clocks whose deltas bracket async-dispatched work
_TIMING_CLOCKS = frozenset({
    "time.perf_counter", "time.monotonic", "time.time", "time.process_time",
})
# calls that force device execution to complete (or read a concrete value)
_MATERIALIZE_FUNCS = frozenset({
    "jax.block_until_ready", "jax.device_get", "float", "int", "bool",
    "numpy.asarray", "numpy.array", "numpy.testing.assert_allclose",
})
_MATERIALIZE_METHODS = frozenset({"block_until_ready", "item", "tolist"})


def _scope_nodes(scope):
    """Every node in ``scope``'s own frame (module or one function body) —
    nested function/lambda bodies excluded, they run when called."""
    for stmt in scope.body:
        yield from _walk_same_frame(stmt)


def _rule_timing_without_block(index: _ModuleIndex, path: str) -> list[Finding]:
    """GL109 (INFO hint): ``perf_counter()`` deltas bracketing a jitted
    call with no ``block_until_ready()``/materialization in between — jax
    dispatch is async, so the delta measured enqueue time, not compute.

    Shape matched per frame: ``t0 = time.perf_counter()`` ... a call of a
    ``jax.jit``-bound name (or a jit-decorated function, or a direct
    ``jax.jit(f)(x)``) ... ``<expr> - t0`` with no materializing call
    (``jax.block_until_ready``/``float``/``np.asarray``/``.item()``/...)
    between the LAST jitted call and the delta.  The bench.py timed-loop
    idiom (jitted steps, then ``float(loss)`` + ``block_until_ready``,
    then the closing clock read) passes clean.  Known miss: timing through
    a method call (``engine.run(...)``) or a helper bound outside the
    module — only bare names the module itself jit-binds are tracked."""
    findings: list[Finding] = []
    jit_fn_names = {
        fn.name for fn in index.functions if id(fn) in index.jit_contexts
    }
    scopes: list = [index.tree] + list(index.functions)
    for scope in scopes:
        clock_assigns: dict[str, list[int]] = {}
        deltas: list[tuple[int, str]] = []
        jit_lines: list[int] = []
        mat_lines: list[int] = []
        for node in _scope_nodes(scope):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and index.canonical(node.value.func) in _TIMING_CLOCKS
            ):
                clock_assigns.setdefault(node.targets[0].id, []).append(node.lineno)
            if (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)
                and isinstance(node.right, ast.Name)
            ):
                deltas.append((node.lineno, node.right.id))
            if isinstance(node, ast.Call):
                canon = index.canonical(node.func)
                if canon in _MATERIALIZE_FUNCS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MATERIALIZE_METHODS
                ):
                    mat_lines.append(node.lineno)
                func = node.func
                if (
                    isinstance(func, ast.Name)
                    and (func.id in index.jit_bound_names or func.id in jit_fn_names)
                ) or (isinstance(func, ast.Call) and index._is_jit_call(func)):
                    jit_lines.append(node.lineno)
        for lineno, name in deltas:
            starts = [l for l in clock_assigns.get(name, []) if l < lineno]
            if not starts:
                continue
            t0_line = max(starts)
            bracketed = [l for l in jit_lines if t0_line < l < lineno]
            if not bracketed:
                continue
            last_jit = max(bracketed)
            if any(last_jit <= l <= lineno for l in mat_lines):
                continue
            findings.append(
                _finding(
                    "GL109",
                    f"clock delta over `{name}` brackets the jitted call at "
                    f"line {last_jit} with no block_until_ready()/"
                    "materialization in between: jax dispatch is async, so "
                    "this measures host enqueue time, not device compute",
                    path, lineno,
                )
            )
    return findings


# GL206: calls that DRAIN a pending async snapshot (or otherwise fence the
# background read) — any of these between the initiator and the donating
# call closes the aliasing window
_SNAPSHOT_DRAIN_NAMES = frozenset({
    "wait_for_checkpoint",
    "wait_for_pending_checkpoint",
    "wait_until_finished",
    "block_until_ready",
    "join",
    "end_training",
})


def _rule_snapshot_donation_race(index: _ModuleIndex, path: str) -> list[Finding]:
    """GL206: a TrainState name handed to an async checkpoint initiator
    (``async_save=True``) is later passed in a DONATED position with no
    rebind or drain in between.

    The background write may still be reading the very buffers the compiled
    program then overwrites in place — the snapshot-aliasing race the
    sharding-preserving copy in ``save_accelerator_state`` (and the
    ``np.array(copy=True)`` in ``peer_ckpt._host_view``) exists to close.
    User code that starts its OWN async write and then donates the same
    state re-opens it.  Rebinding the name (``state, m = step(state, b)``
    consumed by a later save) or any drain call
    (:data:`_SNAPSHOT_DRAIN_NAMES`) between the two closes the window."""
    findings: list[Finding] = []
    scopes: list = [index.tree] + list(index.functions)
    for scope in scopes:
        own = (
            lambda n: index.enclosing_function(n) is scope
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef))
            else index.enclosing_function(n) is None
        )
        initiators: list[tuple[ast.Call, set]] = []  # (call, snapshotted names)
        donators: list[tuple[ast.Call, list]] = []   # (call, donated names)
        drains: list[int] = []
        rebinds: dict[str, list[int]] = {}
        for node in ast.walk(scope):
            if isinstance(node, ast.Name) and own(node):
                aug = isinstance(index._parent.get(id(node)), ast.AugAssign)
                if isinstance(node.ctx, (ast.Store, ast.Del)) and not aug:
                    rebinds.setdefault(node.id, []).append(node.lineno)
            if not (isinstance(node, ast.Call) and own(node)):
                continue
            fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                     else node.func.id if isinstance(node.func, ast.Name)
                     else None)
            if fname in _SNAPSHOT_DRAIN_NAMES:
                drains.append(node.lineno)
                continue
            if any(kw.arg == "async_save"
                   and isinstance(kw.value, ast.Constant)
                   and kw.value.value is True
                   for kw in node.keywords):
                names = {a.id for a in node.args if isinstance(a, ast.Name)}
                names |= {kw.value.id for kw in node.keywords
                          if kw.arg != "async_save"
                          and isinstance(kw.value, ast.Name)}
                if names:
                    initiators.append((node, names))
                continue
            donated: tuple[int, ...] = ()
            if isinstance(node.func, ast.Name) and node.func.id in index.donated_callables:
                donated = index.donated_callables[node.func.id]
            elif isinstance(node.func, ast.Call) and index._is_jit_call(node.func):
                donated = _donate_positions(node.func)
            dnames = [
                node.args[i].id
                for i in donated
                if i < len(node.args) and isinstance(node.args[i], ast.Name)
            ]
            if dnames:
                donators.append((node, dnames))
        for init, snap_names in initiators:
            init_end = getattr(init, "end_lineno", init.lineno) or init.lineno
            for call, dnames in sorted(donators, key=lambda c: c[0].lineno):
                if call.lineno <= init_end:
                    continue
                hot = [n for n in dnames if n in snap_names]
                if not hot:
                    continue
                if any(init_end < l <= call.lineno for l in drains):
                    break  # drained: this and every later donation is safe
                name = hot[0]
                if any(init_end < l < call.lineno
                       for l in rebinds.get(name, [])):
                    continue  # rebound: the snapshotted buffer is detached
                findings.append(
                    _finding(
                        "GL206",
                        f"`{name}` was handed to an async checkpoint at line "
                        f"{init.lineno} (async_save=True) and is donated here "
                        "with no drain or rebind in between: the background "
                        "write may still be reading the buffers the compiled "
                        "program overwrites in place — drain "
                        "(wait_for_checkpoint) or snapshot-copy first",
                        path, call.lineno,
                    )
                )
                break  # one finding per initiator keeps the report readable
    return findings


_ALL_RULES = (
    _rule_donated_reuse,
    _rule_host_sync,
    _rule_shard_map_compat,
    _rule_impure_in_jit,
    _rule_checkpoint_atomicity,
    _rule_shape_dependent_trace,
    _rule_jit_in_hot_loop,
    _rule_timing_without_block,
    _rule_snapshot_donation_race,
)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def lint_source(source: str, path: str = "<string>") -> list[Finding]:
    """All AST findings for one module's source (suppressions not yet
    applied — :func:`lint_paths` resolves them against the real file)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            _finding("GL002", f"unparseable module: {e.msg}", path, e.lineno or 1)
        ]
    index = _ModuleIndex(tree)
    findings = []
    for rule_fn in _ALL_RULES:
        findings.extend(rule_fn(index, path))
    # GL001 contract: EVERY rationale-less marker is reported, including
    # stale ones that no longer match any finding (apply_suppressions
    # dedupes against these when a bare marker does suppress something)
    for lineno, text in enumerate(source.splitlines(), start=1):
        parsed = parse_marker(text)
        if parsed is not None and parsed[1] is None:
            findings.append(
                _finding(
                    "GL001",
                    "suppression marker without a rationale "
                    "(add `-- <why this hazard is intentional>`)",
                    path, lineno,
                )
            )
    return findings


def iter_python_files(paths: Sequence, excludes: Sequence[str] = DEFAULT_EXCLUDES):
    """``*.py`` files under ``paths``.  ``excludes`` (path substrings) and
    :data:`DEFAULT_EXCLUDE_DIRS` (vendored/generated directory names) apply
    only to directory sweeps — a file named explicitly is always yielded,
    even if missing (so :func:`lint_paths` can report the bad target loudly
    instead of letting a typo'd CI path pass as a clean run)."""
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if DEFAULT_EXCLUDE_DIRS.intersection(f.parts):
                    continue
                if any(ex in f.as_posix() for ex in excludes):
                    continue
                yield f
        else:
            yield p


def resolve_targets(
    paths: Sequence,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
) -> tuple[list, list[Finding]]:
    """The ONE target resolver every CLI surface shares (``lint`` and
    ``preflight``): expand ``paths`` to ``(readable sources, GL002 findings
    for every explicitly named target that does not exist or cannot be
    read)``.  Factored so a typo'd CI path fails loudly in every command
    that takes paths — never a silently skipped target passing as clean.

    Returns ``[(Path, source_text), ...]`` plus the error findings.
    """
    sources: list = []
    findings: list[Finding] = []
    for f in iter_python_files(paths, excludes):
        try:
            sources.append((f, f.read_text()))
        except (OSError, UnicodeDecodeError) as e:
            findings.append(_finding("GL002", f"unreadable target: {e}", str(f), 1))
    return sources, findings


def lint_paths(
    paths: Sequence,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
) -> Report:
    """Lint every ``*.py`` under ``paths`` (files or directories), resolve
    inline suppressions, and return the combined :class:`Report`."""
    sources, findings = resolve_targets(paths, excludes)
    for f, source in sources:
        findings.extend(lint_source(source, str(f)))
    return Report(apply_suppressions(findings))
