"""graft-lint: static analysis for donation, transfer, and sharding hazards.

Two engines over one report model (all CPU-safe, nothing executes on
device):

- :mod:`.jaxpr_audit` — traces a step/decode function abstractly
  (``jax.jit(fn).trace``) and walks the ClosedJaxpr for hazards only the
  traced program shows: wasted donations (GL101), const-capture HBM
  blowups (GL102), in-trace memory-kind transfers (GL103), PRNG key reuse
  (GL104), unsharded large outputs (GL105).
- :mod:`.ast_rules` — repo-wide source linter for hazards only the caller's
  source shows: donated-name reuse after a ``donate_argnums`` call site
  (GL201, the PR 2 async-checkpoint race shape), host syncs in jitted code
  (GL202), ``jax.experimental.shard_map`` outside the compat shims (GL203),
  wall-clock/stdlib randomness under trace (GL204).

Surfaces: ``python -m accelerate_tpu lint`` (``commands/lint.py``),
``Accelerator.audit_step()`` / ``ACCELERATE_LINT=1``, ``make lint``, and
``bench.py --plan N --audit``.  Rule catalog and suppression syntax:
``docs/static_analysis.md``.
"""

from .ast_rules import (
    DEFAULT_EXCLUDE_DIRS,
    DEFAULT_EXCLUDES,
    iter_python_files,
    lint_paths,
    lint_source,
)
from .jaxpr_audit import audit_fn, audit_jitted, audit_traced, iter_eqns
from .report import Finding, Report, Severity, apply_suppressions, parse_marker
from .rules import RULES, Rule, rule

__all__ = [
    "DEFAULT_EXCLUDE_DIRS",
    "DEFAULT_EXCLUDES",
    "Finding",
    "Report",
    "RULES",
    "Rule",
    "Severity",
    "apply_suppressions",
    "audit_fn",
    "audit_jitted",
    "audit_traced",
    "iter_eqns",
    "iter_python_files",
    "lint_paths",
    "lint_source",
    "parse_marker",
    "rule",
]
