"""graft-lint: static analysis for donation, transfer, and sharding hazards.

Three engines over one report model (all CPU-safe, nothing executes on
device):

- :mod:`.jaxpr_audit` — traces a step/decode function abstractly
  (``jax.jit(fn).trace``) and walks the ClosedJaxpr for hazards only the
  traced program shows: wasted donations (GL101), const-capture HBM
  blowups (GL102), in-trace memory-kind transfers (GL103), PRNG key reuse
  (GL104), unsharded large outputs (GL105), collective-matmul candidates
  (GL106/GL107), donated promotion drift (GL304).
- :mod:`.ast_rules` — repo-wide source linter for hazards only the caller's
  source shows: donated-name reuse after a ``donate_argnums`` call site
  (GL201, the PR 2 async-checkpoint race shape), host syncs in jitted code
  (GL202), ``jax.experimental.shard_map`` outside the compat shims (GL203),
  wall-clock/stdlib randomness under trace (GL204), non-atomic checkpoint
  writes (GL205), shape-dependent traces (GL305), jit-in-hot-loop (GL306).
- :mod:`.compiled_audit` — AOT ``lower().compile()`` every production
  program and read XLA's decisions off the executable: donation that did
  not alias (GL301), HBM footprint over budget (GL302), compiled program
  set vs the predicted bucket ladder (GL303), plus the flops/bytes cost
  report and the runtime compile-event counter.
- :mod:`.distributed_audit` — cross-program, cross-role contracts over
  PAIRS/SETS of programs (trace-only, zero compiles): collective-schedule
  divergence between mesh roles (GL401), implicit-reshard blowups
  (GL402), prefill/decode wire-schema incompatibility (GL403), and
  role-asymmetric warmup coverage (GL404) — the ``preflight --serve
  --disaggregate`` pair gate and the multichip dryrun's distributed leg.

Surfaces: ``python -m accelerate_tpu lint`` / ``preflight``
(``commands/lint.py``, ``commands/preflight.py``),
``Accelerator.audit_step()`` / ``ACCELERATE_LINT=1``, ``make lint`` /
``make preflight``, and ``bench.py --plan N --audit``.  Rule catalog and
suppression syntax: ``docs/static_analysis.md``.
"""

from .ast_rules import (
    DEFAULT_EXCLUDE_DIRS,
    DEFAULT_EXCLUDES,
    iter_python_files,
    lint_paths,
    lint_source,
    resolve_targets,
)
from .compiled_audit import (
    CompileCounter,
    audit_aot,
    audit_compiled,
    audit_program_set,
    aot_compile_program,
    device_hbm_bytes,
    install_global_compile_counter,
)
from .distributed_audit import (
    CollectiveOp,
    audit_collective_schedules,
    audit_compiled_resharding,
    audit_resharding,
    audit_warmup_coverage,
    audit_wire_schema,
    check_wire_schemas,
    collective_schedule,
    handoff_schedule,
    pair_preflight,
    role_programs,
    warmup_plan,
    wire_schema,
)
from .jaxpr_audit import audit_fn, audit_jitted, audit_traced, iter_eqns
from .report import Finding, Report, Severity, apply_suppressions, parse_marker
from .rules import RULES, Rule, rule

__all__ = [
    "CollectiveOp",
    "CompileCounter",
    "DEFAULT_EXCLUDE_DIRS",
    "DEFAULT_EXCLUDES",
    "Finding",
    "Report",
    "RULES",
    "Rule",
    "Severity",
    "aot_compile_program",
    "apply_suppressions",
    "audit_aot",
    "audit_collective_schedules",
    "audit_compiled",
    "audit_compiled_resharding",
    "audit_fn",
    "audit_jitted",
    "audit_program_set",
    "audit_resharding",
    "audit_traced",
    "audit_warmup_coverage",
    "audit_wire_schema",
    "check_wire_schemas",
    "collective_schedule",
    "device_hbm_bytes",
    "handoff_schedule",
    "install_global_compile_counter",
    "iter_eqns",
    "iter_python_files",
    "lint_paths",
    "lint_source",
    "pair_preflight",
    "parse_marker",
    "resolve_targets",
    "role_programs",
    "rule",
    "warmup_plan",
    "wire_schema",
]
