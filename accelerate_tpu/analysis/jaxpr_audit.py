"""Jaxpr auditor: trace a step/decode function abstractly and flag the
hazard classes that only show up in the traced program.

No device execution anywhere: the engine runs on ``jax.jit(fn).trace(...)``
(the AOT path — accepts :class:`jax.ShapeDtypeStruct` stand-ins), so a 7B
train step audits on a CPU-only CI box.  The hazards, and why each is
invisible to a source-level linter:

- **GL101 wasted donation** — ``donate_argnums`` promises XLA an input
  buffer to reuse for an output of the same byte size.  Whether any output
  qualifies is a property of the traced avals, not the source.  A donation
  nothing can alias frees zero HBM while still invalidating the caller's
  buffer — the worst of both worlds.
- **GL102 const capture** — a closed-over array becomes a jaxpr constant:
  baked per executable, duplicated across retraces, exempt from donation
  and the sharding plan.  Only the trace knows what actually closed over.
- **GL103 transfer in trace** — a ``device_put`` to a different memory kind
  inside traced code is a host<->device copy serialized into the step,
  bypassing the ``ops/streaming.py`` overlap discipline.
- **GL104 key reuse** — one PRNG key consumed by two random primitives
  yields identical streams; the auditor tracks key identity through
  ``pjit``/``scan``/``cond`` sub-jaxprs, which no regex can.
- **GL105 unsharded large output** — an output above the size threshold
  whose producer is not a sharding constraint may be resolved fully
  replicated by GSPMD.
- **GL106 collective-matmul hint** (info) — an ``all_gather`` consumed by
  exactly one ``dot_general`` is the monolithic gather-then-matmul pipe
  that ``ops/collective_matmul.py`` decomposes into a latency-hiding ring;
  only the traced program shows the consumer fan-out.
- **GL107 collective-matmul reduce-scatter hint** (info) — the row-parallel
  mirror: a ``dot_general`` whose result feeds exactly one
  ``reduce_scatter`` serializes the monolithic scatter behind the matmul
  that produced it (``ring_matmul_reduce_scatter`` is the decomposition).
- **GL108 hierarchical-reduction hint** (info) — a large psum spanning the
  ``dcn`` mesh axis jointly with intra-slice axes: the flat reduction's
  cross-slice leg carries one redundant full-size copy per intra-slice
  device over DCN; ``parallel/hierarchical.py`` is the decomposition
  (reduce-scatter over ICI, slab all-reduce over dcn, all-gather back).
- **GL110 unscaled fp8 dot** — a ``dot_general`` over float8 operands whose
  result reaches downstream math with no dequantizing ``mul``/``div`` in
  the chain: fp8 codes are meaningless without their scale, and only the
  traced program shows whether the accumulator was rescaled before use.
- **GL304 donated promotion drift** — a donated input whose only same-shape
  outputs differ in dtype or weak_type (a python/numpy scalar promoted the
  update): feeding the result back re-keys the jit cache every step, and
  the widened output can no longer alias the donated buffer.

Suppression is source-anchored (see :mod:`.report`): each finding resolves
its file/line from the flagged equation's ``source_info``, so the same
inline ``# graft-lint: disable=GLxxx -- reason`` marker works for both
engines.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import jax
import numpy as np

from .report import Finding, Report, apply_suppressions
from .rules import RULES

try:  # jaxpr equations carry their user-code provenance here
    from jax._src import source_info_util as _src_info
except Exception:  # pragma: no cover - private-API drift
    _src_info = None


# random primitives that CONSUME a key (produce bits or a derived stream).
# Structural ops on key arrays (slice/squeeze/broadcast of a split result)
# produce distinct subkeys and are not consumptions.
_KEY_CONSUMERS = frozenset({"random_split", "random_bits", "random_fold_in"})

# producers that satisfy GL105: the output's layout was pinned on purpose
_SHARDING_PRODUCERS = frozenset({"sharding_constraint", "device_put"})


# ---------------------------------------------------------------------------
# small jaxpr helpers
# ---------------------------------------------------------------------------


def _is_key_aval(aval) -> bool:
    try:
        return jax.numpy.issubdtype(aval.dtype, jax.dtypes.prng_key)
    except Exception:
        return False


def _aval_bytes(aval) -> int:
    """Byte size of an abstract value; extended dtypes (PRNG keys) fall back
    to their impl's key size (threefry: 2x uint32)."""
    shape = getattr(aval, "shape", ())
    n = int(np.prod(shape)) if shape else 1
    dtype = getattr(aval, "dtype", None)
    try:
        return n * np.dtype(dtype).itemsize
    except TypeError:
        itemsize = getattr(dtype, "itemsize", None)
        return n * int(itemsize if itemsize else 8)


def _eqn_location(eqn):
    """``(path, line)`` of the user frame that emitted ``eqn``, if any."""
    if _src_info is None or eqn is None:
        return None, None
    try:
        frame = _src_info.user_frame(eqn.source_info)
    except Exception:  # pragma: no cover - private-API drift
        return None, None
    if frame is None:
        return None, None
    return frame.file_name, frame.start_line


def _sub_jaxprs(eqn) -> list:
    """Every (closed) sub-jaxpr carried in an equation's params, normalized
    to ``ClosedJaxpr``-likes with ``.jaxpr`` access."""
    subs = []
    for val in eqn.params.values():
        for item in val if isinstance(val, (list, tuple)) else (val,):
            if hasattr(item, "jaxpr") and hasattr(item, "consts"):
                subs.append(item)  # ClosedJaxpr
            elif hasattr(item, "eqns") and hasattr(item, "invars"):
                subs.append(jax.core.ClosedJaxpr(item, ()))
    return subs


def _walk_eqns(jaxpr) -> Iterable:
    """Depth-first over every equation, including sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub.jaxpr)


def iter_eqns(closed_or_jaxpr) -> Iterable:
    """Public depth-first equation iterator over a ``ClosedJaxpr`` (or bare
    jaxpr), descending into every sub-jaxpr — the one place the sub-jaxpr
    packaging convention lives (callers checking for a primitive, e.g. the
    dryrun's ppermute-engagement probe, should use this rather than
    re-rolling the recursion)."""
    jaxpr = getattr(closed_or_jaxpr, "jaxpr", closed_or_jaxpr)
    return _walk_eqns(jaxpr)


def _finding(rule_id: str, message: str, *, path=None, line=None) -> Finding:
    r = RULES[rule_id]
    return Finding(
        rule=rule_id, severity=r.severity, message=message, fix_hint=r.fix_hint,
        path=path, line=line, engine="jaxpr",
    )


# ---------------------------------------------------------------------------
# the individual audits
# ---------------------------------------------------------------------------


def _audit_donation(jaxpr, donated: list[bool], path_hint) -> list[Finding]:
    """GL101: greedy byte-size matching of donated inputs against outputs —
    the same viability criterion XLA's buffer-donation aliasing applies
    (an input buffer can only be reused by an output of equal size)."""
    findings = []
    out_vars = [v for v in jaxpr.outvars if not isinstance(v, jax.core.Literal)]
    # a donated input returned unchanged IS its own output buffer
    passthrough = {id(v) for v in jaxpr.invars} & {id(v) for v in out_vars}
    out_sizes: dict[int, int] = {}
    for v in out_vars:
        if id(v) in passthrough:
            continue
        size = _aval_bytes(v.aval)
        out_sizes[size] = out_sizes.get(size, 0) + 1
    for i, (var, is_donated) in enumerate(zip(jaxpr.invars, donated)):
        if not is_donated or id(var) in passthrough:
            continue
        size = _aval_bytes(var.aval)
        if out_sizes.get(size, 0) > 0:
            out_sizes[size] -= 1
            continue
        aval = var.aval
        findings.append(
            _finding(
                "GL101",
                f"donated argument {i} ({getattr(aval, 'dtype', '?')}"
                f"{list(getattr(aval, 'shape', ()))}, {size} B) aliases no "
                "output: no un-aliased output of the same byte size remains",
                path=path_hint[0] if path_hint else None,
                line=path_hint[1] if path_hint else None,
            )
        )
    return findings


def _audit_donation_promotion(jaxpr, donated: list[bool], path_hint) -> list[Finding]:
    """GL304: a donated input with no exact-aval output but a same-shape
    output whose dtype or weak_type drifted — the promotion signature of a
    python/numpy scalar mixed into the donated tree.  The drifted result
    re-keys the jit cache when fed back (a recompile every step) and can no
    longer alias the donated buffer."""
    out_vars = [v for v in jaxpr.outvars if not isinstance(v, jax.core.Literal)]
    passthrough = {id(v) for v in jaxpr.invars} & {id(v) for v in out_vars}

    def _sig(aval):
        return (
            tuple(getattr(aval, "shape", ())),
            str(getattr(aval, "dtype", "?")),
            bool(getattr(aval, "weak_type", False)),
        )

    exact: dict[tuple, int] = {}
    by_shape: dict[tuple, list] = {}
    for v in out_vars:
        if id(v) in passthrough:
            continue
        shape, dtype, weak = _sig(v.aval)
        exact[(shape, dtype, weak)] = exact.get((shape, dtype, weak), 0) + 1
        by_shape.setdefault(shape, []).append((dtype, weak))
    findings = []
    for i, (var, is_donated) in enumerate(zip(jaxpr.invars, donated)):
        if not is_donated or id(var) in passthrough:
            continue
        shape, dtype, weak = _sig(var.aval)
        if exact.get((shape, dtype, weak), 0) > 0:
            exact[(shape, dtype, weak)] -= 1
            continue
        drifted = [
            (d, w) for d, w in by_shape.get(shape, []) if (d, w) != (dtype, weak)
        ]
        if not drifted:
            continue  # no same-shape output at all: GL101's case, not drift
        d, w = drifted[0]
        what = f"dtype {dtype} -> {d}" if d != dtype else f"weak_type {weak} -> {w}"
        findings.append(
            _finding(
                "GL304",
                f"donated argument {i} ({dtype}{list(shape)}) only matches "
                f"an output of the same shape with promoted aval ({what}): "
                "a python scalar in the update re-keys the jit cache every "
                "step and breaks the donation alias",
                path=path_hint[0] if path_hint else None,
                line=path_hint[1] if path_hint else None,
            )
        )
    return findings


def _audit_consts(closed, threshold: int, path_hint) -> list[Finding]:
    """GL102: closed-over constants above the size threshold."""
    findings = []
    const_first_use = {}
    for eqn in closed.jaxpr.eqns:
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal) and id(v) not in const_first_use:
                const_first_use[id(v)] = eqn
    for var, const in zip(closed.jaxpr.constvars, closed.consts):
        nbytes = getattr(const, "nbytes", None)
        if nbytes is None:
            nbytes = _aval_bytes(const) if hasattr(const, "shape") else 0
        if nbytes < threshold:
            continue
        path, line = _eqn_location(const_first_use.get(id(var)))
        if path is None and path_hint:
            path, line = path_hint
        findings.append(
            _finding(
                "GL102",
                f"closed-over constant {getattr(const, 'dtype', '?')}"
                f"{list(getattr(const, 'shape', ()))} ({nbytes / 2**20:.1f} MiB) "
                "is baked into the jaxpr",
                path=path, line=line,
            )
        )
    return findings


def _dst_memory_kinds(eqn) -> list:
    kinds = []
    for dst in eqn.params.get("devices", ()) or ():
        kind = getattr(dst, "memory_kind", None)
        if kind is not None:
            kinds.append(kind)
    return kinds


def _default_memory_kind() -> Optional[str]:
    try:
        return jax.devices()[0].default_memory().kind
    except Exception:  # pragma: no cover - no backend
        return None


def _audit_transfers(jaxpr, default_kind: Optional[str]) -> list[Finding]:
    """GL103: in-trace device_put that crosses memory kinds."""
    if default_kind is None:
        return []
    findings = []
    for eqn in _walk_eqns(jaxpr):
        if eqn.primitive.name != "device_put":
            continue
        crossing = sorted({k for k in _dst_memory_kinds(eqn) if k != default_kind})
        if not crossing:
            continue
        path, line = _eqn_location(eqn)
        findings.append(
            _finding(
                "GL103",
                f"device_put to memory kind {'/'.join(crossing)} inside "
                f"traced code (program default: {default_kind}) — an "
                "implicit transfer serialized into the step",
                path=path, line=line,
            )
        )
    return findings


def _audit_key_reuse(closed) -> list[Finding]:
    """GL104: a key var consumed by >1 random primitive.  Key identity is
    threaded through sub-jaxprs by positional invar mapping (pjit/scan align
    exactly; ``cond`` skips its branch index); scopes whose arity doesn't
    align conservatively start fresh roots (documented miss, never a false
    positive)."""
    consumptions: dict[int, list] = {}
    next_root = [0]

    def root_of(var, env: dict) -> int:
        if var not in env:
            env[var] = next_root[0]
            next_root[0] += 1
        return env[var]

    def walk(jaxpr, env: dict, loc_eqn=None):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in _KEY_CONSUMERS:
                for v in eqn.invars:
                    if isinstance(v, jax.core.Literal) or not _is_key_aval(v.aval):
                        continue
                    # report at the outermost enclosing call site: inner
                    # jaxprs are deduplicated across identical calls, so an
                    # inner eqn's source_info may point at the wrong one
                    consumptions.setdefault(root_of(v, env), []).append(loc_eqn or eqn)
            for sub in _sub_jaxprs(eqn):
                inner = sub.jaxpr
                operands = list(eqn.invars)
                if eqn.primitive.name == "cond" and len(operands) == len(inner.invars) + 1:
                    operands = operands[1:]
                sub_env: dict = {}
                if len(operands) == len(inner.invars):
                    for outer, v in zip(operands, inner.invars):
                        if not isinstance(outer, jax.core.Literal):
                            sub_env[v] = root_of(outer, env)
                walk(inner, sub_env, loc_eqn or eqn)

    walk(closed.jaxpr, {})
    findings = []
    for eqns in consumptions.values():
        if len(eqns) < 2:
            continue
        first_path, first_line = _eqn_location(eqns[0])
        path, line = _eqn_location(eqns[1])
        where = f" (first consumed at {first_path}:{first_line})" if first_path else ""
        findings.append(
            _finding(
                "GL104",
                f"PRNG key consumed by {len(eqns)} random primitives "
                f"({', '.join(e.primitive.name for e in eqns)}){where}: "
                "identical streams",
                path=path, line=line,
            )
        )
    return findings


def _audit_collective_matmul(closed) -> list[Finding]:
    """GL106/GL107 (hints): the two monolithic collective-matmul pipes the
    ring schedules decompose — an ``all_gather`` whose result is consumed by
    exactly one ``dot_general`` (GL106, column-parallel), and a
    ``dot_general`` whose result feeds exactly one ``reduce_scatter``
    (GL107, the row-parallel mirror).  Scope-local: jaxpr vars never cross
    sub-jaxpr boundaries except through invars, so consumers are counted
    within each (sub-)jaxpr; a value that escapes the scope or feeds
    anything else (norms, residuals, multiple consumers) is not a pure
    pipe and stays quiet."""
    findings = []

    def scan(jaxpr):
        consumers: dict[int, list] = {}
        gathers = []
        dots = []
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    consumers.setdefault(id(v), []).append(eqn)
            if eqn.primitive.name == "all_gather":
                gathers.append(eqn)
            elif eqn.primitive.name == "dot_general":
                dots.append(eqn)
            for sub in _sub_jaxprs(eqn):
                scan(sub.jaxpr)
        escaped = {id(v) for v in jaxpr.outvars if not isinstance(v, jax.core.Literal)}
        for g in gathers:
            out = g.outvars[0]
            cons = consumers.get(id(out), [])
            if id(out) in escaped or len(cons) != 1:
                continue
            if cons[0].primitive.name != "dot_general":
                continue
            path, line = _eqn_location(g)
            aval = out.aval
            findings.append(
                _finding(
                    "GL106",
                    f"all_gather result {getattr(aval, 'dtype', '?')}"
                    f"{list(getattr(aval, 'shape', ()))} feeds exactly one "
                    "dot_general: a collective-matmul candidate (the gather "
                    "could ride a ppermute ring hidden under the partial "
                    "matmuls — ops/collective_matmul.py)",
                    path=path, line=line,
                )
            )
        for d in dots:
            out = d.outvars[0]
            cons = consumers.get(id(out), [])
            if id(out) in escaped or len(cons) != 1:
                continue
            if cons[0].primitive.name != "reduce_scatter":
                continue
            path, line = _eqn_location(d)
            aval = out.aval
            findings.append(
                _finding(
                    "GL107",
                    f"dot_general result {getattr(aval, 'dtype', '?')}"
                    f"{list(getattr(aval, 'shape', ()))} feeds exactly one "
                    "reduce_scatter: the row-parallel collective-matmul "
                    "candidate (the scatter could ride a ppermute ring "
                    "hidden under the partial matmuls — "
                    "ops/collective_matmul.py ring_matmul_reduce_scatter)",
                    path=path, line=line,
                )
            )

    scan(closed.jaxpr)
    return findings


def _audit_hierarchical_reduce(closed, threshold: int) -> list[Finding]:
    """GL108 (hint): a large all-reduce whose named axes span ``dcn``
    JOINTLY with intra-slice axes.  A flat joint-axis psum decomposes (in
    XLA or in the runtime) into per-axis reductions where the cross-slice
    leg operates on the FULL operand for every intra-slice device — p
    redundant full-size copies over the slow DCN link.  A psum over
    ``('dcn',)`` alone stays quiet: that is the hierarchical path's own
    slab hop (reduce-scatter first, then the dcn-only all-reduce).  Walks
    sub-jaxprs (shard_map/pjit/scan) via :func:`iter_eqns`."""
    findings = []
    for eqn in iter_eqns(closed):
        if eqn.primitive.name != "psum":
            continue
        axes = eqn.params.get("axes") or ()
        if isinstance(axes, str):
            axes = (axes,)
        named = tuple(a for a in axes if isinstance(a, str))
        if "dcn" not in named or len(named) < 2:
            continue
        nbytes = sum(
            int(np.prod(v.aval.shape)) * v.aval.dtype.itemsize
            for v in eqn.invars
            if hasattr(v.aval, "shape") and hasattr(v.aval, "dtype")
        )
        if nbytes < threshold:
            continue
        path, line = _eqn_location(eqn)
        ici = tuple(a for a in named if a != "dcn")
        findings.append(
            _finding(
                "GL108",
                f"psum of {nbytes / 2**20:.1f} MiB over joint axes {named}: "
                f"the cross-slice leg moves one full-size copy per "
                f"{'x'.join(ici)} device over DCN — a hierarchical-reduction "
                "candidate (reduce-scatter over ICI, slab all-reduce over "
                "dcn, all-gather back; parallel/hierarchical.py)",
                path=path, line=line,
            )
        )
    return findings


_FP8_DTYPES = ("float8_e4m3fn", "float8_e5m2", "float8_e4m3fnuz",
               "float8_e5m2fnuz", "float8_e4m3b11fnuz")

# ops that pass a dot result through without changing its VALUES — the
# dequantizing multiply may legitimately sit on the far side of them
_FP8_TRANSPARENT = frozenset({
    "convert_element_type", "transpose", "reshape", "broadcast_in_dim",
    "squeeze", "slice", "stop_gradient",
})


def _is_fp8_aval(aval) -> bool:
    return str(getattr(aval, "dtype", "")) in _FP8_DTYPES


def _audit_fp8_scaling(closed) -> list[Finding]:
    """GL110: a ``dot_general`` with a float8 operand whose result reaches a
    non-multiplicative consumer with no ``mul``/``div`` anywhere in the
    chain.  fp8 codes are fixed-point residue — ``q = x * scale`` cast to
    e4m3/e5m2 — so a correct fp8 matmul ALWAYS dequantizes its accumulator
    (``out * (1 / (x_scale * w_scale))``, the ops/fp8.py contract) before
    downstream math sees it.  The chain is followed through value-preserving
    ops (convert/transpose/reshape/...); a result that escapes its scope
    stays quiet (conservative, the GL106 discipline) since the consumer is
    not visible here."""
    findings = []

    def scan(jaxpr):
        consumers: dict[int, list] = {}
        fp8_dots = []
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    consumers.setdefault(id(v), []).append(eqn)
            if eqn.primitive.name == "dot_general" and any(
                _is_fp8_aval(v.aval) for v in eqn.invars
                if not isinstance(v, jax.core.Literal)
            ):
                fp8_dots.append(eqn)
            for sub in _sub_jaxprs(eqn):
                scan(sub.jaxpr)
        escaped = {id(v) for v in jaxpr.outvars
                   if not isinstance(v, jax.core.Literal)}

        def chain_is_scaled(var, depth=0) -> Optional[bool]:
            """True: a mul/div consumes the value (possibly through
            transparent ops).  False: a value-consuming primitive reads it
            unscaled.  None: undecidable (escapes scope / no consumers) —
            stays quiet."""
            if id(var) in escaped or depth > 16:
                return None
            cons = consumers.get(id(var), [])
            if not cons:
                return None
            verdicts = []
            for c in cons:
                if c.primitive.name in ("mul", "div"):
                    verdicts.append(True)
                elif c.primitive.name in _FP8_TRANSPARENT:
                    verdicts.append(chain_is_scaled(c.outvars[0], depth + 1))
                else:
                    verdicts.append(False)
            if any(v is False for v in verdicts):
                return False  # at least one consumer reads raw codes
            if any(v is None for v in verdicts):
                return None
            return True

        for d in fp8_dots:
            if chain_is_scaled(d.outvars[0]) is not False:
                continue
            path, line = _eqn_location(d)
            dts = "x".join(
                str(getattr(v.aval, "dtype", "?")) for v in d.invars
                if not isinstance(v, jax.core.Literal)
            )
            findings.append(
                _finding(
                    "GL110",
                    f"dot_general over fp8 operands ({dts}) feeds a "
                    "non-multiplicative consumer with no dequantizing "
                    "mul/div in the chain: downstream math runs on raw fp8 "
                    "codes, off by the combined scale factor",
                    path=path, line=line,
                )
            )

    scan(closed.jaxpr)
    return findings


def _audit_output_sharding(jaxpr, threshold: int, path_hint) -> list[Finding]:
    """GL105: large outputs whose producing equation is not a sharding pin."""
    producer = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            producer[id(v)] = eqn
    invar_ids = {id(v) for v in jaxpr.invars} | {id(v) for v in jaxpr.constvars}
    findings = []
    seen: set = set()
    for v in jaxpr.outvars:
        if isinstance(v, jax.core.Literal) or id(v) in invar_ids or id(v) in seen:
            continue  # literals / pass-throughs keep their committed layout
        seen.add(id(v))
        size = _aval_bytes(v.aval)
        if size < threshold:
            continue
        eqn = producer.get(id(v))
        if eqn is not None and eqn.primitive.name in _SHARDING_PRODUCERS:
            continue
        path, line = _eqn_location(eqn)
        if path is None and path_hint:
            path, line = path_hint
        findings.append(
            _finding(
                "GL105",
                f"output {getattr(v.aval, 'dtype', '?')}"
                f"{list(getattr(v.aval, 'shape', ()))} ({size / 2**20:.1f} MiB) "
                "has no sharding constraint on its producer",
                path=path, line=line,
            )
        )
    return findings


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------


def audit_traced(
    traced,
    *,
    donated: Optional[list[bool]] = None,
    const_bytes_threshold: int = 1 << 20,
    output_bytes_threshold: int = 1 << 20,
    dcn_reduce_bytes_threshold: int = 1 << 20,
    default_memory_kind: Optional[str] = None,
    path_hint: Optional[tuple] = None,
) -> Report:
    """Audit a ``jax.jit(fn).trace(*args)`` result (or a bare
    ``ClosedJaxpr`` plus an explicit per-flat-input ``donated`` mask).

    Pure jaxpr walking — nothing executes.  ``default_memory_kind``
    overrides the backend's default for the GL103 comparison (useful for
    auditing TPU-shaped programs from a CPU box); thresholds are in bytes.
    """
    if hasattr(traced, "jaxpr") and hasattr(traced, "args_info"):
        closed = traced.jaxpr
        if donated is None:
            leaves = jax.tree_util.tree_leaves(
                traced.args_info, is_leaf=lambda x: hasattr(x, "donated")
            )
            donated = [bool(getattr(l, "donated", False)) for l in leaves]
    else:
        closed = traced
    if donated is None:
        donated = [False] * len(closed.jaxpr.invars)
    if len(donated) != len(closed.jaxpr.invars):
        raise ValueError(
            f"donated mask has {len(donated)} entries for "
            f"{len(closed.jaxpr.invars)} flat inputs"
        )
    if default_memory_kind is None:
        default_memory_kind = _default_memory_kind()

    findings = []
    findings += _audit_donation(closed.jaxpr, donated, path_hint)
    findings += _audit_donation_promotion(closed.jaxpr, donated, path_hint)
    findings += _audit_consts(closed, const_bytes_threshold, path_hint)
    findings += _audit_transfers(closed.jaxpr, default_memory_kind)
    findings += _audit_key_reuse(closed)
    findings += _audit_collective_matmul(closed)
    findings += _audit_fp8_scaling(closed)
    findings += _audit_hierarchical_reduce(closed, dcn_reduce_bytes_threshold)
    findings += _audit_output_sharding(closed.jaxpr, output_bytes_threshold, path_hint)
    return Report(apply_suppressions(findings))


def _path_hint_of(fn) -> Optional[tuple]:
    code = getattr(fn, "__code__", None)
    if code is None:
        inner = getattr(fn, "__wrapped__", None)
        code = getattr(inner, "__code__", None)
    if code is None:
        return None
    return code.co_filename, code.co_firstlineno


def audit_fn(fn, *example_args, donate_argnums=(), **audit_kwargs) -> Report:
    """Jit ``fn`` with ``donate_argnums``, trace it abstractly against
    ``example_args`` (concrete arrays or ``jax.ShapeDtypeStruct``), and
    audit the result."""
    traced = jax.jit(fn, donate_argnums=donate_argnums).trace(*example_args)
    audit_kwargs.setdefault("path_hint", _path_hint_of(fn))
    return audit_traced(traced, **audit_kwargs)


def audit_jitted(jitted, *example_args, **audit_kwargs) -> Report:
    """Audit an already-jitted callable — a raw ``jax.jit`` wrapper or a
    prepared train step (``Accelerator.prepare_train_step`` results expose
    their inner jit as ``._jitted``)."""
    inner = getattr(jitted, "_jitted", jitted)
    if not hasattr(inner, "trace"):
        raise TypeError(
            f"{jitted!r} is not a jitted callable (no .trace); pass the "
            "jax.jit wrapper or a prepared step exposing ._jitted"
        )
    audit_kwargs.setdefault("path_hint", _path_hint_of(inner))
    return audit_traced(inner.trace(*example_args), **audit_kwargs)


def summarize(report: Report) -> dict[str, Any]:
    """Compact digest for bench/tracker embedding."""
    return report.summary()
