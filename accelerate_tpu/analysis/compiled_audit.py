"""Compiled-artifact auditor: what the lowered XLA executable ACTUALLY does.

The jaxpr auditor (:mod:`.jaxpr_audit`) predicts hazards from the traced
program; this engine reads XLA's decisions off the compiled executable —
``jax.jit(fn).lower().compile()`` (the AOT idiom of ``utils/other.py``'s
``aot_compile``), then ``compiled.memory_analysis()`` /
``compiled.cost_analysis()``:

- **GL301 donation-not-aliased** — ``donate_argnums`` bytes the executable
  provably did not alias (``alias_size_in_bytes`` < donated bytes).  The
  compiled-level twin of GL101: the trace-level rule predicts viability by
  byte-size matching, this one reads the aliasing table XLA actually
  committed to, so it also catches donations declined for layout or
  sharding reasons no trace-level model sees.
- **GL302 hbm-over-budget** — the program's argument+output+temp footprint
  against the device HBM budget (measured from ``memory_stats()`` when the
  backend reports one, or an explicit ``--hbm-gb``).  An over-budget
  program OOMs at first execution — after the deploy took traffic, unless
  preflight catches it here.
- **GL303 recompile-ladder-drift** — the compiled program set against the
  predicted bucket ladder (a serving deploy is exactly
  ``len(prefill_buckets) + 2`` programs: one prefill per bucket, one
  decode, one release), and the backend-compile events observed while
  building it.  Every extra distinct lowering is a mid-traffic recompile
  waiting to happen.

Plus the **cost report**: per-program flops / bytes-accessed from
``cost_analysis()``, the inputs the predicted-MFU arithmetic feeds on.

The compile-event counter (:class:`CompileCounter`) hooks the
``jax.monitoring`` event stream (``/jax/core/compile/
backend_compile_duration`` — one event per real XLA backend compile, cache
hits excluded) and backs the runtime recompile guard:
``ServingEngine.compile_events`` / ``Accelerator.compile_events`` and the
``compiles_predicted`` / ``compiles_measured`` twins bench.py always emits.

Everything here is CPU-safe: AOT compilation needs a backend but never
executes the program, so a deploy preflight runs on the CI box.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np

from .report import Finding, Report, apply_suppressions
from .rules import RULES

try:  # the monitoring hooks live in the private namespace on 0.4.x
    from jax._src import monitoring as _monitoring
except Exception:  # pragma: no cover - private-API drift
    _monitoring = None


# one event per actual XLA backend compilation (persistent-cache hits and
# jit-call cache hits do NOT fire it) — the signal the recompile guard wants
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


@contextlib.contextmanager
def fresh_compile_context():
    """Force REAL backend compiles (no persistent-cache reads) for the scope.

    An executable DESERIALIZED from the cache loses its buffer-donation
    alias table — ``memory_analysis().alias_size_in_bytes`` reads 0 even
    when the original compile aliased everything — so an audit over a
    cache hit would report GL301 on perfectly good donations.  The auditor
    therefore always compiles fresh: a deploy preflight is a one-shot gate,
    and honest stats beat a warm-cache speedup that poisons them.

    Two levers, both needed: the ``jax_enable_compilation_cache`` flag, and
    ``compilation_cache.reset_cache()`` — jax memoizes the is-cache-used
    decision at the process's FIRST compile, so flipping the flag alone is
    ignored once any earlier compile touched the cache.  The reset drops
    that memo (and the cache's in-memory LRU; the on-disk store is
    untouched) so the flag is actually re-read, here and again on exit.
    """
    try:
        prev = jax.config.jax_enable_compilation_cache
    except AttributeError:  # pragma: no cover - much older jax
        yield
        return
    try:
        from jax._src import compilation_cache as _cc
    except Exception:  # pragma: no cover - private-API drift
        _cc = None

    def _drop_memo():
        if _cc is not None:
            try:
                _cc.reset_cache()
            except Exception:  # pragma: no cover - never initialized
                pass

    try:
        jax.config.update("jax_enable_compilation_cache", False)
        _drop_memo()
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)
        _drop_memo()


class CompileCounter:
    """Counts real XLA backend compiles via the jax monitoring stream.

    Usable as a context manager (``with CompileCounter() as c: ...``) for
    scoped measurement, or long-lived through
    :func:`install_global_compile_counter` for the per-object
    ``compile_events`` deltas the engine and accelerator expose.
    """

    def __init__(self):
        self.count = 0
        self._active = False
        self._registered = False

    def _on_event(self, event, duration=None, **kwargs):
        if self._active and event == COMPILE_EVENT:
            self.count += 1

    def start(self) -> "CompileCounter":
        self._active = True
        if not self._registered and _monitoring is not None:
            _monitoring.register_event_duration_secs_listener(self._on_event)
            self._registered = True
        return self

    def stop(self) -> "CompileCounter":
        self._active = False
        if self._registered and _monitoring is not None:
            try:
                _monitoring._unregister_event_duration_listener_by_callback(
                    self._on_event
                )
                self._registered = False
            except Exception:  # pragma: no cover - private-API drift
                pass  # listener stays registered but inert (_active False)
        return self

    def __enter__(self) -> "CompileCounter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


_GLOBAL_COUNTER: Optional[CompileCounter] = None


def install_global_compile_counter() -> CompileCounter:
    """Install (idempotently) the process-wide compile-event counter and
    return it.  Callers snapshot ``.count`` and report deltas — the counter
    itself is never uninstalled, so overlapping consumers (an engine and an
    accelerator in one process) each get a consistent monotonic stream."""
    global _GLOBAL_COUNTER
    if _GLOBAL_COUNTER is None:
        _GLOBAL_COUNTER = CompileCounter().start()
    return _GLOBAL_COUNTER


def device_hbm_bytes(hbm_gb: Optional[float] = None) -> Optional[int]:
    """The HBM budget for GL302: an explicit ``hbm_gb`` wins; otherwise the
    backend's reported ``bytes_limit`` (TPU/GPU — CPU reports none).  None
    means "no budget known": GL302 is skipped rather than guessed."""
    if hbm_gb is not None:
        return int(hbm_gb * 2**30)
    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:  # pragma: no cover - backend without memory_stats
        return None
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    return None


# ---------------------------------------------------------------------------
# per-program compile + audit
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CompiledProgram:
    """One AOT-compiled production program plus its audit inputs."""

    label: str
    compiled: Any                       # jax.stages.Compiled
    traced: Any = None                  # jax.stages.Traced (jaxpr-audit input)
    compile_s: float = 0.0
    compile_events: int = 0             # real backend compiles this one cost
    path_hint: Optional[tuple] = None


def aot_compile_program(
    fn: Callable,
    *example_args,
    donate_argnums=(),
    static_argnums=(),
    label: str = "program",
    path_hint: Optional[tuple] = None,
) -> CompiledProgram:
    """Trace, lower and compile ``fn`` ahead of time (accepts concrete
    arrays or ``jax.ShapeDtypeStruct`` stand-ins — nothing executes), timing
    the wall and counting the real backend-compile events (a persistent-
    cache hit costs 0)."""
    jitted = fn if hasattr(fn, "trace") else jax.jit(
        fn, donate_argnums=donate_argnums, static_argnums=static_argnums
    )
    counter = CompileCounter()
    t0 = time.perf_counter()
    with counter, fresh_compile_context():
        traced = jitted.trace(*example_args)
        compiled = traced.lower().compile()
    return CompiledProgram(
        label=label, compiled=compiled, traced=traced,
        compile_s=time.perf_counter() - t0, compile_events=counter.count,
        path_hint=path_hint,
    )


def _finding(rule_id: str, message: str, path_hint=None) -> Finding:
    r = RULES[rule_id]
    return Finding(
        rule=rule_id, severity=r.severity, message=message, fix_hint=r.fix_hint,
        path=path_hint[0] if path_hint else None,
        line=path_hint[1] if path_hint else None,
        engine="compiled",
    )


def _donated_bytes(compiled) -> int:
    """Total bytes the caller donated, read off the compiled signature."""
    leaves = jax.tree_util.tree_leaves(
        compiled.args_info, is_leaf=lambda x: hasattr(x, "donated")
    )
    total = 0
    for leaf in leaves:
        if not getattr(leaf, "donated", False):
            continue
        shape = getattr(leaf, "shape", ())
        n = int(np.prod(shape)) if shape else 1
        try:
            total += n * np.dtype(leaf.dtype).itemsize
        except TypeError:
            total += n * int(getattr(leaf.dtype, "itemsize", 8) or 8)
    return total


def _cost_dict(compiled) -> dict:
    try:
        cost = compiled.cost_analysis()
    except Exception:  # pragma: no cover - backend without cost analysis
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


def audit_compiled(
    compiled,
    *,
    label: str = "program",
    hbm_budget_bytes: Optional[int] = None,
    donation_slack_bytes: int = 1024,
    path_hint: Optional[tuple] = None,
) -> tuple[list[Finding], dict]:
    """Audit one compiled executable; returns ``(findings, report_row)``.

    ``donation_slack_bytes`` tolerates tiny non-aliased donated members
    (scalar step counters and the like XLA reasonably declines) before
    GL301 fires; ``hbm_budget_bytes=None`` skips GL302 rather than guess.
    """
    findings: list[Finding] = []
    mem = None
    try:
        mem = compiled.memory_analysis()
    except Exception:  # pragma: no cover - backend without memory analysis
        pass

    donated = _donated_bytes(compiled)
    # None (attribute absent on this jaxlib) means "unknown", not "nothing
    # aliased" — GL301 is then skipped, not guessed, like GL302 without a
    # budget; the footprint math conservatively counts outputs in full
    alias_known = mem is not None and hasattr(mem, "alias_size_in_bytes")
    aliased = int(mem.alias_size_in_bytes or 0) if alias_known else 0
    row: dict = {"program": label, "compile_events": None}
    if mem is not None:
        args_b = int(mem.argument_size_in_bytes)
        out_b = int(mem.output_size_in_bytes)
        temp_b = int(mem.temp_size_in_bytes)
        # aliased output bytes live in the donated argument buffers — they
        # must not be double-counted in the resident footprint
        total = args_b + max(out_b - aliased, 0) + temp_b
        row["hbm"] = {
            "arguments": args_b, "outputs": out_b, "temps": temp_b,
            "aliased": aliased, "total": total,
            "total_gib": round(total / 2**30, 6),
        }
        if alias_known and donated - aliased > max(donation_slack_bytes, 0):
            findings.append(
                _finding(
                    "GL301",
                    f"{label}: {donated - aliased} of {donated} donated "
                    "bytes were NOT aliased by the compiled executable "
                    f"(aliased {aliased} B) — the donation frees nothing "
                    "and the caller still loses the buffer",
                    path_hint,
                )
            )
        if hbm_budget_bytes is not None and total > hbm_budget_bytes:
            findings.append(
                _finding(
                    "GL302",
                    f"{label}: compiled footprint {total / 2**30:.3f} GiB "
                    f"(args {args_b} + outputs {max(out_b - aliased, 0)} + "
                    f"temps {temp_b} B) exceeds the HBM budget "
                    f"{hbm_budget_bytes / 2**30:.3f} GiB",
                    path_hint,
                )
            )
    row["donated_bytes"] = donated
    row["aliased_bytes"] = aliased
    cost = _cost_dict(compiled)
    row["flops"] = float(cost.get("flops", 0.0))
    row["bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
    return findings, row


def audit_program_set(
    rows: Sequence[dict],
    predicted_count: int,
    *,
    measured_compile_events: Optional[int] = None,
    path_hint: Optional[tuple] = None,
) -> list[Finding]:
    """GL303: the compiled program set against the predicted ladder.

    ``rows`` are the per-program report rows actually compiled;
    ``predicted_count`` is what the bucket ladder implies (serving:
    ``len(prefill_buckets) + 2``).  ``measured_compile_events`` (when the
    caller counted them) may legitimately be LOWER than the program count —
    persistent-cache hits — but higher means some program lowered more than
    once: a recompile waiting to happen."""
    findings = []
    if len(rows) != predicted_count:
        findings.append(
            _finding(
                "GL303",
                f"compiled {len(rows)} distinct programs where the bucket "
                f"ladder predicts exactly {predicted_count} "
                f"({', '.join(r['program'] for r in rows)})",
                path_hint,
            )
        )
    if measured_compile_events is not None and measured_compile_events > len(rows):
        findings.append(
            _finding(
                "GL303",
                f"{measured_compile_events} backend compile events for "
                f"{len(rows)} programs: some program lowered more than "
                "once during preflight — a mid-traffic recompile shape",
                path_hint,
            )
        )
    return findings


def audit_aot(
    fn: Callable,
    *example_args,
    donate_argnums=(),
    label: str = "program",
    hbm_budget_bytes: Optional[int] = None,
    donation_slack_bytes: int = 1024,
    path_hint: Optional[tuple] = None,
) -> tuple[Report, dict]:
    """One-shot convenience: AOT-compile ``fn`` and audit the executable
    (GL301/GL302 + the cost row).  Returns ``(Report, report_row)`` — the
    jaxpr-level audit of the same program is :func:`.jaxpr_audit.audit_fn`;
    a full deploy preflight composes both (``commands/preflight.py``)."""
    if path_hint is None:
        code = getattr(fn, "__code__", None)
        if code is not None:
            path_hint = (code.co_filename, code.co_firstlineno)
    prog = aot_compile_program(
        fn, *example_args, donate_argnums=donate_argnums, label=label,
        path_hint=path_hint,
    )
    findings, row = audit_compiled(
        prog.compiled, label=label, hbm_budget_bytes=hbm_budget_bytes,
        donation_slack_bytes=donation_slack_bytes, path_hint=path_hint,
    )
    row["compile_s"] = round(prog.compile_s, 4)
    row["compile_events"] = prog.compile_events
    return Report(apply_suppressions(findings)), row
