"""Finding/Report model shared by both graft-lint engines.

Both the jaxpr auditor (:mod:`.jaxpr_audit`) and the AST rule engine
(:mod:`.ast_rules`) reduce to the same output contract: a flat list of
:class:`Finding` records — rule id, severity, source location, message, fix
hint — collected into a :class:`Report` that renders for humans, serializes
to JSON for CI, and decides the process exit code.

Suppression is **source-anchored** for both engines: a finding whose
location carries a file path is suppressed by an inline marker

    # graft-lint: disable=GL103 -- moving host-resident members is the point

on the flagged line or the line directly above it.  The rationale after
``--`` is mandatory — a bare ``disable`` without one is itself reported
(GL001), so every suppression in the tree documents *why* the hazard is
intentional.  Jaxpr findings resolve their file/line from the equation's
``source_info``, so the same marker silences the same hazard whether it was
found syntactically or from the traced program.
"""

from __future__ import annotations

import dataclasses
import enum
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Iterable, Optional


class Severity(enum.IntEnum):
    """Ordered so findings filter with a plain ``>=`` comparison."""

    INFO = 10
    WARNING = 20
    ERROR = 30

    @classmethod
    def parse(cls, name) -> "Severity":
        if isinstance(name, Severity):
            return name
        return cls[str(name).upper()]


@dataclasses.dataclass
class Finding:
    """One diagnostic from either engine.

    ``path``/``line`` locate the hazard (``path`` may be ``None`` for
    jaxpr findings whose equation has no user frame, e.g. synthetic
    programs built in a REPL); ``engine`` is ``"jaxpr"`` or ``"ast"``;
    ``suppressed``/``suppress_reason`` are filled in by
    :func:`apply_suppressions`.
    """

    rule: str
    severity: Severity
    message: str
    fix_hint: str = ""
    path: Optional[str] = None
    line: Optional[int] = None
    engine: str = "ast"
    suppressed: bool = False
    suppress_reason: Optional[str] = None

    @property
    def location(self) -> str:
        if self.path is None:
            return "<no source location>"
        return f"{self.path}:{self.line}" if self.line else self.path

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["severity"] = self.severity.name
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        """Inverse of :meth:`to_dict` — ``from_dict(f.to_dict()) == f``,
        so a JSON report round-trips losslessly (the CI contract)."""
        d = dict(d)
        d["severity"] = Severity.parse(d["severity"])
        return cls(**d)


# ``# graft-lint: disable=GL101 -- why this is fine`` (one or more comma-
# separated rule ids; the rationale after ``--`` is what keeps suppressions
# honest).  Matches anywhere in the line so it can trail code.
_MARKER = re.compile(
    r"#\s*graft-lint:\s*disable=(?P<rules>GL\d+(?:\s*,\s*GL\d+)*)"
    r"(?:\s*--\s*(?P<reason>\S.*?))?\s*$"
)


def parse_marker(line: str):
    """``(rule_ids, rationale)`` of the suppression marker on ``line``, or
    ``None``.  ``rationale`` is ``None`` when the marker omits it (a GL001
    finding at the call-site of :func:`apply_suppressions`)."""
    m = _MARKER.search(line)
    if m is None:
        return None
    rules = tuple(r.strip() for r in m.group("rules").split(","))
    return rules, m.group("reason")


def _markers_for_file(path: str, _cache: dict) -> dict:
    """line number -> (rule ids, rationale) for every marker in ``path``."""
    if path in _cache:
        return _cache[path]
    markers: dict = {}
    try:
        lines = Path(path).read_text().splitlines()
    except OSError:
        _cache[path] = markers
        return markers
    for lineno, text in enumerate(lines, start=1):
        parsed = parse_marker(text)
        if parsed is not None:
            markers[lineno] = parsed
    _cache[path] = markers
    return markers


def _stmt_starts_for_file(path: str, _cache: dict) -> dict:
    """line number -> first line of the logical statement it belongs to,
    for every line of a multi-line statement in ``path``.

    Jaxpr findings anchor at the equation's ``source_info`` line, which for
    a statement wrapped across several lines can be a CONTINUATION line —
    while the author's suppression marker naturally sits on (or above) the
    statement's FIRST line.  This map lets :func:`apply_suppressions`
    normalize the finding back to the statement start so the marker is
    honored.  Tokenize-based: ``NEWLINE`` tokens terminate logical lines,
    ``NL`` tokens (blank/continuation breaks) do not."""
    if path in _cache:
        return _cache[path]
    mapping: dict = {}
    try:
        text = Path(path).read_text()
    except OSError:
        _cache[path] = mapping
        return mapping
    skip = (tokenize.NL, tokenize.COMMENT, tokenize.INDENT, tokenize.DEDENT,
            tokenize.ENDMARKER)
    try:
        start = None
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type in skip:
                continue
            if tok.type == tokenize.NEWLINE:
                start = None
                continue
            if start is None:
                start = tok.start[0]
            for lineno in range(tok.start[0], tok.end[0] + 1):
                mapping.setdefault(lineno, start)
    except (tokenize.TokenError, SyntaxError, IndentationError):
        pass  # an unparseable file falls back to exact-line matching
    _cache[path] = mapping
    return mapping


def apply_suppressions(findings: Iterable[Finding]) -> list[Finding]:
    """Resolve inline markers: mark matching findings suppressed, and emit a
    GL001 finding for every marker that omits its rationale.  A marker
    suppresses findings on its own line and the line below (so it can sit
    above a long expression).  A finding anchored on a CONTINUATION line of
    a multi-line statement is normalized to the statement's first line, so
    a marker there (or directly above) still suppresses it."""
    findings = list(findings)
    cache: dict = {}
    stmt_cache: dict = {}
    bare_marker_sites: set = set()
    for f in findings:
        if f.path is None or f.line is None:
            continue
        markers = _markers_for_file(f.path, cache)
        candidates = [f.line, f.line - 1]
        stmt_start = _stmt_starts_for_file(f.path, stmt_cache).get(f.line)
        if stmt_start is not None and stmt_start != f.line:
            candidates += [stmt_start, stmt_start - 1]
        for lineno in candidates:
            entry = markers.get(lineno)
            if entry is None:
                continue
            rules, reason = entry
            if f.rule in rules:
                f.suppressed = True
                f.suppress_reason = reason
                if reason is None:
                    bare_marker_sites.add((f.path, lineno))
                break
    out = findings
    already = {(f.path, f.line) for f in findings if f.rule == "GL001"}
    for path, lineno in sorted(bare_marker_sites - already):
        out.append(
            Finding(
                rule="GL001",
                severity=Severity.WARNING,
                message="suppression marker without a rationale "
                        "(add `-- <why this hazard is intentional>`)",
                fix_hint="graft-lint: disable=GLxxx -- <reason>",
                path=path,
                line=lineno,
                engine="ast",
            )
        )
    return out


class Report:
    """Ordered collection of findings with the CI-facing reductions."""

    def __init__(self, findings: Iterable[Finding] = ()):
        self.findings: list[Finding] = list(findings)

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, findings: Iterable[Finding]) -> None:
        self.findings.extend(findings)

    def unsuppressed(self, min_severity: Severity = Severity.INFO) -> list[Finding]:
        min_severity = Severity.parse(min_severity)
        return [
            f for f in self.findings
            if not f.suppressed and f.severity >= min_severity
        ]

    def counts(self) -> dict:
        c = {"error": 0, "warning": 0, "info": 0, "suppressed": 0}
        for f in self.findings:
            if f.suppressed:
                c["suppressed"] += 1
            else:
                c[f.severity.name.lower()] += 1
        return c

    def summary(self) -> dict:
        """Compact JSON-able digest (what bench.py / trackers embed)."""
        return {
            **self.counts(),
            "rules": sorted({f.rule for f in self.findings if not f.suppressed}),
            "ok": not self.unsuppressed(Severity.ERROR),
        }

    def exit_code(self, fail_on: Severity = Severity.ERROR) -> int:
        return 1 if self.unsuppressed(Severity.parse(fail_on)) else 0

    def render(self, *, show_suppressed: bool = False) -> str:
        lines = []
        for f in sorted(
            self.findings,
            key=lambda f: (-int(f.severity), f.path or "~", f.line or 0),
        ):
            if f.suppressed and not show_suppressed:
                continue
            tag = f"suppressed:{f.severity.name}" if f.suppressed else f.severity.name
            lines.append(f"{f.location}: {tag} {f.rule} [{f.engine}] {f.message}")
            if f.fix_hint and not f.suppressed:
                lines.append(f"    hint: {f.fix_hint}")
            if f.suppressed and f.suppress_reason:
                lines.append(f"    rationale: {f.suppress_reason}")
        c = self.counts()
        lines.append(
            f"graft-lint: {c['error']} error(s), {c['warning']} warning(s), "
            f"{c['info']} info, {c['suppressed']} suppressed"
        )
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps(
            {"findings": [f.to_dict() for f in self.findings], "summary": self.summary()},
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "Report":
        """Inverse of :meth:`to_json`: a serialized report reloads into an
        equal Report — same findings, same summary, identical re-render
        (the ``make lint`` / preflight-CLI round-trip check)."""
        payload = json.loads(text)
        return cls(Finding.from_dict(d) for d in payload.get("findings", ()))
