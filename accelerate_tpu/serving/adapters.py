"""Multi-tenant adapter management: device pool, hot-swap streaming, LRU.

The serving side of ROADMAP item 2 (batched LoRA): thousands of tenants'
adapters cannot all live in HBM, so the :class:`AdapterStore` keeps a
**fixed-size device pool** of stacked A/B factors (``ops/lora.py``
geometry — slot 0 is the reserved null adapter) and hot-swaps cold
adapters in from host/:class:`~accelerate_tpu.big_modeling.OffloadStore`
memmaps on demand:

- **cold tier**: each published adapter lives as '/'-keyed arrays in an
  ``OffloadStore`` (disk memmaps — the PR 2 streaming tier) or a host
  dict; publishing costs no HBM.
- **hot-swap streaming**: uploads go through the existing
  :class:`~accelerate_tpu.ops.streaming.LayerPrefetcher` double buffer
  (``depth=0`` + explicit :meth:`prefetch`): the scheduler prefetches the
  waiting queue's adapters so the H2D copy flies under the current decode
  step, and the bounded-retry/fault hooks ride along like every other
  host transfer.
- **pool discipline**: a free-list + one donated jitted scatter
  (``pool.at[slot].set``) mirrors ``serving/paged_cache.py`` — the pool
  buffers alias in place, so the decode step stays donation-clean and
  ``ServingEngine.audit_decode_step()`` stays green.
- **pinning**: every in-flight request holding adapter *t* keeps a
  refcount on its slot; LRU eviction only considers refcount-0 slots, so
  evicting a *request* can never evict a **shared hot adapter** another
  tenant's requests are decoding with.

The fine-tuning side (:class:`LoraTrainer`) batches mixed-tenant
gradients through the same gathered einsum and keeps **per-adapter
optimizer state on host** under the ``make_optimizer`` recipes — with the
int8-SR ladder (``lion-sr8``/``adamw-sr8``) an adapter's state is a few
hundred KiB, so host DRAM holds out to huge tenant counts
(:func:`~accelerate_tpu.ops.lora.adapter_state_accounting`).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.lora import (
    DEFAULT_LORA_TARGETS,
    _nest,
    adapter_param_count,
    init_adapter_params,
    init_lora_pool,
    lora_spec,
)
from ..ops.streaming import LayerPrefetcher, StreamStats, predicted_overlap
from ..resilience.faults import maybe_fail_transfer
from ..resilience.retry import DEFAULT_POLICY, with_retries
from ..utils.dataclasses import LoraPlugin


def _flatten(tree, prefix=()) -> dict[str, Any]:
    """Inverse of :func:`~accelerate_tpu.ops.lora._nest`: '/'-keyed leaves
    (the OffloadStore / npz key schema)."""
    out = {}
    for k in sorted(tree):
        v = tree[k]
        if isinstance(v, dict):
            out.update(_flatten(v, prefix + (k,)))
        else:
            out["/".join(prefix + (k,))] = v
    return out


class AdapterPoolFullError(RuntimeError):
    """Every pool slot is pinned by an in-flight request — the scheduler
    must wait for a retire/evict before this tenant's adapter can swap in
    (admission checks :meth:`AdapterStore.can_pin` first, so seeing this
    raised means a scheduling bug, not an operational condition)."""


class AdapterStore:
    """Fixed-size device adapter pool with LRU hot-swap over a cold tier.

    >>> store = AdapterStore(params, LoraPlugin(pool_slots=4))
    >>> store.publish(7, adapter_tree)        # cold tier, no HBM
    >>> slot = store.pin(7)                   # resident + refcounted
    >>> ...                                   # decode with ids[row] = slot
    >>> store.unpin(7)                        # eligible for LRU eviction

    ``pool`` is the ``lora`` variable-collection tree the model consumes
    (``model.apply({**params, "lora": store.pool}, ..., adapter_ids=ids)``);
    inserts rebind it through one donated jitted scatter, so the engine
    always reads the current binding.
    """

    def __init__(self, params, plugin: Optional[LoraPlugin] = None, *,
                 dtype=jnp.bfloat16, offload_dir: Optional[str] = None):
        self.plugin = plugin or LoraPlugin()
        p = self.plugin
        self.spec = lora_spec(params, p.targets or DEFAULT_LORA_TARGETS)
        self.dtype = dtype
        self.pool = init_lora_pool(self.spec, p.pool_slots, p.rank, dtype)
        self._insert = jax.jit(
            lambda pool, staged, slot: jax.tree_util.tree_map(
                lambda pl_, st: pl_.at[slot].set(st.astype(pl_.dtype)), pool, staged
            ),
            donate_argnums=(0,),
        )
        # cold tier: OffloadStore memmaps when a directory is given (the
        # production tier), else host arrays (tests / small tenant counts)
        self._offload = None
        if offload_dir is not None:
            from ..big_modeling import OffloadStore

            self._offload = OffloadStore(offload_dir, autoflush=False)
        self._host: dict[int, dict] = {}
        self._tids: list[int] = []           # registration order (prefetch index)
        self._idx_of: dict[int, int] = {}
        self.slot_of: dict[int, int] = {}    # resident tenant -> pool slot
        self.tid_of: dict[int, int] = {}     # pool slot -> tenant
        self.free_slots: list[int] = list(range(1, p.pool_slots + 1))
        self.refcount: dict[int, int] = {}
        self._last_use: dict[int, int] = {}
        self._use_seq = 0
        self.stats = StreamStats()
        self.hits = 0
        self.swaps = 0
        self._prefetcher: Optional[LayerPrefetcher] = None

    # -- cold tier ----------------------------------------------------------

    def publish(self, tid: int, tree: dict) -> None:
        """Register tenant ``tid``'s adapter tree (``{path: {"a", "b"}}`` in
        the store's :attr:`spec` schema) in the cold tier."""
        if tid < 1:
            raise ValueError(f"adapter id must be >= 1 (0 is the null adapter), got {tid}")
        flat = _flatten(tree)
        want = {f"{path}/{f}" for path in self.spec for f in ("a", "b")}
        if set(flat) != want:
            raise ValueError(
                f"adapter {tid} tree does not match the store spec: "
                f"missing {sorted(want - set(flat))[:3]}, "
                f"extra {sorted(set(flat) - want)[:3]}"
            )
        if self._offload is not None:
            for key, leaf in flat.items():
                self._offload.save(f"adapter_{tid}/{key}", np.asarray(leaf))
            self._offload.flush()
        else:
            self._host[tid] = {k: np.asarray(v) for k, v in flat.items()}
        if tid not in self._idx_of:
            self._idx_of[tid] = len(self._tids)
            self._tids.append(tid)
            self._prefetcher = None  # registry grew: rebuild lazily
        else:
            # RE-publish of a known tenant (continuous fine-tuning →
            # redeploy): a staged prefetch of the old weights must never be
            # served, and a resident slot refreshes in place immediately —
            # in-flight requests pin the SLOT, and the tenant's new weights
            # are what that slot must now hold
            if self._prefetcher is not None:
                self._prefetcher.invalidate(self._idx_of[tid])
            if tid in self.slot_of:
                staged = self._ensure_prefetcher().get(self._idx_of[tid])
                self.pool = self._insert(
                    self.pool, staged, jnp.asarray(self.slot_of[tid], jnp.int32)
                )

    def publish_random(self, tid: int, rng, *, init_b: str = "normal") -> dict:
        """Convenience for benches/tests: publish a seeded random adapter."""
        tree = init_adapter_params(
            rng, self.spec, self.plugin.rank, alpha=self.plugin.alpha,
            dtype=self.dtype, init_b=init_b,
        )
        self.publish(tid, tree)
        return tree

    def known(self, tid: int) -> bool:
        return tid in self._idx_of

    def _host_tree(self, tid: int) -> dict[str, np.ndarray]:
        if self._offload is not None:
            # cold-tier memmap reads fail transiently in exactly the ways
            # checkpoint I/O does (NFS hiccup, stale handle across a
            # preemption) — the bounded retry/backoff budget applies, and
            # the injected-fault hook (site "adapter_memmap") fires inside
            # each attempt so the CPU suite exercises the real backoff path
            def attempt():
                maybe_fail_transfer("adapter_memmap")
                return {
                    f"{path}/{f}": self._offload.load(f"adapter_{tid}/{path}/{f}")
                    for path in self.spec for f in ("a", "b")
                }

            return with_retries(
                attempt, policy=DEFAULT_POLICY,
                site=f"adapter_memmap[{tid}]", on_retry=self._on_retry,
            )
        return self._host[tid]

    def _on_retry(self, site, attempt, exc) -> None:
        self.stats.transfer_retries += 1

    # -- hot-swap streaming -------------------------------------------------

    def _ensure_prefetcher(self) -> LayerPrefetcher:
        if self._prefetcher is None or self._prefetcher.n_layers != len(self._tids):
            def fetch(idx):
                # the serving-specific fault site: an adapter-swap transfer
                # failing mid-prefetch raises HERE, inside the prefetcher's
                # bounded-retry wrapper — a transient blip costs one backoff
                # (counted into StreamStats.transfer_retries, surfaced in
                # the replay report), not the whole replay
                maybe_fail_transfer("adapter_transfer")
                return jax.device_put(_nest(self._host_tree(self._tids[idx])))

            self._prefetcher = LayerPrefetcher(
                fetch, max(1, len(self._tids)), depth=0, stats=self.stats,
            )
        return self._prefetcher

    def warmup_insert(self) -> None:
        """Compile the pool-insert program before traffic: one zeros
        insert into the null slot (zeros over zeros — the slot-0 invariant
        holds).  Without this the FIRST hot-swap would compile mid-traffic
        and trip the engine's ``strict_compiles`` recompile guard — the
        exact class of stall the warmup contract exists to remove."""
        staged = jax.tree_util.tree_map(lambda l: jnp.zeros(l.shape[1:], l.dtype),
                                        self.pool)
        self.pool = self._insert(self.pool, staged, jnp.asarray(0, jnp.int32))

    def prefetch(self, tid: int) -> bool:
        """Dispatch tenant ``tid``'s H2D staging now (non-blocking) so a
        later :meth:`pin` finds the transfer already in flight — the
        scheduler calls this for the waiting queue while the current step's
        matmuls run.  No pool slot is taken yet."""
        if tid == 0 or tid in self.slot_of or not self.known(tid):
            return False
        return self._ensure_prefetcher().prefetch(self._idx_of[tid])

    # -- pinning / LRU ------------------------------------------------------

    def resident(self, tid: int) -> bool:
        return tid == 0 or tid in self.slot_of

    def _evictable(self) -> Optional[int]:
        """The LRU resident tenant no in-flight request holds (deterministic:
        oldest last-use, tid breaks ties)."""
        candidates = [
            t for t in self.slot_of if self.refcount.get(t, 0) == 0
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda t: (self._last_use.get(t, 0), t))

    def can_pin(self, tid: int) -> bool:
        """Could :meth:`pin` succeed right now (resident, or a free /
        LRU-evictable slot exists)?  The admission gate — checked before a
        request is scheduled so admission never half-commits."""
        if tid == 0 or tid in self.slot_of:
            return True
        return self.known(tid) and bool(self.free_slots or self._evictable() is not None)

    def pin(self, tid: int) -> tuple[int, bool]:
        """Make tenant ``tid``'s adapter resident and hold it (refcount).

        Returns ``(pool_slot, swapped)`` — ``swapped`` is True when a cold
        adapter was streamed in (the measured pool-miss).  Id 0 pins
        nothing and always maps to the null slot."""
        if tid == 0:
            return 0, False
        self._use_seq += 1
        self._last_use[tid] = self._use_seq
        if tid in self.slot_of:
            self.refcount[tid] = self.refcount.get(tid, 0) + 1
            self.hits += 1
            return self.slot_of[tid], False
        if not self.known(tid):
            raise KeyError(f"adapter {tid} was never published")
        if self.free_slots:
            slot = self.free_slots.pop(0)
        else:
            victim = self._evictable()
            if victim is None:
                raise AdapterPoolFullError(
                    f"adapter {tid}: all {self.plugin.pool_slots} pool slots "
                    "are pinned by in-flight requests"
                )
            slot = self.slot_of.pop(victim)
            del self.tid_of[slot]
        staged = self._ensure_prefetcher().get(self._idx_of[tid])
        self.pool = self._insert(self.pool, staged, jnp.asarray(slot, jnp.int32))
        self.slot_of[tid] = slot
        self.tid_of[slot] = tid
        self.refcount[tid] = self.refcount.get(tid, 0) + 1
        self.swaps += 1
        return slot, True

    def unpin(self, tid: int) -> None:
        """Release one in-flight hold on ``tid`` (retire/evict of a request
        — the adapter STAYS hot until LRU pressure claims its slot)."""
        if tid == 0:
            return
        n = self.refcount.get(tid, 0)
        if n <= 1:
            self.refcount.pop(tid, None)
        else:
            self.refcount[tid] = n - 1

    def slot(self, tid: int) -> int:
        return 0 if tid == 0 else self.slot_of[tid]

    # -- accounting ---------------------------------------------------------

    @property
    def swap_bytes(self) -> int:
        """H2D bytes streamed by hot-swaps (the prefetcher's exact leaf
        accounting)."""
        return int(self.stats.h2d_bytes)

    def hit_rate(self) -> float:
        total = self.hits + self.swaps
        return round(self.hits / total, 4) if total else 0.0

    def pool_report(self) -> dict:
        return {
            "pool_slots": self.plugin.pool_slots,
            "resident": len(self.slot_of),
            "hits": self.hits,
            "swaps": self.swaps,
            "hit_rate": self.hit_rate(),
            "swap_bytes": self.swap_bytes,
        }


def predicted_adapter_hit_rate(adapter_ids, pool_slots: int) -> float:
    """CheckFreq-style *predicted* twin of the measured pool hit rate: a
    model-free LRU replay over the trace's adapter ids in arrival order
    (one pin per request, no refcount pinning — the prediction error vs
    the measured twin is exactly the in-flight-pin and eviction-reorder
    traffic the arrival sequence cannot know about)."""
    resident: dict[int, int] = {}
    seq = hits = misses = 0
    for tid in adapter_ids:
        tid = int(tid)
        if tid == 0:
            continue
        seq += 1
        if tid in resident:
            hits += 1
        else:
            misses += 1
            if len(resident) >= pool_slots:
                victim = min(resident, key=lambda t: (resident[t], t))
                del resident[victim]
        resident[tid] = seq
    total = hits + misses
    return round(hits / total, 4) if total else 0.0


def adapter_pool_accounting(spec: dict, *, rank: int, pool_slots: int,
                            dtype_bytes: int = 2, pcie_rate_gibs: float = 8.0,
                            decode_step_s: Optional[float] = None) -> dict:
    """Predicted device-pool ladder + swap-bandwidth envelope (the
    multi-tenant row of docs/serving.md's sizing tables; measured twins:
    :meth:`AdapterStore.pool_report` + ``bench --serve --adapters``).

    ``bytes_per_slot`` is one adapter's stacked A+B footprint; the swap
    envelope uses the PR 2 transfer accounting — a swap is hidden when its
    PCIe time fits under the decode step it rides beneath
    (:func:`~accelerate_tpu.ops.streaming.predicted_overlap`)."""
    n_params = adapter_param_count(spec, rank)
    per_slot = n_params * dtype_bytes
    total = per_slot * (pool_slots + 1)  # + the null slot
    swap_s = per_slot / (pcie_rate_gibs * 2**30)
    gib = lambda b: round(b / 2**30, 6)
    out = {
        "rank": rank,
        "pool_slots": pool_slots,
        "params_per_adapter": n_params,
        "bytes_per_slot": per_slot,
        "pool_bytes": total,
        "pool_gib": gib(total),
        "hbm_frac": {
            "v5e_16GiB": round(total / (16 * 2**30), 8),
            "v5p_95GiB": round(total / (95 * 2**30), 8),
            "v6e_32GiB": round(total / (32 * 2**30), 8),
        },
        "swap_s_pred": round(swap_s, 9),
        "kind": "predicted",
    }
    if decode_step_s is not None:
        out["swap_overlap_frac_pred"] = round(
            predicted_overlap(swap_s, decode_step_s), 4
        )
    return out


# ---------------------------------------------------------------------------
# Fine-tuning: batched multi-adapter step, per-adapter host state
# ---------------------------------------------------------------------------


class LoraTrainer:
    """Fine-tune many tenants' adapters against one frozen base model.

    Each step takes a mixed-tenant batch (per-row ``adapter_ids`` are
    TENANT ids) and runs ONE batched forward/backward through the gathered
    einsum — gradients land in the stacked factors, get sliced per tenant,
    and update each tenant's optimizer state under a
    :func:`~accelerate_tpu.optimizer.make_optimizer` recipe.  State lives
    **host-side** per adapter (``np`` trees between steps): with the
    int8-SR recipes the whole per-tenant footprint is
    ``adapter_state_accounting``-tiny, so tenant count scales with host
    DRAM, not HBM.

    The training stack is fixed at ``plugin.pool_slots + 1`` rows (like
    the serving pool), so the jitted step never re-specializes on how many
    tenants a batch mixes — the GL305 discipline applied to training.
    """

    def __init__(self, model, base_params, plugin: Optional[LoraPlugin] = None,
                 *, learning_rate: Optional[float] = None, seed: int = 0):
        from ..optimizer import make_optimizer

        self.model = model
        self.base_params = base_params
        self.plugin = plugin or LoraPlugin()
        p = self.plugin
        self.spec = lora_spec(base_params, p.targets or DEFAULT_LORA_TARGETS)
        dtype = getattr(model.config, "dtype", jnp.bfloat16)
        self.dtype = dtype
        self.tx = make_optimizer(p.optimizer, learning_rate, seed=seed)
        self.adapters: dict[int, dict] = {}      # tid -> host adapter tree
        self.opt_states: dict[int, Any] = {}     # tid -> host optax state
        # one UNstacked zero adapter — the null row every training stack
        # leads with, and the zeros template batch padding copies
        self._null = _nest({
            path: {"a": jnp.zeros((d_in, p.rank), dtype),
                   "b": jnp.zeros((p.rank, d_out), dtype)}
            for path, (d_in, d_out) in self.spec.items()
        })
        self._grad_step = jax.jit(jax.value_and_grad(self._loss, argnums=1))
        self._update = jax.jit(self._apply_update)

    def _loss(self, base_params, pool, batch, slot_ids):
        from ..models.llama import causal_lm_loss

        logits = self.model.apply(
            {**base_params, "lora": pool}, batch["input_ids"],
            adapter_ids=slot_ids,
        )
        return causal_lm_loss(logits, batch["labels"])

    def _apply_update(self, grads, opt_state, params):
        import optax

        updates, new_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    def add_adapter(self, tid: int, rng=None) -> dict:
        if tid < 1:
            raise ValueError(f"adapter id must be >= 1, got {tid}")
        rng = rng if rng is not None else jax.random.PRNGKey(tid)
        tree = init_adapter_params(
            rng, self.spec, self.plugin.rank, alpha=self.plugin.alpha,
            dtype=self.dtype,
        )
        self.adapters[tid] = tree
        self.opt_states[tid] = self.tx.init(tree)
        return tree

    def _stack(self, tids: list[int]) -> dict:
        """Stacked training pool: slot 0 null, slot i+1 = ``tids[i]``,
        padded with zero rows to the fixed ``pool_slots + 1`` width — the
        jitted step never re-specializes on how many tenants a batch mixes."""
        p = self.plugin
        if len(tids) > p.pool_slots:
            raise ValueError(
                f"batch mixes {len(tids)} tenants > pool_slots={p.pool_slots}"
            )

        def build(null_leaf, *adapter_leaves):
            pad = [null_leaf] * (p.pool_slots - len(adapter_leaves))
            rows = [jnp.asarray(l, null_leaf.dtype) for l in adapter_leaves]
            return jnp.stack([null_leaf, *rows, *pad])

        return jax.tree_util.tree_map(
            build, self._null, *[self.adapters[t] for t in tids]
        )

    def step(self, batch, adapter_ids) -> float:
        """One batched multi-adapter step.  ``adapter_ids``: per-row TENANT
        ids (0 = base rows contribute loss but no adapter gradient).
        Returns the mixed-batch loss."""
        ids = [int(t) for t in np.asarray(adapter_ids)]
        tids = sorted({t for t in ids if t != 0})
        for t in tids:
            if t not in self.adapters:
                raise KeyError(f"adapter {t} not added")
        slot_of = {t: i + 1 for i, t in enumerate(tids)}
        slot_ids = jnp.asarray([slot_of.get(t, 0) for t in ids], jnp.int32)
        pool = self._stack(tids)
        loss, grads = self._grad_step(self.base_params, pool, batch, slot_ids)
        for t in tids:
            g = jax.tree_util.tree_map(lambda x, t=t: x[slot_of[t]].astype(jnp.float32),
                                       grads)
            new_params, new_state = self._update(
                g, self.opt_states[t], self.adapters[t]
            )
            # host residency between steps: per-adapter state parks in DRAM
            self.adapters[t] = jax.tree_util.tree_map(
                lambda x: np.asarray(x), new_params
            )
            self.opt_states[t] = jax.device_get(new_state)
        return float(loss)

    def sequential_loss(self, batch, adapter_ids) -> float:
        """Reference schedule for the parity pin: loss computed per tenant
        group (each group's rows through a single-adapter pass), combined
        by token weight — must match :meth:`step`'s batched loss."""
        ids = np.asarray(adapter_ids)
        input_ids = np.asarray(batch["input_ids"])
        labels = np.asarray(batch["labels"])
        total, weight = 0.0, 0
        for t in sorted(set(int(x) for x in ids)):
            rows = np.nonzero(ids == t)[0]
            sub = {"input_ids": jnp.asarray(input_ids[rows]),
                   "labels": jnp.asarray(labels[rows])}
            tids = [t] if t != 0 else []
            slot_ids = jnp.full((len(rows),), 1 if t != 0 else 0, jnp.int32)
            loss = float(self._loss(self.base_params, self._stack(tids), sub, slot_ids))
            n_tok = int((labels[rows][:, 1:] != -100).sum())
            total += loss * n_tok
            weight += n_tok
        return total / max(weight, 1)

    # -- verified checkpointing --------------------------------------------

    def save(self, ckpt_dir: str) -> str:
        """Atomic, verified save of every tenant's (weights, optimizer
        state): stage under ``<dir>.tmp``, write the size+crc32 manifest
        LAST, publish with ONE ``os.replace`` — the resilience layer's
        checkpoint discipline (``checkpointing._finalize_checkpoint``)
        applied to adapters.  Re-saving over an existing directory (or a
        crashed save's stale ``.tmp``) republishes cleanly: both are
        cleared first, so a deleted tenant's shard can never resurrect
        into a fresh manifest."""
        from ..checkpointing import _finalize_checkpoint

        final = str(ckpt_dir)
        tmp = final + ".tmp"
        if os.path.isdir(tmp):
            import shutil

            shutil.rmtree(tmp)  # a crashed prior save must not leak shards
        os.makedirs(tmp)
        for tid in sorted(self.adapters):
            np.savez(
                os.path.join(tmp, f"adapter_{tid}.npz"),
                **{f"w/{k}": self._npz_safe(v)
                   for k, v in _flatten(self.adapters[tid]).items()},
                **{f"s/{i}": self._npz_safe(leaf)
                   for i, leaf in enumerate(
                       jax.tree_util.tree_leaves(self.opt_states[tid]))},
            )
        _finalize_checkpoint(tmp, final)
        return final

    @staticmethod
    def _npz_safe(leaf):
        """npz-representable view of a leaf: typed PRNG keys become their
        key_data, and non-native float dtypes (bf16 & co — ``np.savez``
        degrades them to raw void bytes) upcast to fp32, which is EXACT for
        every <=16-bit float; the loader casts back to the template dtype,
        reconstructing the original bits."""
        if isinstance(leaf, jax.Array) and jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            return np.asarray(jax.random.key_data(leaf))
        arr = np.asarray(leaf)
        if arr.dtype.kind == "V":  # ml_dtypes (bf16/fp8): not npz-native
            return arr.astype(np.float32)
        return arr

    def load(self, ckpt_dir: str) -> list[int]:
        """Verified restore (``verify_checkpoint`` gate first — a torn or
        bit-flipped save raises ``CheckpointCorruptError`` instead of
        silently resuming wrong tenants).  Returns the restored tids."""
        from ..checkpointing import CheckpointCorruptError, verify_checkpoint

        ok, problems = verify_checkpoint(ckpt_dir)
        if not ok:
            raise CheckpointCorruptError(
                f"adapter checkpoint {ckpt_dir} failed verification: {problems}"
            )
        restored = []
        for name in sorted(os.listdir(ckpt_dir)):
            if not (name.startswith("adapter_") and name.endswith(".npz")):
                continue
            tid = int(name[len("adapter_"):-len(".npz")])
            with np.load(os.path.join(ckpt_dir, name)) as z:
                weights = _nest({k[2:]: jnp.asarray(z[k]).astype(self.dtype)
                                 for k in z.files if k.startswith("w/")})
                state_leaves = [z[f"s/{i}"] for i in range(
                    sum(1 for k in z.files if k.startswith("s/")))]
            self.adapters[tid] = weights
            template = self.tx.init(weights)
            t_leaves, treedef = jax.tree_util.tree_flatten(template)
            rebuilt = [
                jax.random.wrap_key_data(jnp.asarray(loaded))
                if isinstance(t, jax.Array) and jnp.issubdtype(t.dtype, jax.dtypes.prng_key)
                else jnp.asarray(loaded, getattr(t, "dtype", None))
                for t, loaded in zip(t_leaves, state_leaves)
            ]
            self.opt_states[tid] = jax.tree_util.tree_unflatten(treedef, rebuilt)
            restored.append(tid)
        return restored

    def host_state_report(self) -> dict:
        """Measured twin of :func:`~accelerate_tpu.ops.lora.adapter_state_accounting`."""
        from ..ops.streaming import tree_bytes

        return {
            "n_adapters": len(self.adapters),
            "optimizer": self.plugin.optimizer,
            "weight_bytes": sum(tree_bytes(t) for t in self.adapters.values()),
            "state_bytes": sum(tree_bytes(s) for s in self.opt_states.values()),
            "kind": "measured",
        }
