"""Fleet routing: N engine replicas behind deterministic prefix- and
adapter-affinity placement (the scale-out layer above one engine or one
disaggregated pair).

One warmed engine is already deterministic end-to-end; a fleet of N must
behave like ONE warm engine, and the router is where that either holds or
breaks.  Three disciplines compose here:

- **Prefix affinity** (the routing key is free): the prefix cache already
  content-addresses every prompt as a chain of block hashes
  (:meth:`~.prefix_cache.PrefixCache.block_hashes`), so the router can ask
  each replica's index — a pure :meth:`~.prefix_cache.PrefixCache.match`
  probe, no stats or LRU mutation — how many leading pages of THIS prompt
  it already holds, and send the request where its preamble is hot.
  Requests routed but not yet admitted are tracked in a per-replica
  *planned* hash set so a burst of same-preamble arrivals converges on one
  replica instead of scattering before the first insert lands.
- **Adapter affinity** (the S-LoRA discipline): a tenant stays on replicas
  whose :class:`~.adapters.AdapterStore` pool holds its weights resident —
  a swap costs host→device bytes and can evict another hot tenant, so the
  router prefers residency, then the tenant's sticky home replica, before
  letting load win.
- **Load-aware tie-breaking**: among equal-affinity replicas the shortest
  queue and emptiest KV pool wins, lowest replica index as the final
  deterministic tie-break — same trace, same fleet, same placement,
  always (the scheduler-determinism contract lifted fleet-wide).

Drain/respawn reuses the single-engine survivors contract: killing a
replica (the ``replica_kill`` fault, site ``fleet_route``) drains it
through ``remaining_requests()`` — completed work stays completed, every
pending request re-routes **exactly once** — and the re-admitted survivors
are pre-marked in the target scheduler's once-only offered-traffic set
(:meth:`~.scheduler.ContinuousBatchingScheduler.mark_prefix_counted`) so
the fleet prefix twin never double-counts a drained request's preamble.
Surviving tokens stay BITWISE identical to the fault-free fleet replay
(pinned by tests and the ``chaos_replay`` fleet leg).

Fleet-wide degradation: :meth:`FleetRouter.attach` chains an
:class:`~accelerate_tpu.telemetry.SLOMonitor`'s trip/recover callbacks to
EVERY replica's :class:`~.overload.DegradationLadder` — one breached SLO
escalates the whole fleet one stage, in lockstep, exactly as one engine
would escalate itself.
"""

from __future__ import annotations

import dataclasses as _dc
import os
from typing import Callable, Optional, Sequence

import numpy as np

from .scheduler import Request


class _Replica:
    """Uniform drive surface over one fleet member — a fused
    :class:`~.engine.ServingEngine` or a
    :class:`~.transfer.DisaggregatedPair` (duck-typed on
    ``prefill_engine``).  Normalizes submit/tick/busy/results, the drain
    contract, and the occupancy + affinity probes the router scores on."""

    def __init__(self, index: int, backend):
        self.index = index
        self.backend = backend
        self.is_pair = hasattr(backend, "prefill_engine")
        self.alive = True
        self.routed = 0
        self.compiles_warmup = 0
        # hash-chains routed here but possibly not yet admitted/inserted:
        # the router's look-ahead prefix index (a burst of same-preamble
        # arrivals must converge BEFORE the first admission inserts pages)
        self.planned: set[bytes] = set()

    # -- the engines under this replica --------------------------------------

    @property
    def engines(self) -> list:
        if self.is_pair:
            return [self.backend.prefill_engine, self.backend.decode_engine]
        return [self.backend]

    @property
    def role(self) -> str:
        return "pair" if self.is_pair else "engine"

    @property
    def _admit_engine(self):
        """The engine whose scheduler admits routed traffic (and therefore
        owns the prefix cache the affinity probe reads): the prefill role
        of a pair, the engine itself otherwise."""
        return self.backend.prefill_engine if self.is_pair else self.backend

    # -- drive surface -------------------------------------------------------

    def warmup(self) -> int:
        return self.backend.warmup()

    def submit(self, request: Request) -> None:
        """Hand one routed request to the backend NOW: the arrival step is
        rebased to the replica's own virtual clock (the fleet clock
        delivered it; each replica keeps its own step time)."""
        r = _dc.replace(request, arrival_step=self._admit_engine.steps)
        if self.is_pair:
            self.backend.submit(r)
        else:
            self.backend.add_request(r)
        self.routed += 1

    def busy(self) -> bool:
        if self.is_pair:
            return self.backend.busy()
        return not self.backend.idle()

    def tick(self) -> None:
        if self.is_pair:
            self.backend.tick()
        else:
            self.backend.step()

    @property
    def results(self) -> dict:
        return self.backend.results

    def remaining_requests(self) -> list[Request]:
        return self.backend.remaining_requests()

    def prefix_counted(self) -> set[int]:
        """Uids whose cacheable preamble this replica already counted as
        offered traffic (admitted at least once) — the set a drain carries
        to the re-route target so the fleet prefix twin counts each
        request exactly once."""
        out: set[int] = set()
        for eng in self.engines:
            out |= eng.sched._prefix_counted
        return out

    def mark_prefix_counted(self, uids) -> None:
        self._admit_engine.sched.mark_prefix_counted(uids)

    # -- routing probes ------------------------------------------------------

    def queue_len(self) -> int:
        n = sum(len(eng.sched.waiting) for eng in self.engines)
        if self.is_pair:
            n += len(self.backend._pending) - self.backend._i
        return n

    def kv_occupancy(self) -> float:
        return max(
            eng.sched.used_pages / eng.sched.num_pages for eng in self.engines
        )

    def prefix_score(self, request: Request) -> int:
        """Prompt tokens of ``request`` this replica's prefix index (live
        pages + planned routes) already covers — 0 with the cache off."""
        pc = self._admit_engine.prefix
        if pc is None:
            return 0
        hashes = pc.block_hashes(request.prompt, request.adapter_id)
        live = len(pc.match(hashes))
        planned = 0
        for h in hashes:
            if h not in self.planned:
                break
            planned += 1
        return max(live, planned) * pc.page_size

    def plan_prefix(self, request: Request) -> None:
        pc = self._admit_engine.prefix
        if pc is not None:
            self.planned.update(
                pc.block_hashes(request.prompt, request.adapter_id)
            )

    def adapter_resident(self, tid: int) -> bool:
        if not tid:
            return False
        # residency on ANY of the replica's pools counts — a pair keeps one
        # store per role and the tenant crosses the split with the request
        return any(
            eng.adapters is not None and eng.adapters.resident(tid)
            for eng in self.engines
        )

    # -- telemetry -----------------------------------------------------------

    def compiles_warmup_by_role(self) -> dict:
        if self.is_pair:
            return dict(getattr(self.backend, "compiles_warmup_by_role", {}))
        return {"engine": self.compiles_warmup}

    def stats_row(self) -> dict:
        prefix_rates, adapter_rates = [], []
        for eng in self.engines:
            if eng.prefix is not None:
                prefix_rates.append(eng.prefix.hit_rate())
            if eng.adapters is not None:
                adapter_rates.append(eng.adapters.hit_rate())
        return {
            "replica": self.index,
            "role": self.role,
            "alive": self.alive,
            "routed": self.routed,
            "completed": len(self.results),
            "engine_steps": sum(eng.steps for eng in self.engines),
            "waiting": self.queue_len(),
            "kv_occupancy": round(self.kv_occupancy(), 4),
            "prefix_hit_rate": round(max(prefix_rates), 4) if prefix_rates else 0.0,
            "adapter_pool_hit_rate": (
                round(max(adapter_rates), 4) if adapter_rates else 0.0
            ),
            "compiles_warmup": self.compiles_warmup,
        }


class FleetRouter:
    """Deterministic affinity router over N replicas (fused engines or
    disaggregated pairs, freely mixed).

    ``policy`` is ``"affinity"`` (prefix → adapter → load, the default) or
    ``"round_robin"`` (the baseline the perf pin beats).  The placement
    score is the lexicographic tuple ``(prefix_tokens, adapter_affinity,
    -queue_len, -kv_occupancy, -index)`` maximized over alive replicas —
    every component is integer-or-exact, so placement is reproducible
    across runs and hosts.

    ``respawn`` (optional) is a factory ``index -> backend``: after a
    ``replica_kill`` drain the router appends a fresh warmed replica so
    fleet capacity recovers.  Without it the fleet just narrows.
    """

    def __init__(self, replicas: Sequence, *, policy: str = "affinity",
                 respawn: Optional[Callable[[int], object]] = None):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        if policy not in ("affinity", "round_robin"):
            raise ValueError(
                f"unknown routing policy {policy!r}: "
                "expected 'affinity' or 'round_robin'"
            )
        self.replicas = [_Replica(i, b) for i, b in enumerate(replicas)]
        self.policy = policy
        self.respawn = respawn
        self.routed_by = {"prefix": 0, "adapter": 0, "load": 0}
        self.drain_events: list[dict] = []
        self.clock = 0
        self.monitor = None
        self._rr = 0
        self._home: dict[int, int] = {}   # tenant -> sticky home replica
        self._compile_base: dict[int, int] = {}

    # -- lifecycle -----------------------------------------------------------

    def warmup(self, prewarm_dir: Optional[str] = None,
               cache_tag: str = "fleet") -> dict:
        """Warm every replica, sharing one compile sweep per role.

        In-process replicas already share the jitted program cache, so the
        first replica of a role pays the sweep and the rest warm nearly
        free.  With ``prewarm_dir`` the first replica of each role also
        packs its scoped compilation cache into ``prewarm-<role>.tar``
        (:func:`~accelerate_tpu.utils.compile_cache.export_prewarm`) and
        later same-role replicas — or other PROCESSES, the real win —
        :func:`~accelerate_tpu.utils.compile_cache.load_prewarm` it before
        warming.  Returns ``compiles_warmup`` summed per role."""
        by_role: dict[str, int] = {}
        exported: set[str] = set()
        if prewarm_dir:
            from ..utils.compile_cache import (enable_scoped_compilation_cache,
                                               export_prewarm, load_prewarm)

            os.makedirs(prewarm_dir, exist_ok=True)
            enable_scoped_compilation_cache(cache_tag,
                                            min_compile_time_secs=0.0)
        for rep in self.replicas:
            archive = (os.path.join(prewarm_dir, f"prewarm-{rep.role}.tar")
                       if prewarm_dir else "")
            if archive and rep.role not in exported and os.path.exists(archive):
                load_prewarm(archive, tag=cache_tag)
                exported.add(rep.role)
            rep.compiles_warmup = rep.warmup()
            for role, n in rep.compiles_warmup_by_role().items():
                by_role[role] = by_role.get(role, 0) + n
            if archive and rep.role not in exported:
                export_prewarm(archive, tag=cache_tag)
                exported.add(rep.role)
        for rep in self.replicas:
            self._compile_base[rep.index] = self._compiles(rep)
        return by_role

    @staticmethod
    def _compiles(rep: _Replica) -> int:
        return sum(eng.compile_events for eng in rep.engines)

    def compiles_measured(self) -> dict[int, int]:
        """Post-warmup compile events per replica — zero everywhere is the
        fleet's strict_compiles contract."""
        return {
            rep.index: self._compiles(rep) - self._compile_base.get(rep.index, 0)
            for rep in self.replicas
        }

    # -- placement -----------------------------------------------------------

    def alive_replicas(self) -> list[_Replica]:
        return [rep for rep in self.replicas if rep.alive]

    def route(self, request: Request) -> _Replica:
        """Place one request: score alive replicas, submit to the winner,
        update the planned-prefix index and the tenant home map.  Returns
        the chosen replica."""
        alive = self.alive_replicas()
        if not alive:
            raise RuntimeError(
                "fleet has no alive replicas to route to "
                "(every replica drained without a respawn factory)"
            )
        if self.policy == "round_robin":
            rep = alive[self._rr % len(alive)]
            self._rr += 1
            reason = "load"
        else:
            tid = request.adapter_id

            def score(rep: _Replica):
                affinity = (2 if rep.adapter_resident(tid)
                            else 1 if tid and self._home.get(tid) == rep.index
                            else 0)
                return (rep.prefix_score(request), affinity,
                        -rep.queue_len(), -rep.kv_occupancy(), -rep.index)

            scored = max(alive, key=score)
            s = score(scored)
            rep = scored
            reason = "prefix" if s[0] > 0 else "adapter" if s[1] > 0 else "load"
        self.routed_by[reason] += 1
        rep.plan_prefix(request)
        if request.adapter_id:
            self._home[request.adapter_id] = rep.index
        rep.submit(request)
        return rep

    # -- drain / respawn -----------------------------------------------------

    def drain(self, rep: _Replica) -> list[Request]:
        """Kill one replica: collect its survivors through the
        ``remaining_requests()`` contract, mark it dead (completed results
        stay attributed to it), re-route every survivor exactly once, and
        pre-seed each target's once-only prefix-counting set for survivors
        the victim already counted as offered traffic.  Returns the
        survivors, in the victim's submission order."""
        survivors = rep.remaining_requests()
        counted = rep.prefix_counted()
        rep.alive = False
        self.drain_events.append({
            "replica": rep.index, "at_clock": self.clock,
            "survivors": len(survivors),
        })
        if self.respawn is not None:
            fresh = _Replica(len(self.replicas), self.respawn(rep.index))
            fresh.compiles_warmup = fresh.warmup()
            self._compile_base[fresh.index] = self._compiles(fresh)
            self.replicas.append(fresh)
        for r in survivors:
            target = self.route(r)
            if r.uid in counted:
                target.mark_prefix_counted([r.uid])
        return survivors

    def _kill_one(self) -> None:
        """The ``replica_kill`` fault body: deterministically pick the
        victim — the highest-index alive replica that is busy (the fault
        wants mid-flight work to re-route), else the highest-index alive —
        and drain it.  A single-replica fleet with no respawn ignores the
        kill: there is nowhere to re-route."""
        alive = self.alive_replicas()
        if len(alive) <= 1 and self.respawn is None:
            return
        busy = [rep for rep in alive if rep.busy()]
        victim = (busy or alive)[-1]
        self.drain(victim)

    # -- the fleet loop ------------------------------------------------------

    def run(self, trace: list[Request], max_steps: int = 500_000) -> dict:
        """Replay a trace through the fleet: one fleet tick delivers due
        arrivals through :meth:`route`, fires the ``fleet_route`` fault
        point, then ticks every busy alive replica once.  Returns the
        merged ``{uid: tokens}`` results."""
        from ..resilience.faults import fault_point

        pending = sorted(trace, key=lambda r: (r.arrival_step, r.uid))
        i = 0
        while True:
            for e in fault_point("fleet_route"):
                if e.kind == "replica_kill":
                    self._kill_one()
            while i < len(pending) and pending[i].arrival_step <= self.clock:
                self.route(pending[i])
                i += 1
            busy = [rep for rep in self.alive_replicas() if rep.busy()]
            if not busy and i >= len(pending):
                break
            for rep in busy:
                rep.tick()
            self.clock += 1
            if self.clock >= max_steps:
                raise RuntimeError(f"fleet replay exceeded {max_steps} ticks")
        return self.results

    @property
    def results(self) -> dict:
        """Merged results across ALL replicas — drained replicas keep the
        work they completed before the drain."""
        out: dict = {}
        for rep in self.replicas:
            out.update(rep.results)
        return out

    # -- fleet-wide degradation ----------------------------------------------

    def attach(self, monitor) -> None:
        """Chain an :class:`~accelerate_tpu.telemetry.SLOMonitor` to the
        WHOLE fleet: a trip escalates every alive replica's degradation
        ladder one stage, a recovery relaxes every one — the fleet moves
        through the ladder in lockstep, like one engine.  Callbacks the
        monitor already carries keep firing (the
        :meth:`~.overload.DegradationLadder.attach` chaining rule)."""
        self.monitor = monitor
        prev_trip, prev_recover = monitor.on_trip, monitor.on_recover

        def trip(metric, quantile, value):
            self.escalate(metric, quantile, value)
            if prev_trip is not None:
                prev_trip(metric, quantile, value)

        def recover(metric, quantile, value):
            self.relax(metric, quantile, value)
            if prev_recover is not None:
                prev_recover(metric, quantile, value)

        monitor.on_trip = trip
        monitor.on_recover = recover

    def escalate(self, metric=None, quantile=None, value=None) -> None:
        for rep in self.alive_replicas():
            for eng in rep.engines:
                eng.ladder.escalate(metric, quantile, value)

    def relax(self, metric=None, quantile=None, value=None) -> None:
        for rep in self.alive_replicas():
            for eng in rep.engines:
                eng.ladder.relax(metric, quantile, value)

    # -- telemetry -----------------------------------------------------------

    def ttft_ticks(self) -> list[int]:
        """Every replica's deterministic TTFT samples (virtual ticks from
        rebased arrival to first token) — the fleet perf pin's clock."""
        out: list[int] = []
        for rep in self.replicas:
            for eng in rep.engines:
                out.extend(eng.ttft_ticks)
        return out

    def prefix_hit_rate(self) -> float:
        """Fleet-aggregate prefix hit rate: index-served cacheable pages
        over cacheable pages offered, summed across every replica's cache
        — each request counted exactly once even across a drain re-route
        (the ``mark_prefix_counted`` hand-off)."""
        hits = lookups = 0
        for rep in self.replicas:
            for eng in rep.engines:
                if eng.prefix is not None:
                    hits += eng.prefix.stats["hit_pages"]
                    lookups += eng.prefix.stats["lookup_pages"]
        return round(hits / lookups, 4) if lookups else 0.0

    def adapter_pool_hit_rate(self) -> float:
        hits = total = 0
        for rep in self.replicas:
            for eng in rep.engines:
                store = eng.adapters
                if store is not None:
                    hits += store.hits
                    total += store.hits + store.swaps
        return round(hits / total, 4) if total else 0.0

    def transfer_bytes(self) -> int:
        return sum(rep.backend.transport.bytes_moved
                   for rep in self.replicas if rep.is_pair)

    def report(self) -> dict:
        return {
            "replicas": len(self.replicas),
            "alive": len(self.alive_replicas()),
            "policy": self.policy,
            "routed_by_prefix": self.routed_by["prefix"],
            "routed_by_adapter": self.routed_by["adapter"],
            "routed_by_load": self.routed_by["load"],
            "drain_events": list(self.drain_events),
            "fleet_clock": self.clock,
            "per_replica": [rep.stats_row() for rep in self.replicas],
        }


def fleet_replay(router: FleetRouter, trace: list[Request], *,
                 strict_compiles: bool = True,
                 prewarm_dir: Optional[str] = None,
                 slo_monitor=None) -> dict:
    """Run a trace through a fleet and compose the fleet serving report
    (the :func:`~.harness.replay` contract lifted over N replicas): every
    field always present, zeros on an empty trace.

    Warmup shares one compile sweep per role (optionally through an
    ``export_prewarm`` pack in ``prewarm_dir``); after the run every
    replica must show ZERO post-warmup compile events — with
    ``strict_compiles`` (default) a violation raises instead of publishing
    a report a recompile stall poisoned.

    Twins recorded into the central registry: ``fleet.request_goodput``
    (clean-run prediction 1.0 — only recorded with no fault plan active),
    ``fleet.prefix_hit_rate`` / ``fleet.adapter_pool_hit_rate`` (predicted
    from the single-cache trace models — informational: a fleet splits
    traffic, affinity routing is what closes the gap), and the summed
    ``transfer.page_bytes`` across every pair replica's transport."""
    from ..resilience.faults import active_fault_plan
    from ..telemetry import twin_registry

    compiles_warmup_by_role = router.warmup(prewarm_dir)
    if slo_monitor is not None:
        router.attach(slo_monitor)
    results = router.run(trace)
    compiles = router.compiles_measured()
    measured_compiles = sum(compiles.values())
    if strict_compiles and measured_compiles > 0:
        bad = {k: v for k, v in compiles.items() if v}
        raise RuntimeError(
            f"post-warmup compile event(s) on replica(s) {bad} during the "
            "fleet replay: a mid-traffic recompile — some replica's program "
            "shape is not pinned to its bucket ladder"
        )
    if slo_monitor is not None:
        for rep in router.replicas:
            for eng in rep.engines:
                if getattr(eng, "slo", None) is not slo_monitor:
                    slo_monitor.observe_many("token_latency_s", eng.token_gaps_s)
                    slo_monitor.observe_many("ttft_s", eng.ttft_s)
    ticks = router.ttft_ticks()
    goodput = round(len(results) / len(trace), 4) if trace else 0.0
    prefix_rate = router.prefix_hit_rate()
    adapter_rate = router.adapter_pool_hit_rate()
    reg = twin_registry()
    reg.record_measured("fleet.request_goodput", goodput,
                        source="serving/router.fleet_replay")
    if active_fault_plan() is None:
        # the clean-run model: nothing sheds, every routed request completes
        reg.record_predicted("fleet.request_goodput",
                             1.0 if trace else 0.0,
                             source="serving/router clean-run model")
    reg.record_measured("fleet.prefix_hit_rate", prefix_rate,
                        source="serving/router.fleet_replay")
    reg.record_measured("fleet.adapter_pool_hit_rate", adapter_rate,
                        source="serving/router.fleet_replay")
    admit = router.replicas[0]._admit_engine
    if admit.prefix is not None and trace:
        from .harness import predicted_prefix_hit_rate

        p = admit.plugin
        reg.record_predicted(
            "fleet.prefix_hit_rate",
            predicted_prefix_hit_rate(
                trace, num_slots=p.num_slots, num_pages=p.num_pages,
                page_size=p.page_size, pages_per_slot=p.pages_per_slot,
                prefill_chunk=p.prefill_chunk,
            ),
            source="serving/router single-cache trace model",
        )
    stores = [eng.adapters for rep in router.replicas for eng in rep.engines
              if eng.adapters is not None]
    if stores and trace:
        from .adapters import predicted_adapter_hit_rate

        tenant_ids = [r.adapter_id for r in
                      sorted(trace, key=lambda r: (r.arrival_step, r.uid))]
        reg.record_predicted(
            "fleet.adapter_pool_hit_rate",
            predicted_adapter_hit_rate(tenant_ids, stores[0].plugin.pool_slots),
            source="serving/router single-pool trace model",
        )
    wire_bytes = router.transfer_bytes()
    if wire_bytes:
        reg.record_measured("transfer.page_bytes", wire_bytes,
                            source="serving/router.fleet_replay")
    return {
        "requests": len(trace),
        "completed": len(results),
        "goodput_frac": goodput,
        "ttft_p50_ticks": (
            round(float(np.percentile(np.asarray(ticks), 50)), 2)
            if ticks else 0.0
        ),
        "prefix_hit_rate": prefix_rate,
        "adapter_pool_hit_rate": adapter_rate,
        "page_transfer_bytes": wire_bytes,
        "compiles_warmup_by_role": compiles_warmup_by_role,
        "compiles_measured": measured_compiles,
        **router.report(),
        "results": results,
    }


def fleet_chaos_replay(router_factory: Callable[[], FleetRouter],
                       trace: list[Request], plan, *,
                       strict_compiles: bool = True,
                       baseline_parity: bool = True) -> dict:
    """Seeded fleet chaos soak: replay the trace through a fleet while the
    :class:`~accelerate_tpu.resilience.FaultPlan` kills replicas
    (``replica_kill`` at the ``fleet_route`` site) mid-traffic.

    The acceptance pin: the router drains each victim through the
    survivors contract and re-routes pending work exactly once, so the
    surviving tokens are **BITWISE identical** to a fault-free replay of
    the same trace through a fresh identical fleet — a kill may change
    WHERE a request decodes, never what it says.  ``strict_compiles``
    holds across the soak (the respawn/warmup path included)."""
    from ..resilience.faults import fault_plan as _fault_plan
    from ..telemetry import twin_registry

    with _fault_plan(plan):
        router = router_factory()
        router.warmup()
        results = router.run(trace)
        compiles = sum(router.compiles_measured().values())
    if strict_compiles and compiles > 0:
        raise RuntimeError(
            f"{compiles} post-warmup compile event(s) during the fleet "
            "chaos soak: a drain/re-route pushed a replica off its warmed "
            "program set"
        )
    token_parity = True
    if baseline_parity and results:
        baseline = router_factory()
        baseline.warmup()
        base_results = baseline.run(trace)
        token_parity = (
            {uid: base_results.get(uid) for uid in results} == results
        )
    goodput = round(len(results) / len(trace), 4) if trace else 0.0
    twin_registry().record_measured(
        "fleet.request_goodput", goodput,
        source="serving/router.fleet_chaos_replay",
    )
    return {
        "requests": len(trace),
        "completed": len(results),
        "goodput_frac": goodput,
        "faults_fired": len(plan.fired),
        "drain_events": list(router.drain_events),
        "token_parity": token_parity,
        "compiles_measured": compiles,
        **{k: v for k, v in router.report().items() if k != "drain_events"},
        "results": results,
    }


__all__ = ["FleetRouter", "fleet_replay", "fleet_chaos_replay"]
