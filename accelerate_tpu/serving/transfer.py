"""Disaggregated prefill→decode: streaming finished KV pages between
engines (DistServe discipline — the first slice).

Once KV pages are a transferable, refcounted resource (the prefix cache's
contract), prefill and decode stop having to share an engine: a **prefill
gang** turns prompts into KV pages + a first token at full chunked-prefill
throughput, and a **decode gang** consumes those pages at decode batch
shapes — neither workload pads out the other's step.  This module lands
the in-process two-engine slice of that split:

- :class:`PagedKVTransport` — two fixed-shape jitted programs move one
  finished slot's pages between pools: ``send`` gathers the slot's
  block-table row into a contiguous wire payload (``[L, pps, Hkv, page,
  D]`` per K/V — the exact bytes a DCN stream would carry), ``recv`` pops
  fresh pages from the destination free stack, scatters the payload into
  them and installs block-table row + ``seq_len``.  Bytes are accounted
  against the ``dcn``-axis model (:func:`transfer_accounting`, the
  ``dcn_comm_accounting`` pattern) as the ``transfer.page_bytes`` twin.
- :class:`DisaggregatedPair` — the host loop over a prefill-role engine
  (``hold_finished=True``: finished slots keep their pages until streamed)
  and a decode-role engine.  Greedy tokens are BITWISE identical to the
  same trace through one engine (pinned by tests/test_prefix_cache.py):
  the payload bytes ARE the K/V, so the decode side attends over exactly
  what a local prefill would have written.

Multi-host streaming is live in the 2-process fabric leg
(``test_utils/scripts/fleet_fabric.py``, launched over jax.distributed by
the dryrun's ``_fleet_leg``): the SAME wire payload crosses a real process
boundary over the ``dcn`` plumbing, gated by the same shared
``wire_schema`` derivation, with independent per-role pool geometry and
the byte twin exact.  N pairs compose into a fleet behind the
deterministic affinity router in :mod:`.router`.
"""

from __future__ import annotations

import dataclasses as _dc
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .engine import ServingEngine
from .paged_cache import allocate, kv_page_bytes, pages_for
from .scheduler import Request


def page_bytes(config, page_size: int, dtype_bytes: int = 2,
               kv_dtype: str = "") -> int:
    """Wire bytes of ONE physical page across all layers — the unit the
    transfer twin counts in (``kv_pool_accounting``'s bytes/page; one
    shared formula, :func:`~.paged_cache.kv_page_bytes`, so predicted and
    measured twins can only agree exactly).  Quantized pools
    (``kv_dtype`` "int8"/"fp8") ship 1-byte codes plus the per-(kv-head,
    page) scales — the scales are page content and travel on the wire."""
    return kv_page_bytes(config, page_size, dtype_bytes, kv_dtype)


def transfer_accounting(config, trace, page_size: int, dtype_bytes: int = 2,
                        dcn_gbps: float = 25.0, kv_dtype: str = "") -> dict:
    """Predicted ``dcn``-axis byte model for a disaggregated replay of
    ``trace`` (the ``dcn_comm_accounting`` pattern): every request ships
    ``pages_for(prompt_len)`` live pages exactly once, prefill→decode.
    The measured twin (``transfer.page_bytes``) comes from the transport's
    executed transfers — the two agree exactly unless a request never made
    it to the handoff (shed, cancelled, drained).  ``dcn_gbps`` turns the
    bytes into a stream-time envelope per the reference DCN link rate.
    Pass the pool's ``kv_dtype`` for quantized pages — the wire unit is
    roughly halved (codes + scales instead of bf16)."""
    per_page = page_bytes(config, page_size, dtype_bytes, kv_dtype)
    pages = sum(int(pages_for(r.prompt_len, page_size)) for r in trace)
    total = pages * per_page
    from ..telemetry import twin_registry

    twin_registry().record_predicted(
        "transfer.page_bytes", total,
        source="serving/transfer.transfer_accounting",
    )
    return {
        "requests": len(trace),
        "pages_predicted": pages,
        "bytes_per_page": per_page,
        "page_transfer_bytes": total,
        "dcn_gbps_ref": dcn_gbps,
        "stream_s_pred": round(total / (dcn_gbps * 1e9), 6) if total else 0.0,
    }


def _transfer_step_fns():
    def send_step(cache, slot):
        # one slot's pages, gathered contiguous through its block-table row
        # — the wire payload a DCN stream would carry (dead pages ride as
        # padding; the byte twin counts live pages only).  Quantized pools
        # also ship the per-(kv-head, page) scales: they are page content
        # (the codes are meaningless without them), so they ride the same
        # payload — the byte twin counts them via kv_page_bytes.
        row = jax.lax.dynamic_slice_in_dim(cache["block_tables"], slot, 1)[0]
        payload = {
            "k": jnp.stack([l["k_pages"][:, row] for l in cache["layers"]]),
            "v": jnp.stack([l["v_pages"][:, row] for l in cache["layers"]]),
        }  # [L, Hkv, pps, page, D] each
        if "k_scales" in cache["layers"][0]:
            payload["k_scales"] = jnp.stack(
                [l["k_scales"][:, row] for l in cache["layers"]])
            payload["v_scales"] = jnp.stack(
                [l["v_scales"][:, row] for l in cache["layers"]])
            # [L, Hkv, pps] each
        return payload

    def recv_step(cache, slot, payload, n_pages, seq_len):
        # pop n_pages fresh pages, install the block-table row, scatter the
        # payload into the popped pages — one donated fixed-shape program
        pps = cache["block_tables"].shape[1]
        lane = jnp.arange(pps, dtype=jnp.int32)
        need = lane < n_pages
        block_tables, free_top = allocate(
            cache["block_tables"], cache["free_stack"], cache["free_top"],
            jnp.full((pps,), slot, jnp.int32), lane, need,
        )
        row = jax.lax.dynamic_slice_in_dim(block_tables, slot, 1)[0]
        num_pages = cache["layers"][0]["k_pages"].shape[1]
        dst = jnp.where(need, row, num_pages)  # OOB -> drop (write-mask rule)
        quantized = "k_scales" in payload
        new_layers = []
        for i, l in enumerate(cache["layers"]):
            layer = {
                "k_pages": l["k_pages"].at[:, dst].set(payload["k"][i],
                                                       mode="drop"),
                "v_pages": l["v_pages"].at[:, dst].set(payload["v"][i],
                                                       mode="drop"),
            }
            if quantized:
                layer["k_scales"] = l["k_scales"].at[:, dst].set(
                    payload["k_scales"][i], mode="drop")
                layer["v_scales"] = l["v_scales"].at[:, dst].set(
                    payload["v_scales"][i], mode="drop")
            new_layers.append(layer)
        return {
            "layers": new_layers,
            "block_tables": block_tables,
            "seq_lens": cache["seq_lens"].at[slot].set(seq_len),
            "free_stack": cache["free_stack"],
            "free_top": free_top,
        }

    return send_step, recv_step


@lru_cache(maxsize=8)
def _transfer_fns(_geom_key):
    send_step, recv_step = _transfer_step_fns()
    return (
        jax.jit(send_step),                      # read-only gather
        jax.jit(recv_step, donate_argnums=(0,)),  # destination pool donates
    )


class PagedKVTransport:
    """Streams one finished slot's KV pages from a prefill-role engine to a
    decode-role engine (in-process: same devices, a gather + scatter; the
    payload shape is the multi-host wire format).  Byte accounting records
    the measured side of the ``transfer.page_bytes`` twin and appends
    ``("page_transfer", uid, n_pages, bytes)`` to the destination
    scheduler's determinism log (the ``page_transfer`` span)."""

    def __init__(self, src: ServingEngine, dst: ServingEngine):
        # one schema derivation for gate and runtime: the GL403 preflight
        # (analysis/distributed_audit.audit_wire_schema) and this runtime
        # rejection read the SAME wire_schema() dict, so they cannot drift
        # — a pair the gate passed constructs, a pair it failed raises here
        from ..analysis.distributed_audit import check_wire_schemas, wire_schema

        ps = src.plugin
        schema_src = wire_schema(src.model.config, ps)
        schema_dst = wire_schema(dst.model.config, dst.plugin)
        check_wire_schemas(schema_src, schema_dst)
        self.src, self.dst = src, dst
        self.schema = schema_src
        self._send, self._recv = _transfer_fns(
            (ps.page_size, ps.pages_per_slot, schema_src["kv_dtype"])
        )
        self._page_bytes = schema_src["page_bytes"]
        self.transfers = 0
        self.pages_moved = 0
        self.bytes_moved = 0
        from ..telemetry import twin_registry

        # the static-vs-runtime wire-unit twin: pair_preflight records the
        # predicted side from the schema alone; this is the measured side
        # off the constructed transport
        twin_registry().record_measured(
            "distributed.wire_bytes_per_page", self._page_bytes,
            source="serving/transfer.PagedKVTransport",
        )

    def warmup(self) -> None:
        """Compile both wire programs before traffic (no-op passes: the
        send gathers slot 0, the recv installs zero pages)."""
        payload = self._send(self.src.cache, jnp.asarray(0, jnp.int32))
        self.dst.cache = self._recv(
            self.dst.cache, jnp.asarray(0, jnp.int32), payload,
            jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
        )

    def transfer(self, src_slot: int, request: Request, first_token: int) -> int:
        """Move one held finished slot: gather on the prefill engine, adopt
        a decode slot, scatter + install on the decode engine, then release
        the source pages (COW-aware — a prefix-shared page on the prefill
        side frees only at refcount zero).  Returns the decode slot."""
        src, dst = self.src, self.dst
        n_pages = int(pages_for(request.prompt_len, src.plugin.page_size))
        payload = self._send(src.cache, jnp.asarray(src_slot, jnp.int32))
        dst_slot = dst.adopt_prefilled(request, first_token)
        dst.cache = self._recv(
            dst.cache, jnp.asarray(dst_slot, jnp.int32), payload,
            jnp.asarray(n_pages, jnp.int32),
            jnp.asarray(request.prompt_len, jnp.int32),
        )
        src.release_held(src_slot)
        moved = n_pages * self._page_bytes
        self.transfers += 1
        self.pages_moved += n_pages
        self.bytes_moved += moved
        for eng in (src, dst):
            eng.metrics["page_transfers"] += 1
            eng.metrics["page_transfer_pages"] += n_pages
            eng.metrics["page_transfer_bytes"] += moved
        dst.sched.events.append(
            ("page_transfer", request.uid, n_pages, moved)
        )
        from ..telemetry import twin_registry

        twin_registry().record_measured(
            "transfer.page_bytes", self.bytes_moved,
            source="serving/transfer.PagedKVTransport",
        )
        return dst_slot


class DisaggregatedPair:
    """The disaggregated prefill→decode deployment shape: one prefill-role
    engine (requests clamped to ``max_new_tokens=1`` — the prompt plus the
    first sampled token), one decode-role engine, and the transport
    streaming finished KV pages between them.

    ``run(trace)`` replays a request trace to completion and returns the
    same ``{uid: tokens}`` dict a single engine's ``run`` would — BITWISE
    identical greedy tokens (the acceptance pin): the first token comes
    off the prefill engine's last-chunk logits exactly as a fused engine
    would sample it, and the decode engine attends over the transferred
    bytes verbatim.  Speculation composes on the decode side
    (``plugin.speculate`` arms the decode engine's verify ladder; the
    prefill role is forced plain — its requests never decode), and
    multi-tenant adapters ride the split with one :class:`AdapterStore`
    per role (``adapters``/``prefill_adapters``, published identical
    weights: each engine's pool refcounts balance independently, and the
    decode role re-pins the tenant at :meth:`~.engine.ServingEngine.
    adopt_prefilled`).

    The incremental API (:meth:`submit` / :meth:`tick` / :meth:`busy`)
    exposes the same host loop one step at a time — the fleet router
    (``serving/router.py``) drives N pairs this way, interleaved, and
    :meth:`remaining_requests` extends the single-engine drain/survivors
    contract across the pair.
    """

    def __init__(self, model, params, plugin=None, generation_config=None,
                 rng=None, prefill_plugin=None, adapters=None,
                 prefill_adapters=None):
        from ..utils.dataclasses import ServingPlugin

        plugin = plugin or ServingPlugin()
        if (adapters is None) != (prefill_adapters is None):
            raise ValueError(
                "adapter traffic crosses the split: pass BOTH role stores "
                "(adapters= for the decode engine, prefill_adapters= for "
                "the prefill engine, published identical weights) or "
                "neither — one engine computing LoRA prompts the other "
                "cannot apply breaks token parity"
            )
        # per-tick deadlines belong to the fused engine's admission story
        # (each half runs its own virtual clock) — disarm the DEFAULT too,
        # not just the per-request field: submit() re-stamps
        # default_deadline_ticks onto any request carrying 0, which would
        # silently defeat run()'s deadline_ticks=0 opt-out
        plugin = _dc.replace(plugin, default_deadline_ticks=0)
        # the prefill role never decodes past the first token, so its
        # verify ladder would warm dead programs — force it plain and let
        # speculation live where the tokens do (the decode role)
        prefill_plugin = _dc.replace(prefill_plugin or plugin,
                                     default_deadline_ticks=0,
                                     speculate="off")
        self.prefill_engine = ServingEngine(
            model, params, prefill_plugin, generation_config,
            rng=rng, hold_finished=True, adapters=prefill_adapters,
        )
        self.decode_engine = ServingEngine(
            model, params, plugin, generation_config, rng=rng,
            adapters=adapters,
        )
        self.transport = PagedKVTransport(self.prefill_engine,
                                          self.decode_engine)
        self._pending: list[Request] = []
        self._i = 0
        self._originals: dict[int, Request] = {}
        self._done: dict[int, list[int]] = {}

    def preflight(self) -> tuple[list, dict]:
        """Run the GL4xx pair audit (wire schema, handoff schedule, traced
        wire programs, per-role warmup coverage) over this pair's configs.

        Trace-only — zero backend compiles — so it is safe to call before
        :meth:`warmup`; the dryrun's ``_distributed_audit_leg`` and
        ``preflight --serve --disaggregate`` both route through here."""
        from ..analysis.distributed_audit import pair_preflight

        return pair_preflight(
            self.prefill_engine.model.config,
            self.prefill_engine.plugin,
            self.decode_engine.plugin,
            adapters=self.decode_engine.adapters is not None,
        )

    def warmup(self) -> int:
        c0 = self.prefill_engine._compile_counter.count
        self.prefill_engine.warmup()
        c1 = self.prefill_engine._compile_counter.count
        self.decode_engine.warmup()
        c2 = self.prefill_engine._compile_counter.count
        self.transport.warmup()
        c3 = self.prefill_engine._compile_counter.count
        # per-role warmup cost off the process-wide counter (the fleet
        # bench's compiles_warmup-per-role rows; replicas sharing a jit
        # cache or a prewarm pack show up here as near-zero roles)
        self.compiles_warmup_by_role = {
            "prefill": c1 - c0, "decode": c2 - c1, "wire": c3 - c2,
        }
        # post-warmup compile baselines: run() must stay compile-free from
        # here (the strict_compiles contract extends across the pair — the
        # wire programs are production programs too)
        self._compile_base = (self.prefill_engine.compile_events,
                              self.decode_engine.compile_events)
        return c3 - c0

    # -- the incremental host loop (the fleet router's drive surface) --------

    def submit(self, request: Request) -> None:
        """Queue one request with the pair (virtual arrival honored against
        the prefill engine's clock).  ``run`` is ``submit`` for the whole
        trace plus ``tick`` until :meth:`busy` clears."""
        import bisect

        key = (request.arrival_step, request.uid)
        lo = self._i + bisect.bisect_right(
            [(r.arrival_step, r.uid) for r in self._pending[self._i:]], key
        )
        self._pending.insert(lo, request)
        self._originals[request.uid] = request

    def tick(self) -> bool:
        """One host-loop decision: deliver due arrivals, stream every held
        finished prefill the decode side can seat, then step exactly one
        engine.  Returns ``False`` when there is nothing left to do."""
        P, D = self.prefill_engine, self.decode_engine
        eos = P.gen_config.eos_token_id
        while self._i < len(self._pending) and \
                self._pending[self._i].arrival_step <= P.steps:
            P.add_request(_dc.replace(self._pending[self._i],
                                      max_new_tokens=1, deadline_ticks=0))
            self._i += 1
        # stream every held finished prefill the decode side can seat
        while P.held and self._dst_capacity():
            slot = P.held[0]
            uid = P.sched.slots[slot].request.uid
            tok = P.results[uid][0]
            if self._originals[uid].max_new_tokens == 1 or \
                    (eos is not None and tok == eos):
                # the first token already finished the request: nothing
                # to decode, nothing to stream
                P.release_held(slot)
                self._done[uid] = [tok]
                continue
            # the decode engine runs on its own virtual clock: per-tick
            # deadlines belong to the fused engine's admission story and
            # stay a documented follow-up for the split
            self.transport.transfer(
                slot, _dc.replace(self._originals[uid], deadline_ticks=0),
                P.results[uid][0],
            )
        if P.held and not self._dst_capacity() and not D.idle():
            # a finished prefill is waiting on decode capacity: drain
            # decode FIRST (prefill idling ahead of a blocked handoff
            # must never starve the decode engine of ticks)
            D.step()
        elif self._p_busy():
            P.step()
        elif not D.idle():
            D.step()
        elif self._i < len(self._pending):
            P.step()  # idle tick — advances the virtual arrival clock
        elif P.held:
            raise RuntimeError(
                "disaggregated handoff wedged: held prefill slots with "
                "an idle decode engine that cannot seat them — "
                "mismatched pool geometry?"
            )  # pragma: no cover - geometry validated at construction
        else:
            return False
        return True

    def busy(self) -> bool:
        """Work anywhere in the pair: undelivered arrivals, a busy prefill
        engine, a held handoff, or a non-idle decode engine."""
        return (self._i < len(self._pending) or self._p_busy()
                or bool(self.prefill_engine.held)
                or not self.decode_engine.idle())

    def run(self, trace: list[Request], max_steps: int = 200_000) -> dict[int, list[int]]:
        for r in sorted(trace, key=lambda r: (r.arrival_step, r.uid)):
            self.submit(r)
        steps = 0
        while self.tick():
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"disaggregated replay exceeded {max_steps} steps"
                )
        # the prefill engine recorded 1-token results; the decode engine
        # owns the full streams (first token included); one-token requests
        # finished at the handoff boundary
        return self.results

    @property
    def results(self) -> dict[int, list[int]]:
        return {**self.decode_engine.results, **self._done}

    @property
    def interrupted(self) -> bool:
        return self.prefill_engine.interrupted or self.decode_engine.interrupted

    def remaining_requests(self) -> list[Request]:
        """The pair-wide drain/survivors contract (the single-engine
        :meth:`~.engine.ServingEngine.remaining_requests` extended across
        the split): every submitted ORIGINAL not yet completed and not
        deliberately retired on either engine — undelivered arrivals,
        prefilling, held at the handoff, or decoding — exactly once, in
        submission order.  The fleet router re-routes these when a replica
        drains."""
        retired = (self.prefill_engine.sched.retired_uids
                   | self.decode_engine.sched.retired_uids)
        results = self.results
        return [
            r for r in self._pending
            if r.uid not in results and r.uid not in retired
        ]

    def _p_busy(self) -> bool:
        P = self.prefill_engine
        return bool(P.sched.waiting) or any(
            not st.finished for st in P.sched.slots.values()
        )

    def _dst_capacity(self) -> bool:
        P, D = self.prefill_engine, self.decode_engine
        if not P.held or not D.sched.free_slots:
            return False
        req = P.sched.slots[P.held[0]].request
        uid = req.uid
        # speculative decode books the worst-case first verify pass at
        # admission (scheduler.admission_page_need) — the handoff seat must
        # reserve the same headroom or the first verify wedges the pool
        depth = 0
        if D.sched.speculate_k:
            depth = 1 + min(D.sched.speculate_k,
                            self._originals[uid].max_new_tokens - 1)
        need = pages_for(req.prompt_len + depth, D.plugin.page_size)
        if need > D.sched.free_pages:
            return False
        # adapter routing across the split: the decode role must be able to
        # pin the tenant before the transfer seats the slot
        if D.adapters is not None and req.adapter_id:
            return D.adapters.can_pin(req.adapter_id)
        return True

    def report(self) -> dict:
        t = self.transport
        base = getattr(self, "_compile_base", (0, 0))
        return {
            "page_transfers": t.transfers,
            "page_transfer_pages": t.pages_moved,
            "page_transfer_bytes": t.bytes_moved,
            "prefill_steps": self.prefill_engine.steps,
            "decode_steps": self.decode_engine.steps,
            # post-warmup compile events per engine — zero is the contract
            "compiles_prefill": self.prefill_engine.compile_events - base[0],
            "compiles_decode": self.decode_engine.compile_events - base[1],
        }


__all__ = [
    "PagedKVTransport", "DisaggregatedPair", "transfer_accounting",
    "page_bytes",
]
