"""Traffic-replay harness: seeded traces, serving metrics, and the
static-batching baseline.

The bench contract (``bench.py --serve``): replay a **seeded request trace**
(Poisson arrivals in virtual engine-step time, mixed prompt/output lengths)
through a :class:`~.engine.ServingEngine` and ALWAYS emit the serving
fields — tokens/s/chip, p50/p99 per-token latency, KV-pool utilization
(predicted + measured, CheckFreq-style twins), padding-waste fraction, and
scheduler occupancy — zeros when the trace is empty, so BENCH_*.json can
track them across rounds.

The **static-batching baseline** is the CPU-measurable proxy for the
continuous-batching win: it re-runs the same per-request work (actual
prompt and generated lengths from the measured run) through the
fixed-batch schedule ``generate()`` implies — pad every prompt to the
batch max, decode until the LAST sequence finishes, only then start the
next batch — and counts scheduled vs useful token-slots.  Padding waste
and scheduled-token efficiency compare directly; wall-clock tokens/s needs
a chip to differ meaningfully, the slot arithmetic does not.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from .paged_cache import pages_for
from .scheduler import Request


def synthesize_trace(
    seed: int,
    n_requests: int,
    *,
    vocab_size: int = 256,
    mean_interarrival_steps: float = 2.0,
    prompt_len_range: tuple = (4, 24),
    new_tokens_range: tuple = (2, 16),
    adapters: int = 0,
    deadline_range: Optional[tuple] = None,
    prefix_share: float = 0.0,
    shared_prefixes: int = 2,
    shared_prefix_len: int = 0,
) -> list[Request]:
    """A deterministic request trace: Poisson arrivals (exponential gaps in
    virtual engine-step time) with uniformly mixed prompt/output lengths.
    Same seed -> same trace, always (the scheduler-determinism contract).

    With ``adapters=N`` each request draws a tenant ``adapter_id`` uniformly
    from ``0..N`` — id 0 rows serve the base model, so every multi-tenant
    trace mixes no-adapter traffic in (the id-0 bitwise contract's coverage).
    With ``deadline_range=(lo, hi)`` each request draws a per-request
    ``deadline_ticks`` uniformly — the deadline-pressure traffic the
    overload tests replay.

    With ``prefix_share=P`` each request opens, with probability ``P``,
    with one of ``shared_prefixes`` seeded **system preambles** of
    ``shared_prefix_len`` tokens (default: the middle of
    ``prompt_len_range``, so preambles span full pages at the test
    geometries) — the shared-system-prompt traffic mix the prefix cache's
    hit rate is measured on (``bench.py --serve --prefix-share P``).  The
    per-request tail stays unique, so shared traffic still exercises the
    copy-on-write fork.
    """
    rng = np.random.default_rng(seed)
    if prefix_share and not shared_prefix_len:
        shared_prefix_len = (prompt_len_range[0] + prompt_len_range[1]) // 2
    preambles = [
        tuple(int(x) for x in rng.integers(1, vocab_size, shared_prefix_len))
        for _ in range(shared_prefixes if prefix_share else 0)
    ]
    trace = []
    t = 0.0
    for uid in range(n_requests):
        t += rng.exponential(mean_interarrival_steps)
        p_len = int(rng.integers(prompt_len_range[0], prompt_len_range[1] + 1))
        n_new = int(rng.integers(new_tokens_range[0], new_tokens_range[1] + 1))
        prompt = tuple(int(x) for x in rng.integers(1, vocab_size, p_len))
        if preambles and rng.random() < prefix_share:
            pre = preambles[int(rng.integers(0, len(preambles)))]
            prompt = pre + prompt
        adapter_id = int(rng.integers(0, adapters + 1)) if adapters > 0 else 0
        deadline = (int(rng.integers(deadline_range[0], deadline_range[1] + 1))
                    if deadline_range is not None else 0)
        trace.append(Request(uid=uid, prompt=prompt, max_new_tokens=n_new,
                             arrival_step=int(t), adapter_id=adapter_id,
                             deadline_ticks=deadline))
    return trace


def _percentile_ms(samples: list, q: float) -> float:
    if not samples:
        return 0.0
    return round(float(np.percentile(np.asarray(samples), q)) * 1e3, 3)


def predicted_pool_utilization(trace: list[Request], *, num_slots: int,
                               num_pages: int, page_size: int,
                               pages_per_slot: int, prefill_chunk: int) -> float:
    """CheckFreq-style *predicted* twin of the measured KV-pool utilization:
    a model-free replay of the scheduler arithmetic over the trace,
    assuming every request runs to its full ``max_new_tokens`` (the
    prediction error vs the measured twin is exactly the EOS-early-exit
    traffic the trace cannot know about)."""
    if not trace:
        return 0.0
    import dataclasses as _dc

    from .scheduler import ContinuousBatchingScheduler

    sched = ContinuousBatchingScheduler(
        num_slots, num_pages, page_size, pages_per_slot, prefill_chunk,
        (prefill_chunk,),
    )
    # page arithmetic only — adapter routing plays no part in the pool
    # utilization model, so the replay strips tenant ids
    pending = [_dc.replace(r, adapter_id=0)
               for r in sorted(trace, key=lambda r: (r.arrival_step, r.uid))]
    i, steps, page_step_sum = 0, 0, 0
    while True:
        while i < len(pending) and pending[i].arrival_step <= steps:
            sched.submit(pending[i])
            i += 1
        if sched.idle() and i >= len(pending):
            break
        sched.admit()
        action = sched.next_action()
        if action[0] == "prefill":
            slot, start, chunk = action[1], action[2], action[3]
            survived, _ = sched.plan_prefill_evictions(slot, chunk)
            if survived:
                sched.note_prefill(slot, chunk)
                st = sched.slots[slot]
                if st.prefill_done:
                    st.tokens.append(0)
                    if len(st.tokens) >= st.request.max_new_tokens:
                        sched.finish(slot)
        elif action[0] == "decode":
            active, _ = sched.plan_evictions(action[1])
            if active:
                sched.note_decode(sched.decode_page_need(active))
                done = []
                for s in active:
                    st = sched.slots[s]
                    st.tokens.append(0)
                    if len(st.tokens) >= st.request.max_new_tokens:
                        done.append(s)
                for s in done:
                    sched.finish(s)
        page_step_sum += sched.used_pages
        steps += 1
        if steps > 1_000_000:  # pragma: no cover - trace arithmetic safety net
            break
    return round(page_step_sum / max(steps, 1) / num_pages, 4)


class _AnyAdapters:
    """Duck-typed adapter shim for prediction replays: every tenant is
    known, pin-able and free — the replay models PAGE arithmetic, not
    adapter-pool contention, but must keep tenant ids flowing so the
    prefix hash chain stays adapter-keyed (cross-tenant prompts never
    alias)."""

    refcount: dict = {}

    def known(self, tid):
        return True

    def can_pin(self, tid):
        return True

    def pin(self, tid):
        return 0, False

    def unpin(self, tid):
        return None

    def prefetch(self, tid):
        return None


def predicted_prefix_hit_rate(trace: list[Request], *, num_slots: int,
                              num_pages: int, page_size: int,
                              pages_per_slot: int, prefill_chunk: int) -> float:
    """CheckFreq-style *predicted* twin of the measured prefix hit rate: a
    model-free replay of the REAL scheduler arithmetic over the trace (the
    :func:`predicted_pool_utilization` pattern) with a virtual
    :class:`~.prefix_cache.PrefixCache` armed — slot concurrency (two
    identical prompts prefilling at once cannot share), LRU reclaim under
    pool pressure, and eviction churn all replay exactly.  Insertions use
    synthetic page ids (the count arithmetic is what matters; no device
    exists here).  The prediction error vs the measured twin is the
    execution traffic the virtual clock cannot see: EOS early exits
    (requests that finish before their modeled decode length frees pages
    earlier) and fault-injected flushes."""
    if not trace:
        return 0.0
    import dataclasses as _dc

    from .prefix_cache import PrefixCache
    from .scheduler import ContinuousBatchingScheduler

    prefix = PrefixCache(page_size)
    sched = ContinuousBatchingScheduler(
        num_slots, num_pages, page_size, pages_per_slot, prefill_chunk,
        (prefill_chunk,), prefix=prefix,
    )
    sched.adapters = _AnyAdapters()
    pending = [_dc.replace(r, deadline_ticks=0)
               for r in sorted(trace, key=lambda r: (r.arrival_step, r.uid))]
    next_page = [0]

    def insert(st):
        hashes = prefix.block_hashes(st.request.prompt, st.request.adapter_id)
        k = len(st.shared_pages)
        if len(hashes) > k:
            ids = list(range(next_page[0], next_page[0] + len(hashes) - k))
            next_page[0] += len(ids)
            st.shared_pages.extend(prefix.insert_owned(hashes[k:], ids))

    i, steps = 0, 0
    while True:
        sched.tick = steps
        while i < len(pending) and pending[i].arrival_step <= steps:
            sched.submit(pending[i])
            i += 1
        if sched.idle() and i >= len(pending):
            break
        sched.admit()
        prefix.pending_free.clear()  # no device: the push is virtual
        action = sched.next_action()
        if action[0] == "prefill":
            slot, start, chunk = action[1], action[2], action[3]
            survived, _ = sched.plan_prefill_evictions(slot, chunk)
            if survived:
                sched.note_prefill(slot, chunk)
                st = sched.slots[slot]
                if st.prefill_done:
                    insert(st)
                    st.tokens.append(0)
                    if len(st.tokens) >= st.request.max_new_tokens:
                        sched.finish(slot)
        elif action[0] == "decode":
            active, _ = sched.plan_evictions(action[1])
            if active:
                sched.note_decode(sched.decode_page_need(active), active)
                done = []
                for s in active:
                    st = sched.slots[s]
                    st.tokens.append(0)
                    if len(st.tokens) >= st.request.max_new_tokens:
                        done.append(s)
                for s in done:
                    sched.finish(s)
        prefix.pending_free.clear()
        steps += 1
        if steps > 1_000_000:  # pragma: no cover - trace arithmetic safety net
            break
    return prefix.hit_rate()


def replay(engine, trace: list[Request], *, strict_compiles: bool = True,
           slo_monitor=None, verify_invariants: bool = False) -> dict:
    """Run the trace through the engine and compose the serving report.
    Every field is always present (zeros on an empty/idle trace).

    The engine is warmed first (``engine.warmup()`` — every fixed-shape
    program compiles before the clock starts), so the report's CheckFreq
    twins ``compiles_predicted``/``compiles_measured`` count POST-warmup
    compile events: the bucket-ladder contract predicts exactly zero, and a
    measured compile mid-replay is a recompile a production deploy would
    eat under traffic.  With ``strict_compiles`` (default) the harness
    fails its report loudly in that case instead of publishing numbers a
    recompile stall just poisoned.

    Telemetry: the serving twins (KV-pool utilization, adapter-pool hit
    rate, steady-state compiles) are recorded into the central
    :func:`~accelerate_tpu.telemetry.twin_registry`; with the engine's
    request tracing on (``ServingEngine.trace``) the report's
    ``telemetry_overhead_frac``/``trace_spans`` fields are measured (zeros
    otherwise — tracing off costs nothing and changes no token).  Pass an
    :class:`~accelerate_tpu.telemetry.SLOMonitor` as ``slo_monitor`` to
    feed it the replay's per-token latency and TTFT samples.

    Overload/resilience fields ride every report zeros-clean:
    ``requests_shed`` / ``deadline_misses`` / ``cancelled`` /
    ``pages_reclaimed_on_cancel`` / ``request_goodput_frac`` (completed over
    completed + deliberately retired) / ``transfer_retries`` (the adapter
    hot-swap path's absorbed transient failures) / the degradation ladder's
    stage and engagement count.  With ``verify_invariants=True`` the full
    resource contract (:func:`~.overload.verify_serving_invariants`) is
    checked after the run and any violation raises.
    """
    import time

    compiles_warmup = engine.warmup() if not engine.warmed_up else 0
    compiles_before = engine.compile_events
    tracer = getattr(engine, "trace", None)
    overhead_before = tracer.recorder.overhead_s if tracer is not None else 0.0
    sp = getattr(engine, "speculator", None)
    draft_before = sp.draft_time_s if sp is not None else 0.0
    t0 = time.perf_counter()
    results = engine.run(trace)
    wall_s = time.perf_counter() - t0
    compiles_measured = engine.compile_events - compiles_before
    if strict_compiles and compiles_measured > 0:
        raise RuntimeError(
            f"{compiles_measured} compile event(s) fired after warmup during "
            f"the serving replay (warmup compiled {compiles_warmup}): a "
            "mid-traffic recompile — some program shape is not pinned to "
            "the bucket ladder (chase with JAX_LOG_COMPILES=1, or pass "
            "strict_compiles=False to report anyway)"
        )
    if verify_invariants:
        from .overload import verify_serving_invariants

        problems = verify_serving_invariants(engine)
        if problems:
            raise RuntimeError(
                "serving invariants violated after replay: " + "; ".join(problems)
            )
    m = engine.metrics
    p = engine.plugin
    import jax

    n_chips = jax.device_count()
    scheduled = m["scheduled_decode_slots"] + m["prefill_scheduled_tokens"]
    useful = m["useful_decode_tokens"] + m["prefill_useful_tokens"]
    work_steps = m["decode_steps"] + m["verify_steps"] + m["prefill_steps"]
    total_steps = work_steps + m["idle_steps"]
    gen = m["generated_tokens"]
    predicted_util = predicted_pool_utilization(
        trace, num_slots=p.num_slots, num_pages=p.num_pages,
        page_size=p.page_size, pages_per_slot=p.pages_per_slot,
        prefill_chunk=p.prefill_chunk,
    )
    measured_util = round(m["page_step_sum"] / max(total_steps, 1) / p.num_pages, 4)
    # the serving rows of the central twin registry (telemetry/twins.py);
    # bench --serve renders registry.drift_report() as the `twins` block
    from ..telemetry import twin_registry

    reg = twin_registry()
    reg.record("kv_pool.utilization", predicted=predicted_util,
               measured=measured_util, source="serving/harness.replay")
    reg.record("compiles.steady_state", predicted=0,
               measured=compiles_measured, source="serving/harness.replay")
    spec_fields = _speculate_fields(engine, trace, results, wall_s,
                                    draft_before=draft_before)
    if slo_monitor is not None and getattr(engine, "slo", None) is not slo_monitor:
        # a monitor already attached to the engine (attach_slo) saw every
        # sample live — re-feeding it here would double-count quantiles and
        # re-fire trips into the report being assembled
        slo_monitor.observe_many("token_latency_s", engine.token_gaps_s)
        slo_monitor.observe_many("ttft_s", engine.ttft_s)
    # overhead as THIS replay's recording cost over THIS replay's wall (a
    # reused traced engine's earlier overhead must not inflate the ratio)
    overhead_s = (tracer.recorder.overhead_s - overhead_before
                  if tracer is not None else 0.0)
    telemetry_fields = {
        "telemetry_overhead_frac": (
            round(min(1.0, overhead_s / wall_s), 6) if wall_s > 0 else 0.0
        ),
        "trace_spans": tracer.recorder.recorded if tracer is not None else 0,
    }
    return {
        "requests": len(trace),
        "completed": len(results),
        "interrupted": engine.interrupted,
        "prompt_tokens": m["prompt_tokens"],
        "generated_tokens": gen,
        "wall_s": round(wall_s, 4),
        "tokens_per_sec": round(gen / wall_s, 2) if wall_s > 0 else 0.0,
        "tokens_per_sec_per_chip": round(gen / wall_s / n_chips, 2) if wall_s > 0 else 0.0,
        "p50_token_latency_ms": _percentile_ms(engine.token_gaps_s, 50),
        "p99_token_latency_ms": _percentile_ms(engine.token_gaps_s, 99),
        "ttft_p50_ms": _percentile_ms(engine.ttft_s, 50),
        # TTFT in virtual engine ticks — the deterministic twin wall clocks
        # cannot give on CPU (the prefix cache's with/without-reuse
        # comparison pins on this)
        "ttft_p50_ticks": (
            round(float(np.percentile(np.asarray(engine.ttft_ticks), 50)), 2)
            if engine.ttft_ticks else 0.0
        ),
        "kv_pool_utilization": measured_util,
        "kv_pool_utilization_predicted": predicted_util,
        "kv_pool_peak_utilization": round(m["peak_used_pages"] / p.num_pages, 4),
        "padding_waste_frac": round(1.0 - useful / scheduled, 4) if scheduled else 0.0,
        "scheduled_token_efficiency": round(useful / scheduled, 4) if scheduled else 0.0,
        "scheduler_occupancy": round(work_steps / max(total_steps, 1), 4),
        "engine_steps": total_steps,
        "decode_steps": m["decode_steps"],
        "prefill_steps": m["prefill_steps"],
        "idle_steps": m["idle_steps"],
        "evictions": m["evictions"],
        "prefill_buckets": list(p.prefill_buckets),
        "num_slots": p.num_slots,
        # CheckFreq twins for the recompile guard: post-warmup the bucket
        # ladder predicts zero compiles; measured is the monitoring stream
        "compiles_predicted": 0,
        "compiles_measured": compiles_measured,
        "compiles_warmup": compiles_warmup,
        # decode + release + first-token sampler, plus — with speculation —
        # one verify program per bucket and the draft provider's own
        # program, plus — with prefix caching — adopt + push_free + the COW
        # release replacing the plain one (net +2)
        "programs_predicted": len(p.prefill_buckets) + 3 + (
            len(p.speculate_buckets) + engine.speculator.provider.programs
            if engine.speculator is not None else 0
        ) + (2 if engine.prefix is not None else 0),
        **spec_fields,
        # prefix-cache + disaggregation fields — ALWAYS present, zeros when
        # the cache is off / no transport is attached
        **_prefix_fields(engine, trace),
        **telemetry_fields,
        # overload-control + cancellation fields — ALWAYS present, zeros on
        # a clean run (the resilience analog of the goodput block)
        **_overload_fields(engine, trace),
        # multi-tenant adapter fields — ALWAYS present (zeros without an
        # AdapterStore), with the predicted/measured pool-hit-rate twins
        **_adapter_fields(engine, trace),
        "results": results,
    }


def _overload_fields(engine, trace: list[Request]) -> dict:
    """The always-emitted overload/cancellation block of the serving report
    (zeros-clean on a clean run): shed/deadline/cancel counters, pages
    reclaimed by cancellation, request-level goodput (completed over
    completed + deliberately retired), the adapter path's absorbed transfer
    retries, and the degradation ladder's standing.  The serving twins
    record their measured side always; the predicted side is the clean-run
    model (zero faults, goodput 1.0) and is only recorded when no fault
    plan is active — a chaos soak records its own predictions."""
    from ..resilience.faults import active_fault_plan
    from ..telemetry import twin_registry

    sched = engine.sched
    completed = len(engine.results)
    retired = len(sched.retired_uids)
    goodput = (round(completed / (completed + retired), 4)
               if completed + retired else 0.0)
    store = getattr(engine, "adapters", None)
    retries = int(store.stats.transfer_retries) if store is not None else 0
    reg = twin_registry()
    measured = {
        "serving.requests_shed": sched.requests_shed,
        "serving.deadline_misses": sched.deadline_misses,
        "serving.cancelled": sched.cancelled,
        "serving.pages_reclaimed_on_cancel": sched.pages_reclaimed_on_cancel,
        "serving.request_goodput_frac": goodput,
    }
    # the zero-events clean-run model only applies when nothing could
    # legitimately shed or expire: no fault plan, no overload knobs armed,
    # no per-request deadlines in the trace — intended admission-control
    # shedding must never read as a twin "error"
    clean_predictions = (
        active_fault_plan() is None
        and not sched.max_queue and not sched.kv_shed_watermark
        and not sched.default_deadline_ticks and not sched.shed_armed
        and not any(r.deadline_ticks for r in trace)
    )
    for name, value in measured.items():
        reg.record_measured(name, value, source="serving/harness._overload_fields")
        if clean_predictions:
            pred = (1.0 if name.endswith("request_goodput_frac") and trace
                    else 0.0)
            reg.record_predicted(name, pred,
                                 source="serving/harness clean-run model")
    return {
        "requests_shed": sched.requests_shed,
        "deadline_misses": sched.deadline_misses,
        "cancelled": sched.cancelled,
        "pages_reclaimed_on_cancel": sched.pages_reclaimed_on_cancel,
        "request_goodput_frac": goodput,
        "transfer_retries": retries,
        "ladder_stage": engine.ladder.stage,
        "ladder_engagements": engine.ladder.engagements,
    }


def _prefix_fields(engine, trace: list[Request]) -> dict:
    """The always-emitted prefix-cache block of the serving report
    (zeros-clean with the cache off — the idle contract):

    - ``prefix_hit_rate`` — index-served cacheable pages over cacheable
      pages demanded at admission, counted once per request (measured),
      with the ``_predicted`` twin from the model-free scheduler replay
      (:func:`predicted_prefix_hit_rate` — concurrency and LRU reclaim
      modeled exactly; the prediction error is EOS-early-exit and
      fault-flush traffic the virtual clock cannot see);
    - ``pages_shared_peak`` — peak physical pages aliased by > 1 holder;
    - ``cow_forks`` — admissions that shared a proper prefix then wrote
      their own divergent pages;
    - ``prefill_tokens_skipped`` — prompt tokens never recomputed;
    - ``page_transfer_bytes`` (+pages/transfers) — the disaggregation
      slice's measured wire bytes (``transfer.page_bytes`` twin; zero
      unless a :class:`~.transfer.PagedKVTransport` streamed this engine).
    """
    m = engine.metrics
    prefix = getattr(engine, "prefix", None)
    fields = {
        "prefix_cache": "on" if prefix is not None else "off",
        "prefix_hit_rate": 0.0,
        "prefix_hit_rate_predicted": 0.0,
        "pages_shared_peak": 0,
        "cow_forks": 0,
        "prefill_tokens_skipped": 0,
        "prefix_evictions": 0,
        "page_transfers": m["page_transfers"],
        "page_transfer_pages": m["page_transfer_pages"],
        "page_transfer_bytes": m["page_transfer_bytes"],
    }
    if prefix is None:
        return fields
    from ..telemetry import twin_registry

    rep = prefix.report()
    fields.update(
        prefix_hit_rate=rep["prefix_hit_rate"],
        pages_shared_peak=rep["pages_shared_peak"],
        cow_forks=rep["cow_forks"],
        prefill_tokens_skipped=rep["prefill_tokens_skipped"],
        prefix_evictions=rep["prefix_evictions"],
    )
    p = engine.plugin
    predicted = predicted_prefix_hit_rate(
        trace, num_slots=p.num_slots, num_pages=p.num_pages,
        page_size=p.page_size, pages_per_slot=p.pages_per_slot,
        prefill_chunk=p.prefill_chunk,
    )
    fields["prefix_hit_rate_predicted"] = predicted
    twin_registry().record(
        "prefix_cache.hit_rate", predicted=predicted,
        measured=rep["prefix_hit_rate"],
        source="serving/harness._prefix_fields",
    )
    return fields


def _speculate_fields(engine, trace: list[Request], results: dict,
                      wall_s: float, draft_before: float = 0.0) -> dict:
    """The always-emitted speculative-decode block of the serving report
    (zeros-clean when speculation is off or the trace is idle):

    - ``accept_rate`` — accepted drafts / drafted tokens (measured), with
      the ``_predicted`` twin from the model-free trace replay
      (:func:`~.speculate.predicted_acceptance` over the MEASURED streams —
      the prediction error is the eviction/recompute re-decode traffic).
      The replay only runs for host-side providers (``provider.programs ==
      0``): replaying a draft MODEL would re-run the whole decode at batch
      1 on device just to fill a report field, so the draft-model twin
      stays idle (measured side only);
    - ``tokens_per_step`` — decode tokens emitted per slot per
      decode/verify pass (exactly 1.0 for plain decode; > 1.0 is the
      speculative win), same predicted twin;
    - ``draft_overhead_frac`` — THIS replay's host drafting time over its
      wall clock (``draft_before`` anchors the delta: a reused warmed
      engine's earlier drafting must not inflate the ratio);
    - ``speculative_rollbacks`` — pages rolled back off rejected drafts.

    Both twins are recorded into the central registry
    (``speculate.accept_rate`` / ``speculate.tokens_per_step``)."""
    m = engine.metrics
    lanes = m["decode_lane_passes"]
    measured_tps = round(m["decode_emitted_tokens"] / lanes, 4) if lanes else 0.0
    drafted = m["draft_tokens"]
    measured_accept = round(m["accepted_draft_tokens"] / drafted, 4) if drafted else 0.0
    sp = engine.speculator
    fields = {
        "speculate": engine.speculate_mode,
        "speculate_k": sp.k if sp is not None else 0,
        "accept_rate": measured_accept,
        "accept_rate_predicted": 0.0,
        "tokens_per_step": measured_tps,
        "tokens_per_step_predicted": 0.0,
        "draft_overhead_frac": 0.0,
        "speculative_rollbacks": m["speculative_rollbacks"],
        "verify_steps": m["verify_steps"],
        "drafted_tokens": drafted,
        "accepted_draft_tokens": m["accepted_draft_tokens"],
    }
    if sp is None:
        return fields
    from ..telemetry import twin_registry

    from .speculate import predicted_acceptance

    draft_s = sp.draft_time_s - draft_before
    fields["draft_overhead_frac"] = (
        round(min(1.0, draft_s / wall_s), 6) if wall_s > 0 else 0.0
    )
    reg = twin_registry()
    if sp.provider.programs == 0:  # model-free drafting: the replay is free
        pred = predicted_acceptance(trace, results, sp.provider, sp.k)
        fields["accept_rate_predicted"] = pred["accept_rate"]
        fields["tokens_per_step_predicted"] = pred["tokens_per_step"]
        reg.record("speculate.accept_rate", predicted=pred["accept_rate"],
                   measured=measured_accept,
                   source="serving/harness._speculate_fields")
        reg.record("speculate.tokens_per_step",
                   predicted=pred["tokens_per_step"], measured=measured_tps,
                   source="serving/harness._speculate_fields")
    else:
        reg.record("speculate.accept_rate", measured=measured_accept,
                   source="serving/harness._speculate_fields")
        reg.record("speculate.tokens_per_step", measured=measured_tps,
                   source="serving/harness._speculate_fields")
    return fields


def _adapter_fields(engine, trace: list[Request]) -> dict:
    """The always-emitted multi-tenant block of the serving report: pool
    hit rate (measured + the LRU-replay predicted twin), swap count/bytes,
    and the tenant census of the trace.  Zeros-clean when the engine runs
    single-tenant."""
    store = getattr(engine, "adapters", None)
    tenant_ids = [r.adapter_id for r in sorted(trace, key=lambda r: (r.arrival_step, r.uid))]
    if store is None:
        return {
            "adapters": 0, "adapter_requests": 0,
            "adapter_pool_slots": 0, "lora_rank": 0,
            "adapter_pool_hit_rate": 0.0,
            "adapter_pool_hit_rate_predicted": 0.0,
            "adapter_swaps": 0, "adapter_swap_bytes": 0,
        }
    from ..telemetry import twin_registry
    from .adapters import predicted_adapter_hit_rate

    predicted_hit = predicted_adapter_hit_rate(tenant_ids, store.plugin.pool_slots)
    twin_registry().record(
        "adapter_pool.hit_rate", predicted=predicted_hit,
        measured=store.hit_rate(), source="serving/harness._adapter_fields",
    )
    return {
        "adapters": len({t for t in tenant_ids if t}),
        "adapter_requests": sum(1 for t in tenant_ids if t),
        "adapter_pool_slots": store.plugin.pool_slots,
        "lora_rank": store.plugin.rank,
        "adapter_pool_hit_rate": store.hit_rate(),
        "adapter_pool_hit_rate_predicted": predicted_hit,
        "adapter_swaps": store.swaps,
        "adapter_swap_bytes": store.swap_bytes,
    }


def chaos_replay(engine_factory: Callable[[], object], trace: list[Request],
                 plan, *, max_restarts: int = 8, verify_invariants: bool = True,
                 strict_compiles: bool = True,
                 baseline_parity: bool = True) -> dict:
    """Seeded chaos soak: replay ``trace`` under a
    :class:`~accelerate_tpu.resilience.FaultPlan` of serving faults
    (cancellation storms, deadline storms, adapter-transfer failures,
    preempt-at-tick / preempt-mid-verify), restarting a fresh engine after
    every drain, until the traffic is fully disposed of (completed, shed or
    cancelled).

    The acceptance pin this function exists for: **surviving requests'
    greedy tokens are BITWISE identical to a fault-free replay of the same
    surviving set** — faults may change *which* requests complete, never
    *what* a completed request says.  After every engine (drained or done)
    the full resource contract runs
    (:func:`~.overload.verify_serving_invariants` — free-page mirror exact,
    zero leaked pages, adapter refcounts balanced), and post-warmup compile
    events stay at zero per engine (``strict_compiles``) — a fault must
    never push the engine off its warmed program set.

    ``engine_factory`` builds a fresh engine per life (the process-shared
    jit cache makes restarts cheap).  Returns the soak report: surviving
    ``results``, ``token_parity``, restart/fault/retirement counters, and
    ``invariant_problems`` (empty on a healthy engine).
    """
    import dataclasses as _dc

    from ..resilience.faults import fault_plan as _fault_plan
    from .overload import verify_serving_invariants

    results: dict[int, list] = {}
    restarts = 0
    compiles_measured = 0
    invariant_problems: list[str] = []
    counters = {"requests_shed": 0, "deadline_misses": 0, "cancelled": 0,
                "pages_reclaimed_on_cancel": 0, "transfer_retries": 0}
    pending = [_dc.replace(r) for r in
               sorted(trace, key=lambda r: (r.arrival_step, r.uid))]
    with _fault_plan(plan):
        while pending:
            engine = engine_factory()
            engine.warmup()
            before = engine.compile_events
            engine.run(pending)
            compiles_measured += engine.compile_events - before
            results.update(engine.results)
            sched = engine.sched
            counters["requests_shed"] += sched.requests_shed
            counters["deadline_misses"] += sched.deadline_misses
            counters["cancelled"] += sched.cancelled
            counters["pages_reclaimed_on_cancel"] += sched.pages_reclaimed_on_cancel
            store = getattr(engine, "adapters", None)
            if store is not None:
                counters["transfer_retries"] += int(store.stats.transfer_retries)
            if verify_invariants:
                invariant_problems.extend(verify_serving_invariants(engine))
            if not engine.interrupted:
                break
            # drained: a fresh engine serves the remainder (arrivals rebased
            # — the drain consumed the virtual clock the originals were
            # keyed on; relative order is preserved by uid)
            pending = [_dc.replace(r, arrival_step=0)
                       for r in engine.remaining_requests()]
            restarts += 1
            if restarts > max_restarts:
                raise RuntimeError(
                    f"chaos replay exceeded {max_restarts} restarts with "
                    f"{len(pending)} requests still pending"
                )
    if strict_compiles and compiles_measured > 0:
        raise RuntimeError(
            f"{compiles_measured} post-warmup compile event(s) during the "
            "chaos soak: a fault pushed the engine off its warmed program set"
        )
    if invariant_problems:
        raise RuntimeError(
            "serving invariants violated during the chaos soak: "
            + "; ".join(invariant_problems)
        )
    token_parity = True
    if baseline_parity and results:
        # fault-free replay of the SAME surviving set (no plan installed):
        # deadlines dropped — the baseline measures what the survivors SAY,
        # and a deadline re-expiring in the quieter baseline schedule would
        # change which requests complete, not their tokens
        survivors = [
            _dc.replace(r, arrival_step=0, deadline_ticks=0)
            for r in sorted(trace, key=lambda r: r.uid) if r.uid in results
        ]
        baseline = engine_factory()
        # the baseline must serve the surviving set UNCONDITIONALLY: its
        # admission controls disarm, because a bounded queue, a pressure
        # watermark or a default deadline would shed/expire survivors the
        # chaos run completed (all rebased to arrival 0) and fail the
        # parity pin spuriously — the pin is about tokens, not policy
        baseline.sched.max_queue = 0
        baseline.sched.kv_shed_watermark = 0.0
        baseline.sched.default_deadline_ticks = 0
        baseline.warmup()
        base_results = baseline.run(survivors)
        token_parity = base_results == results
    from ..telemetry import twin_registry

    total = len(trace)
    twin_registry().record_measured(
        "serving.request_goodput_frac",
        round(len(results) / total, 4) if total else 0.0,
        source="serving/harness.chaos_replay",
    )
    return {
        "requests": total,
        "completed": len(results),
        "survivor_frac": round(len(results) / total, 4) if total else 0.0,
        "restarts": restarts,
        "faults_fired": len(plan.fired),
        "compiles_measured": compiles_measured,
        "token_parity": token_parity,
        "invariant_problems": invariant_problems,
        **counters,
        "results": results,
    }


def static_batching_report(per_request: list, num_slots: int) -> dict:
    """Slot-arithmetic for the fixed-batch schedule ``generate()`` implies.

    ``per_request``: ``(prompt_len, generated_len)`` pairs in arrival order
    — use the MEASURED lengths from the continuous run so both schedules
    account identical work.  Batches of ``num_slots`` run start-to-finish:
    prompts pad to the batch max, decode runs until the batch's longest
    generation finishes.  Every batch is the full ``num_slots`` wide — both
    schedules drive the SAME fixed-shape jitted decode program (the shape-
    bucket contract); static batching just cannot refill a lane until the
    whole batch retires.
    """
    if not per_request:
        return {"padding_waste_frac": 0.0, "scheduled_token_efficiency": 0.0,
                "scheduled_token_slots": 0, "useful_tokens": 0, "batches": 0}
    scheduled = useful = 0
    batches = [per_request[i:i + num_slots] for i in range(0, len(per_request), num_slots)]
    for batch in batches:
        max_prompt = max(p for p, _ in batch)
        max_gen = max(g for _, g in batch)
        scheduled += (max_prompt + max_gen) * num_slots
        useful += sum(p + g for p, g in batch)
    return {
        "padding_waste_frac": round(1.0 - useful / scheduled, 4),
        "scheduled_token_efficiency": round(useful / scheduled, 4),
        "scheduled_token_slots": scheduled,
        "useful_tokens": useful,
        "batches": len(batches),
    }
