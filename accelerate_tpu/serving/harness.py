"""Traffic-replay harness: seeded traces, serving metrics, and the
static-batching baseline.

The bench contract (``bench.py --serve``): replay a **seeded request trace**
(Poisson arrivals in virtual engine-step time, mixed prompt/output lengths)
through a :class:`~.engine.ServingEngine` and ALWAYS emit the serving
fields — tokens/s/chip, p50/p99 per-token latency, KV-pool utilization
(predicted + measured, CheckFreq-style twins), padding-waste fraction, and
scheduler occupancy — zeros when the trace is empty, so BENCH_*.json can
track them across rounds.

The **static-batching baseline** is the CPU-measurable proxy for the
continuous-batching win: it re-runs the same per-request work (actual
prompt and generated lengths from the measured run) through the
fixed-batch schedule ``generate()`` implies — pad every prompt to the
batch max, decode until the LAST sequence finishes, only then start the
next batch — and counts scheduled vs useful token-slots.  Padding waste
and scheduled-token efficiency compare directly; wall-clock tokens/s needs
a chip to differ meaningfully, the slot arithmetic does not.
"""

from __future__ import annotations

import numpy as np

from .paged_cache import pages_for
from .scheduler import Request


def synthesize_trace(
    seed: int,
    n_requests: int,
    *,
    vocab_size: int = 256,
    mean_interarrival_steps: float = 2.0,
    prompt_len_range: tuple = (4, 24),
    new_tokens_range: tuple = (2, 16),
    adapters: int = 0,
) -> list[Request]:
    """A deterministic request trace: Poisson arrivals (exponential gaps in
    virtual engine-step time) with uniformly mixed prompt/output lengths.
    Same seed -> same trace, always (the scheduler-determinism contract).

    With ``adapters=N`` each request draws a tenant ``adapter_id`` uniformly
    from ``0..N`` — id 0 rows serve the base model, so every multi-tenant
    trace mixes no-adapter traffic in (the id-0 bitwise contract's coverage).
    """
    rng = np.random.default_rng(seed)
    trace = []
    t = 0.0
    for uid in range(n_requests):
        t += rng.exponential(mean_interarrival_steps)
        p_len = int(rng.integers(prompt_len_range[0], prompt_len_range[1] + 1))
        n_new = int(rng.integers(new_tokens_range[0], new_tokens_range[1] + 1))
        prompt = tuple(int(x) for x in rng.integers(1, vocab_size, p_len))
        adapter_id = int(rng.integers(0, adapters + 1)) if adapters > 0 else 0
        trace.append(Request(uid=uid, prompt=prompt, max_new_tokens=n_new,
                             arrival_step=int(t), adapter_id=adapter_id))
    return trace


def _percentile_ms(samples: list, q: float) -> float:
    if not samples:
        return 0.0
    return round(float(np.percentile(np.asarray(samples), q)) * 1e3, 3)


def predicted_pool_utilization(trace: list[Request], *, num_slots: int,
                               num_pages: int, page_size: int,
                               pages_per_slot: int, prefill_chunk: int) -> float:
    """CheckFreq-style *predicted* twin of the measured KV-pool utilization:
    a model-free replay of the scheduler arithmetic over the trace,
    assuming every request runs to its full ``max_new_tokens`` (the
    prediction error vs the measured twin is exactly the EOS-early-exit
    traffic the trace cannot know about)."""
    if not trace:
        return 0.0
    import dataclasses as _dc

    from .scheduler import ContinuousBatchingScheduler

    sched = ContinuousBatchingScheduler(
        num_slots, num_pages, page_size, pages_per_slot, prefill_chunk,
        (prefill_chunk,),
    )
    # page arithmetic only — adapter routing plays no part in the pool
    # utilization model, so the replay strips tenant ids
    pending = [_dc.replace(r, adapter_id=0)
               for r in sorted(trace, key=lambda r: (r.arrival_step, r.uid))]
    i, steps, page_step_sum = 0, 0, 0
    while True:
        while i < len(pending) and pending[i].arrival_step <= steps:
            sched.submit(pending[i])
            i += 1
        if sched.idle() and i >= len(pending):
            break
        sched.admit()
        action = sched.next_action()
        if action[0] == "prefill":
            slot, start, chunk = action[1], action[2], action[3]
            survived, _ = sched.plan_prefill_evictions(slot, chunk)
            if survived:
                sched.note_prefill(slot, chunk)
                st = sched.slots[slot]
                if st.prefill_done:
                    st.tokens.append(0)
                    if len(st.tokens) >= st.request.max_new_tokens:
                        sched.finish(slot)
        elif action[0] == "decode":
            active, _ = sched.plan_evictions(action[1])
            if active:
                sched.note_decode(sched.decode_page_need(active))
                done = []
                for s in active:
                    st = sched.slots[s]
                    st.tokens.append(0)
                    if len(st.tokens) >= st.request.max_new_tokens:
                        done.append(s)
                for s in done:
                    sched.finish(s)
        page_step_sum += sched.used_pages
        steps += 1
        if steps > 1_000_000:  # pragma: no cover - trace arithmetic safety net
            break
    return round(page_step_sum / max(steps, 1) / num_pages, 4)


def replay(engine, trace: list[Request], *, strict_compiles: bool = True,
           slo_monitor=None) -> dict:
    """Run the trace through the engine and compose the serving report.
    Every field is always present (zeros on an empty/idle trace).

    The engine is warmed first (``engine.warmup()`` — every fixed-shape
    program compiles before the clock starts), so the report's CheckFreq
    twins ``compiles_predicted``/``compiles_measured`` count POST-warmup
    compile events: the bucket-ladder contract predicts exactly zero, and a
    measured compile mid-replay is a recompile a production deploy would
    eat under traffic.  With ``strict_compiles`` (default) the harness
    fails its report loudly in that case instead of publishing numbers a
    recompile stall just poisoned.

    Telemetry: the serving twins (KV-pool utilization, adapter-pool hit
    rate, steady-state compiles) are recorded into the central
    :func:`~accelerate_tpu.telemetry.twin_registry`; with the engine's
    request tracing on (``ServingEngine.trace``) the report's
    ``telemetry_overhead_frac``/``trace_spans`` fields are measured (zeros
    otherwise — tracing off costs nothing and changes no token).  Pass an
    :class:`~accelerate_tpu.telemetry.SLOMonitor` as ``slo_monitor`` to
    feed it the replay's per-token latency and TTFT samples.
    """
    import time

    compiles_warmup = engine.warmup() if not engine.warmed_up else 0
    compiles_before = engine.compile_events
    tracer = getattr(engine, "trace", None)
    overhead_before = tracer.recorder.overhead_s if tracer is not None else 0.0
    sp = getattr(engine, "speculator", None)
    draft_before = sp.draft_time_s if sp is not None else 0.0
    t0 = time.perf_counter()
    results = engine.run(trace)
    wall_s = time.perf_counter() - t0
    compiles_measured = engine.compile_events - compiles_before
    if strict_compiles and compiles_measured > 0:
        raise RuntimeError(
            f"{compiles_measured} compile event(s) fired after warmup during "
            f"the serving replay (warmup compiled {compiles_warmup}): a "
            "mid-traffic recompile — some program shape is not pinned to "
            "the bucket ladder (chase with JAX_LOG_COMPILES=1, or pass "
            "strict_compiles=False to report anyway)"
        )
    m = engine.metrics
    p = engine.plugin
    import jax

    n_chips = jax.device_count()
    scheduled = m["scheduled_decode_slots"] + m["prefill_scheduled_tokens"]
    useful = m["useful_decode_tokens"] + m["prefill_useful_tokens"]
    work_steps = m["decode_steps"] + m["verify_steps"] + m["prefill_steps"]
    total_steps = work_steps + m["idle_steps"]
    gen = m["generated_tokens"]
    predicted_util = predicted_pool_utilization(
        trace, num_slots=p.num_slots, num_pages=p.num_pages,
        page_size=p.page_size, pages_per_slot=p.pages_per_slot,
        prefill_chunk=p.prefill_chunk,
    )
    measured_util = round(m["page_step_sum"] / max(total_steps, 1) / p.num_pages, 4)
    # the serving rows of the central twin registry (telemetry/twins.py);
    # bench --serve renders registry.drift_report() as the `twins` block
    from ..telemetry import twin_registry

    reg = twin_registry()
    reg.record("kv_pool.utilization", predicted=predicted_util,
               measured=measured_util, source="serving/harness.replay")
    reg.record("compiles.steady_state", predicted=0,
               measured=compiles_measured, source="serving/harness.replay")
    spec_fields = _speculate_fields(engine, trace, results, wall_s,
                                    draft_before=draft_before)
    if slo_monitor is not None:
        slo_monitor.observe_many("token_latency_s", engine.token_gaps_s)
        slo_monitor.observe_many("ttft_s", engine.ttft_s)
    # overhead as THIS replay's recording cost over THIS replay's wall (a
    # reused traced engine's earlier overhead must not inflate the ratio)
    overhead_s = (tracer.recorder.overhead_s - overhead_before
                  if tracer is not None else 0.0)
    telemetry_fields = {
        "telemetry_overhead_frac": (
            round(min(1.0, overhead_s / wall_s), 6) if wall_s > 0 else 0.0
        ),
        "trace_spans": tracer.recorder.recorded if tracer is not None else 0,
    }
    return {
        "requests": len(trace),
        "completed": len(results),
        "interrupted": engine.interrupted,
        "prompt_tokens": m["prompt_tokens"],
        "generated_tokens": gen,
        "wall_s": round(wall_s, 4),
        "tokens_per_sec": round(gen / wall_s, 2) if wall_s > 0 else 0.0,
        "tokens_per_sec_per_chip": round(gen / wall_s / n_chips, 2) if wall_s > 0 else 0.0,
        "p50_token_latency_ms": _percentile_ms(engine.token_gaps_s, 50),
        "p99_token_latency_ms": _percentile_ms(engine.token_gaps_s, 99),
        "ttft_p50_ms": _percentile_ms(engine.ttft_s, 50),
        "kv_pool_utilization": measured_util,
        "kv_pool_utilization_predicted": predicted_util,
        "kv_pool_peak_utilization": round(m["peak_used_pages"] / p.num_pages, 4),
        "padding_waste_frac": round(1.0 - useful / scheduled, 4) if scheduled else 0.0,
        "scheduled_token_efficiency": round(useful / scheduled, 4) if scheduled else 0.0,
        "scheduler_occupancy": round(work_steps / max(total_steps, 1), 4),
        "engine_steps": total_steps,
        "decode_steps": m["decode_steps"],
        "prefill_steps": m["prefill_steps"],
        "idle_steps": m["idle_steps"],
        "evictions": m["evictions"],
        "prefill_buckets": list(p.prefill_buckets),
        "num_slots": p.num_slots,
        # CheckFreq twins for the recompile guard: post-warmup the bucket
        # ladder predicts zero compiles; measured is the monitoring stream
        "compiles_predicted": 0,
        "compiles_measured": compiles_measured,
        "compiles_warmup": compiles_warmup,
        # decode + release + first-token sampler, plus — with speculation —
        # one verify program per bucket and the draft provider's own program
        "programs_predicted": len(p.prefill_buckets) + 3 + (
            len(p.speculate_buckets) + engine.speculator.provider.programs
            if engine.speculator is not None else 0
        ),
        **spec_fields,
        **telemetry_fields,
        # multi-tenant adapter fields — ALWAYS present (zeros without an
        # AdapterStore), with the predicted/measured pool-hit-rate twins
        **_adapter_fields(engine, trace),
        "results": results,
    }


def _speculate_fields(engine, trace: list[Request], results: dict,
                      wall_s: float, draft_before: float = 0.0) -> dict:
    """The always-emitted speculative-decode block of the serving report
    (zeros-clean when speculation is off or the trace is idle):

    - ``accept_rate`` — accepted drafts / drafted tokens (measured), with
      the ``_predicted`` twin from the model-free trace replay
      (:func:`~.speculate.predicted_acceptance` over the MEASURED streams —
      the prediction error is the eviction/recompute re-decode traffic).
      The replay only runs for host-side providers (``provider.programs ==
      0``): replaying a draft MODEL would re-run the whole decode at batch
      1 on device just to fill a report field, so the draft-model twin
      stays idle (measured side only);
    - ``tokens_per_step`` — decode tokens emitted per slot per
      decode/verify pass (exactly 1.0 for plain decode; > 1.0 is the
      speculative win), same predicted twin;
    - ``draft_overhead_frac`` — THIS replay's host drafting time over its
      wall clock (``draft_before`` anchors the delta: a reused warmed
      engine's earlier drafting must not inflate the ratio);
    - ``speculative_rollbacks`` — pages rolled back off rejected drafts.

    Both twins are recorded into the central registry
    (``speculate.accept_rate`` / ``speculate.tokens_per_step``)."""
    m = engine.metrics
    lanes = m["decode_lane_passes"]
    measured_tps = round(m["decode_emitted_tokens"] / lanes, 4) if lanes else 0.0
    drafted = m["draft_tokens"]
    measured_accept = round(m["accepted_draft_tokens"] / drafted, 4) if drafted else 0.0
    sp = engine.speculator
    fields = {
        "speculate": engine.speculate_mode,
        "speculate_k": sp.k if sp is not None else 0,
        "accept_rate": measured_accept,
        "accept_rate_predicted": 0.0,
        "tokens_per_step": measured_tps,
        "tokens_per_step_predicted": 0.0,
        "draft_overhead_frac": 0.0,
        "speculative_rollbacks": m["speculative_rollbacks"],
        "verify_steps": m["verify_steps"],
        "drafted_tokens": drafted,
        "accepted_draft_tokens": m["accepted_draft_tokens"],
    }
    if sp is None:
        return fields
    from ..telemetry import twin_registry

    from .speculate import predicted_acceptance

    draft_s = sp.draft_time_s - draft_before
    fields["draft_overhead_frac"] = (
        round(min(1.0, draft_s / wall_s), 6) if wall_s > 0 else 0.0
    )
    reg = twin_registry()
    if sp.provider.programs == 0:  # model-free drafting: the replay is free
        pred = predicted_acceptance(trace, results, sp.provider, sp.k)
        fields["accept_rate_predicted"] = pred["accept_rate"]
        fields["tokens_per_step_predicted"] = pred["tokens_per_step"]
        reg.record("speculate.accept_rate", predicted=pred["accept_rate"],
                   measured=measured_accept,
                   source="serving/harness._speculate_fields")
        reg.record("speculate.tokens_per_step",
                   predicted=pred["tokens_per_step"], measured=measured_tps,
                   source="serving/harness._speculate_fields")
    else:
        reg.record("speculate.accept_rate", measured=measured_accept,
                   source="serving/harness._speculate_fields")
        reg.record("speculate.tokens_per_step", measured=measured_tps,
                   source="serving/harness._speculate_fields")
    return fields


def _adapter_fields(engine, trace: list[Request]) -> dict:
    """The always-emitted multi-tenant block of the serving report: pool
    hit rate (measured + the LRU-replay predicted twin), swap count/bytes,
    and the tenant census of the trace.  Zeros-clean when the engine runs
    single-tenant."""
    store = getattr(engine, "adapters", None)
    tenant_ids = [r.adapter_id for r in sorted(trace, key=lambda r: (r.arrival_step, r.uid))]
    if store is None:
        return {
            "adapters": 0, "adapter_requests": 0,
            "adapter_pool_slots": 0, "lora_rank": 0,
            "adapter_pool_hit_rate": 0.0,
            "adapter_pool_hit_rate_predicted": 0.0,
            "adapter_swaps": 0, "adapter_swap_bytes": 0,
        }
    from ..telemetry import twin_registry
    from .adapters import predicted_adapter_hit_rate

    predicted_hit = predicted_adapter_hit_rate(tenant_ids, store.plugin.pool_slots)
    twin_registry().record(
        "adapter_pool.hit_rate", predicted=predicted_hit,
        measured=store.hit_rate(), source="serving/harness._adapter_fields",
    )
    return {
        "adapters": len({t for t in tenant_ids if t}),
        "adapter_requests": sum(1 for t in tenant_ids if t),
        "adapter_pool_slots": store.plugin.pool_slots,
        "lora_rank": store.plugin.rank,
        "adapter_pool_hit_rate": store.hit_rate(),
        "adapter_pool_hit_rate_predicted": predicted_hit,
        "adapter_swaps": store.swaps,
        "adapter_swap_bytes": store.swap_bytes,
    }


def static_batching_report(per_request: list, num_slots: int) -> dict:
    """Slot-arithmetic for the fixed-batch schedule ``generate()`` implies.

    ``per_request``: ``(prompt_len, generated_len)`` pairs in arrival order
    — use the MEASURED lengths from the continuous run so both schedules
    account identical work.  Batches of ``num_slots`` run start-to-finish:
    prompts pad to the batch max, decode runs until the batch's longest
    generation finishes.  Every batch is the full ``num_slots`` wide — both
    schedules drive the SAME fixed-shape jitted decode program (the shape-
    bucket contract); static batching just cannot refill a lane until the
    whole batch retires.
    """
    if not per_request:
        return {"padding_waste_frac": 0.0, "scheduled_token_efficiency": 0.0,
                "scheduled_token_slots": 0, "useful_tokens": 0, "batches": 0}
    scheduled = useful = 0
    batches = [per_request[i:i + num_slots] for i in range(0, len(per_request), num_slots)]
    for batch in batches:
        max_prompt = max(p for p, _ in batch)
        max_gen = max(g for _, g in batch)
        scheduled += (max_prompt + max_gen) * num_slots
        useful += sum(p + g for p, g in batch)
    return {
        "padding_waste_frac": round(1.0 - useful / scheduled, 4),
        "scheduled_token_efficiency": round(useful / scheduled, 4),
        "scheduled_token_slots": scheduled,
        "useful_tokens": useful,
        "batches": len(batches),
    }
