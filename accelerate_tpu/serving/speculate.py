"""Speculative multi-token decode: draft providers + acceptance arithmetic.

Decode emits one token per verify pass in the base engine; speculative
decoding (Leviathan et al.-style draft-and-verify) proposes ``k`` candidate
tokens per slot and runs ONE fixed-shape batched verify pass of width
``k + 1`` through the paged cache, accepting the longest greedy-matching
prefix.  Greedily accepted tokens are BITWISE identical to what sequential
single-token decode would have produced — the existing ``generate()``
token-parity pin extends rather than weakens (tests/test_speculate.py).

Two draft providers:

- :class:`NgramDraft` — prompt-lookup / n-gram self-drafting.  Pure
  host-side and model-free: the slot's context (prompt + emitted tokens) is
  searched for the most recent earlier occurrence of its own trailing
  n-gram, and the tokens that followed that occurrence become the proposal.
  Zero extra device programs, zero extra weights; the draft cost is host
  string-matching (measured into ``draft_overhead_frac``).
- :class:`DraftModelDraft` — a small draft model proposes ``k`` tokens
  greedily from a fixed context window through ONE jitted fixed-shape
  forward (no draft KV cache to keep in sync with eviction/rollback), so
  ``strict_compiles`` still holds after :meth:`DraftModelDraft.warmup`.

Rejected drafts cost nothing but the verify lane they rode in: the verify
program rolls speculatively-consumed pages back onto the functional
free-list (``paged_cache.push_pages``) and the host mirror stays exact via
per-slot accepted-length bookkeeping (``scheduler.note_verify``).

:func:`predicted_acceptance` is the CheckFreq-style predicted twin: a
model-free replay of the draft-and-verify arithmetic over the MEASURED
token streams (greedy target tokens ARE the final stream, so per-pass
acceptance is computable from the streams + the drafting algorithm alone).
The prediction error vs the measured twin is the eviction/recompute
traffic the replay cannot know about.
"""

from __future__ import annotations

import time
from functools import lru_cache
from typing import Optional, Sequence

import numpy as np


class NgramDraft:
    """Prompt-lookup self-drafting (host-side, no extra model).

    For each slot, the trailing ``n``-gram of the context (``max_ngram``
    down to ``min_ngram``) is searched for its most recent earlier
    occurrence; the up-to-``k`` tokens that followed that occurrence are the
    proposal.  Deterministic: same context -> same drafts, always (the
    scheduler-determinism contract extends through drafting).  ``window``
    bounds the backward search so drafting stays O(window) per slot on
    arbitrarily long contexts.
    """

    name = "ngram"
    programs = 0  # host-side: no compiled draft program

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 window: int = 512):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.window = window

    def propose_one(self, context: Sequence[int], k: int) -> list:
        ctx = list(context)[-self.window:]
        n_ctx = len(ctx)
        for n in range(min(self.max_ngram, n_ctx - 1), self.min_ngram - 1, -1):
            tail = ctx[n_ctx - n:]
            best: list = []
            # most recent match with a FULL k-token continuation wins;
            # otherwise the longest continuation seen (a trailing cycle's
            # matches near the end are cut short by the context boundary)
            for i in range(n_ctx - n - 1, -1, -1):
                if ctx[i:i + n] == tail:
                    cont = ctx[i + n:i + n + k]
                    if len(cont) == k:
                        return cont
                    if len(cont) > len(best):
                        best = cont
            if best:
                return best
        return []

    def propose(self, contexts: list, k: int,
                adapter_ids=None) -> tuple[np.ndarray, np.ndarray]:
        """Batched proposal: ``(drafts [n, k] int32, draft_lens [n])``.
        Slots with no n-gram hit draft nothing (their verify lane
        degenerates to plain single-token decode).  ``adapter_ids`` is
        accepted for interface parity with the draft-model provider — an
        n-gram over the slot's own context is already tenant-specific."""
        n = len(contexts)
        drafts = np.zeros((n, k), np.int32)
        lens = np.zeros((n,), np.int32)
        for i, ctx in enumerate(contexts):
            prop = self.propose_one(ctx, k)
            lens[i] = len(prop)
            drafts[i, :len(prop)] = prop
        return drafts, lens

    def warmup(self, n_slots: int, k: int) -> None:
        """Host-side provider: nothing to compile."""


@lru_cache(maxsize=8)
def _draft_fns(model, window: int):
    """The jitted draft forward, shared across engines of the same (draft
    model, window): ``[n, window]`` right-padded ids + per-slot lengths ->
    the greedy next token per slot.  ONE fixed-shape program — the draft
    loop calls it ``k`` times per verify pass, never recompiling
    (``strict_compiles`` holds after warmup)."""
    import jax
    import jax.numpy as jnp

    def next_token(params, ids, lens):
        positions = jnp.broadcast_to(jnp.arange(window), ids.shape)
        logits = model.apply(params, ids, positions=positions)
        last = jnp.take_along_axis(
            logits, (lens - 1)[:, None, None], axis=1
        )[:, 0]
        return jnp.argmax(last.astype(jnp.float32), axis=-1).astype(jnp.int32)

    return jax.jit(next_token)


class DraftModelDraft:
    """Draft-model provider: a small model proposes ``k`` greedy tokens.

    Stateless by design: each draft token re-forwards the slot's trailing
    ``window`` tokens through one jitted fixed-shape program (a draft KV
    cache would have to mirror every eviction/rollback of the target cache;
    a windowed forward of a model this small costs less than that
    bookkeeping).  The window slides when full, so contexts of any length
    draft at fixed shape.
    """

    name = "draft"
    programs = 1  # the windowed next-token forward

    def __init__(self, model, params, window: int = 32):
        if window < 2:
            raise ValueError(f"draft window must be >= 2, got {window}")
        self.model = model
        self.params = params
        self.window = window
        self._next = _draft_fns(model, window)

    def propose(self, contexts: list, k: int,
                adapter_ids=None) -> tuple[np.ndarray, np.ndarray]:
        import jax.numpy as jnp

        n = len(contexts)
        w = self.window
        ids = np.zeros((n, w), np.int32)
        lens = np.zeros((n,), np.int32)
        for i, ctx in enumerate(contexts):
            tail = list(ctx)[-w:]
            ids[i, :len(tail)] = tail
            lens[i] = max(1, len(tail))
        drafts = np.zeros((n, k), np.int32)
        for j in range(k):
            tok = np.asarray(self._next(self.params, jnp.asarray(ids),
                                        jnp.asarray(lens)))
            drafts[:, j] = tok
            # slide: append the drafted token, dropping the oldest when full
            full = lens >= w
            ids[full] = np.roll(ids[full], -1, axis=1)
            ids[np.arange(n), np.where(full, w - 1, lens)] = tok
            lens = np.minimum(lens + 1, w)
        return drafts, np.full((n,), k, np.int32)

    def warmup(self, n_slots: int, k: int) -> None:
        """Compile the draft forward before traffic (one program)."""
        self.propose([[1]] * max(1, n_slots), max(1, k))


def make_draft_provider(mode: str, *, draft_model=None, draft_params=None,
                        window: int = 32, max_ngram: int = 3):
    """Resolve a ``ServingPlugin.speculate`` mode to a provider instance."""
    if mode == "ngram":
        return NgramDraft(max_ngram=max_ngram)
    if mode == "draft":
        if draft_model is None or draft_params is None:
            raise ValueError(
                "speculate='draft' needs draft_model and draft_params "
                "(pass them to ServingEngine / generate_paged)"
            )
        return DraftModelDraft(draft_model, draft_params, window=window)
    raise ValueError(f"unknown speculate mode {mode!r} (ngram | draft)")


class Speculator:
    """Host-side drafting state for one engine: the provider, the depth
    ``k``, the verify bucket ladder, and the draft-time accounting the
    ``draft_overhead_frac`` bench field reads."""

    def __init__(self, provider, k: int, buckets: tuple):
        if k < 1:
            raise ValueError(f"speculate_k must be >= 1, got {k}")
        self.provider = provider
        self.k = k
        self.buckets = tuple(sorted(buckets))
        if not self.buckets or self.buckets[-1] < k:
            raise ValueError(
                f"speculate_buckets {buckets} must include a bucket >= k={k}"
            )
        self.draft_time_s = 0.0

    def bucket_for(self, depth: int) -> int:
        for b in self.buckets:
            if b >= depth:
                return b
        return self.buckets[-1]

    def draft(self, contexts: list, remaining: list,
              adapter_ids=None) -> tuple[np.ndarray, np.ndarray]:
        """Propose drafts for the active slots and clamp per-slot depth:
        ``spec_len[i] = min(draft_len, k, remaining-1)`` — a slot one token
        from ``max_new_tokens`` verifies at depth 0 (plain decode in lane
        0), so speculation can never overrun a request's token budget (or,
        transitively, its submit-guarded page capacity)."""
        t0 = time.perf_counter()
        drafts, lens = self.provider.propose(contexts, self.k, adapter_ids)
        self.draft_time_s += time.perf_counter() - t0
        spec_len = np.minimum(
            lens.astype(np.int64),
            np.maximum(np.asarray(remaining, np.int64) - 1, 0),
        ).astype(np.int32)
        return drafts, spec_len


def predicted_acceptance(trace, results: dict, provider, k: int) -> dict:
    """The predicted twin: replay draft-and-verify arithmetic over the
    measured token streams (no model, no device).  For each request, walk
    its final stream: at ``e`` emitted tokens the engine would verify with
    drafts proposed from ``prompt + stream[:e]`` at depth
    ``min(k, max_new - e - 1, draft_len)``; the greedy targets ARE the
    stream, so the accepted prefix length is exact.  Returns
    ``accept_rate`` (accepted drafts / drafted tokens) and
    ``tokens_per_step`` (verify-emitted tokens per verify pass) — the
    measured twins' error vs this is the eviction/recompute re-decode
    traffic the replay cannot see."""
    drafted = accepted = passes = emitted = 0
    window = getattr(provider, "window", None)
    for req in trace:
        stream = results.get(req.uid)
        if not stream:
            continue
        prompt = list(req.prompt)
        e = 1  # the first token is sampled off the prefill logits
        while e < len(stream):
            depth = max(min(k, req.max_new_tokens - e - 1), 0)
            m = 0
            if depth > 0:
                # propose at full k, then clamp — exactly the engine's
                # Speculator.draft order (the provider may pick a different
                # match site for a different k).  Context carries only the
                # provider's trailing window, like the engine's verify tick
                # (a full prompt+stream rebuild per pass is quadratic)
                ctx = prompt + stream[:e] if window is None else \
                    (stream[e - window:e] if e >= window
                     else prompt[e - window:] + stream[:e])
                draft, dl = provider.propose([ctx], k)
                depth = min(depth, int(dl[0]))
                while m < depth and e + m < len(stream) \
                        and int(draft[0, m]) == stream[e + m]:
                    m += 1
            out = min(m + 1, len(stream) - e)
            drafted += depth
            accepted += m
            emitted += out
            passes += 1
            e += out
    return {
        "accept_rate": round(accepted / drafted, 4) if drafted else 0.0,
        "tokens_per_step": round(emitted / passes, 4) if passes else 0.0,
        "drafted": drafted,
        "accepted": accepted,
        "verify_passes": passes,
    }


def speculative_page_need(kv_tokens: int, depth: int, page_size: int) -> int:
    """Worst-case fresh pages one slot's verify pass can consume: page
    starts among the written positions ``[kv, kv + depth]``."""
    from .paged_cache import pages_for

    return int(pages_for(kv_tokens + depth + 1, page_size)
               - pages_for(kv_tokens, page_size))


__all__ = [
    "NgramDraft",
    "DraftModelDraft",
    "Speculator",
    "make_draft_provider",
    "predicted_acceptance",
    "speculative_page_need",
]
