"""Overload control: the SLO-driven degradation ladder + serving invariants.

Serving millions of users is an exercise in *graceful* failure: when p99
per-token latency, TTFT or occupancy breaches its SLO, the engine must shed
capacity pressure in a deterministic order that costs the least quality
first — and it must do so **using only programs that are already warmed**,
so the recompile guard (``strict_compiles``) holds through every stage of
the degradation.  The four stages, in escalation order:

1. **despeculate** — speculative verify passes stop; decode falls back to
   the plain single-token program (warmed in :meth:`ServingEngine.warmup`
   whether or not speculation is on).  Speculation is a throughput
   optimization paid in worst-case page reservations; under pressure those
   reservations are the first thing to go.
2. **shrink_prefill** — prefill chunks clamp to the SMALLEST warmed bucket:
   long prompts stop monopolizing engine ticks, so in-flight decodes see
   latency relief.  Every chunk still pads to a warmed bucket width.
3. **tighten_admission** — admission keeps a free-page reserve
   (``ladder_reserve_frac`` of the pool) while the pool is contended, so
   in-flight sequences stop being evicted to make room for new admissions
   (eviction = recompute-on-readmit = every generated token revoked — the
   worst latency outcome there is).
4. **shed** — the waiting line clamps to ``num_slots`` and sheds by the
   deterministic policy (oldest-beyond-deadline first, then the newcomer).

:func:`verify_serving_invariants` is the resource-contract checker the
cancellation/chaos machinery is pinned against: the host free-page mirror
equals the device allocator, every physical page is either free or owned by
exactly one live sequence (zero leaks, zero double-ownership), device
sequence lengths match the host bookkeeping, slot accounting is exact, and
adapter refcounts balance the in-flight census.  Tests run it after every
chaos scenario; ``replay(..., verify_invariants=True)`` runs it opt-in.
"""

from __future__ import annotations

from collections import Counter
from typing import Optional

import numpy as np

from .paged_cache import pages_for


class DegradationLadder:
    """Deterministic graceful-degradation state machine for one engine.

    Escalation is one stage per :meth:`escalate` call (an SLO trip, a
    deadline-storm fault, or an operator action); :meth:`relax` steps back
    down one stage, restoring that stage's knob.  Every transition appends
    ``("ladder", stage)`` to the scheduler's deterministic event log, so
    the determinism pin covers ladder engagement like every other decision.

    Wire an :class:`~accelerate_tpu.telemetry.SLOMonitor` with
    :meth:`attach`: trips escalate, recoveries relax.  All four stages use
    only already-warmed programs — ``strict_compiles`` holds end-to-end
    (pinned by tests and the multichip dryrun ``_overload_leg``).
    """

    STAGES = ("normal", "despeculate", "shrink_prefill", "tighten_admission",
              "shed")

    def __init__(self, engine, *, reserve_frac: Optional[float] = None):
        self.engine = engine
        self.level = 0
        self.engagements = 0
        frac = (reserve_frac if reserve_frac is not None
                else engine.plugin.ladder_reserve_frac)
        self._reserve_pages = max(1, int(engine.plugin.num_pages * frac))
        self._saved_prefill_chunk = engine.plugin.prefill_chunk

    @property
    def stage(self) -> str:
        return self.STAGES[self.level]

    def escalate(self, metric=None, quantile=None, value=None) -> str:
        """Move one stage up (no-op at the top).  The optional arguments
        match the :class:`SLOMonitor` trip-callback signature so the
        monitor can drive the ladder directly."""
        if self.level >= len(self.STAGES) - 1:
            return self.stage
        self.level += 1
        self.engagements += 1
        self._apply(self.level)
        self.engine.sched.events.append(("ladder", self.stage))
        return self.stage

    def relax(self, metric=None, quantile=None, value=None) -> str:
        """Step one stage down, restoring that stage's knob (no-op at
        normal)."""
        if self.level == 0:
            return self.stage
        self._unapply(self.level)
        self.level -= 1
        self.engine.sched.events.append(("ladder", self.stage))
        return self.stage

    def _apply(self, level: int) -> None:
        eng, sched = self.engine, self.engine.sched
        if level == 1:
            eng.despeculated = True
        elif level == 2:
            sched.prefill_chunk = min(eng.plugin.prefill_buckets)
        elif level == 3:
            sched.admission_reserve_pages = self._reserve_pages
        elif level == 4:
            sched.shed_armed = True

    def _unapply(self, level: int) -> None:
        eng, sched = self.engine, self.engine.sched
        if level == 1:
            eng.despeculated = False
        elif level == 2:
            sched.prefill_chunk = self._saved_prefill_chunk
        elif level == 3:
            sched.admission_reserve_pages = 0
        elif level == 4:
            sched.shed_armed = False

    def attach(self, monitor) -> None:
        """Wire an :class:`~accelerate_tpu.telemetry.SLOMonitor`: trips
        escalate one stage, recoveries relax one.  Callbacks the monitor
        already carries (operator alerting) keep firing — the ladder chains
        in front of them, never replaces them."""
        prev_trip, prev_recover = monitor.on_trip, monitor.on_recover

        def trip(metric, quantile, value):
            self.escalate(metric, quantile, value)
            if prev_trip is not None:
                prev_trip(metric, quantile, value)

        def recover(metric, quantile, value):
            self.relax(metric, quantile, value)
            if prev_recover is not None:
                prev_recover(metric, quantile, value)

        monitor.on_trip = trip
        monitor.on_recover = recover

    def report(self) -> dict:
        return {"stage": self.stage, "level": self.level,
                "engagements": self.engagements}


def verify_serving_invariants(engine) -> list[str]:
    """The serving resource contract, checked exactly (the reusable
    extension of ``ServingEngine.free_page_mirror_in_sync``).  Returns a
    list of violations — empty means every invariant holds:

    - host free-page mirror == device ``free_top``;
    - host page conservation: free + Σ ``pages_for(kv_tokens)`` over
      occupied slots == ``num_pages``;
    - device page conservation: the live free-stack entries are unique, and
      together with every live sequence's block-table prefix they cover the
      physical pages exactly once (zero leaked pages, zero double-owners);
      with prefix caching armed this becomes the REFCOUNTED contract
      (:func:`_verify_refcounted`): shared pages count once however many
      rows alias them, refcounts balance the index + slot holds exactly,
      the host shared-prefix mirror matches the device block-table rows,
      and no referenced page ever sits on the free stack (the double-free
      exclusion);
    - device ``seq_lens`` match the host ``kv_tokens`` per occupied slot and
      read 0 for free slots;
    - slot accounting: ``free_slots`` ∪ occupied == all slots, disjoint;
    - adapter refcounts balance the in-flight census per tenant.

    One host sync (the cache fetch) — a test/replay-time checker, never
    called from the hot path.
    """
    problems: list[str] = []
    sched = engine.sched
    cache = engine.cache
    page = sched.page_size
    prefix = getattr(engine, "prefix", None)
    free_top = int(cache["free_top"])
    if free_top != sched.free_pages:
        problems.append(
            f"free-page mirror diverged: device free_top={free_top} vs "
            f"host free_pages={sched.free_pages}"
        )
    stack = np.asarray(cache["free_stack"])[:max(free_top, 0)].tolist()
    if len(set(stack)) != len(stack):
        problems.append("free stack holds duplicate physical pages")
    seq_lens = np.asarray(cache["seq_lens"])
    block_tables = np.asarray(cache["block_tables"])
    if prefix is None:
        held = sum(int(pages_for(st.kv_tokens, page))
                   for st in sched.slots.values())
        if sched.free_pages + held != sched.num_pages:
            problems.append(
                f"host page conservation broken: free={sched.free_pages} + "
                f"held={held} != num_pages={sched.num_pages}"
            )
        owned: list[int] = []
        for slot in range(seq_lens.shape[0]):
            n = int(pages_for(int(seq_lens[slot]), page))
            owned.extend(int(p) for p in block_tables[slot, :n])
        if sorted(owned + stack) != list(range(sched.num_pages)):
            leaked = set(range(sched.num_pages)) - set(owned) - set(stack)
            doubled = [p for p, c in Counter(owned + stack).items() if c > 1]
            problems.append(
                f"device page conservation broken: leaked={sorted(leaked)} "
                f"double-owned={sorted(doubled)}"
            )
    else:
        problems.extend(_verify_refcounted(engine, stack, seq_lens,
                                           block_tables))
    for slot, st in sched.slots.items():
        if int(seq_lens[slot]) != st.kv_tokens:
            problems.append(
                f"slot {slot}: device seq_len={int(seq_lens[slot])} vs host "
                f"kv_tokens={st.kv_tokens}"
            )
    for slot in range(sched.num_slots):
        if slot not in sched.slots and int(seq_lens[slot]) != 0:
            problems.append(
                f"free slot {slot} still carries device seq_len="
                f"{int(seq_lens[slot])}"
            )
    if sorted(sched.free_slots + list(sched.slots)) != list(range(sched.num_slots)):
        problems.append(
            f"slot accounting broken: free={sched.free_slots} "
            f"occupied={sorted(sched.slots)}"
        )
    if engine.adapters is not None:
        in_flight = Counter(
            st.request.adapter_id for st in sched.slots.values()
            if st.request.adapter_id
        )
        for tid in set(in_flight) | set(engine.adapters.refcount):
            want, got = in_flight.get(tid, 0), engine.adapters.refcount.get(tid, 0)
            if want != got:
                problems.append(
                    f"adapter {tid}: refcount={got} vs {want} in-flight holds"
                )
    return problems


def _verify_refcounted(engine, stack, seq_lens, block_tables) -> list[str]:
    """The refcounted page-conservation contract (prefix caching armed):

    - **mirror exact**: each occupied slot's host ``shared_pages`` list
      equals its device block-table row prefix (the COW release keep-count
      arithmetic depends on it);
    - **refcounts exact**: ``refcount[p] == (index holds p) + (slots
      listing p)`` — no phantom or missing holds;
    - **no referenced page on the free stack** — THE double-free a refcount
      bug causes (the host-side twin is ``PrefixCache.pop_pending``'s
      assertion);
    - **conservation**: free stack ∪ refcounted shared pages ∪ per-slot
      private pages covers the pool exactly once (zero leaks, zero double
      owners — a shared page counts ONCE however many rows alias it);
    - **drained**: no page stuck in ``pending_free`` across a tick boundary.
    """
    problems: list[str] = []
    sched = engine.sched
    prefix = engine.prefix
    page = sched.page_size
    slot_holds: Counter = Counter()
    private: list[int] = []
    for slot, st in sched.slots.items():
        k = len(st.shared_pages)
        total = int(pages_for(st.kv_tokens, page))
        row = [int(p) for p in block_tables[slot, :total]]
        if row[:k] != [int(p) for p in st.shared_pages]:
            problems.append(
                f"slot {slot}: shared-prefix mirror diverged — host "
                f"{st.shared_pages} vs device row {row[:k]}"
            )
        slot_holds.update(int(p) for p in st.shared_pages)
        private.extend(row[k:])
    index_pages = set(prefix.index.values())
    for p in set(slot_holds) | index_pages | set(prefix.refcount):
        want = slot_holds.get(p, 0) + (1 if p in index_pages else 0)
        got = prefix.refcount.get(p, 0)
        if want != got:
            problems.append(
                f"page {p}: refcount={got} vs {want} holds "
                f"(index={p in index_pages}, slots={slot_holds.get(p, 0)})"
            )
    shared = set(prefix.refcount)
    referenced_on_stack = shared & set(stack)
    if referenced_on_stack:
        problems.append(
            f"referenced pages on the free stack (double-free): "
            f"{sorted(referenced_on_stack)}"
        )
    if prefix.pending_free:
        problems.append(
            f"pending_free not drained across the tick boundary: "
            f"{prefix.pending_free}"
        )
    dup_private = [p for p, c in Counter(private).items() if c > 1]
    if dup_private:
        problems.append(f"private pages double-owned: {sorted(dup_private)}")
    cover = sorted(list(shared) + private + stack)
    if cover != list(range(sched.num_pages)):
        counts = Counter(list(shared) + private + stack)
        leaked = set(range(sched.num_pages)) - set(counts)
        doubled = [p for p, c in counts.items() if c > 1]
        problems.append(
            f"refcounted page conservation broken: free={len(stack)} + "
            f"shared={len(shared)} + private={len(private)} vs "
            f"pool={sched.num_pages}; leaked={sorted(leaked)} "
            f"double-class={sorted(doubled)}"
        )
    return problems


__all__ = ["DegradationLadder", "verify_serving_invariants"]
