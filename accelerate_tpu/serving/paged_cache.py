"""Functional device-side page allocator for the paged KV cache.

The pool itself is built by :func:`~accelerate_tpu.models.llama.init_paged_cache`
(fixed-size pages, per-slot block tables, a free-list stack).  This module is
the allocator arithmetic that mutates that structure **functionally** — every
operation is ``jnp`` index math on arrays the serving step carries through
``donate_argnums``, so the jitted decode/prefill steps stay donation-clean
(graft-lint GL101/GL201: the pool buffers alias in place, and no Python name
outlives its donation).

Design notes (vLLM PagedAttention discipline):

- ``free_stack``/``free_top`` form a stack of free physical page ids.  Pops
  never rewrite the stack (entries above ``free_top`` are dead); pushes
  overwrite dead entries.  Both directions are scatter/gather with computed
  ranks, so a *batch* of slots allocates/releases in one fused op.
- Masked lanes route their scatter index out of bounds and drop
  (``mode="drop"``) — the write-mask convention shared with the model's
  paged attention path.
- Exhaustion is the **scheduler's** job: the host mirrors the free count
  deterministically (same arithmetic on the same trace) and evicts before a
  pop could underflow; :func:`allocate` clamps indices so even a scheduler
  bug corrupts allocation, not memory safety.
"""

from __future__ import annotations

import jax.numpy as jnp


def pages_for(tokens, page_size: int):
    """Pages needed to hold ``tokens`` tokens (ceil division; 0 -> 0)."""
    return -(-tokens // page_size)


def allocate(block_tables, free_stack, free_top, slots, logical_pages, need):
    """Pop one page per needing lane and write it into the block table.

    ``slots``/``logical_pages``/``need``: aligned ``[K]`` arrays — lane *i*
    asks for a fresh physical page at ``block_tables[slots[i],
    logical_pages[i]]`` iff ``need[i]``.  Returns ``(block_tables,
    free_top)``; ``free_stack`` itself is untouched (pops only move the
    top).  Lanes with ``need=False`` drop their scatter.
    """
    need = need.astype(bool)
    rank = jnp.cumsum(need.astype(jnp.int32)) - 1           # 0-based grab order
    src = jnp.clip(free_top - 1 - rank, 0, free_stack.shape[0] - 1)
    pages = free_stack[src]
    rows = jnp.where(need, slots, block_tables.shape[0])    # OOB -> drop
    block_tables = block_tables.at[rows, logical_pages].set(pages, mode="drop")
    return block_tables, free_top - jnp.sum(need.astype(jnp.int32))


def release(block_tables, seq_lens, free_stack, free_top, release_mask, page_size: int):
    """Push every page owned by the masked slots back onto the free stack.

    A slot owns ``ceil(seq_len / page_size)`` pages (its block-table prefix).
    Returns ``(seq_lens, free_stack, free_top)`` with released slots' lengths
    zeroed — the block-table rows are left stale on purpose: the positional
    liveness mask never reads past ``seq_len``, so the next tenant just
    overwrites them.
    """
    release_mask = release_mask.astype(bool)
    n = block_tables.shape[1]
    owned = release_mask[:, None] & (
        jnp.arange(n)[None, :] < pages_for(seq_lens, page_size)[:, None]
    )
    free_stack, free_top = push_pages(
        free_stack, free_top, block_tables.reshape(-1), owned.reshape(-1)
    )
    seq_lens = jnp.where(release_mask, 0, seq_lens)
    return seq_lens, free_stack, free_top


def push_pages(free_stack, free_top, pages, mask):
    """Push an arbitrary masked set of physical pages back onto the free
    stack — THE free-stack push primitive (:func:`release` and the
    speculative verify pass's rollback both route through it).  A verify
    pass allocates worst-case pages up front (every page-start among its
    ``k + 1`` candidate positions), then returns the ones past the accepted
    frontier through this scatter, all inside the same donated jitted
    program.  ``pages``/``mask``: aligned ``[K]`` arrays; masked-out lanes
    route their scatter out of bounds and drop (the shared write-mask
    convention).  Returns ``(free_stack, free_top)``.

    **Aliasing contract** (prefix caching, docs/serving.md): a page id may
    reach this scatter ONLY while no holder references it.  The callers
    enforce it — the engine's COW release masks each slot's shared-prefix
    pages out (``release`` here pushes a slot's WHOLE block-table prefix,
    so prefix-armed engines route through the keep-aware variant instead),
    and ``PrefixCache.pop_pending`` hard-asserts refcount zero before the
    ``push_free`` dispatch — while ``verify_serving_invariants()`` checks
    the device-side exclusion (referenced ∩ free-stack = ∅) after the
    fact.  Pushing a still-referenced page is the double-free a refcount
    bug causes — two owners of one physical page — pinned by a planted
    test (tests/test_prefix_cache.py).
    """
    mask = mask.astype(bool)
    rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
    dst = jnp.where(mask, free_top + rank, free_stack.shape[0])  # OOB -> drop
    free_stack = free_stack.at[dst].set(pages, mode="drop")
    return free_stack, free_top + jnp.sum(mask.astype(jnp.int32))


def kv_page_bytes(config, page_size: int, dtype_bytes: int = 2,
                  kv_dtype: str = "") -> int:
    """Bytes of ONE physical page across all layers — the unit the
    allocator hands out AND the disaggregated transfer wire unit
    (``serving/transfer.py`` computes its ``transfer.page_bytes`` twin
    through this same function, so the twin stays exact by construction).

    Dense pages: ``2 (K+V) * L * page_size * Hkv * D * dtype_bytes``.
    Quantized pages (``kv_dtype`` "int8"/"fp8"): 1-byte codes plus the
    per-(kv-head, page) float32 scale that is part of the page's content
    (``2 * L * Hkv * 4`` bytes — it travels with the page on the wire and
    feeds the prefix-cache hash)."""
    if kv_dtype in ("int8", "fp8"):
        data = (2 * config.num_hidden_layers * page_size
                * config.num_key_value_heads * config.head_dim)
        scales = 2 * config.num_hidden_layers * config.num_key_value_heads * 4
        return data + scales
    return (2 * config.num_hidden_layers * page_size
            * config.num_key_value_heads * config.head_dim * dtype_bytes)


def kv_pool_accounting(config, num_pages: int, page_size: int,
                       dtype_bytes: int = 2, kv_dtype: str = "") -> dict:
    """Predicted KV-HBM ladder for a pool geometry (CheckFreq-style
    predicted twin; the measured counterpart is the harness's
    ``kv_pool_utilization``).

    bytes/page is per *physical page across all layers* — the unit the
    allocator hands out: ``2 (K+V) * L * page_size * Hkv * D * dtype``
    (:func:`kv_page_bytes`; quantized pools count the 1-byte codes plus
    the per-page scales).  ``capacity_vs_bf16`` reports the quantized
    pool's token-capacity multiple at equal HBM — the ladder headline
    (~1.9-2x for int8/fp8 once ``page_size * D`` amortizes the scales)."""
    per_page = kv_page_bytes(config, page_size, dtype_bytes, kv_dtype)
    total = per_page * num_pages
    gib = lambda b: round(b / 2**30, 4)
    out = {
        "page_size_tokens": page_size,
        "num_pages": num_pages,
        "bytes_per_page": per_page,
        "pool_bytes": total,
        "pool_gib": gib(total),
        "tokens_capacity": num_pages * page_size,
        # the ladder: how much of each chip generation's HBM the pool takes
        "hbm_frac": {
            "v5e_16GiB": round(total / (16 * 2**30), 6),
            "v5p_95GiB": round(total / (95 * 2**30), 6),
            "v6e_32GiB": round(total / (32 * 2**30), 6),
        },
    }
    if kv_dtype in ("int8", "fp8"):
        bf16_page = kv_page_bytes(config, page_size, 2)
        out["kv_dtype"] = kv_dtype
        out["capacity_vs_bf16"] = round(bf16_page / per_page, 4)
        # predicted side of the kv_quant.page_bytes twin — the measured
        # side is the engine's allocated pool arrays (nbytes per page);
        # exact by construction since both route through kv_page_bytes'
        # codes+scales arithmetic
        from ..telemetry import twin_registry

        twin_registry().record_predicted(
            "kv_quant.page_bytes", per_page,
            source="serving/paged_cache.kv_pool_accounting",
        )
    return out
