"""Content-addressed prefix cache: copy-on-write shared KV pages with
refcounted eviction (vLLM automatic-prefix-caching discipline).

Millions of users share system prompts and few-shot preambles, but a plain
paged engine re-prefills every prompt into private pages.  This module is
the host-side spine of prefix reuse over the existing functional allocator
(``serving/paged_cache.py``):

- **Content addressing**: every FULL page of a prompt gets a chained block
  hash — ``h_j = H(adapter_id, h_{j-1}, tokens[j*page:(j+1)*page])`` — so a
  hash identifies the *entire prefix* up to that block, not just the block
  (two prompts share page *j* only when they agree on everything before it).
  The chain is seeded with the tenant ``adapter_id``: a LoRA tenant's K/V
  depends on its adapter, so cross-tenant prompts NEVER alias pages.
- **Copy-on-write at page granularity**: only full pages are ever shared,
  and the match is capped at ``(prompt_len - 1) // page_size`` pages so the
  first partially-filled page — and at least one real prefill token — is
  always private.  Writes only ever land past the shared boundary, so
  "copy" never actually runs: the fork point is a page boundary by
  construction, and a request that shares a proper prefix then writes its
  own divergent pages is counted as a **cow_fork**.
- **Refcounts**: ``refcount[page] = (1 if the index holds it) + (1 per
  occupied slot listing it in its shared prefix)``.  ``release``/eviction
  decrement; a page is pushed back onto the device free stack **only when
  its refcount reaches zero** (the last holder — slot or index — lets go).
  Eviction victims respect shared refcounts exactly as the
  :class:`~.adapters.AdapterStore` LRU does: only index-only pages
  (refcount == 1) are reclaimable, LRU first.
- **Mirror discipline**: the scheduler owns the free-page *count* mirror;
  this cache owns the page-*id* truth for the shared class.  Pages freed by
  refcount death or LRU reclaim queue in :attr:`pending_free` and the
  engine pushes them through its jitted ``push_free`` program before the
  next allocating dispatch — :meth:`pop_pending` hard-asserts that no
  still-referenced page id ever reaches the device stack (THE double-free
  a refcount bug would cause; ``verify_serving_invariants`` checks the
  same exclusion device-side).

The engine-side programs (adopt-prefix scatter, keep-aware COW release,
free-list push) live in ``serving/engine.py``; the first disaggregated
prefill→decode slice that makes KV pages a *transferable* refcounted
resource is ``serving/transfer.py``.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

from .paged_cache import pages_for


def _block_digest(parent: bytes, tokens: Sequence[int], adapter_id: int) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(parent)
    h.update(adapter_id.to_bytes(8, "little", signed=False))
    for t in tokens:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.digest()


def block_hashes(prompt: Sequence[int], page_size: int,
                 adapter_id: int = 0, kv_dtype: str = "") -> list[bytes]:
    """The prompt's chained block-hash ladder, one entry per CACHEABLE full
    page.  Capped at ``(len(prompt) - 1) // page_size``: the last page is
    never cacheable even when the prompt is page-aligned, so a fully-cached
    admission still prefills at least one real token (the decode loop needs
    the prompt's last-token logits — the COW contract's "first
    partially-filled page is always private" extends to "the last prompt
    token is always prefilled").

    ``kv_dtype`` seeds the chain: a quantized pool's page *content* is
    codes + scales, not bf16 rows, so an int8 pool's hashes must never
    collide with a bf16 or fp8 pool's — the scales are part of what the
    hash addresses."""
    full = max(0, (len(prompt) - 1)) // page_size
    out: list[bytes] = []
    parent = b"prefix-cache-v1"
    if kv_dtype and kv_dtype != "bf16":
        parent += b":kv=" + kv_dtype.encode("ascii")
    for j in range(full):
        parent = _block_digest(
            parent, prompt[j * page_size:(j + 1) * page_size], adapter_id
        )
        out.append(parent)
    return out


class PrefixCache:
    """Host-side content-addressed index + per-physical-page refcounts.

    Pure deterministic bookkeeping (no device access): the scheduler asks
    :meth:`match` during admission feasibility, :meth:`adopt` pins the hit
    pages when a request actually admits, the engine registers a completed
    prefill's new full pages via :meth:`insert_owned`, and every release
    path funnels through :meth:`unref_pages`.  Pages whose refcount hits
    zero queue in :attr:`pending_free` for the engine's next ``push_free``
    dispatch.
    """

    def __init__(self, page_size: int, kv_dtype: str = ""):
        self.page_size = page_size
        self.kv_dtype = kv_dtype  # seeds the hash chain: codes+scales content
        self.index: dict[bytes, int] = {}        # chain hash -> physical page
        self.page_hash: dict[int, bytes] = {}    # reverse map
        self.refcount: dict[int, int] = {}       # page -> index hold + slot holds
        self.pending_free: list[int] = []        # refcount-0 pages awaiting the
                                                 # engine's push_free program
        self._lru_clock = 0
        self._last_use: dict[bytes, int] = {}    # hash -> LRU stamp
        self.stats = {
            "lookup_pages": 0,          # cacheable pages demanded at admission
            "hit_pages": 0,             # of those, served from the index
            "admission_hits": 0,        # admissions with hit_pages > 0
            "admission_lookups": 0,     # admissions with cacheable pages > 0
            "cow_forks": 0,             # proper-prefix hits (shared then diverged)
            "prefill_tokens_skipped": 0,
            "pages_shared_peak": 0,     # peak pages with refcount >= 2
            "prefix_evictions": 0,      # LRU reclaims + flush drops
            "inserted_pages": 0,
        }

    # -- hashing / lookup ----------------------------------------------------

    def block_hashes(self, prompt: Sequence[int], adapter_id: int = 0) -> list[bytes]:
        return block_hashes(prompt, self.page_size, adapter_id, self.kv_dtype)

    def match(self, hashes: Sequence[bytes]) -> list[int]:
        """Physical page ids of the longest indexed prefix of ``hashes``.
        Pure lookup — no refcount or stats mutation (admission feasibility
        probes may call it repeatedly; :meth:`adopt` commits)."""
        out: list[int] = []
        for h in hashes:
            page = self.index.get(h)
            if page is None:
                break
            out.append(page)
        return out

    def hit_tokens(self, prompt: Sequence[int], adapter_id: int = 0) -> int:
        """Prefill tokens the longest cached prefix would skip (a pure
        probe — the scheduler's admission-need arithmetic)."""
        return len(self.match(self.block_hashes(prompt, adapter_id))) * self.page_size

    # -- refcount lifecycle --------------------------------------------------

    def _touch(self, h: bytes) -> None:
        self._lru_clock += 1
        self._last_use[h] = self._lru_clock

    def _note_shared_peak(self) -> None:
        shared = sum(1 for c in self.refcount.values() if c >= 2)
        if shared > self.stats["pages_shared_peak"]:
            self.stats["pages_shared_peak"] = shared

    def adopt(self, hashes: Sequence[bytes], count: bool = True) -> list[int]:
        """Commit an admission's longest-prefix hit: ref every hit page (one
        slot hold each), stamp LRU, and account the hit/miss/cow-fork
        stats.  Returns the adopted page ids (the slot's shared prefix).

        ``count=False`` skips the hit-RATE counters (an evicted request's
        readmission re-hits its own inserted pages — real prefill saved,
        so ``prefill_tokens_skipped`` still accrues, but the hit-rate twin
        counts each request's OFFERED traffic once: its predicted side is
        a trace replay that cannot see recompute-on-readmit churn)."""
        hit = self.match(hashes)
        if hashes and count:
            self.stats["admission_lookups"] += 1
            self.stats["lookup_pages"] += len(hashes)
        if not hit:
            return []
        self.stats["prefill_tokens_skipped"] += len(hit) * self.page_size
        if count:
            self.stats["admission_hits"] += 1
            self.stats["hit_pages"] += len(hit)
            if len(hit) < len(hashes):
                # shared a proper prefix, then writes its own divergent
                # pages — the copy-on-write fork (the fork point is a page
                # boundary, so no copy ever runs; the first partial page is
                # private already)
                self.stats["cow_forks"] += 1
        for h, page in zip(hashes, hit):
            self.refcount[page] = self.refcount.get(page, 0) + 1
            self._touch(h)
        self._note_shared_peak()
        return hit

    def ref_pages(self, pages: Sequence[int]) -> None:
        for p in pages:
            self.refcount[p] = self.refcount.get(p, 0) + 1
        self._note_shared_peak()

    def unref_pages(self, pages: Sequence[int]) -> int:
        """Drop one hold per page (a releasing slot's shared prefix, or an
        index entry letting go).  Pages reaching refcount zero leave the
        refcount map and queue in :attr:`pending_free`; returns how many
        did (the scheduler adds them to its free-page mirror — the device
        push is the engine's next ``push_free`` dispatch)."""
        freed = 0
        for p in pages:
            c = self.refcount.get(p)
            if c is None:
                raise RuntimeError(
                    f"unref of page {p} which holds no reference — a "
                    "refcount bug (double release?)"
                )
            if c == 1:
                del self.refcount[p]
                self.pending_free.append(p)
                freed += 1
            else:
                self.refcount[p] = c - 1
        return freed

    def insert_owned(self, hashes: Sequence[bytes], pages: Sequence[int]) -> list[int]:
        """Register a completed prefill's NEW full pages (hash -> page),
        taking BOTH the index hold and the inserting slot's hold (the page
        was the slot's private page; it is shared-class from here on).
        Insertion stops at the first already-indexed hash so every slot's
        shared set stays a contiguous block-table row prefix (a concurrent
        identical prefill that lost the race keeps its duplicate page
        private — correctness over hit rate).  Returns the page ids
        actually inserted."""
        out: list[int] = []
        for h, p in zip(hashes, pages):
            if h in self.index:
                break
            self.index[h] = int(p)
            self.page_hash[int(p)] = h
            # index hold + the inserting slot's hold
            self.refcount[int(p)] = self.refcount.get(int(p), 0) + 2
            self._touch(h)
            out.append(int(p))
        self.stats["inserted_pages"] += len(out)
        self._note_shared_peak()
        return out

    # -- eviction ------------------------------------------------------------

    def reclaim_one(self, protect: frozenset = frozenset()) -> Optional[int]:
        """LRU-evict ONE index-only page (refcount == 1: held by the index
        and no live slot — the AdapterStore rule: a shared hot page is
        never an eviction victim).  ``protect`` exempts page ids the caller
        has matched but not yet adopted (admission must not reclaim the
        very pages it is about to pin — the match→adopt window).  Returns
        the freed page id (already in :attr:`pending_free`) or ``None``
        when nothing is reclaimable."""
        victim = None
        for h in sorted(self.index, key=lambda h: self._last_use.get(h, 0)):
            page = self.index[h]
            if page not in protect and self.refcount.get(page, 0) == 1:
                victim = h
                break
        if victim is None:
            return None
        page = self._drop_entry(victim)
        self.stats["prefix_evictions"] += 1
        return page

    def _drop_entry(self, h: bytes) -> Optional[int]:
        page = self.index.pop(h)
        self.page_hash.pop(page, None)
        self._last_use.pop(h, None)
        freed = self.unref_pages([page])
        return page if freed else None

    def flush(self) -> int:
        """Drop EVERY index hold (the ``prefix`` fault: a cache-invalidation
        storm).  Entries still referenced by live slots keep their slot
        holds — their pages free later through the normal release path;
        index-only pages queue for the device push now.  Returns how many
        pages freed immediately."""
        freed = 0
        for h in list(self.index):
            if self._drop_entry(h) is not None:
                freed += 1
            self.stats["prefix_evictions"] += 1
        return freed

    def pop_pending(self) -> list[int]:
        """Drain the pages owed to the device free stack.  Hard-asserts the
        double-free exclusion: a page id queued here must hold ZERO
        references — pushing a still-referenced page is exactly the
        corruption a refcount bug causes (two owners of one physical page),
        and it must fail loudly at the host boundary, never reach the
        device."""
        out, self.pending_free = self.pending_free, []
        for p in out:
            if self.refcount.get(p, 0) != 0:
                self.pending_free = out  # leave state inspectable
                raise RuntimeError(
                    f"page {p} queued for the free stack while still "
                    f"referenced (refcount={self.refcount[p]}) — refcount "
                    "double-free guard"
                )
        return out

    # -- reporting -----------------------------------------------------------

    @property
    def shared_pages(self) -> int:
        """Pages currently in the shared class (refcount > 0)."""
        return len(self.refcount)

    def hit_rate(self) -> float:
        """Measured hit rate: index-served cacheable pages over cacheable
        pages demanded, across every admission so far."""
        lk = self.stats["lookup_pages"]
        return round(self.stats["hit_pages"] / lk, 4) if lk else 0.0

    def report(self) -> dict:
        return {
            "prefix_hit_rate": self.hit_rate(),
            "pages_shared_peak": self.stats["pages_shared_peak"],
            "cow_forks": self.stats["cow_forks"],
            "prefill_tokens_skipped": self.stats["prefill_tokens_skipped"],
            "prefix_evictions": self.stats["prefix_evictions"],
            "indexed_pages": len(self.index),
            "shared_pages": self.shared_pages,
        }


def unbounded_prefix_hit_rate(trace, page_size: int) -> float:
    """The capacity-free UPPER model of the prefix hit rate: the
    content-addressed matching replayed over the trace in arrival order
    with an unbounded index, no pool pressure, and every request's
    cacheable pages visible the moment it arrives.  This is the dedup
    ceiling :func:`prefix_cache_accounting` reports; the registered twin's
    predicted side is the *scheduler replay*
    (:func:`~.harness.predicted_prefix_hit_rate`), which models slot
    concurrency and LRU reclaim exactly."""
    seen: set[bytes] = set()
    lookups = hits = 0
    for r in sorted(trace, key=lambda r: (r.arrival_step, r.uid)):
        hashes = block_hashes(r.prompt, page_size, r.adapter_id)
        lookups += len(hashes)
        for h in hashes:
            if h in seen:
                hits += 1
            else:
                break
        seen.update(hashes)
    return round(hits / lookups, 4) if lookups else 0.0


def prefix_cache_accounting(config, trace, page_size: int,
                            dtype_bytes: int = 2) -> dict:
    """Predicted prefix-reuse envelope for a trace + pool geometry: unique
    vs total cacheable pages (the dedup the index can deliver), prefill
    tokens skippable, and the HBM those shared pages pin (the
    ``kv_pool_accounting`` bytes/page unit)."""
    per_page = (2 * config.num_hidden_layers * page_size
                * config.num_key_value_heads * config.head_dim * dtype_bytes)
    total = unique = skippable = 0
    seen: set[bytes] = set()
    for r in sorted(trace, key=lambda r: (r.arrival_step, r.uid)):
        hashes = block_hashes(r.prompt, page_size, r.adapter_id)
        total += len(hashes)
        matched = 0
        for h in hashes:
            if h in seen:
                matched += 1
            else:
                break
        skippable += matched * page_size
        unique += sum(1 for h in hashes if h not in seen)
        seen.update(hashes)
    return {
        "page_size_tokens": page_size,
        "cacheable_pages_total": total,
        "cacheable_pages_unique": unique,
        "dedup_frac": round(1.0 - unique / total, 4) if total else 0.0,
        "prefill_tokens_skippable": skippable,
        "bytes_per_page": per_page,
        "shared_bytes_peak_upper": unique * per_page,
        "hit_rate_upper": unbounded_prefix_hit_rate(trace, page_size),
    }


__all__ = [
    "PrefixCache", "block_hashes", "unbounded_prefix_hit_rate",
    "prefix_cache_accounting",
]
