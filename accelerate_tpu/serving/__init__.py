"""Serving core: paged KV cache + continuous batching (ROADMAP item 1).

The production-inference rebuild of the reference's
``inference.py``/``big_modeling.py`` contract — see docs/serving.md:

- :mod:`.paged_cache` — functional device-side page allocator over the pool
  built by :func:`accelerate_tpu.models.llama.init_paged_cache`;
- :mod:`.scheduler` — deterministic continuous-batching policy (FIFO
  admission, chunked prefill into shape buckets, youngest-first eviction);
- :mod:`.engine` — the jitted, donation-clean prefill/decode/release
  programs and the host-driven serving loop;
- :mod:`.harness` — seeded traffic replay, serving metrics, and the
  static-batching baseline;
- :mod:`.adapters` — multi-tenant batched LoRA (ROADMAP item 2): the
  fixed-size device adapter pool with hot-swap streaming + LRU behind the
  segment-batched adapter matmul (``ops/lora.py``), and the per-adapter
  fine-tuning trainer with host-resident optimizer state;
- :mod:`.speculate` — speculative multi-token decode (draft-and-verify):
  n-gram/prompt-lookup self-drafting and draft-model providers feeding the
  engine's fixed-shape batched verify program, with the model-free
  predicted acceptance replay (the accept-rate twin);
- :mod:`.overload` — serving resilience (docs/serving.md "Overload &
  deadlines"): the SLO-driven graceful-degradation ladder and the
  :func:`~.overload.verify_serving_invariants` resource-contract checker
  behind per-request deadlines, deterministic cancellation, admission
  control/load shedding, and the :func:`~.harness.chaos_replay` soak;
- :mod:`.prefix_cache` — content-addressed COW prefix reuse (ROADMAP
  item 2's first half): full prompt-prefix pages hash-match against shared
  refcounted physical pages, chunked prefill starts at the hit boundary,
  eviction respects shared refcounts (the AdapterStore LRU rule);
- :mod:`.transfer` — the first disaggregated prefill→decode slice: two
  fixed-shape wire programs stream finished KV pages between engines, with
  the ``dcn``-axis byte-accounting twin (``transfer.page_bytes``);
- :mod:`.router` — the fleet layer (ROADMAP item 1's scale-out step): N
  replicas (fused engines or disaggregated pairs) behind deterministic
  prefix-/adapter-affinity routing with load-aware tie-breaking, fleet-wide
  degradation-ladder escalation, drain/respawn on ``replica_kill``, and
  the :func:`~.router.fleet_replay` / :func:`~.router.fleet_chaos_replay`
  harnesses (docs/serving.md "Fleet serving").
"""

from .adapters import (
    AdapterPoolFullError,
    AdapterStore,
    LoraTrainer,
    adapter_pool_accounting,
    predicted_adapter_hit_rate,
)
from .engine import ServingEngine
from .harness import (
    chaos_replay,
    predicted_pool_utilization,
    predicted_prefix_hit_rate,
    replay,
    static_batching_report,
    synthesize_trace,
)
from .overload import DegradationLadder, verify_serving_invariants
from .prefix_cache import (
    PrefixCache,
    block_hashes,
    prefix_cache_accounting,
    unbounded_prefix_hit_rate,
)
from .paged_cache import allocate, kv_pool_accounting, pages_for, push_pages, release
from .router import FleetRouter, fleet_chaos_replay, fleet_replay
from .scheduler import ContinuousBatchingScheduler, Request, SlotState
from .speculate import (
    DraftModelDraft,
    NgramDraft,
    Speculator,
    make_draft_provider,
    predicted_acceptance,
    speculative_page_need,
)
from .transfer import (
    DisaggregatedPair,
    PagedKVTransport,
    page_bytes,
    transfer_accounting,
)

__all__ = [
    "ServingEngine",
    "ContinuousBatchingScheduler",
    "Request",
    "SlotState",
    "AdapterStore",
    "AdapterPoolFullError",
    "LoraTrainer",
    "adapter_pool_accounting",
    "predicted_adapter_hit_rate",
    "allocate",
    "release",
    "push_pages",
    "pages_for",
    "kv_pool_accounting",
    "NgramDraft",
    "DraftModelDraft",
    "Speculator",
    "make_draft_provider",
    "predicted_acceptance",
    "speculative_page_need",
    "synthesize_trace",
    "replay",
    "chaos_replay",
    "static_batching_report",
    "predicted_pool_utilization",
    "DegradationLadder",
    "verify_serving_invariants",
    "PrefixCache",
    "block_hashes",
    "predicted_prefix_hit_rate",
    "unbounded_prefix_hit_rate",
    "prefix_cache_accounting",
    "PagedKVTransport",
    "DisaggregatedPair",
    "transfer_accounting",
    "page_bytes",
    "FleetRouter",
    "fleet_replay",
    "fleet_chaos_replay",
]
