"""Serving core: paged KV cache + continuous batching (ROADMAP item 1).

The production-inference rebuild of the reference's
``inference.py``/``big_modeling.py`` contract — see docs/serving.md:

- :mod:`.paged_cache` — functional device-side page allocator over the pool
  built by :func:`accelerate_tpu.models.llama.init_paged_cache`;
- :mod:`.scheduler` — deterministic continuous-batching policy (FIFO
  admission, chunked prefill into shape buckets, youngest-first eviction);
- :mod:`.engine` — the jitted, donation-clean prefill/decode/release
  programs and the host-driven serving loop;
- :mod:`.harness` — seeded traffic replay, serving metrics, and the
  static-batching baseline;
- :mod:`.adapters` — multi-tenant batched LoRA (ROADMAP item 2): the
  fixed-size device adapter pool with hot-swap streaming + LRU behind the
  segment-batched adapter matmul (``ops/lora.py``), and the per-adapter
  fine-tuning trainer with host-resident optimizer state.
"""

from .adapters import (
    AdapterPoolFullError,
    AdapterStore,
    LoraTrainer,
    adapter_pool_accounting,
    predicted_adapter_hit_rate,
)
from .engine import ServingEngine
from .harness import (
    predicted_pool_utilization,
    replay,
    static_batching_report,
    synthesize_trace,
)
from .paged_cache import allocate, kv_pool_accounting, pages_for, release
from .scheduler import ContinuousBatchingScheduler, Request, SlotState

__all__ = [
    "ServingEngine",
    "ContinuousBatchingScheduler",
    "Request",
    "SlotState",
    "AdapterStore",
    "AdapterPoolFullError",
    "LoraTrainer",
    "adapter_pool_accounting",
    "predicted_adapter_hit_rate",
    "allocate",
    "release",
    "pages_for",
    "kv_pool_accounting",
    "synthesize_trace",
    "replay",
    "static_batching_report",
    "predicted_pool_utilization",
]
