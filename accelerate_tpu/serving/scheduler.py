"""Continuous-batching scheduler — iteration-level admission, chunked
prefill, and page-pressure eviction (Orca/vLLM discipline).

Pure **host-side, deterministic** bookkeeping: given the same request trace
and the same plugin knobs, every decision (admission order, chunk sizes,
interleave, evictions) replays identically — the engine executes on device,
this module only decides.  The scheduler mirrors the device allocator's free
count with the same arithmetic (``paged_cache.pages_for``), so it can evict
*before* a device-side pop could underflow, without a per-step device->host
sync.

Policy (every knob in :class:`~accelerate_tpu.utils.dataclasses.ServingPlugin`):

- **Admission**: FIFO, with a **bounded-age adapter bypass** in multi-tenant
  mode.  A waiting request is admitted when a decode slot is free and the
  pool has pages for its prompt; a request carrying an ``adapter_id`` must
  additionally have its adapter pin-able in the
  :class:`~.adapters.AdapterStore` pool BEFORE it is scheduled (admission
  pins — a scheduled request never waits on a swap mid-decode).  When the
  head of the line is blocked on adapter-pool contention, younger
  requests whose adapters are resident (or who carry none) may admit past
  it — but only for ``max_bypass_age`` engine ticks: after that the line
  holds until the head admits, so a tenant whose adapter needs a swap
  cannot be starved by an endless stream of zero-swap arrivals (the
  fairness contract, pinned by a deterministic trace test).
- **Chunked prefill**: admitted prompts prefill in chunks of at most
  ``prefill_chunk`` tokens, padded up to the smallest **shape bucket** so the
  jitted prefill step compiles once per bucket, never mid-traffic.
- **Interleave**: prefill and decode alternate whenever both have work, so
  a burst of long prompts cannot starve in-flight decodes (and vice versa).
- **Eviction**: when a decode step needs more fresh pages than the pool has,
  the **youngest admitted** sequence is preempted — its pages are released
  and the request requeues at the head of the waiting line with its prompt
  intact (recompute-on-readmit, the vLLM default).
- **Prefix reuse** (with a :class:`~.prefix_cache.PrefixCache` armed):
  admission matches the prompt's content-addressed full-page prefix against
  the index, strikes the hit from the page demand, pins the hit pages
  (one refcount per page) and starts chunked prefill AT the hit boundary —
  the shared region is never recomputed.  Page-pressure paths reclaim LRU
  **index-only** pages (refcount 1: cached, referenced by no live slot)
  before ever evicting a live sequence; a page some slot still shares is
  never a victim (the AdapterStore refcount-LRU rule).  Every release path
  routes through ``_release_slot_pages``: private pages free by count,
  shared pages drop one refcount and free only at zero.
- **Overload control** (docs/serving.md "Overload & deadlines"): the waiting
  line is bounded (``max_queue``) and sheds when the bound or the
  **predicted KV pressure** (used pages + every queued prompt's admission
  demand, as a pool fraction vs ``kv_shed_watermark``) is exceeded.  The
  shed policy is deterministic: **oldest-beyond-deadline first**, then the
  youngest arrival (the newcomer backs off).  Sheds never touch admitted
  sequences — load shedding is an admission-control decision.
- **Deadlines**: a request carrying ``deadline_ticks`` expires
  ``deadline_ticks`` engine ticks after ``arrival_step``; expired queued
  requests shed (reason ``"deadline"``) and expired in-flight requests are
  cancelled by the engine through :meth:`cancel_slot` — both count as
  ``deadline_misses``.
- **Cancellation**: :meth:`cancel_queued` / :meth:`cancel_slot` retire a
  request at any stage, releasing every resource it holds (pages by the
  same ``pages_for(kv_tokens)`` arithmetic finish/evict use, the slot, the
  adapter refcount).  ``retired_uids`` records deliberate retirements so a
  preemption drain never hands a cancelled request back.

Every decision appends to ``events`` — the determinism log now including
``("shed", uid, reason)`` / ``("cancel", uid, stage, reason)`` /
``("ladder", stage)`` entries, pinned by tests/test_overload.py.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

import numpy as np

from .paged_cache import pages_for


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request.

    ``arrival_step`` is in *virtual engine-step time* (the replay harness
    feeds arrivals deterministically by step index, not wall clock).
    ``adapter_id`` is the requesting TENANT's LoRA adapter (0 = the base
    model); admission maps it to a device pool slot through the
    :class:`~.adapters.AdapterStore`.  ``deadline_ticks`` is the request's
    latency budget in the same virtual time: the request expires
    ``deadline_ticks`` ticks after ``arrival_step`` (0 = no deadline) —
    expired queued requests shed, expired in-flight requests cancel, and
    both count as deadline misses.
    """

    uid: int
    prompt: tuple  # int token ids
    max_new_tokens: int
    arrival_step: int = 0
    adapter_id: int = 0
    deadline_ticks: int = 0

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)


@dataclasses.dataclass
class SlotState:
    """Host-side record of one occupied decode slot."""

    request: Request
    admit_seq: int                 # monotone admission counter (eviction order)
    prefilled: int = 0             # prompt tokens written so far
    tokens: Optional[list] = None  # generated token ids
    last_token: int = 0            # decode input for the next step
    finished: bool = False
    adapter_slot: int = 0          # device pool slot the request decodes with
    shared_pages: Optional[list] = None  # prefix-cache page ids this slot
                                   # holds a refcount on — ALWAYS a
                                   # contiguous block-table row prefix
                                   # (adopted prefix + own inserted pages);
                                   # the COW release program skips exactly
                                   # these, the host unrefs them
    kv_len: Optional[int] = None   # explicit device-side KV length (speculative
                                   # decode: EOS inside an accepted window can
                                   # retire the HOST stream short of the KV the
                                   # verify pass already wrote — page accounting
                                   # must follow the device, not len(tokens))

    def __post_init__(self):
        if self.tokens is None:
            self.tokens = []
        if self.shared_pages is None:
            self.shared_pages = []

    @property
    def prefill_done(self) -> bool:
        return self.prefilled >= self.request.prompt_len

    @property
    def seq_len(self) -> int:
        # tokens written into the KV cache (prompt prefix + decoded tokens;
        # the latest sampled token is written by the NEXT decode step)
        return self.prefilled + max(0, len(self.tokens) - 1)

    @property
    def kv_tokens(self) -> int:
        """Tokens actually resident in the device KV cache — ``seq_len``
        unless a verify pass pinned an explicit ``kv_len`` (speculative
        mode).  ALL page arithmetic (evict/finish/need) keys off this, so
        the host free-page mirror tracks the device allocator exactly."""
        return self.kv_len if self.kv_len is not None else self.seq_len


class ContinuousBatchingScheduler:
    """Deterministic admit/prefill/decode/evict policy over a fixed slot set.

    The engine asks :meth:`admit` each tick, then :meth:`next_action`;
    it reports executed work back through ``note_*`` so the host page mirror
    stays exact.  ``events`` is the decision log the determinism test pins.
    """

    def __init__(self, num_slots: int, num_pages: int, page_size: int,
                 pages_per_slot: int, prefill_chunk: int, prefill_buckets: tuple,
                 adapters=None, max_bypass_age: int = 16, speculate_k: int = 0,
                 max_queue: int = 0, kv_shed_watermark: float = 0.0,
                 default_deadline_ticks: int = 0, prefix=None):
        self.num_slots = num_slots
        self.num_pages = num_pages
        self.page_size = page_size
        self.pages_per_slot = pages_per_slot
        self.prefill_chunk = prefill_chunk
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        self.adapters = adapters             # AdapterStore (multi-tenant mode)
        self.prefix = prefix                 # PrefixCache (COW prefix reuse)
        self.max_bypass_age = max_bypass_age
        self.speculate_k = speculate_k       # admission reserves verify pages
        self.max_queue = max_queue           # waiting-line bound (0 = unbounded)
        self.kv_shed_watermark = kv_shed_watermark  # predicted-pressure shed (0 = off)
        self.default_deadline_ticks = default_deadline_ticks
        self.waiting: deque[Request] = deque()
        self.slots: dict[int, SlotState] = {}
        self.free_slots: list[int] = list(range(num_slots))
        self.free_pages = num_pages          # host mirror of the device stack
        self.tick = 0                        # virtual engine time (the engine
                                             # sets it each step; deadlines
                                             # expire against it)
        self._admit_counter = 0
        self._last_was_prefill = False
        self._head_block_age = 0             # ticks the line head has been
        self._head_block_uid = None          # adapter-blocked (fairness bound)
        self.events: list[tuple] = []        # the determinism log
        # overload / cancellation bookkeeping (docs/serving.md): the ladder
        # mutates the two knobs below; the counters feed the serving report
        self.admission_reserve_pages = 0     # tightened-admission free floor
        self.shed_armed = False              # ladder stage 4: queue clamps to
                                             # num_slots and sheds aggressively
        self.requests_shed = 0
        self.deadline_misses = 0
        self.cancelled = 0
        self.pages_reclaimed_on_cancel = 0
        self.retired_uids: set[int] = set()  # shed/cancelled — deliberately
                                             # retired, never handed back
        self.evicted_keep: dict[int, int] = {}  # slot -> shared-prefix page
                                             # count parked by evict() for
                                             # the engine's COW release
        self._prefix_counted: set[int] = set()  # uids already counted in
                                             # the hit-rate twin (readmits
                                             # skip the rate counters)
        self._force_expired: set[int] = set()  # deadline-storm fault payload

    # -- queueing -----------------------------------------------------------

    def submit(self, request: Request) -> None:
        total = request.prompt_len + request.max_new_tokens
        cap = min(self.pages_per_slot, self.num_pages) * self.page_size
        if request.adapter_id:
            if self.adapters is None:
                raise ValueError(
                    f"request {request.uid} carries adapter_id="
                    f"{request.adapter_id} but the engine has no AdapterStore"
                )
            if not self.adapters.known(request.adapter_id):
                raise ValueError(
                    f"request {request.uid}: adapter {request.adapter_id} "
                    "was never published to the AdapterStore"
                )
        if request.prompt_len < 1:
            raise ValueError(f"request {request.uid}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.uid}: max_new_tokens must be >= 1 "
                f"(got {request.max_new_tokens})"
            )
        if total > cap:
            raise ValueError(
                f"request {request.uid}: prompt+max_new_tokens={total} exceeds "
                f"the per-sequence KV capacity {cap} "
                f"(min(pages_per_slot={self.pages_per_slot}, "
                f"num_pages={self.num_pages}) * page_size={self.page_size})"
            )
        if request.deadline_ticks < 0:
            raise ValueError(
                f"request {request.uid}: deadline_ticks must be >= 0 "
                f"(got {request.deadline_ticks})"
            )
        if request.deadline_ticks == 0 and self.default_deadline_ticks:
            request = dataclasses.replace(
                request, deadline_ticks=self.default_deadline_ticks
            )
        self.waiting.append(request)
        self.events.append(("submit", request.uid))
        # backpressure at the door: the bound holds between ticks too, so a
        # burst of submits can never grow the line past max_queue
        if self.max_queue:
            while len(self.waiting) > self.max_queue:
                self._shed(self._shed_victim(), "queue")

    def requeue_front(self, request: Request) -> None:
        self.waiting.appendleft(request)

    def mark_prefix_counted(self, uids) -> None:
        """Pre-seed the once-only offered-traffic set behind the prefix
        hit-rate twin: a request re-routed here after another replica
        drained (serving/router.py) was already counted as offered traffic
        at its FIRST admission — its re-admission on this scheduler must
        not count a second lookup, or the fleet's measured hit rate
        double-counts every drained request's preamble."""
        self._prefix_counted.update(uids)

    # -- deadlines / shedding / cancellation ---------------------------------

    def request_expired(self, req: Request) -> bool:
        """Has ``req``'s deadline passed at the current :attr:`tick`?  A
        deadline-storm fault (:mod:`~accelerate_tpu.resilience.faults`)
        force-expires live uids through :meth:`force_expire_all`."""
        if req.uid in self._force_expired:
            return True
        return bool(req.deadline_ticks) and \
            self.tick >= req.arrival_step + req.deadline_ticks

    def force_expire_all(self) -> None:
        """Deadline storm: every live request (queued + in-flight) expires
        NOW — queued ones shed on the next policy pass, in-flight ones are
        cancelled by the engine's deadline sweep."""
        for req in self.waiting:
            self._force_expired.add(req.uid)
        for st in self.slots.values():
            self._force_expired.add(st.request.uid)

    def _shed_victim(self) -> int:
        """Index into ``waiting`` of the deterministic shed victim:
        **oldest-beyond-deadline first** (earliest arrival, uid breaking
        ties), else the youngest arrival — the newcomer backs off."""
        expired = [
            i for i, req in enumerate(self.waiting) if self.request_expired(req)
        ]
        if expired:
            return min(expired, key=lambda i: (self.waiting[i].arrival_step,
                                               self.waiting[i].uid))
        return max(range(len(self.waiting)),
                   key=lambda i: (self.waiting[i].arrival_step,
                                  self.waiting[i].uid))

    def _shed(self, idx: int, reason: str) -> Request:
        req = self.waiting[idx]
        del self.waiting[idx]
        self.requests_shed += 1
        # an expired victim is a deadline miss whatever triggered the shed
        # (the queue bound may pick the oldest-beyond-deadline first —
        # shedding it one tick earlier must not hide the miss)
        if reason == "deadline" or self.request_expired(req):
            self.deadline_misses += 1
        self.retired_uids.add(req.uid)
        self._force_expired.discard(req.uid)
        self.events.append(("shed", req.uid, reason))
        return req

    def predicted_kv_pressure(self) -> float:
        """Predicted pool pressure if the whole waiting line admitted: used
        pages plus every queued prompt's admission demand, as a fraction of
        the pool (the ``kv_shed_watermark`` comparand)."""
        demand = sum(self.admission_page_need(r) for r in self.waiting)
        return (self.used_pages + demand) / self.num_pages

    def _enforce_queue_policy(self) -> None:
        """The per-tick admission-control pass, in deterministic order:
        (1) expired queued requests shed (deadline misses), (2) the queue
        bound holds, (3) predicted KV pressure sheds down to the watermark,
        (4) the ladder's shed stage clamps the line to ``num_slots``."""
        i = 0
        while i < len(self.waiting):
            if self.request_expired(self.waiting[i]):
                self._shed(i, "deadline")
            else:
                i += 1
        if self.max_queue:
            while len(self.waiting) > self.max_queue:
                self._shed(self._shed_victim(), "queue")
        if self.kv_shed_watermark:
            while self.waiting and \
                    self.predicted_kv_pressure() > self.kv_shed_watermark:
                self._shed(self._shed_victim(), "kv_pressure")
        if self.shed_armed:
            while len(self.waiting) > self.num_slots:
                self._shed(self._shed_victim(), "overload")

    def cancel_queued(self, uid: int, reason: str = "cancel") -> bool:
        """Retire a still-queued request.  Returns False when ``uid`` is not
        in the waiting line (idempotent — the engine's cancel API retries at
        whatever stage the request is actually in)."""
        for i, req in enumerate(self.waiting):
            if req.uid == uid:
                del self.waiting[i]
                self._retire_cancelled(req, "queued", reason, 0)
                return True
        return False

    def cancel_slot(self, slot: int, reason: str = "cancel") -> Request:
        """Retire an admitted request at whatever stage it is in
        (mid-prefill-chunk or decoding), releasing the slot, its pages (the
        same ``pages_for(kv_tokens)`` arithmetic finish/evict use — the
        engine releases the device side with the same mask first) and its
        adapter hold.  The resource contract
        :func:`~.overload.verify_serving_invariants` pins."""
        st = self.slots.pop(slot)
        freed = self._release_slot_pages(st)
        self.free_slots.append(slot)
        self.free_slots.sort()
        if self.adapters is not None:
            self.adapters.unpin(st.request.adapter_id)
        stage = "decode" if st.prefill_done else "prefill"
        self._retire_cancelled(st.request, stage, reason, freed)
        return st.request

    def _retire_cancelled(self, req: Request, stage: str, reason: str,
                          freed: int) -> None:
        self.pages_reclaimed_on_cancel += freed
        if reason == "deadline":
            self.deadline_misses += 1
        else:
            self.cancelled += 1
        self.retired_uids.add(req.uid)
        self._force_expired.discard(req.uid)
        self.events.append(("cancel", req.uid, stage, reason))

    def _release_slot_pages(self, st: SlotState) -> int:
        """The ONE host-side page-release arithmetic (finish, evict and
        cancel all route through it, so the mirror can never drift between
        retirement paths): private pages — everything past the slot's
        shared prefix — free immediately (the engine's COW release program
        pushes exactly those device-side); shared pages drop ONE refcount
        each, and only the ones that reach zero join the free count (they
        queue for the engine's ``push_free`` dispatch — ``release`` never
        pushes an aliased page).  Returns the pages added to the free
        mirror."""
        total = int(pages_for(st.kv_tokens, self.page_size))
        shared = len(st.shared_pages)
        freed = total - shared
        if shared and self.prefix is not None:
            freed += self.prefix.unref_pages(st.shared_pages)
        self.free_pages += freed
        return freed

    # -- admission ----------------------------------------------------------

    def _adapter_ready(self, req: Request) -> bool:
        return (self.adapters is None or req.adapter_id == 0
                or self.adapters.can_pin(req.adapter_id))

    def _pick_admissible(self) -> Optional[int]:
        """Index into ``waiting`` of the next request admission may take:
        the head when its adapter is pin-able, else — within the bounded
        bypass age — the first younger request that is.  ``None`` holds the
        line (head blocked past its age bound, or nothing ready)."""
        if self._adapter_ready(self.waiting[0]):
            return 0
        if self._head_block_age > self.max_bypass_age:
            return None  # fairness: the starved head gets the next free slot
        for i in range(1, len(self.waiting)):
            if self._adapter_ready(self.waiting[i]):
                return i
        return None

    def admit(self) -> list[int]:
        """Admit while a slot is free and the pool can hold the whole
        prompt (prefill feasibility — decode growth is the eviction path's
        job, and ``submit`` already guarantees a lone sequence can never
        outgrow the pool, so admission must not demand more than the pool
        can EVER offer or a submit-accepted request would wait forever).
        FIFO, except that a head blocked on adapter-pool contention is
        bypassed by adapter-ready requests for at most ``max_bypass_age``
        ticks (see the module policy).  Admission PINS the request's
        adapter before scheduling it.  The overload-control pass (deadline
        expiry, queue bound, KV-pressure watermark) runs first, and a
        tightened ladder (:attr:`admission_reserve_pages`) additionally
        keeps a free-page floor the admitted prompt may not dip under.
        Returns the admitted slot ids."""
        self._enforce_queue_policy()
        if self.adapters is not None:
            # hot-swap streaming: dispatch the next arrivals' adapter uploads
            # under the current step's compute (LayerPrefetcher double
            # buffer; a no-op for resident or already-in-flight adapters)
            for req in list(self.waiting)[:2]:
                if req.adapter_id:
                    self.adapters.prefetch(req.adapter_id)
        if self.waiting and not self._adapter_ready(self.waiting[0]):
            head = self.waiting[0]
            # one fairness tick per engine step the head stays blocked
            if self._head_block_uid != head.uid:
                self._head_block_uid = head.uid
                self._head_block_age = 0
            self._head_block_age += 1
            if self.adapters is not None and head.adapter_id:
                # stream the starved tenant's adapter NOW so the pin is a
                # hit the moment a pool slot frees
                self.adapters.prefetch(head.adapter_id)
        else:
            self._head_block_uid = None
            self._head_block_age = 0
        admitted = []
        while self.waiting and self.free_slots:
            idx = self._pick_admissible()
            if idx is None:
                break
            req = self.waiting[idx]
            hashes = hit = ()
            if self.prefix is not None:
                hashes = self.prefix.block_hashes(req.prompt, req.adapter_id)
                hit = self.prefix.match(hashes)
            # the tightened-admission reserve only applies while the pool is
            # actually contended: with zero occupied slots the head admits
            # regardless, so tightening can never idle-spin an empty engine
            # (the admit-vs-submit livelock guard, extended to the ladder)
            reserve = self.admission_reserve_pages if self.slots else 0
            if self.prefix is not None:
                # anti-thrash headroom: a prefix hit makes readmission almost
                # free (the shared region costs nothing), so an evicted
                # request could instantly steal the pages a RUNNING slot
                # needs to grow — and the two then evict each other forever.
                # One page of decode headroom per occupied slot keeps
                # admission from packing past the in-flight set's next step;
                # zero occupied slots ⇒ zero headroom (the livelock guard)
                reserve += len(self.slots)
            need = self.admission_page_need(req, hit_pages=len(hit))
            if need > self.free_pages - reserve:
                # index-only cached pages are the cheapest capacity there is:
                # reclaim them LRU before refusing the admission — but never
                # the pages this very admission just matched (the
                # match→adopt window), and never a page a live slot still
                # references (the AdapterStore rule)
                self._reclaim(need + reserve, protect=frozenset(hit))
                if need > self.free_pages - reserve:
                    break
            del self.waiting[idx]
            adapter_slot = 0
            if self.adapters is not None and req.adapter_id:
                adapter_slot, swapped = self.adapters.pin(req.adapter_id)
                if swapped:
                    self.events.append(("swap", req.adapter_id, adapter_slot))
            if idx > 0:
                self.events.append(("bypass", req.uid, self.waiting[0].uid))
            slot = self.free_slots.pop(0)
            shared: list = []
            hit_tokens = 0
            if hashes:
                # commit the hit (adopt re-matches — the protected reclaim
                # guarantees it finds at least the probed prefix): the slot
                # takes a refcount on every shared page, prefill starts at
                # the hit boundary (chunked prefill skips the shared region
                # entirely), and the engine's adopt program writes the ids
                # into the block-table row.  A readmission (evicted earlier
                # this replay) skips the hit-RATE counters — the twin's
                # predicted replay cannot see recompute churn
                shared = self.prefix.adopt(
                    hashes, count=req.uid not in self._prefix_counted
                )
                self._prefix_counted.add(req.uid)
                hit_tokens = len(shared) * self.page_size
                if shared:
                    self.events.append(("prefix_hit", req.uid, hit_tokens))
                    if len(shared) < len(hashes):
                        self.events.append(("cow_fork", req.uid))
            self.slots[slot] = SlotState(req, self._admit_counter,
                                         adapter_slot=adapter_slot,
                                         shared_pages=shared,
                                         prefilled=hit_tokens)
            self._admit_counter += 1
            admitted.append(slot)
            self.events.append(("admit", req.uid, slot))
        return admitted

    def _reclaim(self, demand: int, protect: frozenset = frozenset()) -> int:
        """Free LRU index-only prefix pages until ``free_pages >= demand``
        (best effort).  Freed ids queue in the prefix cache's
        ``pending_free`` for the engine's next ``push_free`` dispatch; the
        host mirror counts them immediately (the decision-time convention
        every release path uses).  ``protect`` exempts matched-but-not-yet-
        adopted pages.  Returns pages reclaimed."""
        freed = 0
        while self.free_pages < demand and self.prefix is not None:
            page = self.prefix.reclaim_one(protect)
            if page is None:
                break
            self.free_pages += 1
            freed += 1
            self.events.append(("prefix_evict", page))
        return freed

    def admission_page_need(self, req: Request,
                            hit_pages: Optional[int] = None) -> int:
        """Pages admission demands before scheduling ``req``: the prompt,
        plus — in speculative mode — the worst-case pages of the request's
        FIRST verify pass (positions ``prompt_len .. prompt_len + depth``,
        depth clamped to the request's own token budget).  The clamp keeps
        the demand within ``pages_for(prompt + max_new)``, which ``submit``
        already guarantees the pool can offer — the speculative reservation
        can never re-introduce the admit-vs-submit livelock.

        With a :class:`~.prefix_cache.PrefixCache` armed, the longest
        cached prefix's pages come from the index, not the free pool —
        ``hit_pages`` of the demand are struck (``None`` probes the index;
        pass the count when the caller already matched)."""
        if hit_pages is None:
            hit_pages = 0
            if self.prefix is not None:
                hit_pages = len(self.prefix.match(
                    self.prefix.block_hashes(req.prompt, req.adapter_id)))
        if not self.speculate_k:
            return pages_for(req.prompt_len, self.page_size) - hit_pages
        depth = min(self.speculate_k, req.max_new_tokens - 1)
        return pages_for(req.prompt_len + 1 + depth, self.page_size) - hit_pages

    # -- the per-tick decision ----------------------------------------------

    def prefilling_slots(self) -> list[int]:
        return sorted(
            (s for s, st in self.slots.items() if not st.prefill_done),
            key=lambda s: self.slots[s].admit_seq,
        )

    def decoding_slots(self) -> list[int]:
        return sorted(
            s for s, st in self.slots.items()
            if st.prefill_done and not st.finished
        )

    def next_action(self):
        """``("prefill", slot, start, chunk_len, bucket)`` or
        ``("decode", slots)`` or ``("idle",)`` — prefill and decode alternate
        when both have work."""
        pre = self.prefilling_slots()
        dec = self.decoding_slots()
        do_prefill = bool(pre) and not (dec and self._last_was_prefill)
        if do_prefill:
            slot = pre[0]
            st = self.slots[slot]
            start = st.prefilled
            chunk = min(self.prefill_chunk, st.request.prompt_len - start)
            self._last_was_prefill = True
            return ("prefill", slot, start, chunk, self.bucket_for(chunk))
        self._last_was_prefill = False
        if dec:
            return ("decode", dec)
        return ("idle",)

    def bucket_for(self, chunk_len: int) -> int:
        for b in self.prefill_buckets:
            if b >= chunk_len:
                return b
        return self.prefill_buckets[-1]

    # -- page-pressure eviction ---------------------------------------------

    def decode_page_need(self, slots: list[int]) -> list[int]:
        """Slots whose next decode token crosses a page boundary (needs a
        fresh page this step)."""
        return [
            s for s in slots
            if self.slots[s].kv_tokens % self.page_size == 0
        ]

    def verify_page_need(self, slots: list[int], spec_lens: dict) -> dict:
        """Worst-case fresh pages per slot for one speculative verify pass:
        page starts among the written positions ``[kv, kv + spec_len]``.
        The pass itself rolls rejected pages back, but eviction must plan
        for the peak — the device allocator pops the worst case before the
        acceptance comparison exists."""
        from .speculate import speculative_page_need

        return {
            s: speculative_page_need(self.slots[s].kv_tokens,
                                     spec_lens.get(s, 0), self.page_size)
            for s in slots
        }

    def plan_speculative_evictions(self, slots: list[int],
                                   spec_lens: dict) -> tuple[list[int], list[int]]:
        """Fit the verify pass's worst-case page demand — **degrade before
        evicting**: the speculative reservation is transient (rejected
        drafts roll their pages straight back), so paying for it by
        evicting a LIVE sequence (recompute-on-readmit: every generated
        token revoked) is a terrible trade.  Under pressure the planner
        first zeroes draft depths in ``spec_lens`` — youngest-admitted
        first, mirroring the eviction order — which shrinks each slot's
        demand to the plain-decode floor (a depth-0 lane IS plain decode);
        only when the floor itself does not fit does the shared
        evict-until-fit loop run.  Mutates ``spec_lens`` in place (the
        engine builds the pass from it) and returns ``(surviving_slots,
        evicted_slots)``."""
        active = list(slots)

        def over():
            return (sum(self.verify_page_need(active, spec_lens).values())
                    > self.free_pages)

        degraded = []
        while over():
            victims = [
                s for s in sorted(active,
                                  key=lambda s: -self.slots[s].admit_seq)
                if spec_lens.get(s, 0) > 0
            ]
            if not victims:
                break
            spec_lens[victims[0]] = 0
            degraded.append(victims[0])
        if degraded:
            self.events.append(("despeculate", tuple(degraded)))
        evicted = self._evict_until(
            active,
            lambda a: sum(self.verify_page_need(a, spec_lens).values())
            <= self.free_pages,
        )
        return active, evicted

    def _evict_until(self, active: list[int], fits) -> list[int]:
        """The one evict-until-fit loop (plain AND speculative decode share
        it, so victim policy can never drift between the modes): evict the
        youngest-admitted sequence — removing it from ``active`` when it
        was scheduled this tick — until ``fits(active)``.  Returns the
        evicted slots."""
        evicted = []
        while not fits(active):
            # cached-but-unreferenced prefix pages are cheaper capacity than
            # any live sequence (eviction = recompute-on-readmit): reclaim
            # one LRU index-only page and re-test before picking a victim
            if self._reclaim(self.free_pages + 1):
                continue
            # finished slots are exempt: a hold_finished (prefill-role)
            # engine parks finished sequences — pages intact — awaiting the
            # KV transfer; evicting one would requeue an already-finished
            # request and orphan the engine's held-slot bookkeeping
            victims = sorted(
                (s for s in self.slots if not self.slots[s].finished),
                key=lambda s: -self.slots[s].admit_seq,
            )
            if not victims:  # pragma: no cover - submit() capacity guard
                break
            victim = victims[0]
            self.evict(victim)
            evicted.append(victim)
            if victim in active:
                active.remove(victim)
        return evicted

    def plan_evictions(self, slots: list[int]) -> tuple[list[int], list[int]]:
        """Evict youngest-admitted sequences until this decode step's fresh
        pages fit the pool.  Returns ``(surviving_decode_slots,
        evicted_slots)``; the evicted requests are requeued at the front."""
        active = list(slots)
        evicted = self._evict_until(
            active, lambda a: len(self.decode_page_need(a)) <= self.free_pages
        )
        return active, evicted

    def plan_prefill_evictions(self, slot: int, chunk_len: int) -> tuple[bool, list[int]]:
        """Make room for one prefill chunk's fresh pages.  Prefers evicting
        OTHER sequences (youngest first); falls back to cancelling the
        prefilling slot itself when it is the only tenant left.  Returns
        ``(slot_survived, evicted_slots)``."""
        evicted = []
        while True:
            st = self.slots.get(slot)
            if st is None:
                return False, evicted
            needed = (pages_for(st.prefilled + chunk_len, self.page_size)
                      - pages_for(st.prefilled, self.page_size))
            if needed <= self.free_pages:
                return True, evicted
            if self._reclaim(needed):  # index-only pages first, always
                continue
            victims = sorted(
                (s for s in self.slots
                 if s != slot and not self.slots[s].finished),
                key=lambda s: -self.slots[s].admit_seq,
            ) or [slot]
            self.evict(victims[0])
            evicted.append(victims[0])

    def evict(self, slot: int) -> Request:
        st = self.slots.pop(slot)
        # the engine's device-side COW release runs AFTER this pop: park the
        # keep count so the release program still skips the shared prefix
        # (pushing an aliased page here is exactly the double-free the
        # refcount guard exists for)
        if self.prefix is not None:
            self.evicted_keep[slot] = len(st.shared_pages)
        self._release_slot_pages(st)
        self.free_slots.append(slot)
        self.free_slots.sort()
        if self.adapters is not None:
            # drop THIS request's hold only — the adapter itself stays hot
            # while other in-flight requests share it (refcount pinning:
            # evicting a request never evicts a shared hot adapter)
            self.adapters.unpin(st.request.adapter_id)
        self.requeue_front(st.request)
        self.events.append(("evict", st.request.uid, slot))
        return st.request

    # -- execution feedback (keeps the host page mirror exact) ---------------

    def note_prefill(self, slot: int, chunk_len: int) -> None:
        st = self.slots[slot]
        before = pages_for(st.prefilled, self.page_size)
        st.prefilled += chunk_len
        self.free_pages -= pages_for(st.prefilled, self.page_size) - before
        self.events.append(("prefill", st.request.uid, slot, st.prefilled))

    def note_decode(self, slots_needing_pages: list[int],
                    active_slots: Optional[list] = None) -> None:
        self.free_pages -= len(slots_needing_pages)
        if active_slots:
            # a slot carrying an explicit kv_len (set by an earlier verify
            # pass) advances it here too: a despeculated plain-decode step
            # writes exactly one KV position per active slot, and the page
            # arithmetic must keep following the device
            for s in active_slots:
                st = self.slots.get(s)
                if st is not None and st.kv_len is not None:
                    st.kv_len += 1
        self.events.append(("decode", tuple(sorted(slots_needing_pages))))

    def note_verify(self, accepted: dict) -> None:
        """Execution feedback for one verify pass: ``accepted`` maps each
        dispatched slot to the device-accepted draft count ``m`` (the pass
        emitted ``m + 1`` tokens and kept exactly the pages covering them —
        the rejected remainder was rolled back on device).  Advancing
        ``kv_len`` by ``m + 1`` per slot keeps the host free-page mirror
        exact against the allocate-then-push_pages arithmetic."""
        consumed = 0
        for slot in sorted(accepted):
            m = int(accepted[slot])
            st = self.slots[slot]
            kv = st.kv_tokens
            consumed += int(pages_for(kv + m + 1, self.page_size)
                            - pages_for(kv, self.page_size))
            st.kv_len = kv + m + 1
        self.free_pages -= consumed
        self.events.append(
            ("verify", tuple((s, int(accepted[s])) for s in sorted(accepted)))
        )

    def finish(self, slot: int) -> SlotState:
        """Retire a finished sequence: free its pages and its slot."""
        st = self.slots.pop(slot)
        st.finished = True
        self._release_slot_pages(st)
        self.free_slots.append(slot)
        self.free_slots.sort()
        if self.adapters is not None:
            self.adapters.unpin(st.request.adapter_id)
        self._force_expired.discard(st.request.uid)
        self.events.append(("finish", st.request.uid, slot))
        return st

    @property
    def used_pages(self) -> int:
        return self.num_pages - self.free_pages

    def idle(self) -> bool:
        return not self.waiting and not self.slots
