"""The serving engine: paged-KV decode + continuous batching over one model.

This is the production-scale rebuild of the reference's
``inference.py``/``big_modeling.py`` contract (PAPER.md L5): where
:func:`~accelerate_tpu.generation.generate` runs one fixed batch start-to-
finish, the engine keeps a fixed set of **decode slots** and a fixed-size
**page pool** busy under live traffic — requests are admitted, chunk-
prefilled, decoded and retired *per step*, so a finished short request's
slot and pages immediately serve the next arrival instead of padding out
the longest sequence in the batch.

Execution contract:

- every device step is one of THREE jitted programs with **fixed shapes**
  (one decode shape, one prefill shape per bucket, one release shape) — no
  recompiles mid-traffic;
- the cache pytree is **donated** through every step: pools update in place
  (graft-lint GL101/GL201-clean — ``audit_decode_step`` checks on demand);
- the decode loop is host-driven (tokens must surface per step for EOS/
  stop handling anyway — the same shape as ``generate_streamed``'s loop);
- sampling reuses :func:`~accelerate_tpu.generation.sample_logits`, so
  greedy serving emits tokens identical to ``generate()`` (pinned by
  tests/test_serving.py).
"""

from __future__ import annotations

import dataclasses
import time
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.compiled_audit import install_global_compile_counter
from ..generation import GenerationConfig, sample_logits
from ..models.llama import init_paged_cache
from ..resilience import faults as _faults
from ..telemetry import RequestTracer
from ..utils.dataclasses import ServingPlugin, TelemetryPlugin
from .overload import DegradationLadder
from .paged_cache import allocate, pages_for, push_pages, release
from .prefix_cache import PrefixCache
from .scheduler import ContinuousBatchingScheduler, Request, SlotState
from .speculate import Speculator, make_draft_provider, speculative_page_need


def _layer_view(layer, block_tables):
    """One layer's model-facing cache dict.  Quantized pools
    (``ServingPlugin.kv_dtype``) carry their per-(kv-head, page) scale
    arrays alongside the pages — the model detects ``k_scales`` and routes
    quantize-on-write / dequant-on-read."""
    view = {"k_pages": layer["k_pages"], "v_pages": layer["v_pages"],
            "block_tables": block_tables}
    if "k_scales" in layer:
        view["k_scales"] = layer["k_scales"]
        view["v_scales"] = layer["v_scales"]
    return view


def _layer_keep(layer):
    """The engine-side carry of one layer returned by the model (drop the
    per-step block-table alias, keep pages + scales)."""
    keep = {"k_pages": layer["k_pages"], "v_pages": layer["v_pages"]}
    if "k_scales" in layer:
        keep["k_scales"] = layer["k_scales"]
        keep["v_scales"] = layer["v_scales"]
    return keep


def _engine_step_fns(model, gen_config, page_size: int, lora: bool = False,
                     lora_kernel_mode: str = "auto"):
    """The raw (un-jitted) device-program bodies.  :func:`_engine_fns`
    wraps them in the process-shared jit cache for serving;
    :func:`fresh_engine_jits` wraps them fresh for the deploy preflight,
    whose executable-level stats must come from a real compile.

    With ``lora=True`` (multi-tenant mode) decode/prefill additionally take
    the adapter pool (the ``lora`` variable collection — read-only here;
    the AdapterStore's donated insert program owns its mutation) and the
    per-slot adapter ids.  The ids are **normal array arguments**: any
    tenant mix reuses the same compiled program (the fixed-shape contract
    ``strict_compiles`` enforces).  ``lora_kernel_mode`` is applied as a
    SCOPED override around every trace (and keys the program cache), so
    two engines with different kernel knobs never share a traced program
    and engine construction never retargets the process-global mode."""
    if lora:
        from ..ops.lora import lora_kernel

        raw_apply = model.apply

        def apply(*args, **kwargs):
            with lora_kernel(lora_kernel_mode):
                return raw_apply(*args, **kwargs)
    else:
        apply = model.apply

    def decode_step(params, lora_pool, cache, tokens, active, adapter_slots, rng):
        # one token for every slot at once; dead slots write nowhere and
        # their sampled token is ignored by the host
        seq_lens = cache["seq_lens"]
        pos = seq_lens
        n_slots = tokens.shape[0]
        need = active & (pos % page_size == 0)
        block_tables, free_top = allocate(
            cache["block_tables"], cache["free_stack"], cache["free_top"],
            jnp.arange(n_slots, dtype=jnp.int32), pos // page_size, need,
        )
        layer_caches = [_layer_view(l, block_tables) for l in cache["layers"]]
        variables = {**params, "lora": lora_pool} if lora else params
        kwargs = {"adapter_ids": adapter_slots} if lora else {}
        logits, new_layers = apply(
            variables, tokens[:, None], positions=pos[:, None],
            cache=layer_caches, cache_write_mask=active[:, None], **kwargs,
        )
        next_tok = sample_logits(logits[:, 0], rng, gen_config)
        new_cache = {
            "layers": [_layer_keep(l) for l in new_layers],
            "block_tables": block_tables,
            "seq_lens": seq_lens + active.astype(jnp.int32),
            "free_stack": cache["free_stack"],
            "free_top": free_top,
        }
        return new_cache, next_tok

    def prefill_step(params, lora_pool, cache, slot, chunk_ids, start, chunk_len,
                     adapter_slot):
        # one bucket-padded chunk of one sequence's prompt; returns the
        # logits of the chunk's last REAL token (the decode-loop seed once
        # the prompt completes)
        width = chunk_ids.shape[0]
        positions = start + jnp.arange(width, dtype=jnp.int32)
        wmask = jnp.arange(width) < chunk_len
        need = wmask & (positions % page_size == 0)
        block_tables, free_top = allocate(
            cache["block_tables"], cache["free_stack"], cache["free_top"],
            jnp.full((width,), slot, jnp.int32), positions // page_size, need,
        )
        row = jax.lax.dynamic_slice_in_dim(block_tables, slot, 1, axis=0)
        layer_caches = [_layer_view(l, row) for l in cache["layers"]]
        variables = {**params, "lora": lora_pool} if lora else params
        kwargs = {"adapter_ids": jnp.reshape(adapter_slot, (1,))} if lora else {}
        logits, new_layers = apply(
            variables, chunk_ids[None], positions=positions[None],
            cache=layer_caches, cache_write_mask=wmask[None], **kwargs,
        )
        last = jnp.take(logits[0], chunk_len - 1, axis=0)
        new_cache = {
            "layers": [_layer_keep(l) for l in new_layers],
            "block_tables": block_tables,
            "seq_lens": cache["seq_lens"].at[slot].set(start + chunk_len),
            "free_stack": cache["free_stack"],
            "free_top": free_top,
        }
        return new_cache, last

    def verify_step(params, lora_pool, cache, tokens, spec_len, active,
                    adapter_slots, rng):
        # speculative draft-and-verify: ONE fixed-shape pass of width
        # w = bucket + 1 per active slot — lane 0 is the slot's last sampled
        # token (the plain decode input), lanes 1..spec_len its draft
        # proposals.  The pass (1) pops worst-case fresh pages for every
        # page-start among its candidate positions (multi-token paged
        # append: up to ceil(w/page)+1 block-table scatters per slot),
        # (2) writes K/V for the live lanes and computes the greedy target
        # token per lane through the same ragged paged attention the decode
        # step uses, (3) accepts the longest greedy-matching draft prefix,
        # and (4) rolls the pages past the accepted frontier back onto the
        # functional free-list — all inside the one donated jitted program.
        # Accepted tokens are BITWISE what sequential decode would emit.
        seq_lens = cache["seq_lens"]
        n, w = tokens.shape
        lane = jnp.arange(w, dtype=jnp.int32)
        positions = seq_lens[:, None] + lane[None, :]
        live = active[:, None] & (lane[None, :] <= spec_len[:, None])
        logical = positions // page_size
        need = live & (positions % page_size == 0)
        block_tables, free_top = allocate(
            cache["block_tables"], cache["free_stack"], cache["free_top"],
            jnp.repeat(jnp.arange(n, dtype=jnp.int32), w),
            logical.reshape(-1), need.reshape(-1),
        )
        layer_caches = [_layer_view(l, block_tables) for l in cache["layers"]]
        variables = {**params, "lora": lora_pool} if lora else params
        kwargs = {"adapter_ids": adapter_slots} if lora else {}
        logits, new_layers = apply(
            variables, tokens, positions=positions,
            cache=layer_caches, cache_write_mask=live, **kwargs,
        )
        # the exact sampling path decode uses (greedy: argmax over fp32) —
        # the token-parity pin is this shared code path, not a reimplementation
        greedy = sample_logits(
            logits.reshape(n * w, logits.shape[-1]), rng, gen_config
        ).reshape(n, w)
        # longest greedy-matching prefix: draft j accepted iff it equals the
        # target's token after consuming drafts 1..j-1 (greedy[:, j-1])
        match = (tokens[:, 1:] == greedy[:, :-1]) & \
            (lane[None, 1:] <= spec_len[:, None])
        m = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        new_seq_lens = seq_lens + jnp.where(active, m + 1, 0)
        # rollback: pages grabbed for positions past the accepted frontier
        # return to the stack (their stale K/V is unreadable — the next pass
        # rewrites any position before the positional mask can admit it)
        give_back = need & (positions >= new_seq_lens[:, None])
        pages = jnp.take_along_axis(
            block_tables, jnp.clip(logical, 0, block_tables.shape[1] - 1),
            axis=1,
        )
        free_stack, free_top = push_pages(
            cache["free_stack"], free_top, pages.reshape(-1),
            give_back.reshape(-1),
        )
        new_cache = {
            "layers": [_layer_keep(l) for l in new_layers],
            "block_tables": block_tables,
            "seq_lens": new_seq_lens,
            "free_stack": free_stack,
            "free_top": free_top,
        }
        return new_cache, greedy, m

    def release_step(cache, mask):
        seq_lens, free_stack, free_top = release(
            cache["block_tables"], cache["seq_lens"], cache["free_stack"],
            cache["free_top"], mask, page_size,
        )
        return {
            "layers": cache["layers"],
            "block_tables": cache["block_tables"],
            "seq_lens": seq_lens,
            "free_stack": free_stack,
            "free_top": free_top,
        }

    def sample_first(last, rng):
        return sample_logits(last[None], rng, gen_config)[0]

    if lora:
        return decode_step, prefill_step, release_step, sample_first, verify_step

    # single-tenant mode keeps the original program arity (the preflight
    # and every existing caller compile these signatures)
    def decode_legacy(params, cache, tokens, active, rng):
        return decode_step(params, None, cache, tokens, active, None, rng)

    def prefill_legacy(params, cache, slot, chunk_ids, start, chunk_len):
        return prefill_step(params, None, cache, slot, chunk_ids, start,
                            chunk_len, None)

    def verify_legacy(params, cache, tokens, spec_len, active, rng):
        return verify_step(params, None, cache, tokens, spec_len, active,
                           None, rng)

    return decode_legacy, prefill_legacy, release_step, sample_first, verify_legacy


def fresh_engine_jits(model, gen_config, page_size: int, lora: bool = False,
                      lora_kernel_mode: str = "auto"):
    """FRESH jit wrappers over the engine program bodies — deliberately
    outside the shared :func:`_engine_fns` cache.  The deploy preflight
    compiles through these: a wrapper another engine already drove may hold
    an executable deserialized from the persistent compilation cache, and
    deserialized executables LOSE their buffer-donation alias table
    (``memory_analysis().alias_size_in_bytes`` reads 0), which would turn
    every healthy donation into a GL301 false positive.

    Returns ``(decode, prefill, release, sample_first, verify)`` — one
    jitted ``verify`` covers the whole speculative bucket ladder (width is
    a trace-time shape, exactly like the prefill buckets)."""
    decode_step, prefill_step, release_step, sample_first, verify_step = \
        _engine_step_fns(model, gen_config, page_size, lora, lora_kernel_mode)
    cache_arg = 2 if lora else 1
    return (
        jax.jit(decode_step, donate_argnums=(cache_arg,)),
        jax.jit(prefill_step, donate_argnums=(cache_arg,)),
        jax.jit(release_step, donate_argnums=(0,)),
        jax.jit(sample_first),
        jax.jit(verify_step, donate_argnums=(cache_arg,)),
    )


def _prefix_step_fns(page_size: int):
    """The prefix-cache device programs (model-free — pure allocator
    arithmetic on the cache pytree, keyed by page geometry only):

    - ``adopt_step`` writes an admission's shared page ids into the slot's
      block-table row prefix and pins ``seq_lens[slot]`` at the hit
      boundary (the region chunked prefill will skip; no free-stack touch —
      shared pages were never free);
    - ``release_cow_step`` is the keep-aware COW release: per released slot
      it pushes ONLY the pages past ``keep_counts[slot]`` (the slot's
      shared prefix stays off the stack — the host refcounts decide when an
      aliased page actually frees);
    - ``push_free_step`` pushes an explicit masked id set (refcount-zero
      deaths + LRU reclaims the host queued) — the device half of
      ``PrefixCache.pop_pending``'s double-free guard.
    """

    def adopt_step(cache, slot, page_ids, n_shared):
        npp = cache["block_tables"].shape[1]
        keep = jnp.arange(npp, dtype=jnp.int32) < n_shared
        row = jax.lax.dynamic_slice_in_dim(cache["block_tables"], slot, 1)[0]
        row = jnp.where(keep, page_ids, row)
        block_tables = jax.lax.dynamic_update_slice_in_dim(
            cache["block_tables"], row[None], slot, 0
        )
        return {
            "layers": cache["layers"],
            "block_tables": block_tables,
            "seq_lens": cache["seq_lens"].at[slot].set(n_shared * page_size),
            "free_stack": cache["free_stack"],
            "free_top": cache["free_top"],
        }

    def release_cow_step(cache, mask, keep_counts):
        mask = mask.astype(bool)
        n = cache["block_tables"].shape[1]
        logical = jnp.arange(n, dtype=jnp.int32)[None, :]
        owned = mask[:, None] & (logical >= keep_counts[:, None]) & (
            logical < pages_for(cache["seq_lens"], page_size)[:, None]
        )
        free_stack, free_top = push_pages(
            cache["free_stack"], cache["free_top"],
            cache["block_tables"].reshape(-1), owned.reshape(-1),
        )
        return {
            "layers": cache["layers"],
            "block_tables": cache["block_tables"],
            "seq_lens": jnp.where(mask, 0, cache["seq_lens"]),
            "free_stack": free_stack,
            "free_top": free_top,
        }

    def push_free_step(cache, page_ids, mask):
        free_stack, free_top = push_pages(
            cache["free_stack"], cache["free_top"], page_ids, mask
        )
        return {
            "layers": cache["layers"],
            "block_tables": cache["block_tables"],
            "seq_lens": cache["seq_lens"],
            "free_stack": free_stack,
            "free_top": free_top,
        }

    return adopt_step, release_cow_step, push_free_step


@lru_cache(maxsize=8)
def _prefix_fns(page_size: int):
    """Jitted (donated) prefix-cache programs, shared per page geometry —
    each compiles exactly once per process per slot-count shape."""
    adopt_step, release_cow_step, push_free_step = _prefix_step_fns(page_size)
    return (
        jax.jit(adopt_step, donate_argnums=(0,)),
        jax.jit(release_cow_step, donate_argnums=(0,)),
        jax.jit(push_free_step, donate_argnums=(0,)),
    )


@lru_cache(maxsize=8)
def _engine_fns(model, gen_config, page_size: int, lora: bool = False,
                lora_kernel_mode: str = "auto"):
    """The jitted device programs, shared across engines of the same
    (model, config, page geometry, lora kernel) — jax.jit caches per input
    shape, so bucket widths and slot counts each compile exactly once per
    process."""
    return fresh_engine_jits(model, gen_config, page_size, lora, lora_kernel_mode)


class ServingEngine:
    """Continuous-batching serving over one model + param tree.

    >>> engine = ServingEngine(model, params, plugin, generation_config)
    >>> engine.add_request(Request(uid=0, prompt=(1, 2, 3), max_new_tokens=8))
    >>> while not engine.idle():
    ...     engine.step()
    >>> engine.results[0]  # generated token ids

    ``run(trace)`` replays a list of :class:`~.scheduler.Request` with
    virtual-time arrivals (the traffic-replay harness's entry point).
    """

    def __init__(self, model, params, plugin: Optional[ServingPlugin] = None,
                 generation_config: Optional[GenerationConfig] = None, rng=None,
                 adapters=None, telemetry: Optional[TelemetryPlugin] = None,
                 draft_model=None, draft_params=None,
                 hold_finished: bool = False):
        self.plugin = plugin or ServingPlugin()
        self.gen_config = generation_config or GenerationConfig()
        if getattr(getattr(model, "config", None), "scan_layers", False):
            from ..generation import _unrolled_view

            model, params = _unrolled_view(model, params)
        cfg = model.config
        kernel = self.plugin.decode_kernel
        if kernel == "auto":
            kernel = "flash" if jax.default_backend() == "tpu" else "native"
        if cfg.attn_implementation != kernel and kernel in ("native", "flash"):
            cfg = dataclasses.replace(cfg, attn_implementation=kernel)
            model = model.clone(config=cfg) if hasattr(model, "clone") else type(model)(cfg)
        self.model = model
        self.params = params
        # multi-tenant mode: the AdapterStore's pool rides every decode/
        # prefill program as a read-only extra arg, and per-slot adapter
        # ids route each row through its tenant's adapter (ops/lora.py);
        # the plugin's kernel mode scopes the program traces (it never
        # touches the process-global ambient mode)
        self.adapters = adapters
        p = self.plugin
        self.cache = init_paged_cache(
            cfg, p.num_pages, p.page_size, p.num_slots, p.pages_per_slot,
            kv_dtype=p.kv_dtype,
        )
        if p.kv_dtype in ("int8", "fp8"):
            # measured side of the kv_quant.page_bytes twin: the pool
            # arrays as actually allocated (codes + per-page scales),
            # counted per physical page — the predicted side is
            # kv_pool_accounting's kv_page_bytes arithmetic
            pool_nbytes = sum(
                int(arr.nbytes) for layer in self.cache["layers"]
                for arr in layer.values()
            )
            from ..telemetry import twin_registry

            twin_registry().record_measured(
                "kv_quant.page_bytes", pool_nbytes / p.num_pages,
                source="serving/engine.ServingEngine",
            )
        # speculative multi-token decode (serving/speculate.py): a draft
        # provider proposes k tokens per slot and the verify program accepts
        # the longest greedy-matching prefix — greedy only, because the
        # acceptance rule IS the token-parity pin (a sampled verify would
        # need rejection sampling, a different contract)
        self._spec: Optional[Speculator] = None
        if p.speculate != "off":
            if self.gen_config.do_sample:
                raise ValueError(
                    "speculative decode supports greedy decoding only "
                    "(do_sample=True breaks the greedy-prefix acceptance "
                    "pin) — disable ServingPlugin.speculate or sampling"
                )
            provider = make_draft_provider(
                p.speculate, draft_model=draft_model, draft_params=draft_params,
                window=p.speculate_draft_window,
            )
            self._spec = Speculator(provider, p.speculate_k, p.speculate_buckets)
        # content-addressed prefix reuse (serving/prefix_cache.py): COW
        # shared pages with host-side refcounts; the three extra device
        # programs (adopt / keep-aware COW release / push-free) are pure
        # allocator arithmetic keyed by page geometry
        self.prefix: Optional[PrefixCache] = None
        if p.prefix_cache == "on":
            self.prefix = PrefixCache(p.page_size, kv_dtype=p.kv_dtype)
            self._adopt, self._release_cow, self._push_free = _prefix_fns(
                p.page_size
            )
        self.sched = ContinuousBatchingScheduler(
            p.num_slots, p.num_pages, p.page_size, p.pages_per_slot,
            p.prefill_chunk, p.prefill_buckets,
            adapters=adapters,
            max_bypass_age=(adapters.plugin.max_bypass_age
                            if adapters is not None else 16),
            speculate_k=p.speculate_k if self._spec is not None else 0,
            max_queue=p.max_queue, kv_shed_watermark=p.kv_shed_watermark,
            default_deadline_ticks=p.default_deadline_ticks,
            prefix=self.prefix,
        )
        # overload control (serving/overload.py): the degradation ladder is
        # always armed (escalation is explicit — an SLO trip, a deadline
        # storm, or an operator call; every stage reuses warmed programs so
        # strict_compiles holds through the full ladder), and cancellation
        # requests queue here until the next tick boundary processes them
        self.despeculated = False
        self.ladder = DegradationLadder(self)
        self.slo = None                      # optional attached SLOMonitor
        self._pending_cancels: list[int] = []
        (self._decode, self._prefill, self._release, self._sample,
         self._verify) = _engine_fns(
            self.model, self.gen_config, p.page_size, adapters is not None,
            adapters.plugin.kernel if adapters is not None else "auto",
        )
        self._base_rng = rng if rng is not None else jax.random.PRNGKey(0)
        # request-level trace spans (telemetry/spans.py): host-side only —
        # zero added device syncs, no new compiled programs, tokens bitwise
        # identical on or off (pinned by tests + the dryrun telemetry leg).
        # A single attribute check per hook when off.
        self.telemetry = telemetry or TelemetryPlugin()
        self.trace: Optional[RequestTracer] = None
        if self.telemetry.trace_requests:
            self.enable_tracing()
        # recompile guard: compile events are counted process-wide (the
        # jax.monitoring backend-compile stream) and reported as a delta
        # from engine construction — after warmup() this must stay flat
        # (the fixed-shape contract: a mid-traffic compile is a bug)
        self._compile_counter = install_global_compile_counter()
        self._compile_baseline = self._compile_counter.count
        self.warmed_up = False
        self.steps = 0
        self.interrupted = False
        # disaggregation (serving/transfer.py): a prefill-role engine holds
        # finished slots — pages intact — until the transport streams them
        # to the decode engine and calls release_held()
        self.hold_finished = hold_finished
        self.held: list[int] = []
        self._undelivered: list[Request] = []
        self.results: dict[int, list[int]] = {}
        self._arrival_wall: dict[int, float] = {}
        self._last_token_wall: dict[int, float] = {}
        self._ttft_seen: set[int] = set()
        self.metrics = {
            "decode_steps": 0, "prefill_steps": 0, "idle_steps": 0,
            "scheduled_decode_slots": 0, "useful_decode_tokens": 0,
            "prefill_scheduled_tokens": 0, "prefill_useful_tokens": 0,
            "evictions": 0, "page_step_sum": 0, "peak_used_pages": 0,
            "prompt_tokens": 0, "generated_tokens": 0,
            # speculative decode (zeros-clean when speculation is off):
            # verify passes, drafted/accepted lanes, per-lane pass count +
            # emitted tokens (the tokens_per_step twin's numerator and
            # denominator), and pages rolled back off rejected drafts
            "verify_steps": 0, "draft_tokens": 0, "accepted_draft_tokens": 0,
            "decode_lane_passes": 0, "decode_emitted_tokens": 0,
            "speculative_rollbacks": 0,
            # disaggregation (zeros unless a PagedKVTransport streams KV
            # pages out of / into this engine — serving/transfer.py)
            "page_transfers": 0, "page_transfer_pages": 0,
            "page_transfer_bytes": 0,
        }
        self.ttft_s: list[float] = []
        # TTFT in VIRTUAL engine ticks (arrival -> first token), the
        # deterministic twin of the wall-clock ttft_s samples: the prefix
        # cache's with/without-reuse comparison pins on these (wall clocks
        # flake on CPU; tick counts replay identically)
        self.ttft_ticks: list[int] = []
        self.token_gaps_s: list[float] = []

    # -- telemetry -----------------------------------------------------------

    def enable_tracing(self, clock=None, capacity: Optional[int] = None) -> RequestTracer:
        """Arm request-level trace spans (idempotent unless ``clock`` or
        ``capacity`` is passed, which installs a fresh tracer).  ``clock``
        injects a deterministic timestamp source
        (:class:`~accelerate_tpu.telemetry.VirtualClock`) for tests; the
        default is wall ``perf_counter``.  Host-side only — arming this
        changes no token and compiles no program."""
        if self.trace is None or clock is not None or capacity is not None:
            self.trace = RequestTracer(
                capacity=capacity or self.telemetry.ring_capacity, clock=clock,
            )
        return self.trace

    def disable_tracing(self) -> None:
        self.trace = None

    # -- request lifecycle ---------------------------------------------------

    def add_request(self, request: Request) -> None:
        self.sched.submit(request)
        self._arrival_wall[request.uid] = time.perf_counter()

    def cancel(self, uid: int) -> None:
        """Request cancellation of ``uid`` at whatever lifecycle stage it is
        in (queued, mid-prefill-chunk, decoding, or mid-speculative-verify).
        Processed at the next tick boundary — the engine's device programs
        are atomic per tick, so the boundary is the only place every
        resource (KV pages, adapter refcount, slot, speculative state) can
        be released consistently.  Idempotent; unknown/finished uids are
        dropped silently.  A cancel pending at a preemption drain is still
        owed: :meth:`remaining_requests` hands the request back exactly
        once."""
        if uid not in self._pending_cancels:
            self._pending_cancels.append(uid)

    def adopt_prefilled(self, request: Request, first_token: int) -> int:
        """Decode-role half of the disaggregated handoff
        (serving/transfer.py): occupy a free slot for a request whose
        prompt was prefilled on ANOTHER engine, whose first token is
        already sampled, and whose KV pages the transport's ``recv``
        program is about to scatter into this pool.  The host mirror books
        ``pages_for(prompt_len)`` pages (the recv program pops exactly
        those); decode proceeds through the ordinary tick loop from the
        first generated token on.  Returns the slot id."""
        sched = self.sched
        if not sched.free_slots:
            raise RuntimeError("adopt_prefilled: no free decode slot")
        n_pages = int(pages_for(request.prompt_len, self.plugin.page_size))
        if n_pages > sched.free_pages:
            raise RuntimeError(
                f"adopt_prefilled: request {request.uid} needs {n_pages} "
                f"pages, pool has {sched.free_pages} free"
            )
        slot = sched.free_slots.pop(0)
        st = SlotState(request, sched._admit_counter,
                       prefilled=request.prompt_len)
        st.tokens = [int(first_token)]
        if self.adapters is not None and request.adapter_id:
            # adapter routing across the split: the decode role pins the
            # tenant's adapter in ITS pool (the prefill role's pin released
            # with the held slot) — the normal finish path unpins, so the
            # refcount contract balances per engine
            adapter_slot, swapped = self.adapters.pin(request.adapter_id)
            st.adapter_slot = adapter_slot
            if swapped:
                sched.events.append(("swap", request.adapter_id, adapter_slot))
        sched.slots[slot] = st
        sched._admit_counter += 1
        sched.free_pages -= n_pages
        sched.events.append(("admit", request.uid, slot))
        # the prefill engine delivered the first token — TTFT is its story
        self._arrival_wall[request.uid] = time.perf_counter()
        self._last_token_wall[request.uid] = time.perf_counter()
        self._ttft_seen.add(request.uid)
        return slot

    def release_held(self, slot: int) -> None:
        """Prefill-role half of the handoff: retire a held finished slot
        once its pages have been streamed out (device release first, then
        the host mirror — the ordering every retirement path uses)."""
        self.held.remove(slot)
        self._release_slots([slot])
        self.sched.finish(slot)
        self._drain_prefix_frees()

    def attach_slo(self, monitor) -> "DegradationLadder":
        """Feed per-token latency and TTFT samples into ``monitor`` as they
        are measured and wire its trip/recover callbacks to the degradation
        ladder (trip → escalate one stage, recover → relax one).  Returns
        the ladder for inspection."""
        self.slo = monitor
        self.ladder.attach(monitor)
        return self.ladder

    def idle(self) -> bool:
        return self.sched.idle()

    def unfinished_requests(self) -> list[Request]:
        """Everything not yet finished — in admission order then queue order
        (prompt intact, generated tokens discarded: the recompute-on-resume
        contract a preemption drain relies on)."""
        in_flight = [
            self.sched.slots[s].request
            for s in sorted(self.sched.slots, key=lambda s: self.sched.slots[s].admit_seq)
        ]
        return in_flight + list(self.sched.waiting)

    def remaining_requests(self) -> list[Request]:
        """After a drain: everything still owed — in-flight + queued + trace
        arrivals the replay never delivered — **deduplicated by uid** and
        excluding deliberately retired requests (shed / cancelled).  A
        request whose :meth:`cancel` is still pending (the drain interrupted
        before the tick boundary could process it) has NOT been retired and
        is handed back exactly once; a processed cancel never comes back."""
        retired = self.sched.retired_uids
        out, seen = [], set()
        for r in self.unfinished_requests() + list(self._undelivered):
            if r.uid in retired or r.uid in self.results or r.uid in seen:
                continue
            seen.add(r.uid)
            out.append(r)
        return out

    # -- program dispatch (single-tenant vs multi-tenant arity) --------------

    def _run_decode(self, tokens, active, adapter_slots, rng):
        self._drain_prefix_frees()
        if self.adapters is None:
            return self._decode(self.params, self.cache, tokens, active, rng)
        return self._decode(self.params, self.adapters.pool, self.cache,
                            tokens, active, adapter_slots, rng)

    def _run_prefill(self, slot, chunk_ids, start, chunk_len, adapter_slot):
        self._drain_prefix_frees()
        if self.adapters is None:
            return self._prefill(self.params, self.cache, slot, chunk_ids,
                                 start, chunk_len)
        return self._prefill(self.params, self.adapters.pool, self.cache,
                             slot, chunk_ids, start, chunk_len, adapter_slot)

    def _run_verify(self, tokens, spec_len, active, adapter_slots, rng):
        self._drain_prefix_frees()
        if self.adapters is None:
            return self._verify(self.params, self.cache, tokens, spec_len,
                                active, rng)
        return self._verify(self.params, self.adapters.pool, self.cache,
                            tokens, spec_len, active, adapter_slots, rng)

    # -- the engine tick -----------------------------------------------------

    def warmup(self) -> int:
        """Compile every device program before taking traffic: one no-op
        pass through decode, release, and each bucket's prefill (plus the
        first-token sampler), using the engine's real cache and params so
        every shape/dtype matches live traffic exactly.  No-op means no
        slot state changes: decode runs with zero active slots, prefill
        writes a zero-length chunk into an idle slot, release releases an
        empty mask — tokens are never recorded and ``steps`` does not
        advance.  Returns the number of backend compile events the warmup
        cost (0 when the persistent compilation cache was already warm).

        Call before traffic (the replay harness does); after it,
        :attr:`compile_events` staying flat IS the no-mid-traffic-recompile
        contract.
        """
        if self.sched.slots:
            raise RuntimeError("warmup() must run before any traffic is admitted")
        before = self._compile_counter.count
        n = self.plugin.num_slots
        rng = jax.random.fold_in(self._base_rng, 0)  # warms the fold_in program
        cache, _ = self._run_decode(
            jnp.asarray(np.zeros((n,), np.int32)),
            jnp.asarray(np.zeros((n,), bool)),
            jnp.asarray(np.zeros((n,), np.int32)), rng,
        )
        self.cache = cache
        last = None
        for bucket in self.plugin.prefill_buckets:
            cache, last = self._run_prefill(
                jnp.asarray(0, jnp.int32),
                jnp.asarray(np.zeros((bucket,), np.int32)),
                jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
                jnp.asarray(0, jnp.int32),
            )
            self.cache = cache
        if last is not None:
            self._sample(last, rng)
        if self._spec is not None:
            # every verify bucket is a production program: one no-op pass
            # per width (zero active slots, zero spec depth), plus the draft
            # provider's own program (the draft-model windowed forward; the
            # n-gram provider compiles nothing)
            for bucket in self.plugin.speculate_buckets:
                cache, _, _ = self._run_verify(
                    jnp.asarray(np.zeros((n, bucket + 1), np.int32)),
                    jnp.asarray(np.zeros((n,), np.int32)),
                    jnp.asarray(np.zeros((n,), bool)),
                    jnp.asarray(np.zeros((n,), np.int32)), rng,
                )
                self.cache = cache
            self._spec.provider.warmup(n, self.plugin.speculate_k)
        if self.prefix is not None:
            # the three prefix programs are production programs: a first
            # hit / COW release / refcount-death push mid-traffic must hit
            # a warm cache (no-op passes: zero shared pages, empty masks)
            pps = self.plugin.pages_per_slot
            self.cache = self._adopt(
                self.cache, jnp.asarray(0, jnp.int32),
                jnp.asarray(np.zeros((pps,), np.int32)),
                jnp.asarray(0, jnp.int32),
            )
            self.cache = self._release_cow(
                self.cache, jnp.asarray(np.zeros((n,), bool)),
                jnp.asarray(np.zeros((n,), np.int32)),
            )
            self.cache = self._push_free(
                self.cache, jnp.asarray(np.zeros((pps,), np.int32)),
                jnp.asarray(np.zeros((pps,), bool)),
            )
        else:
            self.cache = self._release(
                self.cache, jnp.asarray(np.zeros((n,), bool))
            )
        # Decode compiled FIRST, against the fresh host-built cache — but
        # every program OUTPUT carries the steady-state placement GSPMD
        # chose (under a mesh-sharded param tree the KV pools come back
        # tp-sharded, not replicated).  One more no-op decode warms the
        # program against THAT layout, so the first post-warmup decode —
        # plain serving under sharded params, or the ladder's despeculate
        # stage re-entering decode after verify — can never recompile
        # mid-traffic.
        cache, _ = self._run_decode(
            jnp.asarray(np.zeros((n,), np.int32)),
            jnp.asarray(np.zeros((n,), bool)),
            jnp.asarray(np.zeros((n,), np.int32)), rng,
        )
        self.cache = cache
        if self.adapters is not None:
            # the pool-insert scatter is a fixed-shape production program
            # too: a first hot-swap mid-traffic must hit a warm cache
            self.adapters.warmup_insert()
        self.warmed_up = True
        return self._compile_counter.count - before

    def warmup_programs(self) -> frozenset:
        """The static set of program labels :meth:`warmup` compiles for
        this engine's plugin — the same ``warmup_plan`` derivation the
        GL404 pair audit checks dispatch coverage against
        (``analysis/distributed_audit.py``), exposed on the engine so the
        runtime warmup and the preflight gate read one source of truth."""
        from ..analysis.distributed_audit import warmup_plan

        return warmup_plan(self.plugin, adapters=self.adapters is not None)

    @property
    def compile_events(self) -> int:
        """Real XLA backend compiles observed since this engine was built
        (process-wide jax.monitoring stream, reported as a delta).  After
        :meth:`warmup` this must not grow — every program is fixed-shape."""
        return self._compile_counter.count - self._compile_baseline

    def step(self) -> dict:
        """One scheduler decision + at most one device program.

        With tracing on (:attr:`trace`) the tick records its phase spans —
        ``schedule`` (admission + the scheduler decision), ``dispatch:*``
        (the async device-program call) and ``host_sync`` (the token fetch)
        — plus the per-request lifecycle spans derived from the scheduler's
        event log.  All host-side: the device programs are identical."""
        tr = self.trace
        for ev in _faults.fault_point("serve_step"):
            if ev.kind == "preempt":
                # drain: stop taking work, hand every in-flight request back
                # (the serving analog of the trainer's SIGTERM-at-step-
                # boundary stop; resilience/preemption.py discipline)
                self.interrupted = True
                self._drain_prefix_frees()
                return {"type": "preempted", "step": self.steps}
            if ev.kind == "cancel":
                # cancellation storm: the oldest live request cancels —
                # deterministic, so the event-log pin covers the storm
                self._inject_cancel_oldest()
            elif ev.kind == "deadline":
                # deadline storm: every live request expires NOW, and the
                # overload signal escalates the degradation ladder one stage
                self.sched.force_expire_all()
                self.ladder.escalate()
            elif ev.kind == "prefix":
                # cache-invalidation storm: every index hold drops — live
                # slots keep their shared refcounts (their pages free later
                # through the normal release path), future admissions miss.
                # Tokens stay bitwise: a flush only changes WHERE K/V gets
                # computed, never what it holds.
                if self.prefix is not None:
                    freed = self.prefix.flush()
                    self.sched.free_pages += freed
                    self.sched.events.append(("prefix_flush", freed))
        self.sched.tick = self.steps
        self._process_control()
        t_sched = tr.stamp() if tr is not None else 0.0
        admitted = self.sched.admit()
        if self.prefix is not None:
            # push refcount-death / LRU-reclaim pages BEFORE any allocating
            # dispatch (the host mirror counted them at decision time), then
            # write each adopted prefix into its slot's block-table row
            self._drain_prefix_frees()
            for s in admitted:
                st = self.sched.slots[s]
                if st.shared_pages:
                    pps = self.plugin.pages_per_slot
                    ids = np.zeros((pps,), np.int32)
                    ids[:len(st.shared_pages)] = st.shared_pages
                    self.cache = self._adopt(
                        self.cache, jnp.asarray(s, jnp.int32),
                        jnp.asarray(ids),
                        jnp.asarray(len(st.shared_pages), jnp.int32),
                    )
        action = self.sched.next_action()
        if tr is not None:
            tr.phase("schedule", t_sched, action=action[0], step=self.steps)
        window = None
        event: dict = {"type": action[0], "step": self.steps}
        if action[0] == "prefill":
            _, slot, start, chunk, bucket = action
            survived, evicted = self.sched.plan_prefill_evictions(slot, chunk)
            self._release_evicted(evicted)
            if survived:
                st = self.sched.slots[slot]
                ids = np.zeros((bucket,), np.int32)
                ids[:chunk] = st.request.prompt[start:start + chunk]
                t_disp = tr.stamp() if tr is not None else 0.0
                cache, last = self._run_prefill(
                    jnp.asarray(slot, jnp.int32),
                    jnp.asarray(ids), jnp.asarray(start, jnp.int32),
                    jnp.asarray(chunk, jnp.int32),
                    jnp.asarray(st.adapter_slot, jnp.int32),
                )
                if tr is not None:
                    tr.phase("dispatch:prefill", t_disp, slot=slot,
                             chunk=chunk, bucket=bucket, step=self.steps)
                self.cache = cache
                self.sched.note_prefill(slot, chunk)
                m = self.metrics
                m["prefill_steps"] += 1
                m["prefill_scheduled_tokens"] += bucket
                m["prefill_useful_tokens"] += chunk
                m["prompt_tokens"] += chunk
                event.update(slot=slot, chunk=chunk, bucket=bucket)
                if self.prefix is not None and st.prefill_done:
                    # the completed prompt's NEW full pages register in the
                    # content index (one small block-row fetch; the engine
                    # syncs a token this tick anyway)
                    self._insert_prefix(slot, st)
                if st.prefill_done:
                    # the prompt's last-token logits seed the decode loop —
                    # the first generated token, exactly like generate()
                    t_sync = tr.stamp() if tr is not None else 0.0
                    tok = int(self._sample(last, self._step_rng()))
                    if tr is not None:
                        tr.phase("host_sync", t_sync, step=self.steps)
                    m["generated_tokens"] += 1
                    self._record_token(slot, tok)
                if tr is not None:
                    window = (t_disp, tr.recorder.clock())
            else:
                event["cancelled"] = True
        elif action[0] == "decode" and self._spec is not None \
                and not self.despeculated:
            event["type"] = "verify"
            window = self._verify_tick(action[1], tr, event)
            if self.interrupted:  # preempt-mid-verify fault: nothing ran
                self._drain_prefix_frees()
                return {"type": "preempted", "step": self.steps}
        elif action[0] == "decode":
            active_slots, evicted = self.sched.plan_evictions(action[1])
            self._release_evicted(evicted)
            if active_slots:
                needing = self.sched.decode_page_need(active_slots)
                n = self.plugin.num_slots
                tokens = np.zeros((n,), np.int32)
                active = np.zeros((n,), bool)
                adapter_slots = np.zeros((n,), np.int32)
                for s in active_slots:
                    tokens[s] = self.sched.slots[s].tokens[-1]
                    active[s] = True
                    adapter_slots[s] = self.sched.slots[s].adapter_slot
                t_disp = tr.stamp() if tr is not None else 0.0
                cache, next_tok = self._run_decode(
                    jnp.asarray(tokens), jnp.asarray(active),
                    jnp.asarray(adapter_slots), self._step_rng(),
                )
                if tr is not None:
                    tr.phase("dispatch:decode", t_disp,
                             slots=list(active_slots), step=self.steps)
                self.cache = cache
                self.sched.note_decode(needing, active_slots)
                t_sync = tr.stamp() if tr is not None else 0.0
                next_np = np.asarray(next_tok)
                if tr is not None:
                    tr.phase("host_sync", t_sync, step=self.steps)
                    window = (t_disp, tr.recorder.clock())
                done_slots = []
                for s in active_slots:
                    if self._record_token(s, int(next_np[s]), release=False):
                        done_slots.append(s)
                if done_slots and not self.hold_finished:
                    self._release_slots(done_slots)
                    self._finish_decode_slots(done_slots)
                m = self.metrics
                m["decode_steps"] += 1
                m["scheduled_decode_slots"] += n
                m["useful_decode_tokens"] += len(active_slots)
                m["generated_tokens"] += len(active_slots)
                m["decode_lane_passes"] += len(active_slots)
                m["decode_emitted_tokens"] += len(active_slots)
                event.update(slots=tuple(active_slots))
            else:
                event["cancelled"] = True
        else:
            self.metrics["idle_steps"] += 1
        used = self.sched.used_pages
        self.metrics["page_step_sum"] += used
        self.metrics["peak_used_pages"] = max(self.metrics["peak_used_pages"], used)
        if tr is not None:
            # lifecycle spans off the scheduler's deterministic event log
            # (submit/admit/swap/bypass/prefill/evict/finish this tick)
            tr.consume_scheduler_events(self.sched.events, self.steps,
                                        window=window)
        # the tick boundary owes the device every refcount-death push the
        # host counted this tick (mirror exact at every boundary — the
        # refcounted invariant checker runs between ticks)
        self._drain_prefix_frees()
        self.steps += 1
        return event

    def run(self, trace: list[Request], max_steps: int = 200_000) -> dict[int, list[int]]:
        """Replay ``trace`` (arrivals keyed on virtual step time) to
        completion — or to the first injected preemption."""
        pending = sorted(trace, key=lambda r: (r.arrival_step, r.uid))
        i = 0
        while True:
            while i < len(pending) and pending[i].arrival_step <= self.steps:
                self.add_request(pending[i])
                i += 1
            if self.interrupted or (self.idle() and i >= len(pending)):
                break
            self.step()
            if self.steps >= max_steps:
                raise RuntimeError(f"serving replay exceeded {max_steps} steps")
        # arrivals that never reached the engine before a drain still count
        # as unfinished work for the resume path
        self._undelivered = pending[i:]
        return self.results

    # -- internals -----------------------------------------------------------

    def _process_control(self) -> None:
        """The tick-boundary control pass: apply pending cancellations, then
        retire in-flight requests whose deadline has passed.  Runs BEFORE
        admission, so a cancelled/expired request's pages and slot are
        available to this very tick's admissions."""
        sched = self.sched
        for uid in list(self._pending_cancels):
            if self._apply_cancel(uid, reason="cancel"):
                self._pending_cancels.remove(uid)
            elif uid in self.results or uid in sched.retired_uids:
                # raced a finish/shed: nothing left to cancel
                self._pending_cancels.remove(uid)
            # else: not yet arrived — the cancel stays pending
        for slot in sorted(sched.slots):
            # a held finished slot already delivered its tokens — pages stay
            # parked for the KV transfer; a deadline cannot revoke them
            if sched.slots[slot].finished:
                continue
            if sched.request_expired(sched.slots[slot].request):
                self._cancel_slot(slot, reason="deadline")

    def _apply_cancel(self, uid: int, reason: str) -> bool:
        """Cancel ``uid`` at whatever stage it is in right now.  Returns
        True when a live request was retired."""
        sched = self.sched
        for slot, st in sched.slots.items():
            if st.request.uid == uid and not st.finished:
                # (a held finished slot is already in results — the caller's
                # raced-a-finish branch drops the stale cancel)
                self._cancel_slot(slot, reason=reason)
                return True
        return sched.cancel_queued(uid, reason=reason)

    def _cancel_slot(self, slot: int, reason: str) -> None:
        """Retire an admitted request: device pages back to the functional
        free-list first (the same release program finish/evict drive), then
        the scheduler's mirrored host-side release — the exact ordering that
        keeps ``verify_serving_invariants`` green at every boundary."""
        uid = self.sched.slots[slot].request.uid
        self._release_slots([slot])
        self.sched.cancel_slot(slot, reason=reason)
        self._arrival_wall.pop(uid, None)
        self._last_token_wall.pop(uid, None)
        self._ttft_seen.discard(uid)

    def _inject_cancel_oldest(self) -> None:
        """The cancellation-storm fault payload: cancel the oldest live
        request — oldest-admitted in-flight first, else the head of the
        waiting line.  Deterministic by construction."""
        sched = self.sched
        live = [s for s in sched.slots if not sched.slots[s].finished]
        if live:
            slot = min(live, key=lambda s: sched.slots[s].admit_seq)
            self._cancel_slot(slot, reason="cancel")
        elif sched.waiting:
            sched.cancel_queued(sched.waiting[0].uid, reason="cancel")

    def _verify_tick(self, candidate_slots, tr, event):
        """One speculative draft-and-verify pass (the decode action with
        speculation armed).  Draft first (the proposals size the page
        reservation), evict for the WORST-CASE page demand, dispatch the
        bucket-padded verify program, then settle the host mirror off the
        device-accepted lengths.  Returns the tracing window (or None).

        The ``verify_step`` fault site fires FIRST — a ``preempt`` armed
        there drains the engine mid-verify with nothing dispatched and no
        state touched, so the drain/resume contract (and every invariant)
        holds at the finest-grained boundary speculation has."""
        for ev in _faults.fault_point("verify_step"):
            if ev.kind == "preempt":
                self.interrupted = True
                event["preempted"] = True
                return None
        sp = self._spec
        sched = self.sched
        cand = list(candidate_slots)
        n = self.plugin.num_slots
        # the draft batch is padded to the FULL slot width like every other
        # engine program: a draft-model provider jits per batch shape, and a
        # shape that tracked the live candidate count would recompile
        # mid-traffic the first time occupancy changed (strict_compiles).
        # Contexts carry only the provider's trailing window — rebuilding
        # the full prompt+generated history per pass would be quadratic in
        # stream length — and the assembly counts as draft time (it exists
        # only to feed the drafting layer).
        t_ctx = time.perf_counter()
        win = max(2, getattr(sp.provider, "window", 512))
        contexts = [[1]] * n
        remaining = [1] * n  # dummy rows clamp to depth 0
        tenant_ids = [0] * n
        for s in cand:
            st = sched.slots[s]
            toks = st.tokens
            if len(toks) >= win:
                contexts[s] = toks[-win:]
            else:
                contexts[s] = list(st.request.prompt[len(toks) - win:]) + toks
            remaining[s] = st.request.max_new_tokens - len(toks)
            tenant_ids[s] = st.request.adapter_id
        sp.draft_time_s += time.perf_counter() - t_ctx
        drafts, spec_lens = sp.draft(contexts, remaining, tenant_ids)
        spec_by_slot = {s: int(spec_lens[s]) for s in cand}
        active_slots, evicted = sched.plan_speculative_evictions(
            cand, spec_by_slot
        )
        self._release_evicted(evicted)
        if not active_slots:
            event["cancelled"] = True
            return None
        worst_need = sched.verify_page_need(active_slots, spec_by_slot)
        bucket = sp.bucket_for(max(spec_by_slot[s] for s in active_slots))
        w = bucket + 1
        tokens = np.zeros((n, w), np.int32)
        spec_arr = np.zeros((n,), np.int32)
        active = np.zeros((n,), bool)
        adapter_slots = np.zeros((n,), np.int32)
        for s in active_slots:
            st = sched.slots[s]
            d = spec_by_slot[s]
            tokens[s, 0] = st.tokens[-1]
            if d:
                tokens[s, 1:1 + d] = drafts[s, :d]
            spec_arr[s] = d
            active[s] = True
            adapter_slots[s] = st.adapter_slot
        t_disp = tr.stamp() if tr is not None else 0.0
        cache, greedy, m_dev = self._run_verify(
            jnp.asarray(tokens), jnp.asarray(spec_arr), jnp.asarray(active),
            jnp.asarray(adapter_slots), self._step_rng(),
        )
        if tr is not None:
            tr.phase("dispatch:verify", t_disp, slots=list(active_slots),
                     bucket=bucket, step=self.steps)
        self.cache = cache
        t_sync = tr.stamp() if tr is not None else 0.0
        greedy_np = np.asarray(greedy)
        m_np = np.asarray(m_dev)
        if tr is not None:
            tr.phase("host_sync", t_sync, step=self.steps)
        window = (t_disp, tr.recorder.clock()) if tr is not None else None
        accepted = {s: int(m_np[s]) for s in active_slots}
        m = self.metrics
        # rollback accounting against the PRE-pass kv lengths (note_verify
        # advances them)
        for s in active_slots:
            kept = speculative_page_need(
                sched.slots[s].kv_tokens, accepted[s], self.plugin.page_size
            )
            m["speculative_rollbacks"] += worst_need[s] - kept
        sched.note_verify(accepted)
        done_slots = []
        recorded = 0
        delivered_drafts = 0
        for s in active_slots:
            r = 0
            for tok in greedy_np[s, :accepted[s] + 1]:
                r += 1
                if self._record_token(s, int(tok), release=False):
                    # EOS (or max_new) inside the accepted window retires
                    # the sequence; the remainder of the window is
                    # discarded exactly as sequential decode never would
                    # have produced it
                    done_slots.append(s)
                    break
            recorded += r
            # accepted drafts DELIVERED (each pass emits m+1 for m accepted
            # drafts; an EOS truncation discards the tail, and discarded
            # drafts must not inflate the measured accept-rate twin — the
            # predicted replay caps at the stream end the same way)
            delivered_drafts += r - 1
        if done_slots and not self.hold_finished:
            self._release_slots(done_slots)
            self._finish_decode_slots(done_slots)
        m["verify_steps"] += 1
        m["scheduled_decode_slots"] += n * w
        m["useful_decode_tokens"] += recorded
        m["generated_tokens"] += recorded
        m["decode_lane_passes"] += len(active_slots)
        m["decode_emitted_tokens"] += recorded
        m["draft_tokens"] += sum(spec_by_slot[s] for s in active_slots)
        m["accepted_draft_tokens"] += delivered_drafts
        event.update(slots=tuple(active_slots), bucket=bucket,
                     accepted=tuple(accepted[s] for s in active_slots))
        return window

    def _step_rng(self):
        return jax.random.fold_in(self._base_rng, self.steps)

    def _record_token(self, slot: int, tok: int, release: bool = True) -> bool:
        """Append a sampled token; retire the sequence on EOS/max_new.
        Returns True when the sequence finished (caller releases if it opted
        out of the immediate release)."""
        st = self.sched.slots[slot]
        now = time.perf_counter()
        uid = st.request.uid
        if not st.tokens:
            # once per request: an evicted-and-readmitted sequence must not
            # re-sample its TTFT (the first life already delivered a token)
            if uid not in self._ttft_seen:
                self._ttft_seen.add(uid)
                self.ttft_s.append(now - self._arrival_wall[uid])
                self.ttft_ticks.append(self.steps - st.request.arrival_step)
                if self.slo is not None:
                    self.slo.observe("ttft_s", self.ttft_s[-1])
        elif uid in self._last_token_wall:
            self.token_gaps_s.append(now - self._last_token_wall[uid])
            if self.slo is not None:
                self.slo.observe("token_latency_s", self.token_gaps_s[-1])
        self._last_token_wall[uid] = now
        st.tokens.append(tok)
        if not st.prefill_done:
            raise AssertionError("token recorded before prefill completed")
        eos = self.gen_config.eos_token_id
        finished = (eos is not None and tok == eos) or \
            len(st.tokens) >= st.request.max_new_tokens
        if finished:
            self.results[uid] = list(st.tokens)
            # retire the per-request wall clocks: the serving loop is
            # long-lived, so live-request bookkeeping must not grow with
            # total requests served
            self._arrival_wall.pop(uid, None)
            self._last_token_wall.pop(uid, None)
            self._ttft_seen.discard(uid)
            if self.hold_finished:
                # prefill-role engine: the KV pages stay resident until the
                # transport streams them to the decode engine
                st.finished = True
                self.held.append(slot)
            elif release:
                self._release_slots([slot])
                self.sched.finish(slot)
            return True
        return False

    def _release_slots(self, slots: list[int]) -> None:
        mask = np.zeros((self.plugin.num_slots,), bool)
        mask[slots] = True
        if self.prefix is None:
            self.cache = self._release(self.cache, jnp.asarray(mask))
            return
        # COW release: the device pushes ONLY the pages past each slot's
        # shared prefix — an aliased page never reaches the free stack from
        # here (the host refcounts in _release_slot_pages decide when it
        # actually frees, through the push_free program)
        keep = np.zeros((self.plugin.num_slots,), np.int32)
        for s in slots:
            st = self.sched.slots.get(s)
            if st is not None:
                keep[s] = len(st.shared_pages)
            else:
                # evict() popped the state already; it parked the keep count
                keep[s] = self.sched.evicted_keep.pop(s, 0)
        self.cache = self._release_cow(self.cache, jnp.asarray(mask),
                                       jnp.asarray(keep))

    def _drain_prefix_frees(self) -> None:
        """Push every refcount-death / LRU-reclaim page the host queued onto
        the device free stack (fixed-width batches of ``pages_per_slot`` —
        one warmed program shape).  ``pop_pending`` hard-asserts none of
        them still holds a reference (the double-free guard)."""
        if self.prefix is None or not self.prefix.pending_free:
            return
        pages = self.prefix.pop_pending()
        width = self.plugin.pages_per_slot
        for i in range(0, len(pages), width):
            chunk = pages[i:i + width]
            ids = np.zeros((width,), np.int32)
            mask = np.zeros((width,), bool)
            ids[:len(chunk)] = chunk
            mask[:len(chunk)] = True
            self.cache = self._push_free(self.cache, jnp.asarray(ids),
                                         jnp.asarray(mask))

    def _insert_prefix(self, slot: int, st) -> None:
        """Register a completed prefill's NEW full pages in the content
        index.  The physical ids come from one small block-row fetch (the
        device popped them; the host mirror only tracks counts) — the
        slot's shared set stays a contiguous row prefix, so the COW release
        keep-count arithmetic holds."""
        hashes = self.prefix.block_hashes(st.request.prompt,
                                          st.request.adapter_id)
        k = len(st.shared_pages)
        if len(hashes) <= k:
            return
        row = np.asarray(self.cache["block_tables"])[slot, :len(hashes)]
        inserted = self.prefix.insert_owned(hashes[k:],
                                            [int(p) for p in row[k:]])
        st.shared_pages.extend(inserted)

    def _release_evicted(self, evicted: list[int]) -> None:
        if evicted:
            self._release_slots(evicted)
            self.metrics["evictions"] += len(evicted)
            # the evicted sequences' generated tokens were revoked: their
            # inter-token clock must not bridge across the readmission
            for req in self.sched.waiting:
                self._last_token_wall.pop(req.uid, None)

    def _finish_decode_slots(self, slots: list[int]) -> None:
        for s in slots:
            self.sched.finish(s)

    # -- introspection --------------------------------------------------------

    def audit_decode_step(self, **audit_kwargs):
        """graft-lint jaxpr audit of the decode step (trace-only — the
        donated pool buffers stay intact).  The pool update must come back
        clean: donation fully consumed (no GL101), no in-trace transfers,
        no donated-name reuse (the AST sweep covers GL201 separately).
        In multi-tenant mode the audited program includes the adapter pool
        and id routing — the contract is identical."""
        from ..analysis import audit_jitted

        n = self.plugin.num_slots
        if self.adapters is None:
            return audit_jitted(
                self._decode, self.params, self.cache,
                jax.ShapeDtypeStruct((n,), jnp.int32),
                jax.ShapeDtypeStruct((n,), jnp.bool_),
                self._base_rng, **audit_kwargs,
            )
        return audit_jitted(
            self._decode, self.params, self.adapters.pool, self.cache,
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((n,), jnp.bool_),
            jax.ShapeDtypeStruct((n,), jnp.int32),
            self._base_rng, **audit_kwargs,
        )

    @property
    def speculator(self) -> Optional["Speculator"]:
        """The engine's speculative-decode state (None when off)."""
        return self._spec

    @property
    def speculate_mode(self) -> str:
        return self.plugin.speculate if self._spec is not None else "off"

    def audit_verify_step(self, **audit_kwargs):
        """graft-lint jaxpr audit of the speculative verify step at the
        largest bucket width — the allocate + multi-token append +
        page-rollback pytree must alias the donated cache exactly like the
        decode step (no GL101 wasted donation, no in-trace transfers)."""
        from ..analysis import audit_jitted

        if self._spec is None:
            raise RuntimeError("speculation is off: no verify program to audit")
        n = self.plugin.num_slots
        w = self._spec.buckets[-1] + 1
        sds = jax.ShapeDtypeStruct
        if self.adapters is None:
            return audit_jitted(
                self._verify, self.params, self.cache,
                sds((n, w), jnp.int32), sds((n,), jnp.int32),
                sds((n,), jnp.bool_), self._base_rng, **audit_kwargs,
            )
        return audit_jitted(
            self._verify, self.params, self.adapters.pool, self.cache,
            sds((n, w), jnp.int32), sds((n,), jnp.int32),
            sds((n,), jnp.bool_), sds((n,), jnp.int32),
            self._base_rng, **audit_kwargs,
        )

    def free_page_mirror_in_sync(self) -> bool:
        """Test hook: the host scheduler's free-page mirror equals the
        device allocator's ``free_top`` (one scalar fetch).  The full
        resource contract — page conservation, slot accounting, adapter
        refcount balance — is the reusable
        :func:`~.overload.verify_serving_invariants` checker this grew
        into; chaos tests and ``replay(..., verify_invariants=True)`` run
        that one."""
        return int(self.cache["free_top"]) == self.sched.free_pages
