"""Big-model loading & dispatch.

TPU-native re-design of reference ``big_modeling.py`` + ``utils/modeling.py``
+ ``utils/offload.py`` (SURVEY §2.7):

- ``init_empty_weights`` (reference big_modeling.py:61 monkey-patches
  ``register_parameter`` onto the meta device) → :func:`abstract_init` /
  ``init_empty_weights``: ``jax.eval_shape`` gives the ShapeDtypeStruct tree
  for free — no monkey-patching, no materialization.
- ``infer_auto_device_map`` (reference modeling.py:1278 greedy layer placement
  across gpu/cpu/disk budgets) → :func:`infer_auto_placement`: under GSPMD a
  *sharding plan* replaces the per-layer device map for multi-chip; the
  planner survives for **over-HBM** models, deciding which subtrees live in
  device HBM vs pinned host memory vs disk.
- ``load_checkpoint_in_model`` (reference modeling.py:1788 streams safetensor
  slices per device) → :func:`load_checkpoint_in_model`: safetensors shards
  stream **directly into device shards** per NamedSharding — each host
  touches only bytes it owns; host/disk-assigned leaves become lazy memmaps.
- ``AlignDevicesHook`` forward hooks (reference hooks.py:227 move weights
  in/out per-forward) → :func:`offloaded_apply`: a functional wrapper that
  fetches offloaded leaves before ``apply`` and drops them after — same
  capability, no monkey-patched ``forward``.
- ``OffloadedWeightsLoader`` (reference offload.py:127 lazy mmap of .dat +
  index.json) → :class:`OffloadStore`, same on-disk format idea.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
from pathlib import Path
from typing import Any, Callable, Mapping, Optional, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .logging import get_logger
from .utils.constants import SAFE_WEIGHTS_INDEX_NAME

logger = get_logger(__name__)


# ---------------------------------------------------------------------------
# Abstract init (meta device analog)
# ---------------------------------------------------------------------------


def abstract_init(module, rng, *sample_args, **sample_kwargs):
    """ShapeDtypeStruct tree of a flax module's params — zero memory."""
    return jax.eval_shape(lambda: module.init(rng, *sample_args, **sample_kwargs))


def init_params_leafwise(model, accelerator, sample_ids, *, scale: float = 0.02,
                         dtype=None):
    """Materialize params leaf-by-leaf straight into their planned shards —
    peak device memory is one leaf, like the streaming checkpoint loader.

    This is the big-model alternative to ``Accelerator.init_params`` when
    the full-precision tree exceeds HBM (e.g. 7B fp32 masters on a 16GiB
    chip under host offload): flax's monolithic init executable stages the
    whole tree on device before writing outputs (measured OOM at 7B).  The
    initialization is *synthetic* (normal(0, scale) matrices, ones for
    norm scales, zeros elsewhere) — real 7B flows load trained weights via
    :func:`load_checkpoint_in_model`, which is leaf-streamed already.
    """
    import jax.numpy as jnp

    from .parallel.sharding import host_offload_supported, host_plan, path_str

    abstract = jax.eval_shape(lambda: model.init(jax.random.key(0), sample_ids))
    if dtype is not None:
        # storage-dtype override: bf16 "masters" for the stochastic-rounding
        # optimizer path (halves the host/PCIe bytes of every param leaf)
        abstract = jax.tree_util.tree_map(
            lambda s: jax.ShapeDtypeStruct(s.shape, dtype)
            if jnp.issubdtype(s.dtype, jnp.floating) else s,
            abstract,
        )
    plan = accelerator._params_plan(abstract)
    if accelerator._offload_flags()[1] and host_offload_supported():
        plan = host_plan(plan)
    flat, treedef = jax.tree_util.tree_flatten_with_path(abstract)
    shardings = jax.tree_util.tree_leaves(plan, is_leaf=lambda x: hasattr(x, "spec"))
    # one jit per distinct (kind, shape, dtype, sharding) — NOT per leaf
    # (a per-leaf closure would pay a full compile hundreds of times)
    jits: dict = {}

    def initializer(kind, shape, dtype, sh):
        key = (kind, shape, str(dtype), sh)
        if key not in jits:
            if kind == "normal":
                jits[key] = jax.jit(
                    lambda k: (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype),
                    out_shardings=sh,
                )
            elif kind == "ones":
                jits[key] = jax.jit(lambda: jnp.ones(shape, dtype), out_shardings=sh)
            else:
                jits[key] = jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sh)
        return jits[key]

    out = []
    for i, ((path, sds), sh) in enumerate(zip(flat, shardings)):
        name = path_str(path)
        if sds.ndim >= 2:
            out.append(initializer("normal", sds.shape, sds.dtype, sh)(jax.random.key(i)))
        elif "scale" in name or "norm" in name.lower():
            out.append(initializer("ones", sds.shape, sds.dtype, sh)())
        else:
            out.append(initializer("zeros", sds.shape, sds.dtype, sh)())
    return jax.tree_util.tree_unflatten(treedef, out)


@contextlib.contextmanager
def init_empty_weights(include_buffers: bool = False):
    """API-parity context (reference :61).  Under JAX initialization is
    already lazy/functional; the context exists so ported user code runs
    unchanged — inside it, use :func:`abstract_init` instead of
    ``module.init``."""
    yield


init_on_device = init_empty_weights


# ---------------------------------------------------------------------------
# Size accounting (reference compute_module_sizes modeling.py:651)
# ---------------------------------------------------------------------------


def _dtype_size(dtype) -> int:
    return np.dtype(dtype).itemsize if not hasattr(dtype, "itemsize") else dtype.itemsize


def compute_module_sizes(params, prefix: str = "") -> dict[str, int]:
    """Bytes per subtree path ('' = total), like reference modeling.py:651."""
    sizes: dict[str, int] = {}

    def _walk(node, path):
        if isinstance(node, Mapping):
            total = 0
            for k, v in node.items():
                total += _walk(v, f"{path}.{k}" if path else str(k))
            sizes[path] = total
            return total
        if isinstance(node, (list, tuple)):
            total = 0
            for i, v in enumerate(node):
                total += _walk(v, f"{path}.{i}" if path else str(i))
            sizes[path] = total
            return total
        nbytes = int(np.prod(node.shape)) * _dtype_size(node.dtype) if hasattr(node, "shape") else 0
        sizes[path] = nbytes
        return nbytes

    total = _walk(params, prefix)
    sizes[""] = total
    return sizes


def get_max_memory(max_memory: Optional[dict] = None) -> dict:
    """Available budget per target (reference get_max_memory modeling.py:744):
    one entry per local device (HBM limit) + 'cpu' (host RAM).  Values may be
    overridden with ints or strings like '10GB'."""
    from .checkpointing import parse_size

    if max_memory is not None:
        return {
            k: (parse_size(v) if isinstance(v, str) else v) for k, v in max_memory.items()
        }
    out = {}
    for i, d in enumerate(jax.local_devices()):
        stats = d.memory_stats() or {}
        # leave 10% headroom like the reference's 90% scaling
        out[i] = int(stats.get("bytes_limit", 16 * 2**30) * 0.9)
    try:
        import psutil

        out["cpu"] = int(psutil.virtual_memory().available * 0.9)
    except ImportError:
        out["cpu"] = int(_available_host_memory() * 0.9)
    return out


def _available_host_memory() -> int:
    """Available (not total) host RAM, /proc/meminfo fallback for no-psutil
    hosts; budgeting total RAM would overcommit an already-loaded host."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    # last resort: assume half of physical RAM is usable
    return int(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES") * 0.5)


# ---------------------------------------------------------------------------
# Placement planner (device_map analog for over-HBM models)
# ---------------------------------------------------------------------------


def _placement_units(
    params, sizes: dict[str, int], max_unit: int, no_split: frozenset[str]
) -> list[str]:
    """Split the tree into placement units: descend into any subtree larger
    than ``max_unit`` (the biggest single budget) unless it is listed in
    ``no_split``; keep tree (layer) order so adjacent layers stay on the
    same tier (reference infer_auto_device_map iterates modules in order)."""
    units: list[str] = []

    def _walk(node, path):
        splittable = (
            isinstance(node, Mapping)
            and len(node) > 0
            and path not in no_split
            and (path == "" or sizes.get(path, 0) > max_unit)
        )
        if splittable:
            for k, v in node.items():
                _walk(v, f"{path}.{k}" if path else str(k))
        elif path:
            units.append(path)

    _walk(params, "")
    return units


def infer_auto_placement(
    params,
    max_memory: Optional[dict] = None,
    no_split_paths: Optional[list[str]] = None,
    offload_to_disk: bool = True,
) -> dict[str, Union[int, str]]:
    """Greedy assignment of subtrees to device HBM / 'cpu' / 'disk' budgets
    (reference infer_auto_device_map modeling.py:1278).  Returns
    {subtree_path: target} with dot-separated paths.  Subtrees too big for
    any single budget are recursively split down to leaves (flax trees have
    a single 'params' root, so descending is required for tiering to do
    anything); ``no_split_paths`` pins listed subtrees to one tier.  Under
    GSPMD multi-chip sharding handles *splitting*; this planner handles
    *capacity overflow* (host/disk tiers for >HBM models)."""
    budgets = dict(get_max_memory(max_memory))
    sizes = compute_module_sizes(params)
    device_targets = [k for k in budgets if isinstance(k, int)]
    order = device_targets + ["cpu"] + (["disk"] if offload_to_disk else [])
    # Units larger than the biggest HBM budget are split so device memory can
    # still be packed; cpu budget is the ceiling only when no devices exist.
    max_unit = max(
        (budgets[t] for t in device_targets),
        default=budgets.get("cpu", 0),
    )
    units = _placement_units(params, sizes, max_unit, frozenset(no_split_paths or ()))

    placement: dict[str, Union[int, str]] = {}
    for path in units:
        size = sizes.get(path, 0)
        placed = False
        for target in order:
            if target == "disk":
                placement[path] = "disk"
                placed = True
                break
            if budgets.get(target, 0) >= size:
                budgets[target] -= size
                placement[path] = target
                placed = True
                break
        if not placed:
            raise ValueError(
                f"Cannot place subtree {path!r} ({size} bytes) within max_memory {budgets}; "
                "enable offload_to_disk or raise budgets"
            )
    return placement


# ---------------------------------------------------------------------------
# Offload store (reference utils/offload.py)
# ---------------------------------------------------------------------------


class OffloadStore:
    """Disk-backed weights: one .dat memmap per tensor + index.json
    (reference OffloadedWeightsLoader offload.py:127 format)."""

    def __init__(self, save_folder: Union[str, os.PathLike], autoflush: bool = True):
        self.folder = Path(save_folder)
        self.folder.mkdir(parents=True, exist_ok=True)
        self.index_file = self.folder / "index.json"
        self.autoflush = autoflush
        self._dirty = False
        self.index: dict[str, dict] = (
            json.loads(self.index_file.read_text()) if self.index_file.exists() else {}
        )

    def save(self, key: str, array) -> None:
        arr = np.asarray(array)
        path = self.folder / f"{key.replace('/', '--')}.dat"
        mm = np.memmap(path, dtype=arr.dtype, mode="w+", shape=arr.shape or (1,))
        mm[...] = arr.reshape(arr.shape or (1,))
        mm.flush()
        self.index[key] = {"dtype": str(arr.dtype), "shape": list(arr.shape)}
        self._dirty = True
        if self.autoflush:
            self.flush()

    def flush(self) -> None:
        """Write index.json once; bulk writers pass autoflush=False and call
        this at the end (index rewrite per tensor is O(n²) over a 10k-tensor
        checkpoint)."""
        if self._dirty:
            self.index_file.write_text(json.dumps(self.index))
            self._dirty = False

    def load(self, key: str) -> np.ndarray:
        meta = self.index[key]
        path = self.folder / f"{key.replace('/', '--')}.dat"
        shape = tuple(meta["shape"])
        return np.memmap(path, dtype=np.dtype(meta["dtype"]), mode="r", shape=shape or (1,)).reshape(shape)

    def keys(self):
        return self.index.keys()

    def __contains__(self, key):
        return key in self.index


def offload_state_dict(save_dir: str, state_dict: Mapping[str, Any]) -> OffloadStore:
    """reference offload_state_dict (offload.py:85)."""
    store = OffloadStore(save_dir, autoflush=False)
    for k, v in state_dict.items():
        store.save(k, v)
    store.flush()
    return store


def offload_store_params(store: OffloadStore) -> dict:
    """Rebuild the nested params pytree from an :class:`OffloadStore` as
    **lazy memmap leaves** — the disk tier behind
    :func:`~accelerate_tpu.generation.generate_streamed`.

    Each leaf stays an ``np.memmap`` until its layer's turn to stream, so
    building the tree costs no RAM; ``generate_streamed``'s
    :class:`~accelerate_tpu.ops.streaming.LayerPrefetcher` then uploads
    layer *k+1* straight from its ``.dat`` files into the device-side double
    buffer while layer *k*'s matmuls run (page-cache-warm files overlap like
    host RAM; cold files add the disk read to the hidden transfer).  Keys
    are the '/'-joined tree paths :func:`offload_state_dict` /
    :func:`load_checkpoint_in_model` wrote."""
    tree: dict = {}
    for key in store.keys():
        parts = key.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = store.load(key)
    return tree


# ---------------------------------------------------------------------------
# Checkpoint streaming into shards
# ---------------------------------------------------------------------------


def _path_key(path) -> str:
    """'/'-joined key for a tree_flatten_with_path path (DictKey/SequenceKey/
    GetAttrKey all covered)."""
    parts = []
    for k in path:
        for attr in ("key", "idx", "name"):
            if hasattr(k, attr):
                parts.append(str(getattr(k, attr)))
                break
        else:
            parts.append(str(k))
    return "/".join(parts)


def _normalize_placement(placement: Mapping[str, Any]) -> dict[str, Any]:
    """Placement maps use dot-separated paths (the compute_module_sizes /
    infer_auto_placement convention) but '/' is accepted too."""
    return {k.replace(".", "/"): v for k, v in placement.items()}


def _lookup_placement(key: str, normalized: Mapping[str, Any]):
    """Most-specific entry for '/'-keyed ``key`` in a ``_normalize_placement``
    result; ancestors match, deepest wins."""
    parts = key.split("/")
    for depth in range(len(parts), 0, -1):
        hit = normalized.get("/".join(parts[:depth]))
        if hit is not None:
            return hit
    return normalized.get("")  # root catch-all ({"": "cpu"} = whole tree)


def _iter_checkpoint_tensors(checkpoint_path):
    """Yield (name, numpy array (possibly lazy)) from a file, a sharded dir,
    or — for stream adapters like hf_interop's expert stacking — any
    already-built iterable of (name, array) pairs, passed through."""
    if not isinstance(checkpoint_path, (str, os.PathLike)):
        yield from checkpoint_path
        return
    p = Path(checkpoint_path)
    files: list[Path]
    if p.is_dir():
        index = p / SAFE_WEIGHTS_INDEX_NAME
        if index.exists():
            names = sorted(set(json.loads(index.read_text())["weight_map"].values()))
            files = [p / n for n in names]
        else:
            files = sorted(p.glob("*.safetensors")) or sorted(p.glob("*.npz"))
    else:
        files = [p]
    for f in files:
        if f.suffix == ".safetensors":
            from .utils.serialization import LazySafetensorsFile

            sf = LazySafetensorsFile(str(f))
            for name in sf.keys():
                yield name, sf.get(name)
        elif f.suffix == ".npz":
            data = np.load(f)
            for name in data.files:
                yield name, data[name]
        else:
            raise ValueError(f"unsupported checkpoint file {f}")


def load_checkpoint_in_model(
    abstract_params,
    checkpoint: Union[str, os.PathLike],
    sharding_plan=None,
    dtype=None,
    offload_placement: Optional[dict[str, Union[int, str]]] = None,
    offload_folder: Optional[str] = None,
    strict: bool = False,
    key_map: Optional[Callable[[str], str]] = None,
    tensor_map: Optional[Callable[[str, np.ndarray], np.ndarray]] = None,
):
    """Stream a checkpoint directly into (sharded) device arrays.

    ``abstract_params``: pytree of ShapeDtypeStruct (from abstract_init) or
    real arrays; ``sharding_plan``: matching pytree of NamedSharding (e.g.
    from make_sharding_plan).  Tensors assigned to 'cpu'/'disk' by
    ``offload_placement`` stay on host / in an OffloadStore.

    ``key_map``/``tensor_map`` adapt FOREIGN checkpoint layouts at stream
    time: key_map renames (return None to skip a tensor), tensor_map
    receives (our_key, array) and may transpose/reshape — e.g. torch
    ``Linear.weight`` [out, in] into a flax kernel [in, out]; see
    ``models/hf_interop.py`` for the HuggingFace-format maps.

    Returns (params pytree, OffloadStore|None).  reference:
    load_checkpoint_in_model modeling.py:1788 + set_module_tensor_to_device
    :217 — but no per-layer hooks: arrays land in their final shards.
    """
    flat_abstract = {
        _path_key(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(abstract_params)[0]
    }
    flat_plan = {}
    if sharding_plan is not None:
        flat_plan = {
            _path_key(path): s
            for path, s in jax.tree_util.tree_flatten_with_path(
                sharding_plan, is_leaf=lambda x: isinstance(x, NamedSharding)
            )[0]
        }
    store = OffloadStore(offload_folder, autoflush=False) if offload_folder else None
    normalized_placement = _normalize_placement(offload_placement) if offload_placement else None
    loaded: dict[str, Any] = {}
    unexpected = []

    def _normalize(name: str) -> Optional[str]:
        name = key_map(name) if key_map else name
        return None if name is None else name.replace(".", "/")

    try:
        for name, tensor in _iter_checkpoint_tensors(checkpoint):
            key = _normalize(name)
            if key is None:  # key_map skip (e.g. HF rotary inv_freq buffers)
                continue
            if key not in flat_abstract:
                unexpected.append(name)
                continue
            target_dtype = dtype or flat_abstract[key].dtype
            tensor = np.asarray(tensor)
            if tensor_map is not None:
                tensor = np.asarray(tensor_map(key, tensor))
            if tuple(tensor.shape) != tuple(flat_abstract[key].shape):
                raise ValueError(
                    f"shape mismatch for {name}: checkpoint {tensor.shape} vs model {flat_abstract[key].shape}"
                )
            placement = _lookup_placement(key, normalized_placement) if normalized_placement else None
            if placement == "disk":
                if store is None:
                    raise ValueError("offload_placement says 'disk' but no offload_folder given")
                store.save(key, tensor.astype(target_dtype))
                loaded[key] = store.load(key)
            elif placement == "cpu":
                loaded[key] = tensor.astype(target_dtype)
            else:
                sharding = flat_plan.get(key)
                # cast on HOST before the transfer: device_put ships exactly
                # the target dtype's bytes (fp32 ckpt -> bf16 target halves
                # H2D traffic, which dominates load time on thin links)
                arr = tensor if tensor.dtype == np.dtype(target_dtype) else tensor.astype(target_dtype)
                if sharding is not None:
                    loaded[key] = jax.device_put(arr, sharding)
                elif isinstance(placement, (int, np.integer)):
                    loaded[key] = jax.device_put(arr, jax.local_devices()[int(placement)])
                else:
                    loaded[key] = jax.device_put(arr)
    finally:
        # keep index.json consistent with any .dat files already rewritten,
        # even when a shape-mismatch/strict error aborts the stream
        if store is not None:
            store.flush()

    missing = [k for k in flat_abstract if k not in loaded]
    if strict and (missing or unexpected):
        raise ValueError(f"missing keys: {missing}; unexpected keys: {unexpected}")
    for k in missing:
        logger.warning("key %s missing from checkpoint; leaving abstract", k)
        loaded[k] = flat_abstract[k]

    # unflatten back to the original structure
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(abstract_params)
    leaves = [loaded[_path_key(path)] for path, _ in paths_leaves]
    return jax.tree_util.tree_unflatten(treedef, leaves), store


def load_checkpoint_and_dispatch(
    module,
    checkpoint: Union[str, os.PathLike],
    rng=None,
    sample_args: tuple = (),
    sample_kwargs: Optional[dict] = None,
    mesh: Optional[Mesh] = None,
    device_map: Union[str, dict, None] = "auto",
    max_memory: Optional[dict] = None,
    offload_folder: Optional[str] = None,
    dtype=None,
    strict: bool = False,
    key_map: Optional[Callable[[str], str]] = None,
    tensor_map: Optional[Callable[[str, np.ndarray], np.ndarray]] = None,
):
    """One-call UX (reference load_checkpoint_and_dispatch big_modeling.py:513):
    abstract-init the module, plan sharding/offload, stream the checkpoint
    into final placement.  Returns (params, offload_store).
    ``key_map``/``tensor_map`` adapt foreign checkpoint layouts (see
    load_checkpoint_in_model)."""
    rng = rng if rng is not None else jax.random.key(0)
    abstract = abstract_init(module, rng, *sample_args, **(sample_kwargs or {}))

    plan = None
    if mesh is not None:
        from .parallel.sharding import make_sharding_plan
        from .state import AcceleratorState

        state = AcceleratorState()
        plan = make_sharding_plan(abstract, mesh, parallelism_config=state.parallelism_config)

    placement = None
    if device_map == "auto":
        sizes = compute_module_sizes(abstract)
        budgets = get_max_memory(max_memory)
        total_hbm = sum(v for k, v in budgets.items() if isinstance(k, int))
        if sizes[""] > total_hbm:
            placement = infer_auto_placement(abstract, max_memory, offload_to_disk=offload_folder is not None)
    elif isinstance(device_map, dict):
        placement = device_map

    return load_checkpoint_in_model(
        abstract, checkpoint, sharding_plan=plan, dtype=dtype,
        offload_placement=placement, offload_folder=offload_folder, strict=strict,
        key_map=key_map, tensor_map=tensor_map,
    )


def serve_model(model, params, serving_plugin=None, generation_config=None, rng=None):
    """Stand up a continuous-batching :class:`~accelerate_tpu.serving.ServingEngine`
    over an already-dispatched param tree — the serving-side completion of
    the reference's load→dispatch→generate contract (big_modeling.py:513 +
    benchmarks/big_model_inference), rebuilt at production scale: paged KV
    cache, per-step admission/eviction, chunked prefill (docs/serving.md).

    ``params`` is whatever :func:`load_checkpoint_and_dispatch` or
    :meth:`~accelerate_tpu.accelerator.Accelerator.init_params` produced —
    including int8 ``QuantizedTensor`` leaves, which decode through the
    Pallas in-tile-dequant matmuls unchanged."""
    from .serving import ServingEngine

    return ServingEngine(model, params, serving_plugin, generation_config, rng=rng)


def load_checkpoint_and_serve(
    module,
    checkpoint: Union[str, os.PathLike],
    *,
    serving_plugin=None,
    generation_config=None,
    sample_args: tuple = (),
    dtype=None,
    **dispatch_kwargs,
):
    """One call from checkpoint to serving engine:
    :func:`load_checkpoint_and_dispatch` then :func:`serve_model`."""
    params, _store = load_checkpoint_and_dispatch(
        module, checkpoint, sample_args=sample_args, dtype=dtype, **dispatch_kwargs
    )
    return serve_model(module, params, serving_plugin, generation_config)


def dispatch_model(params, placement: dict[str, Union[int, str]], offload_folder: Optional[str] = None):
    """Place an already-materialized pytree per a placement map
    (reference dispatch_model big_modeling.py:310)."""
    devices = jax.local_devices()
    store = OffloadStore(offload_folder, autoflush=False) if offload_folder else None
    normalized = _normalize_placement(placement)

    def _place(path, leaf):
        key = _path_key(path)
        target = _lookup_placement(key, normalized)
        if target is None:
            target = 0
        if target == "disk":
            if store is None:
                raise ValueError("disk placement requires offload_folder")
            store.save(key, leaf)
            return store.load(key)
        if target == "cpu":
            return np.asarray(leaf)
        return jax.device_put(leaf, devices[int(target)])

    try:
        placed = jax.tree_util.tree_map_with_path(_place, params)
    finally:
        if store is not None:
            store.flush()
    return placed, store


def cpu_offload(params, apply_fn: Optional[Callable] = None, execution_device=None):
    """Whole-tree host offload (reference big_modeling.py:cpu_offload:175):
    every leaf moves to host memory; with ``apply_fn`` given, also returns a
    wrapped apply that ships leaves to ``execution_device`` just-in-time per
    call and frees them after.  For layer-granular streaming at generation
    time, prefer :func:`accelerate_tpu.generation.generate_streamed`."""
    placed, _ = dispatch_model(params, {"": "cpu"})
    if apply_fn is None:
        return placed
    return placed, offloaded_apply(apply_fn, execution_device)


def disk_offload(params, offload_dir: Union[str, os.PathLike],
                 apply_fn: Optional[Callable] = None, execution_device=None):
    """Whole-tree disk offload (reference big_modeling.py:disk_offload:226):
    leaves are written to ``offload_dir`` and rebound as memory-maps; with
    ``apply_fn`` given, also returns the just-in-time wrapped apply."""
    placed, _store = dispatch_model(params, {"": "disk"}, offload_folder=str(offload_dir))
    if apply_fn is None:
        return placed
    return placed, offloaded_apply(apply_fn, execution_device)


# Reference-name alias (reference modeling.py:infer_auto_device_map:1278):
# same planner, TPU-native semantics — GSPMD sharding handles *splitting*,
# this handles *capacity overflow* into host/disk tiers.
infer_auto_device_map = infer_auto_placement


def offloaded_apply(apply_fn: Callable, device=None):
    """Wrap ``apply_fn(params, *args)`` so host/disk-resident leaves are
    shipped to device just-in-time and freed after — the AlignDevicesHook
    capability (reference hooks.py:227), functionally."""

    def wrapped(params, *args, **kwargs):
        def _fetch(x):
            if isinstance(x, np.memmap) or isinstance(x, np.ndarray):
                return jax.device_put(np.asarray(x), device)
            return x

        device_params = jax.tree_util.tree_map(_fetch, params)
        try:
            return apply_fn(device_params, *args, **kwargs)
        finally:
            del device_params

    return wrapped
