"""``python -m accelerate_tpu`` → the root CLI."""

from .commands.accelerate_cli import main

if __name__ == "__main__":
    main()
