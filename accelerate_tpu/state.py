"""Process/device state singletons — the L2 kernel of the framework.

TPU-native re-design of reference ``state.py`` (1,365 LoC):

- :class:`PartialState` (reference :122) — borg singleton holding process
  rank/world/devices; initializes the collective runtime.  On JAX the
  collective runtime is ``jax.distributed.initialize`` (one process per host)
  instead of ``torch.distributed.init_process_group`` (reference :243), and
  the "backend zoo" (reference ``_prepare_backend`` :753) collapses to the
  XLA platform probe.
- :class:`AcceleratorState` (reference :863) — layers mixed-precision and
  parallelism/mesh resolution on top.
- :class:`GradientState` (reference :1225) — gradient-accumulation bookkeeping
  shared by dataloader/optimizer/scheduler wrappers.

Process-control helpers (``main_process_first``, ``split_between_processes``,
``wait_for_everyone`` — reference :376-560) are preserved with identical
semantics; barriers use ``multihost_utils.sync_global_devices``.
"""

from __future__ import annotations

import logging
import math
import os
from contextlib import contextmanager
from functools import partial
from typing import Any, Callable, Optional

import jax
import numpy as np

from .parallelism_config import ParallelismConfig
from .utils.dataclasses import (
    DistributedType,
    GradientAccumulationPlugin,
    InitProcessGroupKwargs,
    MixedPrecisionType,
)
from .utils.environment import parse_choice_from_env, parse_flag_from_env

logger = logging.getLogger(__name__)

_jax_distributed_initialized = False


def _maybe_init_jax_distributed(kwargs: Optional[InitProcessGroupKwargs]) -> None:
    """Bring up the multi-host collective runtime exactly once.

    Analog of ``torch.distributed.init_process_group`` (reference state.py:243).
    A coordinator address in env/kwargs signals a multi-host launch; otherwise
    JAX's single-process world is already live.
    """
    global _jax_distributed_initialized
    if _jax_distributed_initialized:
        return
    # NOTE: do NOT touch jax.process_count()/jax.devices() here — any backend
    # query initializes JAX and makes jax.distributed.initialize impossible.
    coordinator = None
    num_processes = process_id = None
    if kwargs is not None and kwargs.coordinator_address:
        coordinator = kwargs.coordinator_address
        num_processes = kwargs.num_processes
        process_id = kwargs.process_id
    elif os.environ.get("ACCELERATE_COORDINATOR_ADDRESS"):
        coordinator = os.environ["ACCELERATE_COORDINATOR_ADDRESS"]
        num_processes = int(os.environ.get("ACCELERATE_NUM_PROCESSES", "0")) or None
        process_id = int(os.environ.get("ACCELERATE_PROCESS_ID", "-1"))
        process_id = None if process_id < 0 else process_id
    if coordinator is None:
        return
    # CPU gangs need an explicit collectives backend: without it the CPU
    # backend REJECTS any cross-process computation ("Multiprocess
    # computations aren't implemented on the CPU backend"), which silently
    # reduced every `launch --cpu` gang to collectives-free scripts.  Gloo
    # ships in jaxlib; set it BEFORE initialize (it is read at client
    # construction).  ACCELERATE_CPU_COLLECTIVES overrides ("none" opts
    # out); harmless on TPU, where the TPU backend owns the collectives.
    impl = os.environ.get("ACCELERATE_CPU_COLLECTIVES", "gloo")
    if impl and impl != "none":
        try:
            jax.config.update("jax_cpu_collectives_implementation", impl)
        except (AttributeError, ValueError):  # jax without the knob/impl
            pass
    init_kwargs: dict[str, Any] = {"coordinator_address": coordinator}
    if num_processes is not None:
        init_kwargs["num_processes"] = num_processes
    if process_id is not None:
        init_kwargs["process_id"] = process_id
    if kwargs is not None:
        timeout = kwargs.initialization_timeout
        if timeout is None and kwargs.timeout is not None:
            timeout = int(kwargs.timeout.total_seconds())
        if timeout:
            init_kwargs["initialization_timeout"] = timeout
    jax.distributed.initialize(**init_kwargs)
    _jax_distributed_initialized = True


class PartialState:
    """Singleton with information about the current process/device world.

    reference state.py:122 — same borg pattern (``_shared_state``), same public
    attribute names (``process_index``, ``num_processes``, ``device``,
    ``distributed_type``, ``debug``), same process-control context managers.
    """

    _shared_state: dict = {}

    def __init__(self, cpu: bool = False, **kwargs):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        init_pg_kwargs = kwargs.pop("init_process_group_kwargs", None)
        self.debug = parse_flag_from_env("ACCELERATE_DEBUG_MODE")
        if parse_flag_from_env("ACCELERATE_CPU_AFFINITY"):
            # opt-in (reference state.py:314).  MUST run before the first
            # backend touch: XLA's thread pools inherit the calling thread's
            # mask only at spawn, so the rank/world come from the launcher's
            # env, not from jax.  Only co-located ranks partition — on a real
            # pod (TPU_WORKER_ID set, one process per host) every host owns
            # all of its cores and there is nothing to split.
            from .utils.environment import get_int_from_env, set_cpu_affinity

            _n_local = get_int_from_env(["ACCELERATE_NUM_PROCESSES"], 1)
            _on_pod = os.environ.get("TPU_WORKER_ID") or os.environ.get("CLOUD_TPU_TASK_ID")
            if _n_local > 1 and not _on_pod:
                set_cpu_affinity(
                    get_int_from_env(["ACCELERATE_PROCESS_ID"], 0),
                    total_local_processes=_n_local,
                )
        if cpu or parse_flag_from_env("ACCELERATE_USE_CPU"):
            jax.config.update("jax_platforms", "cpu")
        _maybe_init_jax_distributed(init_pg_kwargs)

        self.devices = jax.devices()
        self.local_devices = jax.local_devices()
        self.num_devices = len(self.devices)
        self.num_local_devices = len(self.local_devices)
        self.process_index = jax.process_index()
        self.num_processes = jax.process_count()
        self.local_process_index = self.process_index  # one process per host
        self.device = self.local_devices[0]
        self.platform = self.device.platform

        if self.num_processes > 1:
            self.distributed_type = DistributedType.MULTI_HOST
        elif self.num_devices > 1:
            self.distributed_type = DistributedType.MULTI_DEVICE
        else:
            self.distributed_type = DistributedType.NO
        self.fork_launched = parse_flag_from_env("FORK_LAUNCHED", False)

    def __repr__(self):
        return (
            f"Distributed environment: {self.distributed_type}\n"
            f"Num processes: {self.num_processes}\n"
            f"Process index: {self.process_index}\n"
            f"Local process index: {self.local_process_index}\n"
            f"Num devices: {self.num_devices} ({self.platform})\n"
            f"Device: {self.device}\n"
        )

    @property
    def initialized(self) -> bool:
        return "distributed_type" in self.__dict__

    @staticmethod
    def _reset_state():
        """Reset borg state — test hygiene (reference state.py:855)."""
        PartialState._shared_state.clear()

    @property
    def use_distributed(self) -> bool:
        return self.distributed_type != DistributedType.NO

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    # -- barriers & ordering (reference state.py:376-560) -------------------

    def wait_for_everyone(self):
        """Cross-host barrier (reference :376).  No-op single-process."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    @contextmanager
    def main_process_first(self):
        """Main process runs the block first, others wait (reference :515)."""
        yield from self._goes_first(self.is_main_process)

    @contextmanager
    def local_main_process_first(self):
        yield from self._goes_first(self.is_local_main_process)

    def _goes_first(self, is_main: bool):
        if not is_main:
            self.wait_for_everyone()
        yield
        if is_main:
            self.wait_for_everyone()

    @contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Split a list/tuple/dict/array evenly across processes
        (reference state.py:424-513 — same tail/padding semantics: uneven
        remainders go to the first processes; ``apply_padding`` repeats the
        last element so every process gets the same count)."""
        if self.num_processes == 1:
            yield inputs
            return
        length = len(inputs)
        if isinstance(inputs, dict):
            lengths = {len(v) for v in inputs.values()}
            if len(lengths) != 1:
                raise ValueError("All dict values must have the same length to split between processes")
            length = lengths.pop()

        num_samples_per_process = math.ceil(length / self.num_processes)
        start = self.process_index * num_samples_per_process
        end = start + num_samples_per_process

        def _split(obj):
            if isinstance(obj, (list, tuple, np.ndarray)) or hasattr(obj, "shape"):
                sliced = obj[start:end]
                if apply_padding and len(sliced) < num_samples_per_process and len(obj) > 0:
                    pad = [obj[-1]] * (num_samples_per_process - len(sliced))
                    if isinstance(obj, np.ndarray) or hasattr(obj, "shape"):
                        sliced = np.concatenate([np.asarray(sliced), np.stack(pad)], axis=0)
                    else:
                        sliced = list(sliced) + pad
                return sliced
            return obj

        if isinstance(inputs, dict):
            yield {k: _split(v) for k, v in inputs.items()}
        else:
            yield _split(inputs)

    # -- decorators (reference state.py:565-640) ----------------------------

    def on_main_process(self, function: Callable = None):
        if function is None:
            return partial(self.on_main_process)

        def _inner(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return _inner

    def on_local_main_process(self, function: Callable = None):
        if function is None:
            return partial(self.on_local_main_process)

        def _inner(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)

        return _inner

    def on_last_process(self, function: Callable):
        def _inner(*args, **kwargs):
            if self.is_last_process:
                return function(*args, **kwargs)

        return _inner

    def on_process(self, function: Callable = None, process_index: int = None):
        if function is None:
            return partial(self.on_process, process_index=process_index)

        def _inner(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return _inner

    def print(self, *args, **kwargs):
        """Print once per node-0 (reference state.py:644)."""
        if self.is_main_process:
            print(*args, **kwargs)

    def destroy_process_group(self):
        """Tear down the multi-host runtime (reference state.py:700-715).

        Barriers first: without it the first process to exit kills the
        coordination service while peers still heartbeat, turning a clean run
        into a fatal "Socket closed" on the laggards."""
        global _jax_distributed_initialized
        if _jax_distributed_initialized:
            self.wait_for_everyone()
            jax.distributed.shutdown()
            _jax_distributed_initialized = False


class AcceleratorState:
    """Adds precision + parallelism/mesh resolution on top of PartialState
    (reference state.py:863)."""

    _shared_state: dict = {}

    def __init__(
        self,
        mixed_precision: Optional[str] = None,
        cpu: bool = False,
        parallelism_config: Optional[ParallelismConfig] = None,
        **kwargs,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            if parallelism_config is not None and self.parallelism_config != parallelism_config:
                raise ValueError(
                    "AcceleratorState already initialized with a different parallelism_config; "
                    "call AcceleratorState._reset_state() first (test hygiene, reference testing.py:650)."
                )
            return
        # Everything below may raise (bad mixed_precision, invalid mesh
        # config).  ``initialized`` is true as soon as ``_partial`` lands, so
        # a failed construction must roll the borg dicts back — otherwise the
        # next (corrected) AcceleratorState returns the poisoned state early
        # or rejects it as "already initialized with a different
        # parallelism_config".  PartialState rolls back only if THIS call
        # created it (a pre-existing one is the user's, and valid).
        partial_preexisting = bool(PartialState._shared_state)
        try:
            self._init_validated(mixed_precision, cpu, parallelism_config, kwargs)
        except Exception:
            self._shared_state.clear()
            if not partial_preexisting:
                PartialState._reset_state()
            raise

    def _init_validated(self, mixed_precision, cpu, parallelism_config, kwargs):
        self._partial = PartialState(cpu=cpu, **kwargs)
        mixed_precision = (
            parse_choice_from_env("ACCELERATE_MIXED_PRECISION", "no")
            if mixed_precision is None
            else mixed_precision.lower()
        )
        if mixed_precision not in MixedPrecisionType:
            raise ValueError(
                f"mixed_precision must be one of {MixedPrecisionType.list()}, got {mixed_precision!r}"
            )
        if mixed_precision == "fp8":
            from .ops.precision import fp8_hardware_supported

            if not fp8_hardware_supported():
                if parse_flag_from_env("ACCELERATE_FP8_FALLBACK_BF16"):
                    logger.warning(
                        "mixed_precision='fp8' requested but this accelerator has no "
                        "fp8 matmul units; falling back to bf16 "
                        "(ACCELERATE_FP8_FALLBACK_BF16 is set)."
                    )
                    mixed_precision = "bf16"
                else:
                    logger.warning(
                        "mixed_precision='fp8' requested but this accelerator has no "
                        "fp8 matmul units — the quantize/descale work is pure overhead "
                        "(measured slower than bf16 on TPU v5e). Training proceeds in "
                        "fp8 as requested; set ACCELERATE_FP8_FALLBACK_BF16=true to "
                        "auto-fall-back to bf16 on unsupported hardware."
                    )
        self.mixed_precision = mixed_precision
        if parallelism_config is None and os.environ.get("PARALLELISM_CONFIG_DP_SHARD_SIZE"):
            parallelism_config = ParallelismConfig.from_env()
        self.parallelism_config = parallelism_config
        self._mesh: Optional[jax.sharding.Mesh] = None
        if parallelism_config is not None:
            # surface mesh-shape errors at construction (same check the lazy
            # mesh build runs) so they hit the rollback above instead of
            # poisoning the singleton from inside the first .mesh access.
            # An explicit device subset (ParallelismConfig.devices) validates
            # against ITS size — sub-meshes are legal (dryrun legs, tests).
            parallelism_config._validate(
                len(parallelism_config.devices)
                if parallelism_config.devices is not None
                else self.num_devices
            )

    # Delegate the PartialState surface ------------------------------------

    def __getattr__(self, name):
        partial_state = self.__dict__.get("_partial")
        if partial_state is not None and hasattr(partial_state, name):
            return getattr(partial_state, name)
        raise AttributeError(f"AcceleratorState has no attribute {name!r}")

    @property
    def initialized(self) -> bool:
        return "_partial" in self.__dict__

    @staticmethod
    def _reset_state(reset_partial_state: bool = False):
        AcceleratorState._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()
        # ambient trace-time knobs owned by an Accelerator die with its
        # state: a stale ring-matmul override must not leak into the next
        # (possibly plugin-less) construction
        from .ops.collective_matmul import set_collective_matmul

        set_collective_matmul(None)

    @property
    def mesh(self) -> jax.sharding.Mesh:
        """The device mesh.  Built lazily; defaults to pure data-parallel over
        all devices when no parallelism_config was given."""
        if self._mesh is None:
            cfg = self.parallelism_config
            if cfg is None:
                cfg = ParallelismConfig(dp_shard_size=self.num_devices)
                self.parallelism_config = cfg
            self._mesh = cfg.build_device_mesh()
        return self._mesh

    @mesh.setter
    def mesh(self, value):
        self._mesh = value


class GradientState:
    """Gradient-accumulation bookkeeping singleton (reference state.py:1225).

    ``sync_gradients`` flips at accumulation boundaries; dataloader wrappers
    flip ``end_of_dataloader``/``remainder`` so ``gather_for_metrics`` can drop
    duplicate tail samples (reference accelerator.py:3040).  Under the
    TPU-native ``in_step`` accumulation mode this object only serves the
    *outer-loop* bookkeeping — the actual accumulation is a ``lax.scan`` inside
    the jitted step (see ``accelerator.py``).
    """

    _shared_state: dict = {}

    def __init__(self, gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references = [None]
            self.plugin_kwargs = {}
            self.plugin = GradientAccumulationPlugin()
            self._is_xla_gradients_synced = True
        if gradient_accumulation_plugin is not None:
            self.plugin = gradient_accumulation_plugin

    @property
    def initialized(self) -> bool:
        return "sync_gradients" in self.__dict__

    @property
    def num_steps(self) -> int:
        return self.plugin.num_steps

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin.adjust_scheduler

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin.sync_with_dataloader

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _set_sync_gradients(self, sync_gradients: bool):
        self.sync_gradients = sync_gradients

    def _add_dataloader(self, dataloader):
        if not self.initialized:  # revived after a test-hygiene reset
            GradientState.__init__(self)
        self.active_dataloader = dataloader
        self.dataloader_references.append(dataloader)

    def _remove_dataloader(self, dataloader):
        if not self.initialized:  # reset happened while a loader was live
            return
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    @staticmethod
    def _reset_state():
        GradientState._shared_state.clear()

    def __repr__(self):
        return (
            f"Sync Gradients: {self.sync_gradients}\n"
            f"At end of current dataloader: {self.end_of_dataloader}\n"
            f"Extra samples added: {self.remainder}\n"
            f"Gradient accumulation plugin: {self.plugin}\n"
        )


def is_initialized() -> bool:
    return AcceleratorState._shared_state != {}
