"""Experiment trackers.

TPU-native port of reference ``tracking.py`` (1,317 LoC): the same
``GeneralTracker`` ABC (reference :101 — ``name``/``requires_logging_directory``
/``start``/``store_init_configuration``/``log``/``finish``) with
``main_process_only`` enforcement via the ``on_main_process`` decorator
(reference :77-94), and the same backend set where the library is installed
(TensorBoard, W&B, CometML, MLflow, Aim, ClearML, DVCLive, SwanLab, Trackio).
A dependency-free JSONL tracker is always available (and doubles as the test
backend)."""

from __future__ import annotations

import atexit
import functools
import json
import os
import time
from abc import ABC, abstractmethod
from pathlib import Path
from typing import Any, Optional, Union

from .logging import get_logger
from .state import PartialState
from .utils import imports
from .utils.dataclasses import LoggerType

logger = get_logger(__name__)

def _flatten(d: dict, sep: str = ".", _prefix: str = "") -> dict:
    """``{"opt": {"lr": 0.1}} -> {"opt.lr": 0.1}`` to arbitrary depth — the
    shape hparam/metric backends want."""
    out = {}
    for k, v in d.items():
        key = f"{_prefix}{sep}{k}" if _prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, sep, key))
        else:
            out[key] = v
    return out


def on_main_process(function):
    """Run only on the main process when the tracker asks for it
    (reference tracking.py:77-94)."""

    @functools.wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if getattr(self, "main_process_only", False) and not PartialState().is_main_process:
            return None
        return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker(ABC):
    """reference GeneralTracker (tracking.py:101)."""

    main_process_only = True

    def __init__(self, _blank: bool = False):
        if not _blank:
            err = []
            for attr in ("name", "requires_logging_directory"):
                if not hasattr(self, attr):
                    err.append(attr)
            if err:
                raise NotImplementedError(f"Tracker must implement: {err}")

    @abstractmethod
    def store_init_configuration(self, values: dict): ...

    @abstractmethod
    def log(self, values: dict, step: Optional[int] = None, **kwargs): ...

    def finish(self):
        pass

    @property
    def tracker(self):
        return getattr(self, "_tracker", None)


class JSONLTracker(GeneralTracker):
    """Dependency-free metrics log: one JSON object per line.

    Torn-line hardening (the checkpointing atomicity discipline applied to
    metrics): each record is serialized in full, then handed to the kernel
    as ONE unbuffered ``write`` on a persistent append handle — never
    streamed through a userspace buffer a crash could flush halfway.  An
    ``atexit`` close backs the handle; a killed run's file therefore
    contains only complete, parseable lines (pinned by the killed-
    subprocess witness in tests/test_observability.py).  This is also the
    always-available telemetry sink: ``Accelerator.log(
    twin_registry().flat_metrics())`` lands the twin/SLO tables here with
    no extra dependency."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Union[str, os.PathLike] = "."):
        super().__init__()
        self.run_name = run_name
        self.dir = Path(logging_dir or ".") / run_name
        self.dir.mkdir(parents=True, exist_ok=True)
        self.path = self.dir / "metrics.jsonl"
        # buffering=0: one os-level write per log line (whole-line or
        # nothing under any kill signal for sane line sizes)
        self._fh = open(self.path, "ab", buffering=0)
        atexit.register(self._close)
        self._tracker = self

    def _close(self):
        # drop the atexit entry too: it holds a strong reference to this
        # tracker, and a long-lived service creating per-run trackers must
        # not accumulate dead ones until process exit
        atexit.unregister(self._close)
        fh, self._fh = getattr(self, "_fh", None), None
        if fh is not None and not fh.closed:
            try:
                fh.flush()
                os.fsync(fh.fileno())
            except OSError:
                pass  # close() below still runs; the write already hit the kernel
            fh.close()

    @on_main_process
    def store_init_configuration(self, values: dict):
        (self.dir / "config.json").write_text(json.dumps(values, default=str, indent=2))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        record = {"_step": step, "_time": time.time(), **values}
        line = (json.dumps(record, default=float) + "\n").encode()
        if self._fh is None or self._fh.closed:  # post-finish stragglers
            with open(self.path, "ab", buffering=0) as f:
                f.write(line)
            return
        self._fh.write(line)

    @on_main_process
    def finish(self):
        self._close()


class TensorBoardTracker(GeneralTracker):
    """reference tracking.py:182."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Union[str, os.PathLike] = ".", **kwargs):
        super().__init__()
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir or ".", run_name)
        self._tracker = tensorboard.SummaryWriter(self.logging_dir, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict):
        self._tracker.add_hparams(_flatten(values), metric_dict={})
        self._tracker.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in _flatten(values).items():
            if isinstance(v, (int, float)):
                self._tracker.add_scalar(k, v, global_step=step, **kwargs)
            elif isinstance(v, str):
                self._tracker.add_text(k, v, global_step=step, **kwargs)
        self._tracker.flush()

    @on_main_process
    def finish(self):
        self._tracker.close()


class WandBTracker(GeneralTracker):
    """reference tracking.py:297."""

    name = "wandb"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        import wandb

        self.run_name = run_name
        self._tracker = wandb.init(project=run_name, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict):
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self._tracker.log(values, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self._tracker.finish()


class MLflowTracker(GeneralTracker):
    """reference tracking.py:696."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        super().__init__()
        import mlflow

        self.run_name = run_name
        mlflow.set_experiment(run_name)
        self._tracker = mlflow.start_run(**kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict):
        import mlflow

        for k, v in _flatten(values).items():
            mlflow.log_param(k, v)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import mlflow

        mlflow.log_metrics({k: v for k, v in _flatten(values).items() if isinstance(v, (int, float))}, step=step)

    @on_main_process
    def finish(self):
        import mlflow

        mlflow.end_run()


class CometMLTracker(GeneralTracker):
    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        from comet_ml import Experiment

        self.run_name = run_name
        self._tracker = Experiment(project_name=run_name, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict):
        self._tracker.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self._tracker.set_step(step)
        self._tracker.log_metrics(_flatten(values), step=step)

    @on_main_process
    def finish(self):
        self._tracker.end()


class AimTracker(GeneralTracker):
    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Optional[str] = ".", **kwargs):
        super().__init__()
        from aim import Run

        self._tracker = Run(repo=logging_dir, experiment=run_name, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict):
        self._tracker["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in values.items():
            self._tracker.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self._tracker.close()


class ClearMLTracker(GeneralTracker):
    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        from clearml import Task

        self._tracker = Task.init(project_name=run_name, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict):
        self._tracker.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        clearml_logger = self._tracker.get_logger()
        for k, v in _flatten(values).items():
            if isinstance(v, (int, float)):
                title, _, series = k.partition("/")
                clearml_logger.report_scalar(title=title, series=series or title, value=v, iteration=step or 0)

    @on_main_process
    def finish(self):
        self._tracker.close()


class DVCLiveTracker(GeneralTracker):
    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: Optional[str] = None, live=None, **kwargs):
        super().__init__()
        from dvclive import Live

        self._tracker = live if live is not None else Live(**kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict):
        self._tracker.log_params(_flatten(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self._tracker.step = step
        for k, v in _flatten(values).items():
            self._tracker.log_metric(k, v, **kwargs)
        self._tracker.next_step()

    @on_main_process
    def finish(self):
        self._tracker.end()


class SwanLabTracker(GeneralTracker):
    name = "swanlab"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        import swanlab

        self._tracker = swanlab.init(project=run_name, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict):
        import swanlab

        swanlab.config.update(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self._tracker.log(values, step=step)

    @on_main_process
    def finish(self):
        self._tracker.finish()


class TrackioTracker(GeneralTracker):
    name = "trackio"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        import trackio

        self._tracker = trackio.init(project=run_name, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict):
        self._tracker.config.update(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import trackio

        trackio.log(values)

    @on_main_process
    def finish(self):
        import trackio

        trackio.finish()


LOGGER_TYPE_TO_CLASS = {
    "tensorboard": (TensorBoardTracker, imports.is_tensorboard_available),
    "wandb": (WandBTracker, imports.is_wandb_available),
    "comet_ml": (CometMLTracker, imports.is_comet_ml_available),
    "mlflow": (MLflowTracker, imports.is_mlflow_available),
    "aim": (AimTracker, imports.is_aim_available),
    "clearml": (ClearMLTracker, imports.is_clearml_available),
    "dvclive": (DVCLiveTracker, imports.is_dvclive_available),
    "swanlab": (SwanLabTracker, imports.is_swanlab_available),
    "trackio": (TrackioTracker, imports.is_trackio_available),
    "jsonl": (JSONLTracker, lambda: True),
}


def filter_trackers(log_with, logging_dir=None):
    """Resolve requested tracker names to available classes
    (reference filter_trackers tracking.py:1256)."""
    out = []
    for item in log_with if isinstance(log_with, (list, tuple)) else [log_with]:
        if isinstance(item, GeneralTracker):
            out.append(item)
            continue
        name = str(item).lower()
        if name == "all":
            out.extend(cls for n, (cls, avail) in LOGGER_TYPE_TO_CLASS.items() if avail() and n != "jsonl")
            continue
        cls, avail = LOGGER_TYPE_TO_CLASS.get(name, (None, None))
        if cls is None:
            raise ValueError(f"unknown tracker {item!r}; options: {sorted(LOGGER_TYPE_TO_CLASS)}")
        if not avail():
            logger.warning("Tracker %s requested but its library is not installed; skipping", name)
            continue
        out.append(cls)
    return out


def resolve_tracker(item, project_name: str, logging_dir=None, **init_kwargs):
    """Instantiate one tracker (used by Accelerator.init_trackers)."""
    if isinstance(item, GeneralTracker):
        return item
    classes = filter_trackers(item, logging_dir)
    if not classes:
        return None
    cls = classes[0]
    if getattr(cls, "requires_logging_directory", False):
        return cls(project_name, logging_dir=logging_dir or ".", **init_kwargs)
    return cls(project_name, **init_kwargs)
