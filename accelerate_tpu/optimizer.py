"""Optimizer wrapper over optax transforms.

TPU-native re-design of reference ``optimizer.py`` (213 LoC,
``AcceleratedOptimizer`` :38).  The reference wraps a *built* torch optimizer
and gates ``step``/``zero_grad`` on ``GradientState.sync_gradients``
(:162/:113); under JAX the update is pure and lives inside the jitted train
step, so the user hands over the optimizer *construction* (an optax
``GradientTransformation``) — exactly the design shift SURVEY §7 'hard parts'
calls for: owning the train-state pytree kills the reference's
param-identity remapping dance (accelerator.py:1524-1568, 1693-1744).

The wrapper still exposes the reference's imperative surface (``step``,
``zero_grad``, ``is_overflow``, ``param_groups``-style hyperparam access) for
loop-compatibility: ``step()`` outside a prepared train step raises a clear
error instead of silently doing nothing.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import optax

from .state import AcceleratorState, GradientState


class AcceleratedOptimizer:
    """Wraps an ``optax.GradientTransformation`` (reference optimizer.py:38).

    Attributes:
        tx: the optax transform (possibly wrapped with clipping/accumulation).
        learning_rate: the schedule or float the transform was built with, if
            known (used by trackers and ``AcceleratedScheduler``).
    """

    def __init__(
        self,
        tx: optax.GradientTransformation,
        learning_rate: Optional[Any] = None,
        scheduler=None,
    ):
        if not isinstance(tx, optax.GradientTransformation):
            raise TypeError(
                f"AcceleratedOptimizer expects an optax.GradientTransformation, got {type(tx)}. "
                "Hand over the optimizer *construction* (e.g. optax.adamw(lr)), not a stepped object."
            )
        self.tx = tx
        self.learning_rate = learning_rate
        self.scheduler = scheduler
        self.accelerator_state = AcceleratorState()
        self.gradient_state = GradientState()
        self._is_overflow = False
        self._accelerator_backward_called = False

    # -- functional surface (used by Accelerator/train step) ----------------

    def init(self, params):
        return self.tx.init(params)

    def update(self, grads, opt_state, params=None):
        return self.tx.update(grads, opt_state, params)

    # -- reference-API compatibility surface --------------------------------

    @property
    def step_was_skipped(self) -> bool:
        """Whether the last step overflowed (reference optimizer.py:197)."""
        return self._is_overflow

    def step(self, closure=None):
        raise RuntimeError(
            "Under accelerate_tpu the optimizer update runs inside the jitted train step. "
            "Use `state, metrics = accelerator.step(state, batch)` (or the function returned by "
            "`accelerator.prepare_train_step(loss_fn)`) instead of calling optimizer.step()."
        )

    def zero_grad(self, set_to_none: Optional[bool] = None):
        raise RuntimeError(
            "Gradients are functional values under JAX — there is nothing to zero. "
            "Remove optimizer.zero_grad() from the loop; the prepared train step handles accumulation."
        )

    def state_dict(self):
        raise RuntimeError(
            "Optimizer state lives in the TrainState pytree; use accelerator.save_state() "
            "or checkpoint the TrainState directly."
        )

    def __repr__(self):
        return f"AcceleratedOptimizer(tx={self.tx}, learning_rate={self.learning_rate})"
