"""Optimizer wrapper over optax transforms.

TPU-native re-design of reference ``optimizer.py`` (213 LoC,
``AcceleratedOptimizer`` :38).  The reference wraps a *built* torch optimizer
and gates ``step``/``zero_grad`` on ``GradientState.sync_gradients``
(:162/:113); under JAX the update is pure and lives inside the jitted train
step, so the user hands over the optimizer *construction* (an optax
``GradientTransformation``) — exactly the design shift SURVEY §7 'hard parts'
calls for: owning the train-state pytree kills the reference's
param-identity remapping dance (accelerator.py:1524-1568, 1693-1744).

The wrapper still exposes the reference's imperative surface (``step``,
``zero_grad``, ``is_overflow``, ``param_groups``-style hyperparam access) for
loop-compatibility: ``step()`` outside a prepared train step raises a clear
error instead of silently doing nothing.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from .state import AcceleratorState, GradientState


# ---------------------------------------------------------------------------
# Named optimizer recipes (the measured operating points of bench.py /
# docs/performance.md, constructible by name).  Families:
#   <base>      — fp32 masters, bf16 first moment (the stock recipe)
#   <base>-sr   — bf16 params with stochastic rounding, bf16 moments
#                 (ops/stochastic_rounding.py; no fp32 master tree)
#   <base>-sr8  — bf16 SR params + int8 blockwise moment state with
#                 SR-dithered requantization (ops/int8_state.py; the
#                 host-byte floor of the offload ladder)
# ---------------------------------------------------------------------------

OPTIMIZER_RECIPES: dict[str, str] = {
    "lion": "optax.lion, fp32 masters + bf16 momentum",
    "adamw": "optax.adamw, fp32 masters + bf16 first moment",
    "lion-sr": "bf16 SR params + bf16 momentum (16 -> 10 host-B/param)",
    "adamw-sr": "bf16 SR params + bf16 m/v (28 -> 14 host-B/param)",
    "lion-sr8": "bf16 SR params + int8 momentum (10 -> ~8 host-B/param)",
    "adamw-sr8": "bf16 SR params + int8 m + uint8 v (14 -> ~10 host-B/param)",
}


def reference_recipe(name: str) -> str:
    """The fp32-master reference recipe an -sr/-sr8 recipe is validated
    against (benchmarks/sr_quality.py): ``lion-sr8`` -> ``lion``."""
    return name.split("-", 1)[0]


def make_optimizer(
    name: str,
    learning_rate: Optional[float] = None,
    *,
    weight_decay: float = 0.0,
    block_size: Optional[int] = None,
    seed: int = 0,
) -> optax.GradientTransformation:
    """Build a named optimizer recipe at its benchmarked hyperparameters.

    ``learning_rate`` defaults to the bench operating points (lion family
    1e-4, adam family 3e-4).  ``weight_decay`` is passed **explicitly** to
    every recipe — including the stock optax references, whose own defaults
    differ (optax.adamw 1e-4, optax.lion 1e-3) — so an SR-vs-reference
    comparison built from this registry really runs at the same
    hyperparameters (the sr_quality harness contract).  ``block_size``
    applies to the -sr8 recipes only (per-block scale granularity,
    default :data:`~.ops.int8_state.DEFAULT_BLOCK_SIZE`); ``seed`` keys
    the deterministic SR hash of the -sr/-sr8 recipes.
    """
    from .ops.int8_state import DEFAULT_BLOCK_SIZE, adamw_int8_sr, lion_int8_sr
    from .ops.stochastic_rounding import adamw_bf16_sr, lion_bf16_sr

    if name not in OPTIMIZER_RECIPES:
        raise ValueError(
            f"unknown optimizer recipe {name!r}; options: {sorted(OPTIMIZER_RECIPES)}"
        )
    if block_size is not None:
        if not name.endswith("-sr8"):
            raise ValueError(
                f"block_size only applies to the -sr8 int8-state recipes, got {name!r}"
            )
        if block_size < 1:
            # mirror the plugin knob's validation — the same value arriving
            # via --int8-block must not silently fall back or, worse, pass
            # a negative through to int8_scale_shape (one scale PER ELEMENT)
            raise ValueError(f"block_size must be >= 1, got {block_size}")
    lion_family = reference_recipe(name) == "lion"
    lr = learning_rate if learning_rate is not None else (1e-4 if lion_family else 3e-4)
    block = DEFAULT_BLOCK_SIZE if block_size is None else block_size
    if name == "lion":
        return optax.lion(lr, b1=0.9, b2=0.99, weight_decay=weight_decay,
                          mu_dtype=jnp.bfloat16)
    if name == "adamw":
        return optax.adamw(lr, b1=0.9, b2=0.999, eps=1e-8,
                           weight_decay=weight_decay, mu_dtype=jnp.bfloat16)
    if name == "lion-sr":
        return lion_bf16_sr(lr, b1=0.9, b2=0.99, weight_decay=weight_decay, seed=seed)
    if name == "adamw-sr":
        return adamw_bf16_sr(lr, b1=0.9, b2=0.999, eps=1e-8,
                             weight_decay=weight_decay, seed=seed)
    if name == "lion-sr8":
        return lion_int8_sr(lr, b1=0.9, b2=0.99, weight_decay=weight_decay,
                            seed=seed, block_size=block)
    return adamw_int8_sr(lr, b1=0.9, b2=0.999, eps=1e-8,
                         weight_decay=weight_decay, seed=seed, block_size=block)


class AcceleratedOptimizer:
    """Wraps an ``optax.GradientTransformation`` (reference optimizer.py:38).

    Attributes:
        tx: the optax transform (possibly wrapped with clipping/accumulation).
        learning_rate: the schedule or float the transform was built with, if
            known (used by trackers and ``AcceleratedScheduler``).
    """

    def __init__(
        self,
        tx: optax.GradientTransformation,
        learning_rate: Optional[Any] = None,
        scheduler=None,
    ):
        if not isinstance(tx, optax.GradientTransformation):
            raise TypeError(
                f"AcceleratedOptimizer expects an optax.GradientTransformation, got {type(tx)}. "
                "Hand over the optimizer *construction* (e.g. optax.adamw(lr)), not a stepped object."
            )
        self.tx = tx
        self.learning_rate = learning_rate
        self.scheduler = scheduler
        self.accelerator_state = AcceleratorState()
        self.gradient_state = GradientState()
        self._is_overflow = False
        self._accelerator_backward_called = False

    # -- functional surface (used by Accelerator/train step) ----------------

    def init(self, params):
        return self.tx.init(params)

    def update(self, grads, opt_state, params=None):
        return self.tx.update(grads, opt_state, params)

    # -- reference-API compatibility surface --------------------------------

    @property
    def step_was_skipped(self) -> bool:
        """Whether the last step overflowed (reference optimizer.py:197)."""
        return self._is_overflow

    def step(self, closure=None):
        raise RuntimeError(
            "Under accelerate_tpu the optimizer update runs inside the jitted train step. "
            "Use `state, metrics = accelerator.step(state, batch)` (or the function returned by "
            "`accelerator.prepare_train_step(loss_fn)`) instead of calling optimizer.step()."
        )

    def zero_grad(self, set_to_none: Optional[bool] = None):
        raise RuntimeError(
            "Gradients are functional values under JAX — there is nothing to zero. "
            "Remove optimizer.zero_grad() from the loop; the prepared train step handles accumulation."
        )

    def state_dict(self):
        raise RuntimeError(
            "Optimizer state lives in the TrainState pytree; use accelerator.save_state() "
            "or checkpoint the TrainState directly."
        )

    def __repr__(self):
        return f"AcceleratedOptimizer(tx={self.tx}, learning_rate={self.learning_rate})"
