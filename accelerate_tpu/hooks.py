"""Functional hooks engine — the module-hook lifecycle, TPU-native.

The reference monkey-patches ``nn.Module.forward`` to interpose behavior
(reference hooks.py: ``ModelHook`` :43, ``SequentialHook`` :101,
``add_hook_to_module`` :132, ``AlignDevicesHook`` :227 with ``pre_forward``
:331, ``attach_align_device_hook_on_blocks`` :559, ``CpuOffload`` :693,
``LayerwiseCastingHook`` :757).  In JAX there is no mutable module object to
patch; the same capability is function composition: a hook transforms
``(params, args, kwargs)`` before the wrapped ``apply_fn`` runs and the
output after.  Everything here stays jit-compatible as long as individual
hooks are (device placement hooks intentionally run OUTSIDE jit — they exist
to move host-resident weights, which is a host-side concern).

``add_hook_to_apply(apply_fn, hook)`` is the ``add_hook_to_module`` analog,
returning a new callable with ``_at_hook`` metadata so hooks can be
inspected, replaced (latest wins, like ``append=False``), or removed
(``remove_hook_from_apply``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "ModelHook",
    "SequentialHook",
    "add_hook_to_apply",
    "remove_hook_from_apply",
    "AlignDevicesHook",
    "CpuOffloadHook",
    "LayerwiseCastingHook",
    "attach_align_device_hook",
]


class ModelHook:
    """Lifecycle object interposed around an ``apply_fn`` call
    (reference ModelHook hooks.py:43-98)."""

    def init_hook(self, apply_fn: Callable) -> Callable:
        """Called once at attach time; may return a replacement apply_fn."""
        return apply_fn

    def pre_forward(self, params, *args, **kwargs):
        """Transform inputs; returns (params, args, kwargs)."""
        return params, args, kwargs

    def post_forward(self, params, output):
        """Transform the output."""
        return output

    def detach_hook(self, apply_fn: Callable) -> Callable:
        """Called at removal; may undo init_hook effects."""
        return apply_fn


class SequentialHook(ModelHook):
    """Compose hooks in order (reference SequentialHook hooks.py:101)."""

    def __init__(self, *hooks: ModelHook):
        self.hooks = list(hooks)

    def init_hook(self, apply_fn):
        for h in self.hooks:
            apply_fn = h.init_hook(apply_fn)
        return apply_fn

    def pre_forward(self, params, *args, **kwargs):
        for h in self.hooks:
            params, args, kwargs = h.pre_forward(params, *args, **kwargs)
        return params, args, kwargs

    def post_forward(self, params, output):
        for h in reversed(self.hooks):
            output = h.post_forward(params, output)
        return output

    def detach_hook(self, apply_fn):
        for h in reversed(self.hooks):
            apply_fn = h.detach_hook(apply_fn)
        return apply_fn


def add_hook_to_apply(apply_fn: Callable, hook: ModelHook, append: bool = False) -> Callable:
    """Wrap ``apply_fn(params, *args, **kwargs)`` with a hook (reference
    add_hook_to_module hooks.py:132).  ``append=True`` chains onto an
    existing hook instead of replacing it."""
    if append and getattr(apply_fn, "_at_hook", None) is not None:
        hook = SequentialHook(apply_fn._at_hook, hook)
        apply_fn = apply_fn._at_original
    elif getattr(apply_fn, "_at_hook", None) is not None:
        apply_fn = apply_fn._at_original  # replace (reference :141-147)

    inner = hook.init_hook(apply_fn)

    def wrapped(params, *args, **kwargs):
        params, args, kwargs = hook.pre_forward(params, *args, **kwargs)
        output = inner(params, *args, **kwargs)
        return hook.post_forward(params, output)

    wrapped._at_hook = hook
    wrapped._at_original = apply_fn
    return wrapped


def remove_hook_from_apply(apply_fn: Callable) -> Callable:
    """Inverse of :func:`add_hook_to_apply` (reference remove_hook_from_module
    hooks.py:189)."""
    hook = getattr(apply_fn, "_at_hook", None)
    if hook is None:
        return apply_fn
    return hook.detach_hook(apply_fn._at_original)


class AlignDevicesHook(ModelHook):
    """Ship host/disk-resident param leaves to device just-in-time and drop
    the device copies after the call (reference AlignDevicesHook
    hooks.py:227: execution_device + offload mode).

    ``io_buffer`` True routes disk reads through the native IO engine's
    parallel pread when the leaf is an :class:`~numpy.memmap` (OffloadStore
    .dat files).
    """

    def __init__(self, execution_device=None, offload: bool = True, io_buffer: bool = True):
        self.execution_device = execution_device
        # offload=False: fetch once and keep the device copies (weights fit;
        # the hook only exists to place them).  True: re-fetch per call and
        # let the copies die after (weights larger than device memory).
        self.offload = offload
        self.io_buffer = io_buffer
        self._cached = None

    def _fetch(self, x):
        if isinstance(x, np.memmap) and self.io_buffer:
            from . import native

            out = np.empty(x.shape, x.dtype)
            try:
                native.read_file(x.filename, nbytes=out.nbytes, offset=x.offset, out=out)
            except (OSError, AttributeError):
                out = np.asarray(x)
            return jax.device_put(out, self.execution_device)
        if isinstance(x, np.ndarray):
            return jax.device_put(x, self.execution_device)
        return x

    def pre_forward(self, params, *args, **kwargs):
        if not self.offload:
            if self._cached is None:
                self._cached = jax.tree_util.tree_map(self._fetch, params)
            return self._cached, args, kwargs
        return jax.tree_util.tree_map(self._fetch, params), args, kwargs

    def post_forward(self, params, output):
        # offload=True: device copies of offloaded leaves die with the
        # pre_forward tree — nothing to do beyond letting them go out of scope
        return output

    def detach_hook(self, apply_fn):
        self._cached = None
        return apply_fn


class CpuOffloadHook(ModelHook):
    """Keep params on host between calls; device-put on use (reference
    CpuOffload hooks.py:693)."""

    def __init__(self, execution_device=None):
        self.execution_device = execution_device

    def init_hook(self, apply_fn):
        self._align = AlignDevicesHook(self.execution_device)
        return apply_fn

    def pre_forward(self, params, *args, **kwargs):
        host_params = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, params
        )
        return self._align.pre_forward(host_params, *args, **kwargs)


class LayerwiseCastingHook(ModelHook):
    """Upcast storage-dtype params to compute dtype in-call (reference
    LayerwiseCastingHook hooks.py:757; pairs with
    ops.precision.layerwise_casting which handles the storage side)."""

    def __init__(self, storage_dtype=jnp.float8_e4m3fn, compute_dtype=jnp.bfloat16):
        self.storage_dtype = jnp.dtype(storage_dtype)
        self.compute_dtype = compute_dtype

    def pre_forward(self, params, *args, **kwargs):
        params = jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype)
            if hasattr(x, "dtype") and x.dtype == self.storage_dtype
            else x,
            params,
        )
        return params, args, kwargs


def attach_align_device_hook(
    apply_fn: Callable,
    execution_device=None,
    offload: bool = True,
    extra_hooks: Optional[Sequence[ModelHook]] = None,
) -> Callable:
    """One-call AlignDevicesHook attachment (reference
    attach_align_device_hook_on_blocks hooks.py:559) — compose with any
    ``extra_hooks`` in order."""
    hooks: list[ModelHook] = [AlignDevicesHook(execution_device, offload=offload)]
    if extra_hooks:
        hooks.extend(extra_hooks)
    hook: ModelHook = hooks[0] if len(hooks) == 1 else SequentialHook(*hooks)
    return add_hook_to_apply(apply_fn, hook)
