"""Goodput accounting: how much of the wall clock became training progress.

"Goodput" (CheckFreq's framing) is the fraction of run time that produced
*retained* training steps — what's left after subtracting steps replayed
because the newest checkpoint predated the crash, steps skipped by the NaN
guard, restart overheads, and checkpoint stalls.  Like the streaming
overlap accounting (``ops/streaming.py`` ``StreamStats`` /
``offload_transfer_accounting``), it comes in a **measured** and a
**predicted** flavor:

- :class:`GoodputTracker` — the measured twin, owned by every
  ``Accelerator`` (``accelerator.goodput``): step/skip/restart/retry
  counters fed by the step wrapper, the guard, ``maybe_resume`` and the
  retry sites.  ``bench.py`` ALWAYS emits ``nan_skips`` / ``restarts`` /
  ``goodput_frac`` from it (zeros / 1.0 when the run was clean).
- :func:`goodput_accounting` — the predicted model: first-order CheckFreq
  arithmetic over step time, checkpoint cadence/cost, and a Poisson
  preemption rate, for sizing checkpoint intervals before burning chips.
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class GoodputTracker:
    """Measured resilience counters for one process's run.

    ``steps`` counts *executed* prepared-step calls (replays included);
    ``steps_recomputed`` is the replayed share a resume reports (known when
    the resume point and the prior progress are both known — the fault
    matrix tests and the dryrun leg pass it explicitly); ``time_lost_s``
    accumulates restart/drain overheads.  ``goodput_frac`` multiplies the
    step-retention fraction by the time-retention fraction — 1.0 for a
    clean run, degrading with every skip, replay, and restart.
    """

    steps: int = 0
    nan_skips: int = 0
    restarts: int = 0
    preemptions: int = 0
    steps_recomputed: int = 0
    time_lost_s: float = 0.0
    io_retries: int = 0
    transfer_retries: int = 0
    started_at: float = dataclasses.field(default_factory=time.monotonic)

    # -- feeders (step wrapper / guard / resume / retry sites) --------------

    def record_step(self) -> None:
        self.steps += 1

    def record_nan_skip(self, n: int = 1) -> None:
        self.nan_skips += n

    def record_preemption(self) -> None:
        self.preemptions += 1

    def record_restart(self, steps_recomputed: int = 0, time_lost_s: float = 0.0) -> None:
        self.restarts += 1
        self.steps_recomputed += int(steps_recomputed)
        self.time_lost_s += float(time_lost_s)

    def record_retry(self, site: str, attempt: int, exc: BaseException) -> None:
        """``with_retries`` ``on_retry`` adapter: checkpoint sites count as
        I/O retries, everything else as transfer retries."""
        if "checkpoint" in site:
            self.io_retries += 1
        else:
            self.transfer_retries += 1

    # -- persistence (save_accelerator_state rides this through METADATA) ---

    _PERSISTED = ("steps", "nan_skips", "restarts", "preemptions",
                  "steps_recomputed", "time_lost_s", "io_retries",
                  "transfer_retries")

    def state_dict(self) -> dict:
        """Counters only — ``started_at`` stays per-incarnation on purpose:
        ``goodput_frac``'s time fraction measures THIS process's wall clock,
        while the step/skip/restart counters span the whole run across
        restarts (so ``goodput.goodput_frac`` reflects the replayed work a
        preemption cost, not just the post-resume slice)."""
        return {k: getattr(self, k) for k in self._PERSISTED}

    def load_state_dict(self, sd: dict) -> None:
        for k in self._PERSISTED:
            if k in sd:
                setattr(self, k, type(getattr(self, k))(sd[k]))

    # -- reductions ---------------------------------------------------------

    def goodput_frac(self) -> float:
        elapsed = max(time.monotonic() - self.started_at, 1e-9)
        if self.steps > 0:
            wasted = min(self.steps, self.nan_skips + self.steps_recomputed)
            step_frac = (self.steps - wasted) / self.steps
        else:
            step_frac = 1.0
        time_frac = max(0.0, 1.0 - self.time_lost_s / elapsed)
        return max(0.0, min(1.0, step_frac * time_frac))

    def report(self) -> dict:
        """The JSON-able digest bench.py embeds (``kind: "measured"`` — the
        predicted counterpart is :func:`goodput_accounting`).  Also records
        the MEASURED side of the ``goodput.goodput_frac`` twin
        (telemetry/twins.py)."""
        from ..telemetry import twin_registry

        twin_registry().record_measured(
            "goodput.goodput_frac", self.goodput_frac(),
            source="resilience/goodput.GoodputTracker",
        )
        return {
            "steps": self.steps,
            "nan_skips": self.nan_skips,
            "restarts": self.restarts,
            "preemptions": self.preemptions,
            "steps_recomputed": self.steps_recomputed,
            "time_lost_s": round(self.time_lost_s, 3),
            "io_retries": self.io_retries,
            "transfer_retries": self.transfer_retries,
            "goodput_frac": round(self.goodput_frac(), 4),
            "kind": "measured",
        }


def goodput_accounting(
    step_time_s: float,
    ckpt_interval_steps: int,
    *,
    save_overhead_s: float = 0.0,
    preemption_rate_per_hour: float = 0.0,
    restart_overhead_s: float = 60.0,
) -> dict:
    """Predicted goodput of periodic-checkpoint training under a Poisson
    preemption process (CheckFreq's first-order model).

    Per preemption the run loses on average half a checkpoint interval of
    steps (uniform arrival within the interval) plus the restart overhead;
    checkpointing itself taxes every interval by ``save_overhead_s`` (≈0
    for async saves — the snapshot is the only synchronous part).  The
    returned ``goodput_frac`` is what survives both taxes; sweeping
    ``ckpt_interval_steps`` against a provider's measured preemption rate
    finds the CheckFreq-optimal cadence without burning a single chip-hour.
    """
    if step_time_s <= 0 or ckpt_interval_steps <= 0:
        raise ValueError("step_time_s and ckpt_interval_steps must be positive")
    interval_s = step_time_s * ckpt_interval_steps
    ckpt_overhead_frac = save_overhead_s / interval_s
    rate_per_s = preemption_rate_per_hour / 3600.0
    lost_s_per_preemption = interval_s / 2.0 + restart_overhead_s
    lost_frac = min(1.0, rate_per_s * lost_s_per_preemption)
    goodput = max(0.0, (1.0 - lost_frac) / (1.0 + ckpt_overhead_frac))
    from ..telemetry import twin_registry

    twin_registry().record_predicted(
        "goodput.goodput_frac", goodput,
        source="resilience/goodput.goodput_accounting",
    )
    return {
        "step_time_s": step_time_s,
        "ckpt_interval_steps": ckpt_interval_steps,
        "ckpt_overhead_frac": round(ckpt_overhead_frac, 4),
        "lost_frac_per_preemption_window": round(lost_frac, 4),
        "goodput_frac": round(goodput, 4),
        "kind": "predicted",
    }
