"""NaN/Inf step guard: skip the update, keep the state, count the damage.

One NaN burst — a bad batch, an fp16 overflow outside the loss-scale's
reach, a transient numerics bug — poisons params *and* optimizer moments,
and everything after it is wasted accelerator time until someone notices
the loss is ``nan`` and restores a checkpoint by hand.  The guard makes the
step self-defending: when the loss or global grad-norm is non-finite, the
update is dropped **inside the jit** and the returned params/opt-state are
bitwise the input buffers.

Mechanism: a ``where``-select per leaf (:func:`select_tree`) gated on a
single finiteness scalar, the same skipped-step discipline the fp16
loss-scale path already uses (reference overflow handling).  A select
rather than a ``lax.cond`` over the whole state on purpose: ``cond`` cannot
mix memory spaces, and under ``cpu_offload`` the opt-state/master leaves
live in pinned host memory — the select runs *inside* the host-compute
update region where every operand already shares a space (the same
constraint that keeps ``across_steps``'s accumulator in HBM,
``accelerator.py``).  ``jnp.where(pred, x, y)`` with a scalar ``pred``
returns ``y``'s exact bytes when the predicate is false, which is what the
"params bitwise unchanged" acceptance test pins.

Skip counters ride the TrainState (``guard_state``) so they survive
checkpoint/resume; the Python-side abort (``max_consecutive_nan_skips``)
turns a persistent divergence into a loud :class:`NanGuardAbort` instead of
an infinite skip loop.

Known limitation: with gradient accumulation ``mode="across_steps"`` the
carried accumulator is polluted *before* the boundary-step guard can see
it; the default ``in_step`` mode folds microbatches inside the step, so the
guard covers the whole accumulated gradient there.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

# metric keys the guarded step adds (bench/trackers read these)
GUARD_METRIC_KEYS = ("nan_skipped", "nan_skips", "consecutive_nan_skips")


class NanGuardAbort(RuntimeError):
    """Raised by the step wrapper after K consecutive non-finite steps.

    The params/opt-state were held at their last finite values the whole
    time, so the newest checkpoint (or an emergency save by the caller) is
    clean — aborting here converts silent wasted accelerator time into an
    actionable failure."""


def init_guard_state() -> dict:
    """Fresh on-device skip counters (a TrainState.guard_state value)."""
    return {
        "nan_skips": jnp.int32(0),
        "consecutive_nan_skips": jnp.int32(0),
    }


def finite_and(*flags):
    """AND-reduce finiteness flags/scalar predicates into one bool scalar."""
    out = jnp.bool_(True)
    for f in flags:
        out = jnp.logical_and(out, f)
    return out


def select_tree(finite, new_tree, old_tree):
    """The jit-compatible skip-step: per leaf, ``new`` when ``finite`` else
    the *bitwise* ``old`` buffer.  Leaves whose shapes differ between new
    and old (e.g. optimizer-state members an update legitimately reshapes)
    pass the new value through — matching the loss-scale skip semantics in
    ``accelerator.apply_update``."""

    def _sel(n, o):
        if hasattr(n, "shape") and n.shape == getattr(o, "shape", None):
            return jnp.where(finite, n, o)
        return n

    return jax.tree_util.tree_map(_sel, new_tree, old_tree)


def update_guard_counters(guard_state: dict, finite) -> dict:
    """Advance the on-device counters for one step: total skips accumulate,
    the consecutive counter resets on any finite step."""
    skipped = jnp.logical_not(finite)
    return {
        "nan_skips": guard_state["nan_skips"] + skipped.astype(jnp.int32),
        "consecutive_nan_skips": jnp.where(
            skipped, guard_state["consecutive_nan_skips"] + 1, 0
        ).astype(jnp.int32),
    }


def guard_metrics(metrics: dict, finite, new_guard_state: dict) -> dict:
    """Attach the guard's observability keys to the step metrics."""
    metrics["nan_skipped"] = jnp.logical_not(finite)
    metrics["nan_skips"] = new_guard_state["nan_skips"]
    metrics["consecutive_nan_skips"] = new_guard_state["consecutive_nan_skips"]
    return metrics


def check_abort(consecutive: int, threshold: int) -> None:
    """Host-side abort check (the step wrapper calls this with the fetched
    counter).  ``threshold`` <= 0 disables the abort — counters keep
    accumulating either way."""
    if threshold and threshold > 0 and consecutive >= threshold:
        raise NanGuardAbort(
            f"{consecutive} consecutive non-finite steps (threshold "
            f"{threshold}): params/opt-state were held at their last finite "
            "values; inspect the data pipeline / loss scaling and resume "
            "from the newest checkpoint"
        )
