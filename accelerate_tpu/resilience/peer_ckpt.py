"""Peer-redundant hot checkpoints: buddy-rank host-RAM snapshots for training.

Disk checkpoints survive anything but cost a filesystem round-trip on every
restore; a preempted gang that only lost ONE rank's state should not pay it.
Gemini (Wang et al., SOSP'23) shows the cheap middle rung: keep the newest
snapshots in a *peer's* host RAM, so rank loss recovers over the interconnect
in seconds, and CheckFreq (Mohan et al., FAST'21) shows the snapshot itself
can be nearly free — the device→host copy is the only synchronous part, and
it runs every few steps instead of every checkpoint interval.

:class:`PeerSnapshotter` is that middle rung for this repo's training loop:

- **Two-phase snapshot.**  Phase 1 (synchronous): copy every ``TrainState``
  leaf to host RAM with an explicit ``np.array(copy=True)`` — the copy is
  load-bearing, it breaks aliasing with the donated device buffers the next
  step overwrites in place (the exact hazard graft-lint's GL206 flags when
  user code skips it).  Phase 2: stream the host snapshot to the buddy rank
  (``rank ^ 1``) over the dcn/gloo broadcast plumbing in sorted wire-name
  order, the discipline ``serving/transfer.py`` established — both ranks
  issue identical collectives, receivers pass schema-shaped zeros.
- **Schema gate.**  Construction derives :func:`snapshot_schema` from the
  state template and all-reduces its hash; ranks whose templates disagree
  fail LOUDLY at arm time, not with a shape error mid-exchange.
- **CRC-verified recovery.**  :meth:`PeerSnapshotter.recover` intersects the
  waves every rank can still obtain (its own host copies ∪ what its buddy
  holds for it), agrees on the newest common wave with fixed-shape int64
  collectives, re-streams missing copies from buddies, re-verifies per-leaf
  crc32s, and rebuilds device arrays on the template's shardings.  A torn or
  bit-flipped copy (the ``partial_ckpt`` fault) fails crc and drops that
  wave out of the intersection — the gang falls back to an older wave or,
  past the RAM horizon, to :meth:`~accelerate_tpu.Accelerator.recover`'s
  disk rung.

The predicted/measured twin: :func:`peer_ckpt_accounting` prices a snapshot
wave in bytes from the schema alone (predicted side of
``recovery.peer_snapshot_bytes``); each phase-1 capture records the measured
side.  Tolerance is 0 — any disagreement between the model and the captured
host bytes is a bug, same contract as ``transfer.page_bytes``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import time
import zlib
from typing import Any

import jax
import numpy as np


class PeerSchemaError(RuntimeError):
    """Ranks tried to arm peer snapshots over disagreeing state schemas."""


class PeerSnapshotCorruptError(RuntimeError):
    """A peer-held snapshot failed crc re-verification after re-streaming."""


def _flat_leaves(train_state) -> dict[str, Any]:
    """Wire-name → leaf, the checkpoint convention: flatten order indexed by
    position, typed PRNG keys exposed as their raw key data."""
    from ..checkpointing import _is_key_array

    leaves = jax.tree_util.tree_flatten(train_state)[0]
    out = {}
    for i, leaf in enumerate(leaves):
        if _is_key_array(leaf):
            leaf = jax.random.key_data(leaf)
        out[str(i)] = leaf
    return out


def snapshot_schema(train_state) -> dict:
    """Wire schema of one snapshot wave: per-leaf (shape, dtype) plus the
    total byte price.  Both the construction-time cross-rank gate and
    :func:`peer_ckpt_accounting` read THIS dict, so they cannot drift."""
    leaves = {}
    total = 0
    for name, leaf in _flat_leaves(train_state).items():
        shape = tuple(int(s) for s in np.shape(leaf))
        dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        leaves[name] = {"shape": list(shape), "dtype": dtype.str}
        total += int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    return {"leaves": leaves, "snapshot_bytes": int(total)}


def check_snapshot_schemas(a: dict, b: dict) -> None:
    """Raise :class:`PeerSchemaError` unless two schemas agree exactly."""
    if a != b:
        mine, theirs = set(a["leaves"]), set(b["leaves"])
        extra = sorted(mine ^ theirs)
        raise PeerSchemaError(
            "peer snapshot schemas disagree"
            + (f" (leaf set differs: {extra})" if extra else
               f" (byte price {a['snapshot_bytes']} != {b['snapshot_bytes']}"
               " or per-leaf shape/dtype mismatch)")
        )


def peer_ckpt_accounting(train_state) -> dict:
    """Predicted byte price of one peer snapshot wave.

    Records the predicted side of the ``recovery.peer_snapshot_bytes`` twin
    (tolerance 0 vs the captured host bytes) — the ``offload_transfer_accounting``
    pattern applied to the recovery ladder."""
    schema = snapshot_schema(train_state)
    from ..telemetry import twin_registry

    twin_registry().record_predicted(
        "recovery.peer_snapshot_bytes", float(schema["snapshot_bytes"]),
        source="resilience/peer_ckpt.peer_ckpt_accounting",
    )
    return {
        "leaves": len(schema["leaves"]),
        "snapshot_bytes": schema["snapshot_bytes"],
        "kind": "predicted",
    }


@dataclasses.dataclass
class HostSnapshot:
    """One captured wave: host-RAM leaves + per-leaf crc32s."""

    step: int
    leaves: dict[str, np.ndarray]
    crc: dict[str, int]
    nbytes: int
    taken_at: float

    def verify(self) -> bool:
        return all(
            zlib.crc32(np.ascontiguousarray(v).tobytes()) & 0xFFFFFFFF == self.crc[k]
            for k, v in self.leaves.items()
        )


def _host_view(x) -> np.ndarray:
    """Device leaf → detached host copy.  ``copy=True`` is the CheckFreq
    phase-1 contract: after this returns, the donated device buffer may be
    overwritten by the next step without corrupting the snapshot."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        return np.array(x.addressable_data(0), copy=True)
    return np.array(jax.device_get(x), copy=True)


def capture_host_snapshot(train_state, step: int = 0) -> HostSnapshot:
    """Phase 1 alone: one crc-tagged host-RAM copy of a live state (the
    synchronous part of the two-phase snapshot — also handy standalone for
    compile-free state cloning in harnesses)."""
    host = {k: _host_view(v) for k, v in _flat_leaves(train_state).items()}
    return HostSnapshot(
        step=int(step),
        leaves=host,
        crc={k: zlib.crc32(np.ascontiguousarray(v).tobytes()) & 0xFFFFFFFF
             for k, v in host.items()},
        nbytes=sum(int(v.nbytes) for v in host.values()),
        taken_at=time.monotonic(),
    )


def restore_host_snapshot(snap: HostSnapshot, template):
    """Host wave → device state on the template's shardings (typed PRNG keys
    re-wrapped from raw key data, the checkpoint discipline).  Only the
    template's METADATA is read — donated/deleted leaves are fine."""
    from ..checkpointing import _is_key_array

    leaves, treedef = jax.tree_util.tree_flatten(template)
    out = []
    for i, leaf in enumerate(leaves):
        host = snap.leaves[str(i)]
        if _is_key_array(leaf):
            kd = jax.eval_shape(jax.random.key_data, leaf)
            host_t = np.asarray(host, dtype=kd.dtype)
            arr = jax.make_array_from_callback(
                kd.shape, leaf.sharding, lambda idx, h=host_t: h[idx])
            out.append(jax.random.wrap_key_data(
                arr, impl=jax.random.key_impl(leaf)))
        elif isinstance(leaf, jax.Array):
            host_t = np.asarray(host, dtype=leaf.dtype)
            out.append(jax.make_array_from_callback(
                leaf.shape, leaf.sharding, lambda idx, h=host_t: h[idx]))
        else:
            out.append(np.array(host, copy=True))
    return jax.tree_util.tree_unflatten(treedef, out)


def _buddy(rank: int, world: int) -> int:
    """Pair adjacent ranks (0↔1, 2↔3, …); the odd rank out buddies itself
    (its 'peer' copies are just extra local waves — still crc-verified)."""
    b = rank ^ 1
    return b if b < world else rank


class PeerSnapshotter:
    """Interval-driven buddy-rank host-RAM snapshots of one ``TrainState``.

    Armed lazily by the prepared step when
    ``ResiliencePlugin.peer_snapshot_every > 0``; the Accelerator exposes it
    as ``accelerator.peer_snapshotter``.  Holds the newest ``keep`` waves of
    its OWN state (``local``) and of its buddy's (``peer``)."""

    def __init__(self, template, interval: int, *, keep: int = 2):
        if interval <= 0:
            raise ValueError("peer snapshot interval must be positive")
        if keep < 1:
            raise ValueError("peer_snapshot_keep must be >= 1")
        from ..state import PartialState

        state = PartialState()
        self.rank = state.process_index
        self.world = state.num_processes
        self.buddy = _buddy(self.rank, self.world)
        self.interval = int(interval)
        self.keep = int(keep)
        self.schema = snapshot_schema(template)
        self.local: list[HostSnapshot] = []   # my waves, newest last
        self.peer: list[HostSnapshot] = []    # buddy's waves I hold for it

        # gate 1: peer copies require process-replicated leaves — a leaf
        # whose process-local block is a strict subset of the global value
        # cannot be re-streamed whole from one buddy's RAM
        for name, leaf in _flat_leaves(template).items():
            if (isinstance(leaf, jax.Array) and not leaf.is_fully_addressable
                    and leaf.addressable_data(0).shape != leaf.shape):
                raise PeerSchemaError(
                    f"peer snapshots need process-replicated state, but leaf "
                    f"{name} is sharded across processes "
                    f"(local block {leaf.addressable_data(0).shape} != global "
                    f"{leaf.shape}); use dp_shard within one process or disk "
                    f"checkpoints"
                )

        # gate 2: every rank must see the SAME schema (the transfer.py
        # discipline) — hash it and all-gather the fixed-shape digest so a
        # mismatch fails loudly at arm time on every rank at once
        if self.world > 1:
            from jax.experimental import multihost_utils

            digest = hashlib.sha256(
                json.dumps(self.schema, sort_keys=True).encode()
            ).digest()[:8]
            mine = np.frombuffer(digest, dtype=np.int64)
            gathered = np.asarray(
                multihost_utils.process_allgather(mine, tiled=False)
            ).reshape(self.world, -1)
            if not (gathered == gathered[0]).all():
                raise PeerSchemaError(
                    "peer snapshot schema hash differs across ranks — "
                    "templates disagree in shape, dtype, or leaf order"
                )

    # -- phase 1 + 2: capture and exchange ---------------------------------

    def maybe_snapshot(self, train_state, step: int) -> HostSnapshot | None:
        if step % self.interval != 0:
            return None
        return self.snapshot(train_state, step)

    def snapshot(self, train_state, step: int) -> HostSnapshot:
        """Capture one wave (synchronous device→host copy) and stream it to
        the buddy.  EVERY rank in the gang must call this at the same step —
        phase 2 is collective."""
        snap = capture_host_snapshot(train_state, step)
        from ..telemetry import twin_registry

        twin_registry().record_measured(
            "recovery.peer_snapshot_bytes", float(snap.nbytes),
            source="resilience/peer_ckpt.PeerSnapshotter",
        )
        self.local.append(snap)
        del self.local[: -self.keep]
        if self.world > 1:
            self._exchange(snap)
        self._maybe_tear()
        return snap

    def _exchange(self, snap: HostSnapshot) -> None:
        """Phase 2: every rank broadcasts its wave; each rank keeps only its
        buddy's copy.  All ranks issue the SAME collectives in the SAME
        sorted wire-name order (receivers pass schema-shaped zeros; gloo
        widens small dtypes, so receivers restore dtype host-side)."""
        from ..ops import operations

        for src in range(self.world):
            received: dict[str, np.ndarray] = {}
            crc_vec_in = np.zeros(len(self.schema["leaves"]), dtype=np.int64)
            names = sorted(self.schema["leaves"], key=int)
            if src == self.rank:
                crc_vec_in = np.array([snap.crc[n] for n in names], dtype=np.int64)
            # mask on receive: without x64 the collective narrows int64 to
            # int32, wrapping crcs above 2**31 negative — the low 32 bits
            # (all a crc32 has) survive the trip
            crc_vec = np.asarray(
                operations.broadcast(crc_vec_in, from_process=src)
            ).astype(np.int64) & 0xFFFFFFFF
            for name in names:
                spec = self.schema["leaves"][name]
                dtype = np.dtype(spec["dtype"])
                if src == self.rank:
                    payload = snap.leaves[name]
                else:
                    payload = np.zeros(tuple(spec["shape"]), dtype=dtype)
                out = operations.broadcast(payload, from_process=src)
                if _buddy(src, self.world) == self.rank and src != self.rank:
                    received[name] = np.asarray(out, dtype=dtype).reshape(
                        tuple(spec["shape"])
                    ).copy()
            if received:
                self.peer.append(HostSnapshot(
                    step=snap.step,
                    leaves=received,
                    crc={n: int(crc_vec[i]) for i, n in enumerate(names)},
                    nbytes=sum(int(v.nbytes) for v in received.values()),
                    taken_at=time.monotonic(),
                ))
                del self.peer[: -self.keep]

    def _maybe_tear(self) -> None:
        """``partial_ckpt`` fault hook: tear the newest stored copy (peer if
        any, else local) by flipping one byte WITHOUT updating its crc, so
        recovery's re-verification must skip the wave."""
        from .faults import fault_point

        for ev in fault_point("peer_snapshot"):
            if ev.kind != "partial_ckpt":
                continue
            store = self.peer if self.peer else self.local
            if not store:
                continue
            snap = store[-1]
            name = sorted(snap.leaves, key=int)[0]
            leaf = np.ascontiguousarray(snap.leaves[name])
            flat = leaf.view(np.uint8).reshape(-1)
            flat[0] ^= 0xFF
            snap.leaves[name] = flat.view(leaf.dtype.str).reshape(leaf.shape)

    # -- rank-loss bookkeeping ---------------------------------------------

    def forget_local(self) -> None:
        """Simulate this rank's state loss (the ``rank_loss`` fault): drop
        every wave of OUR OWN state.  What the buddy holds for us survives —
        that is the whole point."""
        self.local.clear()

    def reset(self) -> None:
        self.local.clear()
        self.peer.clear()

    # -- recovery -----------------------------------------------------------

    def newest_restorable_step(self) -> int | None:
        """Newest crc-valid wave THIS rank could restore alone (no
        collectives — safe to call rank-locally for reporting)."""
        steps = [s.step for s in self.local if s.verify()]
        if self.buddy == self.rank:
            steps += [s.step for s in self.peer if s.verify()]
        return max(steps) if steps else None

    def recover(self, template):
        """Collectively agree on the newest wave EVERY rank can restore,
        re-stream missing copies from buddies, and rebuild the state on the
        template's shardings.  Returns ``(train_state, step)`` or ``None``
        when no common wave survives (callers fall back to disk).

        All ranks must call this together — the agreement and any re-send
        are collective."""
        if self.world <= 1:
            candidates = [s for s in self.local + self.peer if s.verify()]
            if not candidates:
                return None
            snap = max(candidates, key=lambda s: s.step)
            return self._restore(snap, template), snap.step

        from jax.experimental import multihost_utils

        def _vec(snaps):
            steps = sorted({s.step for s in snaps if s.verify()})[-self.keep:]
            v = np.full(self.keep, -1, dtype=np.int64)
            v[: len(steps)] = steps
            return v

        mine = np.asarray(multihost_utils.process_allgather(
            _vec(self.local), tiled=False)).reshape(self.world, self.keep)
        held = np.asarray(multihost_utils.process_allgather(
            _vec(self.peer), tiled=False)).reshape(self.world, self.keep)

        # computed identically on every rank: rank r can obtain a wave it
        # still holds, or one its buddy holds FOR it
        common: set[int] | None = None
        for r in range(self.world):
            obtainable = {int(s) for s in mine[r] if s >= 0}
            obtainable |= {int(s) for s in held[_buddy(r, self.world)] if s >= 0}
            common = obtainable if common is None else common & obtainable
        if not common:
            return None
        agreed = max(common)

        names = sorted(self.schema["leaves"], key=int)
        snap = next((s for s in self.local if s.step == agreed and s.verify()), None)
        from ..ops import operations

        # re-send legs: for every rank missing the agreed wave, its buddy
        # streams the held copy back — again all ranks issue identical
        # collectives, in rank order then sorted wire-name order
        for r in range(self.world):
            if any(int(s) == agreed for s in mine[r]):
                continue
            src = _buddy(r, self.world)
            src_snap = None
            if src == self.rank:
                src_snap = next(
                    (s for s in self.peer if s.step == agreed and s.verify()), None)
            crc_vec_in = np.zeros(len(names), dtype=np.int64)
            if src_snap is not None:
                crc_vec_in = np.array(
                    [src_snap.crc[n] for n in names], dtype=np.int64)
            crc_vec = np.asarray(
                operations.broadcast(crc_vec_in, from_process=src)
            ).astype(np.int64) & 0xFFFFFFFF  # undo the x64-off int32 wrap
            received = {}
            for name in names:
                spec = self.schema["leaves"][name]
                dtype = np.dtype(spec["dtype"])
                payload = (src_snap.leaves[name] if src_snap is not None
                           else np.zeros(tuple(spec["shape"]), dtype=dtype))
                out = operations.broadcast(payload, from_process=src)
                if r == self.rank:
                    received[name] = np.asarray(out, dtype=dtype).reshape(
                        tuple(spec["shape"])).copy()
            if r == self.rank:
                snap = HostSnapshot(
                    step=agreed,
                    leaves=received,
                    crc={n: int(crc_vec[i]) for i, n in enumerate(names)},
                    nbytes=sum(int(v.nbytes) for v in received.values()),
                    taken_at=time.monotonic(),
                )
                if not snap.verify():
                    raise PeerSnapshotCorruptError(
                        f"re-streamed wave {agreed} failed crc re-verification"
                    )
        if snap is None:  # pragma: no cover - agreement guarantees a copy
            return None
        return self._restore(snap, template), agreed

    def _restore(self, snap: HostSnapshot, template):
        return restore_host_snapshot(snap, template)
