"""Bounded retry with exponential backoff for host-side I/O.

The two host-driven transfer surfaces this wraps — checkpoint reads/writes
(``checkpointing.py``) and host↔device staging (``ops/streaming.py``'s
:class:`LayerPrefetcher`, the dataloaders' device placement) — fail
transiently in exactly the ways CheckFreq (Mohan et al., FAST'21) catalogs:
a shared filesystem hiccup, a PCIe DMA that times out under host pressure,
an NFS handle going stale across a preemption.  Crashing a multi-hour run on
the first such blip throws away everything since the last checkpoint; an
*unbounded* retry loop silently wedges the run instead.  This module is the
middle path: a small, explicit budget of re-attempts with exponential
backoff, after which the original exception propagates loudly.

Genuinely-fatal filesystem errors (missing paths, permission walls) are
never retried — re-attempting those only delays the real diagnosis.

Deterministic fault injection (``resilience/faults.py``) raises
:class:`TransientIOError` subclasses through the same call sites, so the
retry discipline is exercised end-to-end by the CPU test suite.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from ..logging import get_logger

logger = get_logger(__name__)


class TransientIOError(OSError):
    """A retry-worthy I/O failure.

    Raised by the fault-injection harness and available for user transfer
    callables to signal "try again" explicitly; plain ``OSError``s are also
    retried unless they are in the fatal set below."""


# errors where a retry can only reproduce the same answer more slowly
_FATAL_OS_ERRORS = (
    FileNotFoundError,
    NotADirectoryError,
    IsADirectoryError,
    PermissionError,
    FileExistsError,
)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry budget for one I/O call site.

    ``retries`` is the number of *re*-attempts (0 = fail on first error);
    the sleep before re-attempt ``k`` is
    ``min(backoff_s * multiplier**k, max_backoff_s)``.
    """

    retries: int = 3
    backoff_s: float = 0.05
    multiplier: float = 2.0
    max_backoff_s: float = 2.0
    retryable: tuple = (OSError, ConnectionError, TimeoutError)

    def __post_init__(self):
        if self.retries < 0:
            raise ValueError(f"retries must be >= 0, got {self.retries}")
        if self.backoff_s < 0:
            raise ValueError(f"backoff_s must be >= 0, got {self.backoff_s}")


DEFAULT_POLICY = RetryPolicy()


def _is_retryable(exc: BaseException, policy: RetryPolicy) -> bool:
    if isinstance(exc, TransientIOError):
        return True
    if isinstance(exc, _FATAL_OS_ERRORS):
        return False
    return isinstance(exc, policy.retryable)


def with_retries(
    fn: Callable,
    *args,
    policy: RetryPolicy = DEFAULT_POLICY,
    site: str = "io",
    on_retry: Optional[Callable[[str, int, BaseException], None]] = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, re-attempting transient failures.

    At most ``policy.retries`` re-attempts with exponential backoff; a
    non-retryable exception propagates immediately, and the last retryable
    one propagates once the budget is spent — the wrapper never swallows a
    failure, it only defers giving up.  ``on_retry(site, attempt, exc)``
    fires before each sleep (goodput accounting hooks in here).
    """
    delay = policy.backoff_s
    for attempt in range(policy.retries + 1):
        try:
            return fn(*args, **kwargs)
        except BaseException as e:  # re-raised below unless retryable
            if attempt >= policy.retries or not _is_retryable(e, policy):
                raise
            logger.warning(
                "%s: transient failure (attempt %d/%d): %s — retrying in %.3gs",
                site, attempt + 1, policy.retries + 1, e, delay,
            )
            if on_retry is not None:
                on_retry(site, attempt, e)
            time.sleep(delay)
            delay = min(delay * policy.multiplier, policy.max_backoff_s)
