"""Deterministic fault injection for the save/restore/step spine.

CheckFreq (Mohan et al., FAST'21) and Varuna (Athlur et al., EuroSys'22)
treat faults as the *normal case* of large training runs — preemptions,
transient transfer failures, NaN bursts, torn checkpoint writes — and the
only way to trust the recovery machinery is to rehearse every one of them
on demand.  This module is that rehearsal harness: a **seeded, fully
deterministic fault plan** delivered through fixed hook points in the real
hot paths (no monkeypatching — the production code calls
:func:`fault_point` itself, and with no plan installed the hook is a single
``None`` check).

Hook sites and the fault kinds they arm:

========================  =====================================================
site                      kinds
========================  =====================================================
``step``                  ``preempt`` (a real ``SIGTERM`` via ``os.kill``,
                          delivered through the installed
                          :class:`~.preemption.PreemptionHandler`) and
                          ``nan_grad`` (the incoming batch is NaN-poisoned, so
                          the non-finite gradients flow through the *genuine*
                          ``value_and_grad`` → guard path)
``transfer``              ``transfer`` — a :class:`InjectedTransferError`
                          raised from host↔device staging
                          (``ops/streaming.LayerPrefetcher``, dataloader
                          device placement)
``checkpoint_io``         ``transfer`` — same, from checkpoint read/write
``post_save``             ``corrupt_ckpt`` — the just-published checkpoint has
                          one shard file truncated or bit-flipped (the torn
                          write / bit-rot simulation the verified-manifest
                          load path must catch)
``serve_step``            ``preempt`` (drain the serving engine at a tick
                          boundary), ``cancel`` (a cancellation storm: the
                          oldest live request cancels) and ``deadline`` (a
                          deadline storm: every live request expires NOW and
                          the degradation ladder escalates one stage)
``verify_step``           ``preempt`` — drain mid-speculative-verify, before
                          the pass dispatches (the finest-grained serving
                          boundary; nothing runs, every invariant holds)
``adapter_transfer``      ``transfer`` — the hot-swap H2D staging fails
                          mid-prefetch, inside the bounded-retry wrapper
``adapter_memmap``        ``transfer`` — the cold-tier memmap read fails,
                          inside its own retry wrapper
``fleet_route``           ``replica_kill`` — the fleet router
                          (``serving/router.py``) loses one replica
                          mid-traffic: the victim drains through
                          ``remaining_requests()`` and the router re-routes
                          its survivors exactly once (tokens stay bitwise —
                          the fleet chaos leg pins it)
``step`` (training)       ``rank_loss`` — one training rank dies at a step
                          boundary (:class:`RankLostError` from the prepared
                          step): the gang rolls back through the recovery
                          ladder (``resilience/peer_ckpt.py`` — newest
                          consistent peer/host-RAM snapshot, else the newest
                          verified disk checkpoint) and ``straggler`` — a
                          deterministic host-side stall on this rank, so
                          preemption notices land at *mismatched* boundaries
                          and the agreed-stop reduction has real skew to
                          close over
``peer_snapshot``         ``partial_ckpt`` — the peer-replicated snapshot
                          wave just streamed is torn on the receiving side
                          (one stored leaf corrupted): the crc gate must
                          skip the wave and the recovery ladder fall back to
                          an older consistent wave or disk
========================  =====================================================

Occurrence counting is per-site and 1-based: an event ``FaultEvent("preempt",
at=4)`` fires on the 4th prepared-train-step call of the process, every time,
for every seed — which is what makes the resilience acceptance matrix
reproducible in CI.  ``FaultPlan.from_seed`` derives a random-but-deterministic
plan from a seed for soak-style testing.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
from collections import defaultdict
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

from ..logging import get_logger
from .retry import TransientIOError

logger = get_logger(__name__)

FAULT_KINDS = ("preempt", "nan_grad", "transfer", "corrupt_ckpt", "cancel",
               "deadline", "prefix", "replica_kill", "rank_loss", "straggler",
               "partial_ckpt")

# default hook site per kind (a transfer event may override its site to
# "checkpoint_io"/"adapter_transfer"/"adapter_memmap" to target checkpoint
# I/O or the serving hot-swap path instead of the training streaming path;
# a preempt event may override its site to "serve_step"/"verify_step" to
# drain the serving engine instead of SIGTERM-ing the trainer)
KIND_DEFAULT_SITE = {
    "preempt": "step",
    "nan_grad": "step",
    "transfer": "transfer",
    "corrupt_ckpt": "post_save",
    "cancel": "serve_step",
    "deadline": "serve_step",
    # cache-invalidation storm: the serving engine flushes its prefix index
    # (every index hold drops; live slots keep their shared refcounts) —
    # future admissions miss, tokens stay bitwise (the prefix interplay leg
    # of the chaos soak pins it)
    "prefix": "serve_step",
    # fleet-replica loss: the router's per-tick hook drains the victim and
    # re-routes its pending work to the surviving replicas (exactly once)
    "replica_kill": "fleet_route",
    # training-rank loss: the prepared step raises RankLostError at the
    # boundary; the harness routes the gang through the recovery ladder
    # (peer RAM -> verified disk -> fresh, resilience/peer_ckpt.py)
    "rank_loss": "step",
    # deterministic host-side stall on this rank's step: skews the boundary
    # arrival times the agreed preemption stop must reduce over
    "straggler": "step",
    # torn peer-snapshot stream: the receiver's stored copy of the wave is
    # corrupted; the crc gate skips it on restore
    "partial_ckpt": "peer_snapshot",
}

CORRUPTION_MODES = ("truncate", "bitflip")


class InjectedTransferError(TransientIOError):
    """The fault plan's transient transfer failure (retryable by design)."""


class RankLostError(RuntimeError):
    """An injected ``rank_loss`` fault: this rank's training state is gone.

    Raised by the prepared step at the boundary the plan names — NOT
    retryable.  The training loop (or the chaos harness) is expected to
    route the gang through the recovery ladder
    (:meth:`~accelerate_tpu.Accelerator.recover`): the lost rank's newest
    snapshot lives in its buddy's host RAM, and the whole gang rolls back to
    the newest wave every rank can restore."""


# deterministic host-side stall a ``straggler`` fault injects (seconds):
# long enough to skew step-boundary arrival times across ranks, short
# enough for CI
STRAGGLER_STALL_S = 0.25


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault.

    ``at`` is the 1-based occurrence index of the hook site this event arms;
    ``count`` extends it over consecutive occurrences (a ``transfer`` event
    with ``count=2`` fails two attempts in a row — one past the default
    retry budget's first re-attempt, still within the bounded budget);
    ``mode`` selects the corruption flavor for ``corrupt_ckpt``.
    """

    kind: str
    at: int = 1
    count: int = 1
    mode: str = "truncate"
    site: str = ""

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; options: {FAULT_KINDS}")
        if self.at < 1 or self.count < 1:
            raise ValueError(f"at/count must be >= 1 (got at={self.at}, count={self.count})")
        if self.kind == "corrupt_ckpt" and self.mode not in CORRUPTION_MODES:
            raise ValueError(f"unknown corruption mode {self.mode!r}; options: {CORRUPTION_MODES}")
        if not self.site:
            object.__setattr__(self, "site", KIND_DEFAULT_SITE[self.kind])

    def covers(self, occurrence: int) -> bool:
        return self.at <= occurrence < self.at + self.count


class FaultPlan:
    """A deterministic schedule of :class:`FaultEvent`\\ s.

    Install with :func:`install_fault_plan` / the :func:`fault_plan` context
    manager, or ship it to a subprocess as JSON through the
    ``ACCELERATE_FAULT_PLAN`` environment variable (the Accelerator installs
    an env-borne plan at construction).  ``fired`` records every delivered
    event — the test-side audit trail.
    """

    def __init__(self, events: Sequence[FaultEvent] = (), seed: int = 0):
        self.events = tuple(events)
        self.seed = int(seed)
        self._occurrences: dict[str, int] = defaultdict(int)
        self.fired: list[tuple[str, int, FaultEvent]] = []

    def fire(self, site: str) -> tuple[FaultEvent, ...]:
        """Advance ``site``'s occurrence counter and return the events armed
        for this occurrence (usually empty)."""
        self._occurrences[site] += 1
        occ = self._occurrences[site]
        hits = tuple(e for e in self.events if e.site == site and e.covers(occ))
        for e in hits:
            self.fired.append((site, occ, e))
            logger.warning("fault injection: %s fires at %s occurrence %d", e.kind, site, occ)
        return hits

    def occurrences(self, site: str) -> int:
        return self._occurrences[site]

    # -- construction --------------------------------------------------------

    @classmethod
    def from_spec(cls, spec: dict) -> "FaultPlan":
        """Build from the JSON shape ``{"seed": 0, "events": [{"kind": ...,
        "at": ..., "count": ..., "mode": ..., "site": ...}, ...]}``."""
        events = [
            FaultEvent(
                kind=d["kind"], at=int(d.get("at", 1)), count=int(d.get("count", 1)),
                mode=d.get("mode", "truncate"), site=d.get("site", ""),
            )
            for d in spec.get("events", [])
        ]
        return cls(events, seed=int(spec.get("seed", 0)))

    @classmethod
    def from_env(cls, var: str = "ACCELERATE_FAULT_PLAN") -> Optional["FaultPlan"]:
        raw = os.environ.get(var)
        if not raw:
            return None
        return cls.from_spec(json.loads(raw))

    @classmethod
    def from_seed(
        cls, seed: int, n_steps: int, *,
        p_preempt: float = 0.0, p_nan: float = 0.0,
        p_transfer: float = 0.0, p_corrupt: float = 0.0,
        p_cancel: float = 0.0, p_deadline: float = 0.0,
        serving: bool = False,
    ) -> "FaultPlan":
        """A random-but-reproducible plan: each step draws each enabled fault
        kind independently at its probability.  Same seed → same plan,
        always — the soak-test entry point.

        ``serving=True`` targets the serving sites: ``preempt`` lands at
        ``serve_step`` (the chaos-replay drain-and-restart loop absorbs it,
        so later events stay armed), ``transfer`` at ``adapter_transfer``,
        and the ``cancel``/``deadline`` storms draw at their probabilities
        (their default site is already ``serve_step``)."""
        rng = np.random.default_rng(seed)
        events = []
        for step in range(1, n_steps + 1):
            if p_preempt and rng.random() < p_preempt:
                events.append(FaultEvent(
                    "preempt", at=step,
                    site="serve_step" if serving else "",
                ))
                if not serving:
                    break  # a training preemption ends the process; a
                    # serving drain restarts — later events stay armed
            if p_nan and rng.random() < p_nan:
                events.append(FaultEvent("nan_grad", at=step))
            if p_transfer and rng.random() < p_transfer:
                events.append(FaultEvent(
                    "transfer", at=step,
                    site="adapter_transfer" if serving else "",
                ))
            if p_corrupt and rng.random() < p_corrupt:
                events.append(FaultEvent("corrupt_ckpt", at=step,
                                         mode=CORRUPTION_MODES[int(rng.integers(2))]))
            if p_cancel and rng.random() < p_cancel:
                events.append(FaultEvent("cancel", at=step))
            if p_deadline and rng.random() < p_deadline:
                events.append(FaultEvent("deadline", at=step))
        return cls(events, seed=seed)

    def to_spec(self) -> dict:
        return {
            "seed": self.seed,
            "events": [dataclasses.asdict(e) for e in self.events],
        }

    def __repr__(self):
        return f"FaultPlan(seed={self.seed}, events={list(self.events)!r})"


# ---------------------------------------------------------------------------
# the ambient plan + hook points (what the production code calls)
# ---------------------------------------------------------------------------


_ACTIVE_PLAN: Optional[FaultPlan] = None


def install_fault_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``plan`` as the process-wide active plan (``None`` disarms);
    returns the previous plan so callers can restore it."""
    global _ACTIVE_PLAN
    previous = _ACTIVE_PLAN
    _ACTIVE_PLAN = plan
    return previous


def active_fault_plan() -> Optional[FaultPlan]:
    return _ACTIVE_PLAN


@contextlib.contextmanager
def fault_plan(plan: Optional[FaultPlan]):
    """Scope a plan to a ``with`` block (tests; restores the previous plan)."""
    previous = install_fault_plan(plan)
    try:
        yield plan
    finally:
        install_fault_plan(previous)


def fault_point(site: str) -> tuple[FaultEvent, ...]:
    """The hook the real hot paths call.  With no plan installed this is one
    global-read + ``None`` check — cheap enough for per-step/per-batch
    placement."""
    if _ACTIVE_PLAN is None:
        return ()
    return _ACTIVE_PLAN.fire(site)


def maybe_fail_transfer(site: str = "transfer") -> None:
    """Raise :class:`InjectedTransferError` when the plan arms a ``transfer``
    fault for this occurrence of ``site`` — called at the top of each
    host-transfer attempt, *inside* the retry wrapper, so every injected
    failure exercises the real backoff path."""
    for e in fault_point(site):
        if e.kind == "transfer":
            raise InjectedTransferError(
                f"injected transient transfer failure at {site} "
                f"(occurrence {_ACTIVE_PLAN.occurrences(site)})"
            )


# ---------------------------------------------------------------------------
# fault payloads
# ---------------------------------------------------------------------------


def poison_batch(batch):
    """NaN-fill every inexact array leaf of ``batch`` (integer leaves — token
    ids, masks — pass through untouched).

    This is how ``nan_grad`` faults enter the step: the poisoned batch flows
    through the *real* loss → ``value_and_grad`` → guard path, so the skip
    machinery is tested against genuine non-finite gradients, not a mock."""
    import jax
    import jax.numpy as jnp

    def _p(x):
        if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jnp.inexact):
            return jnp.full_like(x, jnp.nan)
        if isinstance(x, np.ndarray) and np.issubdtype(x.dtype, np.inexact):
            return np.full_like(x, np.nan)
        return x

    return jax.tree_util.tree_map(_p, batch)


def corrupt_checkpoint(ckpt_dir, mode: str = "truncate", seed: int = 0) -> str:
    """Deterministically corrupt one data file of a written checkpoint —
    the torn-write (``truncate``) / bit-rot (``bitflip``) simulation that
    ``checkpointing.verify_checkpoint`` must catch.  Prefers a train-state
    shard (the biggest loss surface); the choice is seeded.  Returns the
    corrupted file's path."""
    from ..utils.constants import CHECKPOINT_MANIFEST_NAME, TRAIN_STATE_DIR

    if mode not in CORRUPTION_MODES:
        raise ValueError(f"unknown corruption mode {mode!r}; options: {CORRUPTION_MODES}")
    root = Path(ckpt_dir)
    files = sorted(
        p for p in root.rglob("*")
        if p.is_file() and p.name != CHECKPOINT_MANIFEST_NAME and p.stat().st_size > 0
    )
    if not files:
        raise FileNotFoundError(f"no corruptible files under {root}")
    shard_files = [p for p in files if TRAIN_STATE_DIR in p.parts]
    candidates = shard_files or files
    rng = np.random.default_rng(seed)
    target = candidates[int(rng.integers(len(candidates)))]
    data = target.read_bytes()
    if mode == "truncate":
        target.write_bytes(data[: len(data) // 2])
    else:  # bitflip
        pos = int(rng.integers(len(data)))
        buf = bytearray(data)
        buf[pos] ^= 0xFF
        target.write_bytes(bytes(buf))
    logger.warning("fault injection: corrupted %s (%s)", target, mode)
    return str(target)
