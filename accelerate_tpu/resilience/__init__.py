"""Preemption-safe resilience layer (CheckFreq / Varuna discipline).

The subsystem that turns faults from run-killers into accounting entries:

- :mod:`.faults` — deterministic, seeded fault injection through fixed hook
  points in the real hot paths (preemptions, NaN bursts, transient
  transfers, checkpoint corruption);
- :mod:`.guard` — jit-compatible NaN/Inf skip-step with persisted counters
  and a consecutive-skip abort;
- :mod:`.preemption` — SIGTERM → cross-rank agreed stop → emergency
  checkpoint at one common step → distinct resume exit code (75,
  ``EX_TEMPFAIL``);
- :mod:`.peer_ckpt` — buddy-rank host-RAM snapshots (Gemini-style): the
  fast rung of the recovery ladder, crc-verified, byte-priced by
  ``peer_ckpt_accounting`` at tolerance 0;
- :mod:`.retry` — bounded retry/backoff for checkpoint I/O and host↔device
  staging;
- :mod:`.goodput` — measured + predicted goodput accounting (the
  ``StreamStats`` discipline applied to fault handling).

Checkpoint verification (manifests, atomic publish, valid-fallback load)
lives in :mod:`accelerate_tpu.checkpointing`; the knobs live on
:class:`~accelerate_tpu.utils.dataclasses.ResiliencePlugin`
(``ACCELERATE_RESILIENCE=1`` arms the guard + preemption handling).
"""

from .faults import (  # noqa: F401
    CORRUPTION_MODES,
    FAULT_KINDS,
    STRAGGLER_STALL_S,
    FaultEvent,
    FaultPlan,
    InjectedTransferError,
    RankLostError,
    active_fault_plan,
    corrupt_checkpoint,
    fault_plan,
    fault_point,
    install_fault_plan,
    maybe_fail_transfer,
    poison_batch,
)
from .goodput import GoodputTracker, goodput_accounting  # noqa: F401
from .peer_ckpt import (  # noqa: F401
    HostSnapshot,
    PeerSchemaError,
    PeerSnapshotCorruptError,
    PeerSnapshotter,
    capture_host_snapshot,
    check_snapshot_schemas,
    peer_ckpt_accounting,
    restore_host_snapshot,
    snapshot_schema,
)
from .guard import (  # noqa: F401
    GUARD_METRIC_KEYS,
    NanGuardAbort,
    check_abort,
    finite_and,
    guard_metrics,
    init_guard_state,
    select_tree,
    update_guard_counters,
)
from .preemption import RESUME_EXIT_CODE, PreemptionHandler  # noqa: F401
from .retry import (  # noqa: F401
    DEFAULT_POLICY,
    RetryPolicy,
    TransientIOError,
    with_retries,
)
