"""Graceful preemption: SIGTERM → step boundary → emergency checkpoint → 75.

Spot/preemptible capacity is the cheapest accelerator time there is, and
the only thing standing between "preemption" and "lost work" is this flow
(Varuna, Athlur et al., EuroSys'22; CheckFreq, Mohan et al., FAST'21):

1. the cloud sends ``SIGTERM`` with a short grace window;
2. the handler here only **sets a flag** (the async-signal-safe minimum —
   no allocation, no I/O, no JAX calls in signal context);
3. the Accelerator's prepared-step wrapper checks the flag **after** the
   step in flight completes — the post-step state is exactly consistent
   with the dataloader position and step counters, so the resumed run
   replays nothing and skips nothing;
4. the in-flight async checkpoint (if any) is drained, an **emergency
   checkpoint** of the boundary state is written through the verified
   atomic path, and the process exits with :data:`RESUME_EXIT_CODE`;
5. the supervisor (k8s restartPolicy, a shell loop, the test harness) sees
   the distinct code, re-launches, and ``Accelerator.maybe_resume`` picks
   up the newest *valid* checkpoint.

``RESUME_EXIT_CODE`` is 75 — BSD ``EX_TEMPFAIL``, "transient failure,
re-run me" — deliberately distinct from 0 (done) and 1 (crash) so restart
policies can re-queue preemptions without masking real failures.

Multi-host coordination: the flag itself stays per-process (signal context
allows nothing more), but the boundary check is **gang-agreed** — the
Accelerator's step wrapper reduces the flag across ranks (max over the gang
via ``process_allgather``, throttled by
``ResiliencePlugin.preemption_check_every``) before acting on it, so a
SIGTERM that lands on ONE rank mid-interval stops EVERY rank at the same
lockstep boundary and the emergency checkpoint's shards all carry one step.
The 2-process chaos harness (``test_utils/scripts/train_fabric.py``,
``preempt`` mode) pins exactly this: mismatched signal arrival → a single
consistent emergency checkpoint, metadata step agreed across ranks.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable, Union

from ..logging import get_logger

logger = get_logger(__name__)

# BSD EX_TEMPFAIL: the canonical "re-run me" code, distinct from crash/success
RESUME_EXIT_CODE = 75


def _resolve_signal(sig: Union[str, int]) -> signal.Signals:
    if isinstance(sig, str):
        return getattr(signal, sig)
    return signal.Signals(sig)


class PreemptionHandler:
    """Flag-only signal handler; the step wrapper polls :attr:`requested`.

    ``install()`` swaps the process handlers in (remembering the previous
    ones for :meth:`uninstall`); ``request()`` arms the flag
    programmatically — the fault-injection harness and tests use it (or a
    real ``os.kill(os.getpid(), SIGTERM)``) interchangeably with a genuine
    external preemption notice.
    """

    def __init__(self, signals: Iterable[Union[str, int]] = ("SIGTERM",)):
        self.signals = tuple(_resolve_signal(s) for s in signals)
        self._requested = threading.Event()
        self._previous: dict = {}
        self._installed = False

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            # CPython only allows signal.signal on the main thread; a worker
            # thread (e.g. a notebook executor) degrades to programmatic
            # request() with a loud note rather than crashing construction
            logger.warning(
                "preemption handler not installed: signal handlers can only "
                "be set from the main thread; use handler.request() or rely "
                "on the supervisor's own checkpoint discipline"
            )
            return self
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        logger.debug("preemption handler installed for %s", [s.name for s in self.signals])
        return self

    def _on_signal(self, signum, frame):  # async-signal-safe: flag only
        self._requested.set()

    def request(self) -> None:
        """Arm the stop flag without a signal (tests / fault injection)."""
        self._requested.set()

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def clear(self) -> None:
        self._requested.clear()

    def uninstall(self) -> None:
        """Restore the previous handlers (test hygiene)."""
        if not self._installed:
            return
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # non-main thread / exotic prev
                logger.warning("could not restore previous handler for %s", sig)
        self._previous.clear()
        self._installed = False
