"""accelerate_tpu — a TPU-native training-acceleration framework.

Brand-new JAX/XLA/Pallas re-design with the capability surface of the
reference HuggingFace-Accelerate fork (see SURVEY.md): a user writes a plain
training step; the framework supplies device meshes, GSPMD sharding (DP/FSDP/
HSDP/TP/CP/SP/EP), mixed precision, data sharding, checkpointing,
observability, and a launcher CLI.
"""

__version__ = "0.1.0"

from .parallelism_config import MESH_AXIS_ORDER, ParallelismConfig
from .state import AcceleratorState, GradientState, PartialState
from .utils.dataclasses import (
    AutocastKwargs,
    ContextParallelConfig,
    DataLoaderConfiguration,
    DistributedOperationException,
    DistributedType,
    ExpertParallelConfig,
    FP8RecipeKwargs,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    GradSyncKwargs,
    InitProcessGroupKwargs,
    MixedPrecisionType,
    ProfileKwargs,
    ProjectConfiguration,
    ResiliencePlugin,
    SequenceParallelConfig,
    ServingPlugin,
    ShardingStrategy,
    TelemetryPlugin,
    TensorParallelConfig,
)

# Populated as modules land; guarded so partial builds stay importable.
try:
    from .accelerator import Accelerator
except ImportError:  # pragma: no cover
    pass
try:
    from .data_loader import prepare_data_loader, skip_first_batches
except ImportError:  # pragma: no cover
    pass
try:
    from .big_modeling import (
        abstract_init,
        cpu_offload,
        disk_offload,
        dispatch_model,
        infer_auto_device_map,
        infer_auto_placement,
        init_empty_weights,
        load_checkpoint_and_dispatch,
        load_checkpoint_in_model,
        offload_state_dict,
        offload_store_params,
        offloaded_apply,
    )
except ImportError:  # pragma: no cover
    pass
try:
    from .utils.memory import find_executable_batch_size
except ImportError:  # pragma: no cover
    pass
try:
    from .utils.random import set_seed, synchronize_rng_states
except ImportError:  # pragma: no cover
    pass
try:
    from .launchers import debug_launcher, notebook_launcher
except ImportError:  # pragma: no cover
    pass
try:
    from .parallel.pipeline_parallel import PipelinedModel, prepare_pipeline
except ImportError:  # pragma: no cover
    pass
try:
    from .local_sgd import LocalSGD
except ImportError:  # pragma: no cover
    pass
try:
    from .utils.other import extract_model_from_parallel
except ImportError:  # pragma: no cover
    pass
try:
    from .hooks import (
        AlignDevicesHook,
        ModelHook,
        SequentialHook,
        add_hook_to_apply,
        attach_align_device_hook,
        remove_hook_from_apply,
    )
except ImportError:  # pragma: no cover
    pass
try:
    from .utils.quantization import (
        QuantizationConfig,
        load_and_quantize_model,
        quantize_params,
        quantized_apply,
    )
except ImportError:  # pragma: no cover
    pass
try:
    from .generation import (
        GenerationConfig,
        beam_search,
        generate,
        generate_seq2seq,
        generate_streamed,
        place_params_host,
        sample_logits,
    )
except ImportError:  # pragma: no cover
    pass
try:
    from .ops.streaming import LayerPrefetcher, StreamStats
except ImportError:  # pragma: no cover
    pass
try:
    from .telemetry import (
        SLOMonitor,
        SpanRecorder,
        TrainTimeline,
        TwinRegistry,
        twin_registry,
    )
except ImportError:  # pragma: no cover
    pass
