"""Data pipeline: per-rank sharding math + globally-sharded device batches.

TPU-native re-design of reference ``data_loader.py`` (1,451 LoC).  Same
sharding semantics — ``BatchSamplerShard`` (reference :110) index-level
stride/split modes with ``even_batches`` head-sample padding,
``IterableDatasetShard`` (:266), dispatch-from-rank-0 mode (:704), seedable
deterministic shuffling (:73), skip/resume (:1312-1375) — but the device
boundary is native JAX: every yielded batch is a **global sharded
``jax.Array``** laid out along the mesh's data axes
(``jax.make_array_from_process_local_data``; each host feeds only its
addressable shards), with one-batch lookahead so H2D overlaps compute
(the ``MpDeviceLoaderWrapper`` analog, reference :654).

Device-mesh-aware rank remap: TP/CP/SP ranks must receive *identical* batches,
so the dataloader collapses ``process_index`` by ``non_data_parallel_size``
(reference data_loader.py:1109-1145).
"""

from __future__ import annotations

import contextlib
import math
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .ops.operations import (
    broadcast_object_list,
    find_batch_size,
    host_local_to_global,
    recursively_apply,
    send_to_device,
    slice_tensors,
)
from .resilience.faults import maybe_fail_transfer
from .resilience.retry import DEFAULT_POLICY, with_retries
from .state import GradientState, PartialState
from .utils.dataclasses import RNGType
from .utils.imports import is_torch_available
from .utils.random import get_rng_key, synchronize_rng_states


def _is_torch_loader(obj) -> bool:
    if not is_torch_available():
        return False
    import torch.utils.data

    return isinstance(obj, torch.utils.data.DataLoader)


def _to_numpy(batch):
    """Convert torch tensors / lists in a batch pytree to numpy."""

    def _conv(t):
        if is_torch_available():
            import torch

            if isinstance(t, torch.Tensor):
                return t.detach().cpu().numpy()
        return np.asarray(t)

    def _is_leaf(x):
        if is_torch_available():
            import torch

            if isinstance(x, torch.Tensor):
                return True
        return isinstance(x, (np.ndarray, jax.Array))

    return recursively_apply(_conv, batch, test_type=_is_leaf)


def _native_prefetch_available() -> bool:
    from . import native

    return native.is_available()


class _RingPrefetcher:
    """Background host-staging pipeline over the native staging ring.

    A producer thread pulls batches from the inner iterable, converts them to
    numpy, and copies the bytes into an aligned slot of the native ring
    (native/src/ring.cc) — large numpy copies release the GIL, so staging
    overlaps the main thread's device feeding.  The consumer side rebuilds
    zero-copy views over the slot, runs the device put, waits for the
    transfer, and recycles the slot.  This is the in-tree analog of the
    torch DataLoader worker + ``MpDeviceLoader`` background-transfer pair the
    reference leans on (reference data_loader.py:654, :567-583).

    Batches that do not fit a slot ride the descriptor queue directly (rare;
    slot size is derived from the first batch with headroom).
    """

    _ALIGN = 64

    def __init__(self, inner: Iterable, device_put: Callable, depth: int = 2):
        import queue as _queue

        from . import native

        self.inner = inner
        self.device_put = device_put
        self.depth = max(2, depth)
        self._native = native
        self._queue: _queue.Queue = _queue.Queue(maxsize=self.depth + 1)
        self._ring = None
        self._closed = False
        self._thread = None

    # -- producer -----------------------------------------------------------

    def _stage(self, batch) -> tuple:
        """Copy a numpy batch pytree into a ring slot; returns a descriptor.

        Descriptor kinds: ("ring", treedef, specs) — bytes staged in FIFO
        slot order; ("raw", treedef, leaves) — oversized batch carried
        directly; ("err", exc) / None — error / end of stream.
        """
        raw_leaves, treedef = jax.tree_util.tree_flatten(batch)
        leaves = [np.ascontiguousarray(leaf) for leaf in raw_leaves]
        specs, offset = [], 0
        for leaf in leaves:
            specs.append((offset, leaf.dtype, leaf.shape, leaf.nbytes))
            offset += -(-leaf.nbytes // self._ALIGN) * self._ALIGN
        if self._ring is None:
            slot_bytes = max(int(offset * 1.5), 1 << 20)
            self._ring = self._native.StagingRing(self.depth, slot_bytes)
        if offset > self._ring.slot_bytes:
            return ("raw", treedef, leaves)
        slot = self._ring.acquire()
        if slot is None:  # closed under us
            return None
        for leaf, (off, dtype, shape, nbytes) in zip(leaves, specs):
            if nbytes:
                np.copyto(slot[off : off + nbytes].view(dtype).reshape(shape), leaf)
        self._ring.commit(slot, offset)
        return ("ring", treedef, specs)

    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer is gone."""
        import queue as _queue

        while not self._closed:
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _produce(self):
        try:
            for batch in self.inner:
                if self._closed:
                    return
                desc = self._stage(_to_numpy(batch))
                if desc is None or not self._put(desc):
                    return
            self._put(None)
        except BaseException as e:  # noqa: BLE001 — surface in consumer
            self._put(("err", e))

    # -- consumer -----------------------------------------------------------

    def __iter__(self):
        import threading

        self._thread = threading.Thread(target=self._produce, daemon=True, name="at-prefetch")
        self._thread.start()
        try:
            while True:
                desc = self._queue.get()
                if desc is None:
                    return
                kind = desc[0]
                if kind == "err":
                    raise desc[1]
                if kind == "raw":
                    _, treedef, leaves = desc
                    yield self.device_put(jax.tree_util.tree_unflatten(treedef, leaves))
                    continue
                _, treedef, specs = desc
                view = self._ring.pop()
                if view is None:
                    return
                leaves = [
                    view[off : off + nbytes].view(dtype).reshape(shape)
                    for off, dtype, shape, nbytes in specs
                ]
                if jax.default_backend() == "cpu":
                    # CPU jax zero-copies aligned host buffers into Arrays —
                    # those must not alias a recycled slot
                    leaves = [np.array(leaf) for leaf in leaves]
                out = self.device_put(jax.tree_util.tree_unflatten(treedef, leaves))
                # the slot is recycled next acquire — make sure the H2D copy
                # is finished before handing it back
                jax.block_until_ready(out)
                self._ring.release(view)
                yield out
        finally:
            self.close()

    def close(self):
        self._closed = True
        if self._ring is not None:
            self._ring.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # producer stuck (e.g. slow dataset read): leak the ring
                # rather than free memory the thread may still touch
                return
        if self._ring is not None:
            self._ring.destroy()
            self._ring = None


class SeedableRandomSampler:
    """Deterministic shuffling reseeded per epoch with ``seed + epoch``
    (reference SeedableRandomSampler data_loader.py:73-107)."""

    def __init__(self, data_source_len: int, seed: Optional[int] = None, epoch: int = 0):
        self.data_source_len = data_source_len
        from .utils.random import get_root_seed

        self.initial_seed = seed if seed is not None else get_root_seed()
        self.epoch = epoch

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        return self.data_source_len

    def __iter__(self) -> Iterator[int]:
        rng = np.random.default_rng(self.initial_seed + self.epoch)
        yield from rng.permutation(self.data_source_len).tolist()
        self.epoch += 1


class BatchSamplerShard:
    """Shard a batch sampler's index batches across ``num_processes``
    (reference BatchSamplerShard data_loader.py:110-263).

    - ``split_batches=False`` (stride): process k yields batch k of every
      consecutive group of ``num_processes`` batches.
    - ``split_batches=True``: each global batch is sliced into
      ``num_processes`` chunks.
    - ``even_batches=True`` pads the tail by cycling samples from the
      beginning so every process yields the same number of equally-sized
      batches (the duplicates are dropped later by ``gather_for_metrics``).
    """

    def __init__(
        self,
        batch_sampler: Iterable[list[int]],
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches and getattr(batch_sampler, "batch_size", None) is not None:
            if batch_sampler.batch_size % num_processes != 0:
                raise ValueError(
                    f"batch_size {batch_sampler.batch_size} must be divisible by num_processes "
                    f"{num_processes} when split_batches=True"
                )
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    @property
    def total_length(self):
        return len(self.batch_sampler)

    def __len__(self):
        n = len(self.batch_sampler)
        if self.split_batches:
            return n
        if n % self.num_processes == 0:
            return n // self.num_processes
        if self.even_batches and not self.drop_last:
            return math.ceil(n / self.num_processes)
        return n // self.num_processes + (
            0 if self.even_batches or self.drop_last else int(self.process_index < n % self.num_processes)
        )

    def set_epoch(self, epoch: int):
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)
        sampler = getattr(self.batch_sampler, "sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(epoch)

    def __iter__(self):
        return self._iter_with_split() if self.split_batches else self._iter_with_stride()

    def _iter_with_split(self):
        initial_data: list[int] = []
        for idx, batch in enumerate(self.batch_sampler):
            if idx == 0:
                initial_data = list(batch)
            if self.batch_size is None:
                # unknown batch size: infer from first batch
                self.batch_size = len(batch)
            chunk = self.batch_size // self.num_processes
            if len(batch) == self.batch_size:
                yield batch[self.process_index * chunk : (self.process_index + 1) * chunk]
            else:  # smaller tail batch
                if self.drop_last:
                    return
                if not self.even_batches:
                    piece = batch[self.process_index * chunk : (self.process_index + 1) * chunk]
                    if len(piece):
                        yield piece
                else:
                    while len(batch) < self.batch_size:
                        batch = batch + initial_data[: self.batch_size - len(batch)]
                    yield batch[self.process_index * chunk : (self.process_index + 1) * chunk]

    def _iter_with_stride(self):
        initial_data: list[int] = []
        batch_to_yield: Optional[list[int]] = None
        cycle_pos = -1
        batch_size = self.batch_size
        for idx, batch in enumerate(self.batch_sampler):
            if batch_size is None:
                batch_size = len(batch)
            # collect one full cycle of batches for tail padding
            if idx < self.num_processes:
                initial_data += list(batch)
            cycle_pos = idx % self.num_processes
            if cycle_pos == self.process_index:
                batch_to_yield = list(batch)
            if cycle_pos == self.num_processes - 1:
                if len(batch) == batch_size or (not self.even_batches and batch_to_yield):
                    yield batch_to_yield
                    batch_to_yield = None
                elif self.even_batches and not self.drop_last:
                    # last batch of the cycle is short: pad it (and this
                    # rank's batch if short) by cycling initial samples
                    if batch_to_yield is not None:
                        while len(batch_to_yield) < batch_size:
                            batch_to_yield += initial_data[: batch_size - len(batch_to_yield)]
                        yield batch_to_yield
                        batch_to_yield = None
        if cycle_pos == self.num_processes - 1 or cycle_pos == -1:
            return
        # dataloader ended mid-cycle
        if self.drop_last:
            return
        if not self.even_batches:
            if batch_to_yield:
                yield batch_to_yield
            return
        # even_batches: every rank must yield one more batch; ranks beyond the
        # cycle end cycle through initial samples
        if batch_to_yield is None:
            start = (self.process_index - cycle_pos - 1) * (batch_size or 1)
            pool = initial_data
            while len(pool) < start + (batch_size or 1):
                pool = pool + initial_data
            batch_to_yield = pool[start : start + (batch_size or 1)]
        while batch_size is not None and len(batch_to_yield) < batch_size:
            batch_to_yield += initial_data[: batch_size - len(batch_to_yield)]
        yield batch_to_yield


class IterableDatasetShard:
    """Shard an iterable dataset: buffer ``num_processes * batch_size`` items,
    take this process's slice (reference IterableDatasetShard
    data_loader.py:266-365)."""

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int = 1,
        drop_last: bool = False,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches

    def set_epoch(self, epoch: int):
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self):
        n = len(self.dataset)
        real_batch = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        if self.drop_last:
            return (n // real_batch) * real_batch // self.num_processes
        return math.ceil(n / real_batch) * real_batch // self.num_processes

    def __iter__(self):
        real_batch_size = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        process_batch_size = self.batch_size // self.num_processes if self.split_batches else self.batch_size
        first_batch = None
        buffer: list = []
        for element in self.dataset:
            buffer.append(element)
            if len(buffer) == real_batch_size:
                start = self.process_index * process_batch_size
                yield from buffer[start : start + process_batch_size]
                if first_batch is None:
                    first_batch = buffer.copy()
                buffer = []
        if not self.drop_last and len(buffer) > 0:
            if first_batch is None:
                first_batch = buffer.copy()
            while len(buffer) < real_batch_size:
                buffer += first_batch[: real_batch_size - len(buffer)]
            start = self.process_index * process_batch_size
            yield from buffer[start : start + process_batch_size]


# ---------------------------------------------------------------------------
# per-host batch sharding — which rows of the GLOBAL batch this process feeds
# ---------------------------------------------------------------------------


def batch_rows_by_device(mesh: Mesh, spec, shape) -> dict:
    """``{device: (start, stop)}`` — the global-batch-dim row range each mesh
    device owns under ``spec``.  Derived from the sharding itself (never
    assumed from mesh order), so it stays correct for any axis layout the
    partition spec names."""
    sharding = NamedSharding(mesh, spec)
    out = {}
    for dev, idx in sharding.devices_indices_map(tuple(shape)).items():
        s0 = idx[0] if idx else slice(None)
        out[dev] = (
            s0.start if s0.start is not None else 0,
            s0.stop if s0.stop is not None else shape[0],
        )
    return out


def _rows_union(ranges, what: str) -> tuple[int, int]:
    """Union of per-device row ranges, verified to tile ONE contiguous block
    (ranges may repeat — replication over non-batch axes — but a gap means
    the process would have to feed disjoint slices, which
    ``make_array_from_process_local_data`` cannot express)."""
    start = min(r[0] for r in ranges)
    stop = max(r[1] for r in ranges)
    cursor = start
    for s, e in sorted(set(ranges)):
        if s > cursor:
            raise ValueError(
                f"{what} owns non-contiguous global-batch rows "
                f"{sorted(set(ranges))}: the mesh's batch axes do not map "
                "this process to one block — keep the data-parallel axes "
                "(dcn, dp_replicate, dp_shard) outermost in the mesh order"
            )
        cursor = max(cursor, e)
    return start, stop


def process_local_rows(mesh: Mesh, spec, shape, process_index: Optional[int] = None) -> slice:
    """The contiguous ``[start, stop)`` block of the global batch dimension
    that ``process_index``'s addressable devices own under ``(mesh, spec)``.

    This is the per-host dataloader-sharding contract: every launched
    process reads the same deterministic global batch stream and feeds only
    its own block — process-disjoint by construction, identical global
    coverage at ANY process count (the launcher may re-shard the same mesh
    over 1, 2 or N hosts and the union of blocks is always the full batch),
    which is what makes mid-epoch resume exact across an elastic
    process-count change."""
    pid = jax.process_index() if process_index is None else process_index
    ranges = [
        r for dev, r in batch_rows_by_device(mesh, spec, shape).items()
        if dev.process_index == pid
    ]
    if not ranges:
        raise ValueError(f"process {pid} owns no devices of mesh {mesh}")
    start, stop = _rows_union(ranges, f"process {pid}")
    return slice(start, stop)


def shard_global_batch(batch, mesh: Mesh, spec):
    """Slice this process's rows out of a host-replicated GLOBAL batch and
    assemble the global sharded ``jax.Array`` (explicit ``global_shape`` —
    nothing inferred).  The single-process case degenerates to the whole
    batch, so a stream consumed this way is bit-identical at any process
    count."""

    def _make(x):
        x = np.asarray(x)
        s = NamedSharding(mesh, spec(x) if callable(spec) else spec)
        rows = process_local_rows(mesh, s.spec, x.shape)
        return jax.make_array_from_process_local_data(
            s, np.ascontiguousarray(x[rows.start:rows.stop]), tuple(x.shape)
        )

    return recursively_apply(_make, batch, error_on_other_type=True)


class DataLoaderStateMixin:
    """end-of-dataloader / remainder signaling into ``GradientState``
    (reference data_loader.py:365-405)."""

    def __init_subclass__(cls, **kwargs):
        cls.end_of_dataloader = False
        cls.remainder = -1

    def reset(self):
        self.end_of_dataloader = False
        self.remainder = -1

    def begin(self):
        self.reset()
        try:
            length = self.total_dataset_length
            total_batch_size = self.total_batch_size
            if length is not None and total_batch_size:
                self.remainder = length % total_batch_size
        except TypeError:  # length-less iterable dataset
            pass
        self.gradient_state._add_dataloader(self)

    def end(self):
        self.gradient_state._remove_dataloader(self)


class DataLoaderShard(DataLoaderStateMixin):
    """Per-rank device loader: wraps an inner batch iterable, synchronizes RNG
    at epoch start, converts each batch to a global sharded ``jax.Array`` with
    one-batch lookahead (reference DataLoaderShard data_loader.py:500-650 +
    MpDeviceLoaderWrapper :654)."""

    def __init__(
        self,
        inner: Iterable,
        device=None,
        mesh: Optional[Mesh] = None,
        batch_spec: Optional[Callable[[Any], PartitionSpec] | PartitionSpec] = None,
        rng_types: Optional[list] = None,
        synchronized_generator=None,
        skip_batches: int = 0,
        put_on_device: bool = True,
        prefetch_size: int = 0,
        even_batches: bool = True,
        _non_blocking: bool = True,
        _loader_batch_size: Optional[int] = None,
        transfer_retry_policy=None,
        on_transfer_retry=None,
        shard_across_processes: bool = False,
    ):
        self.inner = inner
        self.device = device
        self.mesh = mesh
        self.batch_spec = batch_spec
        self.even_batches = even_batches
        # per-host sharding (multi-process launch): the inner iterable yields
        # the same deterministic GLOBAL batch on every process and each host
        # feeds only its sharding-derived contiguous block
        # (process_local_rows) — process-disjoint coverage, resume positions
        # counted in global batches so a checkpoint restores exactly at any
        # process count
        self.shard_across_processes = shard_across_processes
        self.rng_types = rng_types
        self.synchronized_generator = synchronized_generator
        self.skip_batches = skip_batches
        self.put_on_device = put_on_device
        self.prefetch_size = prefetch_size
        self.gradient_state = GradientState()
        self.iteration = 0
        self._loader_batch_size = _loader_batch_size
        self._batches_yielded = 0  # intra-epoch stateful-resume position
        self._skip_once = False    # skip_batches came from load_state_dict
        # bounded-retry knobs for the H2D staging (resilience/retry.py);
        # the Accelerator threads its ResiliencePlugin budget + goodput hook
        self._retry_policy = transfer_retry_policy or DEFAULT_POLICY
        self._on_transfer_retry = on_transfer_retry
        # training timeline (telemetry/timeline.py): the Accelerator attaches
        # its TrainTimeline here when armed — data_wait brackets the inner
        # iterable, h2d_staging the device placement.  None = zero overhead.
        self._timeline = None

    # -- device placement ---------------------------------------------------

    def _pad_to_device_multiple(self, batch):
        """Device-level even_batches: a partial final batch whose dp-sharded
        dim does not divide the mesh's data-parallel size cannot be laid out
        as a global array — pad it by cycling samples from the batch head
        (reference even_batches semantics, BatchSamplerShard :110).
        ``gather_for_metrics`` drops the duplicate tail on the way back out
        via ``GradientState.remainder``."""
        def _pad(x):
            spec = self.batch_spec(x) if callable(self.batch_spec) else self.batch_spec
            if not spec or len(spec) == 0 or spec[0] is None:
                return x
            names = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
            div = 1
            for nm in names:
                div *= self.mesh.shape[nm]
            n = x.shape[0]
            if div <= 1 or n % div == 0:
                return x
            need = div - n % div
            reps = -(-need // n)  # cycle if the batch is shorter than the pad
            return np.concatenate([x] + [x] * (reps - 1) + [x[: need - (reps - 1) * n]], axis=0) \
                if reps > 1 else np.concatenate([x, x[:need]], axis=0)

        return jax.tree_util.tree_map(_pad, batch)

    def _device_put_batch(self, batch):
        timeline = self._timeline
        cm = timeline.phase("h2d_staging") if timeline is not None \
            else contextlib.nullcontext()
        with cm:
            return self._device_put_batch_inner(batch)

    def _device_put_batch_inner(self, batch):
        batch = _to_numpy(batch)
        if not self.put_on_device:
            return batch
        if self.mesh is not None and self.batch_spec is not None:
            if self.even_batches:
                batch = self._pad_to_device_multiple(batch)

            def _place():
                # injected-fault hook + bounded retry: a transient H2D
                # staging failure costs a backoff, not the training run
                maybe_fail_transfer("transfer")
                if self.shard_across_processes:
                    return shard_global_batch(batch, self.mesh, self.batch_spec)
                return host_local_to_global(batch, self.mesh, self.batch_spec)

            return with_retries(_place, site="dataloader-h2d",
                                policy=self._retry_policy,
                                on_retry=self._on_transfer_retry)

        def _send():
            maybe_fail_transfer("transfer")
            return send_to_device(batch, self.device)

        return with_retries(_send, site="dataloader-h2d",
                            policy=self._retry_policy,
                            on_retry=self._on_transfer_retry)

    def _timed_data_wait(self, it):
        """Yield from ``it``, bracketing each blocking ``next`` in the
        timeline's ``data_wait`` phase when a timeline is attached."""
        while True:
            timeline = self._timeline
            cm = timeline.phase("data_wait") if timeline is not None \
                else contextlib.nullcontext()
            try:
                with cm:
                    item = next(it)
            except StopIteration:
                return
            yield item

    def __iter__(self):
        if self.rng_types is not None:
            synchronize_rng_states(self.rng_types, self.synchronized_generator)
        self.begin()
        if hasattr(self.inner, "set_epoch"):
            self.inner.set_epoch(self.iteration)
        # intra-epoch position, skipped batches included: a state_dict taken
        # mid-epoch must record how far into *this* pass the consumer is, not
        # a lifetime count (a cumulative count restored as skip_batches would
        # exceed the epoch after the first one and the loader would go silent)
        self._batches_yielded = self.skip_batches
        prefetcher = None
        try:
            # source yields device-placed batches.  With prefetch_size >= 2
            # and the native runtime built, a background thread stages batch
            # bytes through the native ring while we feed the device;
            # otherwise plain in-line conversion (jax dispatch is async, so
            # the one-batch lookahead below still overlaps H2D with compute).
            if self.prefetch_size >= 2 and self.put_on_device and _native_prefetch_available():
                prefetcher = _RingPrefetcher(
                    self.inner, self._device_put_batch, self.prefetch_size
                )
                source = self._timed_data_wait(iter(prefetcher))
            else:
                # data_wait brackets ONLY the inner iterable (h2d_staging is
                # its own phase inside _device_put_batch — no double count)
                source = (self._device_put_batch(b)
                          for b in self._timed_data_wait(iter(self.inner)))
            # one-batch lookahead: current batch transfers H2D while the
            # previous one is being consumed
            batch_idx = 0
            current = None
            have_current = False
            while True:
                try:
                    nxt = next(source)
                except StopIteration:
                    break
                if have_current:
                    if batch_idx > self.skip_batches:
                        # count before yielding: state_dict() must reflect
                        # batches already handed out even mid-iteration
                        self._batches_yielded += 1
                        yield current
                current = nxt
                have_current = True
                batch_idx += 1
            if have_current:
                self.end_of_dataloader = True
                if batch_idx > self.skip_batches:
                    self._batches_yielded += 1
                    yield current
        finally:
            if prefetcher is not None:
                prefetcher.close()
            self.iteration += 1
            if self.end_of_dataloader:
                # a completed pass consumed any restore-time skip; the next
                # epoch starts at batch 0
                self._batches_yielded = 0
                if self._skip_once:
                    self.skip_batches, self._skip_once = 0, False
            self.end()

    def __len__(self):
        inner_len = len(self.inner)
        return max(inner_len - self.skip_batches, 0)

    def set_epoch(self, epoch: int):
        self.iteration = epoch
        if hasattr(self.inner, "set_epoch"):
            self.inner.set_epoch(epoch)

    @property
    def total_batch_size(self):
        if self._loader_batch_size is not None:
            return self._loader_batch_size
        bs = getattr(self.inner, "batch_size", None)
        if bs is None:
            sampler = getattr(self.inner, "batch_sampler", None)
            bs = getattr(sampler, "batch_size", None)
        return bs

    @property
    def total_dataset_length(self):
        dataset = getattr(self.inner, "dataset", self.inner)
        return len(dataset)

    # -- stateful resume (reference DataLoaderAdapter :408-498) ------------

    def state_dict(self):
        by, it = self._batches_yielded, self.iteration
        try:
            full = len(self.inner)
        except TypeError:  # length-less iterable
            full = None
        if full is not None and by >= full > 0:
            # saved on the pass's last batch (mid-iteration, before the
            # epoch-end reset ran): the position IS the next epoch's start —
            # recording it as a full-epoch skip would silence the restored
            # loader's first pass
            by, it = 0, it + 1
        return {"batches_yielded": by, "iteration": it}

    def load_state_dict(self, state_dict):
        # resume-time skip applies to the next pass only (torchdata
        # StatefulDataLoader semantics); a skip_first_batches-built wrapper
        # keeps its persistent skip
        self.skip_batches = state_dict.get("batches_yielded", 0)
        self._skip_once = self.skip_batches > 0
        self.iteration = state_dict.get("iteration", 0)
        # a state_dict taken between restore and the first iteration must
        # already report the restored position
        self._batches_yielded = self.skip_batches


class DataLoaderDispatcher(DataLoaderStateMixin):
    """Process 0 reads the data and broadcasts it; every process slices its
    shard — for iterable/non-replicable sources (reference DataLoaderDispatcher
    data_loader.py:704-960)."""

    def __init__(
        self,
        inner: Iterable,
        split_batches: bool = False,
        mesh: Optional[Mesh] = None,
        batch_spec=None,
        device=None,
        skip_batches: int = 0,
        slice_fn: Optional[Callable] = None,
        _loader_batch_size: Optional[int] = None,
        transfer_retry_policy=None,
        on_transfer_retry=None,
    ):
        self.inner = inner
        self.split_batches = split_batches
        self.mesh = mesh
        self.batch_spec = batch_spec
        self.device = device
        self.skip_batches = skip_batches
        self.slice_fn = slice_fn or slice_tensors
        self.state = PartialState()
        self.gradient_state = GradientState()
        self.iteration = 0
        self._loader_batch_size = _loader_batch_size
        self._batches_yielded = 0  # intra-epoch stateful-resume position
        self._skip_once = False    # skip_batches came from load_state_dict
        self._retry_policy = transfer_retry_policy or DEFAULT_POLICY
        self._on_transfer_retry = on_transfer_retry
        # TrainTimeline hook, same contract as DataLoaderShard._timeline
        self._timeline = None

    def _fetch_batches(self, iterator):
        """Rank 0 reads one global batch (split mode) or num_processes batches
        (stride mode) and broadcasts them (reference _fetch_batches :786).
        With a timeline attached the read+broadcast is the ``data_wait``
        phase."""
        timeline = self._timeline
        cm = timeline.phase("data_wait") if timeline is not None \
            else contextlib.nullcontext()
        with cm:
            return self._fetch_batches_inner(iterator)

    def _fetch_batches_inner(self, iterator):
        from .ops.operations import concatenate

        batches, batch = None, None
        stop_iteration = False
        if self.state.is_main_process:
            try:
                if self.split_batches:
                    batch = _to_numpy(next(iterator))
                else:
                    batches = [_to_numpy(next(iterator)) for _ in range(self.state.num_processes)]
                    batch = concatenate(batches, dim=0)
            except StopIteration:
                stop_iteration = True
        payload = [batch, stop_iteration]
        if self.state.num_processes > 1:
            broadcast_object_list(payload, from_process=0)
        return payload[0], payload[1]

    def __iter__(self):
        self.begin()
        if hasattr(self.inner, "set_epoch"):
            self.inner.set_epoch(self.iteration)
        main_iterator = iter(self.inner) if self.state.is_main_process else None
        self._batches_yielded = self.skip_batches
        batch_idx = 0
        completed = False

        def _prepare_local(batch):
            whole = find_batch_size(batch)
            slice_size = whole // self.state.num_processes
            start = self.state.process_index * slice_size
            local = self.slice_fn(batch, slice(start, start + slice_size))

            def _place():
                # same bounded-retry H2D staging discipline as
                # DataLoaderShard._device_put_batch (resilience/retry.py)
                maybe_fail_transfer("transfer")
                if self.mesh is not None and self.batch_spec is not None:
                    return host_local_to_global(local, self.mesh, self.batch_spec)
                if self.device is not None:
                    return send_to_device(local, self.device)
                return local

            timeline = self._timeline
            cm = timeline.phase("h2d_staging") if timeline is not None \
                else contextlib.nullcontext()
            with cm:
                return with_retries(_place, site="dataloader-h2d",
                                    policy=self._retry_policy,
                                    on_retry=self._on_transfer_retry)

        try:
            # one-batch lookahead, like DataLoaderShard: the NEXT batch's
            # broadcast + H2D placement starts (device puts are async) while
            # the consumer computes on the current one
            current, have_current = None, False
            while True:
                batch, stop = self._fetch_batches(main_iterator)
                if stop or batch is None:
                    completed = True
                    break
                nxt = _prepare_local(batch)
                if have_current:
                    if batch_idx > self.skip_batches:
                        self._batches_yielded += 1
                        yield current
                current, have_current = nxt, True
                batch_idx += 1
            if have_current:
                self.end_of_dataloader = True
                if batch_idx > self.skip_batches:
                    self._batches_yielded += 1
                    yield current
        finally:
            self.iteration += 1
            if completed:
                self._batches_yielded = 0
                if self._skip_once:
                    self.skip_batches, self._skip_once = 0, False
            self.end()

    def __len__(self):
        whole_length = len(self.inner)
        if self.split_batches:
            return whole_length
        return math.ceil(whole_length / self.state.num_processes)

    @property
    def total_batch_size(self):
        bs = self._loader_batch_size or getattr(self.inner, "batch_size", None)
        if bs is None:
            return None
        return bs if self.split_batches else bs * self.state.num_processes

    @property
    def total_dataset_length(self):
        dataset = getattr(self.inner, "dataset", self.inner)
        return len(dataset)

    def set_epoch(self, epoch: int):
        self.iteration = epoch
        if hasattr(self.inner, "set_epoch"):
            self.inner.set_epoch(epoch)

    def state_dict(self):
        by, it = self._batches_yielded, self.iteration
        try:
            full = len(self)  # fetch rounds per pass
        except TypeError:
            full = None
        if full is not None and by >= full > 0:
            # epoch-boundary save, see DataLoaderShard.state_dict
            by, it = 0, it + 1
        return {"batches_yielded": by, "iteration": it}

    def load_state_dict(self, state_dict):
        # next-pass-only skip, like DataLoaderShard.load_state_dict
        self.skip_batches = state_dict.get("batches_yielded", 0)
        self._skip_once = self.skip_batches > 0
        self.iteration = state_dict.get("iteration", 0)
        self._batches_yielded = self.skip_batches


# ---------------------------------------------------------------------------
# prepare_data_loader — the entry point (reference data_loader.py:996-1310)
# ---------------------------------------------------------------------------


def prepare_data_loader(
    dataloader,
    device=None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: Optional[list] = None,
    dispatch_batches: Optional[bool] = None,
    even_batches: bool = True,
    slice_fn_for_dispatch: Optional[Callable] = None,
    use_seedable_sampler: bool = False,
    data_seed: Optional[int] = None,
    non_blocking: bool = True,
    use_stateful_dataloader: bool = False,
    mesh: Optional[Mesh] = None,
    batch_spec: Optional[PartitionSpec] = None,
    parallelism_config=None,
    prefetch_size: int = 0,
    transfer_retry_policy=None,
    on_transfer_retry=None,
    shard_across_processes: Optional[bool] = None,
):
    """Re-wrap a dataloader (torch DataLoader or any batch iterable) for
    per-rank sharding + global-array device placement.

    Mirrors reference ``prepare_data_loader`` (data_loader.py:996): the
    process grid used for sharding is the **data-parallel** sub-grid — TP/CP/
    SP ranks are collapsed so they receive identical data
    (``process_index //= non_data_parallel_size``, reference :1109-1145).

    ``shard_across_processes`` (default auto) is the multi-process contract
    for plain batch iterables: torch loaders shard at the sampler
    (``BatchSamplerShard`` — each process READS only its share), while a
    generic iterable is treated as the same deterministic GLOBAL stream on
    every process and each host feeds only its sharding-derived block
    (:func:`process_local_rows`) — process-disjoint, and exact to resume at
    a different process count because positions are counted in global
    batches.
    """
    state = PartialState()
    num_processes = num_processes if num_processes is not None else state.num_processes
    process_index = process_index if process_index is not None else state.process_index

    if parallelism_config is not None and parallelism_config.non_data_parallel_size > 1:
        # Collapse non-DP model ranks: all hosts inside one dp group read the
        # same batches.  On JAX one process spans many devices, so this only
        # matters multi-host; device-level splitting is done by the global
        # array sharding itself.
        non_dp = parallelism_config.non_data_parallel_size
        if num_processes % non_dp == 0 and non_dp <= num_processes:
            process_index = process_index // non_dp
            num_processes = num_processes // non_dp

    if dispatch_batches is None:
        is_iterable = _is_torch_loader(dataloader) and not hasattr(dataloader.dataset, "__getitem__")
        dispatch_batches = is_iterable and put_on_device

    if dispatch_batches:
        if prefetch_size >= 2:
            import logging

            logging.getLogger(__name__).warning(
                "prefetch_size is not supported in dispatch mode (rank-0 reads + "
                "broadcast can't stage ahead through the ring) — ignoring it"
            )
        return DataLoaderDispatcher(
            dataloader,
            split_batches=split_batches,
            mesh=mesh,
            batch_spec=batch_spec,
            device=device if put_on_device else None,
            slice_fn=slice_fn_for_dispatch,
            _loader_batch_size=getattr(dataloader, "batch_size", None),
            transfer_retry_policy=transfer_retry_policy,
            on_transfer_retry=on_transfer_retry,
        )

    synchronized_generator = None
    inner = dataloader
    loader_batch_size = getattr(dataloader, "batch_size", None)

    if shard_across_processes is None:
        shard_across_processes = (
            not _is_torch_loader(dataloader)
            and state.num_processes > 1
            and put_on_device
            and mesh is not None
            and batch_spec is not None
        )
        if shard_across_processes:
            # say it once, loudly: this flips the iterable's multi-process
            # contract from "each process yields its LOCAL shard" to "every
            # process yields the same GLOBAL batch and feeds only its
            # sharding-derived block".  Pipelines that genuinely produce
            # per-process shards must pass shard_across_processes=False.
            import logging

            logging.getLogger(__name__).warning(
                "multi-process launch: treating the prepared iterable as the "
                "same deterministic GLOBAL batch stream on every process — "
                "each host feeds only its sharding-derived row block "
                "(process-disjoint, resume-exact at any process count). "
                "Pass shard_across_processes=False if the iterable yields "
                "per-process local shards instead."
            )

    if _is_torch_loader(dataloader):
        import torch.utils.data

        dataset = dataloader.dataset
        if isinstance(dataset, torch.utils.data.IterableDataset):
            if num_processes > 1:
                dataset = IterableDatasetShard(
                    dataset,
                    batch_size=dataloader.batch_size,
                    drop_last=dataloader.drop_last,
                    num_processes=num_processes,
                    process_index=process_index,
                    split_batches=split_batches,
                )
            inner = torch.utils.data.DataLoader(
                dataset,
                batch_size=(dataloader.batch_size // num_processes if split_batches else dataloader.batch_size),
                collate_fn=dataloader.collate_fn,
                num_workers=dataloader.num_workers,
                drop_last=dataloader.drop_last,
            )
        else:
            batch_sampler = dataloader.batch_sampler
            sampler = getattr(batch_sampler, "sampler", None)
            if use_seedable_sampler and isinstance(sampler, torch.utils.data.RandomSampler):
                seedable = SeedableRandomSampler(len(dataset), seed=data_seed)
                batch_sampler = torch.utils.data.BatchSampler(
                    seedable, batch_sampler.batch_size, batch_sampler.drop_last
                )
            if num_processes > 1:
                batch_sampler = BatchSamplerShard(
                    batch_sampler,
                    num_processes=num_processes,
                    process_index=process_index,
                    split_batches=split_batches,
                    even_batches=even_batches,
                )
            inner = torch.utils.data.DataLoader(
                dataset,
                batch_sampler=batch_sampler,
                collate_fn=dataloader.collate_fn,
                num_workers=dataloader.num_workers,
            )
        if rng_types is None:
            rng_types = [RNGType.JAX]

    return DataLoaderShard(
        inner,
        device=device if put_on_device else None,
        mesh=mesh if put_on_device else None,
        batch_spec=batch_spec,
        rng_types=rng_types,
        synchronized_generator=synchronized_generator,
        put_on_device=put_on_device,
        prefetch_size=prefetch_size,
        even_batches=even_batches,
        _non_blocking=non_blocking,
        _loader_batch_size=loader_batch_size,
        transfer_retry_policy=transfer_retry_policy,
        on_transfer_retry=on_transfer_retry,
        shard_across_processes=bool(shard_across_processes and not _is_torch_loader(dataloader)),
    )


# ---------------------------------------------------------------------------
# Skip / resume (reference data_loader.py:1312-1451)
# ---------------------------------------------------------------------------


class SkipBatchSampler:
    """Yield batches of an inner batch sampler starting at ``skip_batches``
    (reference :1312)."""

    def __init__(self, batch_sampler, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    def __iter__(self):
        for idx, batch in enumerate(self.batch_sampler):
            if idx >= self.skip_batches:
                yield batch

    def __len__(self):
        return max(len(self.batch_sampler) - self.skip_batches, 0)


class SkipDataLoader:
    """Iterate a dataloader skipping the first N batches (reference :1335)."""

    def __init__(self, dataloader, skip_batches: int = 0):
        self.dataloader = dataloader
        self.skip_batches = skip_batches

    def __iter__(self):
        for idx, batch in enumerate(self.dataloader):
            if idx >= self.skip_batches:
                yield batch

    def __len__(self):
        return max(len(self.dataloader) - self.skip_batches, 0)

    def __getattr__(self, name):
        return getattr(self.__dict__["dataloader"], name)


def skip_first_batches(dataloader, num_batches: int = 0):
    """Fast-forward a (prepared or raw) dataloader for mid-epoch resume
    (reference skip_first_batches data_loader.py:1375-1449)."""
    if isinstance(dataloader, (DataLoaderShard, DataLoaderDispatcher)):
        dataloader.skip_batches = num_batches
        return dataloader
    return SkipDataLoader(dataloader, skip_batches=num_batches)
