"""Version comparison helpers (reference capability role: utils/versions.py
``compare_versions``/``is_torch_version`` — here the pinned library is jax).
"""

from __future__ import annotations

import importlib.metadata
import operator as op
from typing import Union

from packaging.version import Version, parse

STR_OPERATION_TO_FUNC = {
    ">": op.gt, ">=": op.ge, "==": op.eq, "!=": op.ne, "<=": op.le, "<": op.lt,
}

jax_version = parse(importlib.metadata.version("jax"))


def compare_versions(
    library_or_version: Union[str, Version], operation: str, requirement_version: str
) -> bool:
    """Compare an installed library's version (by name) or a Version against
    a requirement using ``operation`` (one of > >= == != <= <)."""
    if operation not in STR_OPERATION_TO_FUNC:
        raise ValueError(
            f"`operation` must be one of {list(STR_OPERATION_TO_FUNC)}, received {operation}"
        )
    fn = STR_OPERATION_TO_FUNC[operation]
    if isinstance(library_or_version, str):
        library_or_version = parse(importlib.metadata.version(library_or_version))
    return fn(library_or_version, parse(requirement_version))


def is_jax_version(operation: str, version: str) -> bool:
    """Compare the running jax version against ``version``."""
    return compare_versions(jax_version, operation, version)
