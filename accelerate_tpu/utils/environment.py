"""Environment-variable parsing and patching helpers.

TPU-native re-design of the reference's ``utils/environment.py`` (see
/root/reference/src/accelerate/utils/environment.py:31-130 for ``str_to_bool``,
``parse_flag_from_env``, ``parse_choice_from_env`` and :341-411 for
``clear_environment`` / ``patch_environment``).  Config crosses the process
boundary exclusively through ``ACCELERATE_*`` environment variables, exactly
like the reference launcher (reference utils/launch.py:198-423).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from functools import lru_cache
from typing import Any


def str_to_bool(value: str) -> int:
    """Convert a string into a 1/0 truth value.

    Accepts y/yes/t/true/on/1 and n/no/f/false/off/0 (case-insensitive).
    Mirrors reference environment.py:31-43.
    """
    value = value.lower()
    if value in ("y", "yes", "t", "true", "on", "1"):
        return 1
    if value in ("n", "no", "f", "false", "off", "0"):
        return 0
    raise ValueError(f"invalid truth value {value!r}")


def get_int_from_env(env_keys, default: int) -> int:
    """Return the first positive int found among ``env_keys``."""
    for key in env_keys:
        val = int(os.environ.get(key, -1))
        if val >= 0:
            return val
    return default


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key, str(default))
    return bool(str_to_bool(value))


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, str(default))


def are_libraries_initialized(*library_names: str) -> list[str]:
    """Return the sub-list of ``library_names`` already imported."""
    import sys

    return [lib for lib in library_names if lib in sys.modules]


@contextmanager
def clear_environment():
    """Temporarily clear ``os.environ``, restoring it on exit.

    Mirrors reference environment.py:341-374 (restores the *same* mapping
    object so references held elsewhere stay valid).
    """
    backup = os.environ.copy()
    os.environ.clear()
    try:
        yield
    finally:
        os.environ.clear()
        os.environ.update(backup)


@contextmanager
def patch_environment(**kwargs: Any):
    """Temporarily set environment variables (keys upper-cased).

    Mirrors reference environment.py:376-410.
    """
    existing: dict[str, str] = {}
    missing: set[str] = set()
    for key, value in kwargs.items():
        key = key.upper()
        if key in os.environ:
            existing[key] = os.environ[key]
        else:
            missing.add(key)
        os.environ[key] = str(value)
    try:
        yield
    finally:
        for key in kwargs:
            key = key.upper()
            if key in missing:
                os.environ.pop(key, None)
            else:
                os.environ[key] = existing[key]


def purge_accelerate_environment(func):
    """Decorator that strips ``ACCELERATE_*`` env vars around a callable.

    Mirrors reference environment.py:412-470 (test hygiene).
    """
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        backup = {k: v for k, v in os.environ.items() if k.startswith("ACCELERATE_")}
        for k in backup:
            del os.environ[k]
        try:
            return func(*args, **kwargs)
        finally:
            for k in list(os.environ):
                if k.startswith("ACCELERATE_"):
                    del os.environ[k]
            os.environ.update(backup)

    return wrapper


@lru_cache
def get_tpu_env_metadata() -> dict[str, str]:
    """Collect TPU topology hints from the environment (GCE metadata style)."""
    keys = (
        "TPU_WORKER_ID",
        "TPU_WORKER_HOSTNAMES",
        "TPU_ACCELERATOR_TYPE",
        "TPU_CHIPS_PER_HOST_BOUNDS",
        "TPU_HOST_BOUNDS",
        "MEGASCALE_COORDINATOR_ADDRESS",
        "MEGASCALE_NUM_SLICES",
        "MEGASCALE_SLICE_ID",
    )
    return {k: os.environ[k] for k in keys if k in os.environ}


def get_free_port() -> int:
    """Pick an unused localhost TCP port (reference utils/other.py:478)."""
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def check_port_in_use(port: int, host: str = "localhost") -> bool:
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        try:
            s.bind((host, port))
            return False
        except OSError:
            return True


@lru_cache
def set_cpu_affinity(local_process_index: int, total_local_processes: int | None = None,
                     verbose: bool | None = None) -> None:
    """Partition the host's CPU cores across co-located ranks (reference
    ``set_numa_affinity`` utils/environment.py:323 + thread pinning
    state.py:266-281 — minus the GPU-NUMA lookup, which has no TPU analog).

    Why this is NOT on the default launch path: a Cloud TPU host runs ONE
    training process that owns the whole (single-socket) VM, and the TPU
    runtime manages its own thread pools — there is no contended NUMA
    boundary to pin across, so pinning can only take cores away.  The two
    real uses are (a) the local CPU-gang rehearsal mode, where N spawned
    ranks otherwise thrash each other's caches, and (b) multi-socket custom
    hosts feeding host-side dataloader workers / the C++ staging ring, where
    the caller knows the topology.  Both opt in explicitly (or via
    ``ACCELERATE_CPU_AFFINITY=1``, the reference's env knob).

    Cached per process index; no-op on platforms without
    ``os.sched_setaffinity`` (macOS).
    """
    if not hasattr(os, "sched_setaffinity"):
        return
    cores = sorted(os.sched_getaffinity(0))
    n = max(total_local_processes or get_int_from_env(["ACCELERATE_NUM_PROCESSES"], 1), 1)
    idx = local_process_index % n
    # striped assignment: cores[idx::n] distributes any remainder (no
    # stranded tail cores) and keeps ranks disjoint; with more ranks than
    # cores the overflow ranks degrade to one (shared) core each instead of
    # grabbing the whole mask back
    mine = cores[idx::n] if idx < len(cores) else []
    if not mine:
        mine = [cores[idx % len(cores)]]
    os.sched_setaffinity(0, mine)
    if verbose or (verbose is None and parse_flag_from_env("ACCELERATE_DEBUG_MODE")):
        from ..logging import get_logger

        get_logger(__name__).info(
            "Pinned process %d to %d/%d cpu cores: %s",
            local_process_index, len(mine), len(cores), mine,
        )


# ---------------------------------------------------------------------------
# Quiet-box discipline for host-compute probes (VERDICT r5 weak #7)
# ---------------------------------------------------------------------------
# The offloaded 7B step is host-DRAM-bound, so any host-bandwidth number
# taken on a loaded box measures the load, not the machine (the r5 probe
# swung 0.35-1.61 GiB/s with operator-box load).  These helpers turn the
# documented prose discipline into an enforced precondition: a loadavg gate
# plus a short host-compute calibration chain compared against the quiet
# reference baseline.

# Serialized single-stream host-region rate measured on the quiet reference
# worker host at 1 GiB granularity (benchmarks/host_compute_probe.py,
# docs/performance.md "7B-offload ceiling").
HOST_COMPUTE_BASELINE_GIBS = 1.71


def host_load_status(max_load_per_cpu: float = 0.25) -> dict:
    """1-minute loadavg normalized by core count; ``loaded`` flips when the
    box is busy enough to distort a host-bandwidth measurement."""
    try:
        load1 = os.getloadavg()[0]
    except (OSError, AttributeError):  # pragma: no cover - exotic platforms
        load1 = 0.0
    ncpu = os.cpu_count() or 1
    per_cpu = load1 / ncpu
    return {
        "load1": round(load1, 2),
        "cpus": ncpu,
        "load_per_cpu": round(per_cpu, 3),
        "loaded": per_cpu > max_load_per_cpu,
        "max_load_per_cpu": max_load_per_cpu,
    }


def calibrate_host_compute(gib: float = 0.125, iters: int = 4,
                           streams: int = 1) -> dict:
    """The ONE lion-shaped host-compute measurement kernel (read fp32
    master + bf16 momentum + bf16 grad, write master + momentum, inside
    ``compute_on("device_host")``) — the same op shape as the 7B offload
    step.  At the defaults it is the ~1-second quiet-box calibration chain;
    ``benchmarks/host_compute_probe.py`` drives the same function at 1-GiB
    granularity and ``streams`` independent regions, so calibration and
    baseline can never drift onto different kernels.  Each call varies a
    traced salt so identical-dispatch caching cannot serve a replay."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.compute_on import compute_on
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.sharding import host_offload_supported

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("d",))
    kind = "pinned_host" if host_offload_supported() else None
    sh = (NamedSharding(mesh, PartitionSpec(), memory_kind=kind) if kind
          else NamedSharding(mesh, PartitionSpec()))
    S = max(1, streams)
    n = int(gib * 256 * 1024 * 1024)
    masters = [jax.device_put(jnp.zeros((n,), jnp.float32), sh) for _ in range(S)]
    moms = [jax.device_put(jnp.zeros((n,), jnp.bfloat16), sh) for _ in range(S)]
    grads = [jax.device_put(jnp.ones((n,), jnp.bfloat16), sh) for _ in range(S)]

    @jax.jit
    def step(masters, moms, grads, salt):
        # grads and salt ride as jit ARGUMENTS, never closure constants: a
        # captured GiB-scale array would be baked into the executable as a
        # trace-time constant (compile blowup, memory kind not guaranteed),
        # and every operand entering the host region — the salt included —
        # must already sit in host memory space (jax rejects mixed-space
        # elementwise ops)
        new_masters, new_moms, parts = [], [], []
        for master, mom, grad in zip(masters, moms, grads):
            with compute_on("device_host"):
                g = grad.astype(jnp.float32) + salt
                m = mom.astype(jnp.float32)
                new_master = master - 1e-4 * jnp.sign(0.9 * m + 0.1 * g)
                new_mom = (0.99 * m + 0.01 * g).astype(jnp.bfloat16)
                part = new_master[0] + new_master[-1]
            new_masters.append(jax.device_put(new_master, sh))
            new_moms.append(jax.device_put(new_mom, sh))
            parts.append(part)
        # summed OUTSIDE the regions: a cross-region checksum chain would
        # serialize the streams the probe exists to measure independently
        return new_masters, new_moms, sum(parts)

    def _salt(v):
        return jax.device_put(jnp.float32(v), sh)

    masters, moms, cs = step(masters, moms, grads, _salt(0.0))  # compile + warm
    float(cs)
    t0 = time.perf_counter()
    for i in range(iters):
        masters, moms, cs = step(masters, moms, grads, _salt(i + 1.0))
        float(cs)
    dt = time.perf_counter() - t0
    bytes_per = n * (4 + 2 + 2 + 4 + 2) * S
    return {
        "gib": gib,
        "iters": iters,
        "streams": S,
        "seconds": round(dt, 3),
        "secs_per_iter": round(dt / iters, 3),
        "gibs": round(bytes_per * iters / dt / 2**30, 3),
    }


def quiet_box_gate(
    baseline_gibs: float = HOST_COMPUTE_BASELINE_GIBS,
    *,
    calibrate: bool = True,
    min_frac: float = 0.5,
    max_load_per_cpu: float = 0.25,
) -> dict:
    """The enforced quiet-box precondition: loadavg gate + calibration chain
    vs the documented baseline.  ``ok`` is False when the box is loaded or
    the calibration lands under ``min_frac`` of ``baseline_gibs`` — callers
    warn (bench) or refuse without ``--force`` (the probe).  The baseline
    comparison only binds on TPU worker hosts (CPU backends run the same
    chain at whatever the operator box does, reported but not judged)."""
    import jax

    rep: dict = {"load": host_load_status(max_load_per_cpu)}
    warnings = []
    if rep["load"]["loaded"]:
        warnings.append(
            f"box is loaded (load1/cpu {rep['load']['load_per_cpu']} > "
            f"{max_load_per_cpu}): host-bandwidth numbers would measure the "
            "load, not the machine"
        )
    if calibrate:
        rep["calibration"] = calibrate_host_compute()
        rep["baseline_gibs"] = baseline_gibs
        on_tpu = jax.default_backend() == "tpu"
        rep["baseline_binding"] = on_tpu
        if on_tpu and rep["calibration"]["gibs"] < min_frac * baseline_gibs:
            warnings.append(
                f"calibration chain measured {rep['calibration']['gibs']} GiB/s "
                f"< {min_frac} x the quiet baseline {baseline_gibs} GiB/s: "
                "the worker host is degraded or contended"
            )
    rep["warnings"] = warnings
    rep["ok"] = not warnings
    return rep
