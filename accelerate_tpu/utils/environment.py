"""Environment-variable parsing and patching helpers.

TPU-native re-design of the reference's ``utils/environment.py`` (see
/root/reference/src/accelerate/utils/environment.py:31-130 for ``str_to_bool``,
``parse_flag_from_env``, ``parse_choice_from_env`` and :341-411 for
``clear_environment`` / ``patch_environment``).  Config crosses the process
boundary exclusively through ``ACCELERATE_*`` environment variables, exactly
like the reference launcher (reference utils/launch.py:198-423).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from functools import lru_cache
from typing import Any


def str_to_bool(value: str) -> int:
    """Convert a string into a 1/0 truth value.

    Accepts y/yes/t/true/on/1 and n/no/f/false/off/0 (case-insensitive).
    Mirrors reference environment.py:31-43.
    """
    value = value.lower()
    if value in ("y", "yes", "t", "true", "on", "1"):
        return 1
    if value in ("n", "no", "f", "false", "off", "0"):
        return 0
    raise ValueError(f"invalid truth value {value!r}")


def get_int_from_env(env_keys, default: int) -> int:
    """Return the first positive int found among ``env_keys``."""
    for key in env_keys:
        val = int(os.environ.get(key, -1))
        if val >= 0:
            return val
    return default


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key, str(default))
    return bool(str_to_bool(value))


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, str(default))


def are_libraries_initialized(*library_names: str) -> list[str]:
    """Return the sub-list of ``library_names`` already imported."""
    import sys

    return [lib for lib in library_names if lib in sys.modules]


@contextmanager
def clear_environment():
    """Temporarily clear ``os.environ``, restoring it on exit.

    Mirrors reference environment.py:341-374 (restores the *same* mapping
    object so references held elsewhere stay valid).
    """
    backup = os.environ.copy()
    os.environ.clear()
    try:
        yield
    finally:
        os.environ.clear()
        os.environ.update(backup)


@contextmanager
def patch_environment(**kwargs: Any):
    """Temporarily set environment variables (keys upper-cased).

    Mirrors reference environment.py:376-410.
    """
    existing: dict[str, str] = {}
    missing: set[str] = set()
    for key, value in kwargs.items():
        key = key.upper()
        if key in os.environ:
            existing[key] = os.environ[key]
        else:
            missing.add(key)
        os.environ[key] = str(value)
    try:
        yield
    finally:
        for key in kwargs:
            key = key.upper()
            if key in missing:
                os.environ.pop(key, None)
            else:
                os.environ[key] = existing[key]


def purge_accelerate_environment(func):
    """Decorator that strips ``ACCELERATE_*`` env vars around a callable.

    Mirrors reference environment.py:412-470 (test hygiene).
    """
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        backup = {k: v for k, v in os.environ.items() if k.startswith("ACCELERATE_")}
        for k in backup:
            del os.environ[k]
        try:
            return func(*args, **kwargs)
        finally:
            for k in list(os.environ):
                if k.startswith("ACCELERATE_"):
                    del os.environ[k]
            os.environ.update(backup)

    return wrapper


@lru_cache
def get_tpu_env_metadata() -> dict[str, str]:
    """Collect TPU topology hints from the environment (GCE metadata style)."""
    keys = (
        "TPU_WORKER_ID",
        "TPU_WORKER_HOSTNAMES",
        "TPU_ACCELERATOR_TYPE",
        "TPU_CHIPS_PER_HOST_BOUNDS",
        "TPU_HOST_BOUNDS",
        "MEGASCALE_COORDINATOR_ADDRESS",
        "MEGASCALE_NUM_SLICES",
        "MEGASCALE_SLICE_ID",
    )
    return {k: os.environ[k] for k in keys if k in os.environ}


def get_free_port() -> int:
    """Pick an unused localhost TCP port (reference utils/other.py:478)."""
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def check_port_in_use(port: int, host: str = "localhost") -> bool:
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        try:
            s.bind((host, port))
            return False
        except OSError:
            return True


@lru_cache
def set_cpu_affinity(local_process_index: int, total_local_processes: int | None = None,
                     verbose: bool | None = None) -> None:
    """Partition the host's CPU cores across co-located ranks (reference
    ``set_numa_affinity`` utils/environment.py:323 + thread pinning
    state.py:266-281 — minus the GPU-NUMA lookup, which has no TPU analog).

    Why this is NOT on the default launch path: a Cloud TPU host runs ONE
    training process that owns the whole (single-socket) VM, and the TPU
    runtime manages its own thread pools — there is no contended NUMA
    boundary to pin across, so pinning can only take cores away.  The two
    real uses are (a) the local CPU-gang rehearsal mode, where N spawned
    ranks otherwise thrash each other's caches, and (b) multi-socket custom
    hosts feeding host-side dataloader workers / the C++ staging ring, where
    the caller knows the topology.  Both opt in explicitly (or via
    ``ACCELERATE_CPU_AFFINITY=1``, the reference's env knob).

    Cached per process index; no-op on platforms without
    ``os.sched_setaffinity`` (macOS).
    """
    if not hasattr(os, "sched_setaffinity"):
        return
    cores = sorted(os.sched_getaffinity(0))
    n = max(total_local_processes or get_int_from_env(["ACCELERATE_NUM_PROCESSES"], 1), 1)
    idx = local_process_index % n
    # striped assignment: cores[idx::n] distributes any remainder (no
    # stranded tail cores) and keeps ranks disjoint; with more ranks than
    # cores the overflow ranks degrade to one (shared) core each instead of
    # grabbing the whole mask back
    mine = cores[idx::n] if idx < len(cores) else []
    if not mine:
        mine = [cores[idx % len(cores)]]
    os.sched_setaffinity(0, mine)
    if verbose or (verbose is None and parse_flag_from_env("ACCELERATE_DEBUG_MODE")):
        from ..logging import get_logger

        get_logger(__name__).info(
            "Pinned process %d to %d/%d cpu cores: %s",
            local_process_index, len(mine), len(cores), mine,
        )
