"""Safetensors (de)serialization on the native IO engine.

The reference writes model weights through the safetensors library's Rust
core (reference utils/other.py ``save`` :354, modeling.py ``load_state_dict``
:1620 lazy slices).  Here the format is produced/consumed in-tree: the JSON
header is built in Python and the tensor payload moves through the native
parallel segment writer/reader (native/src/io_engine.cc) — each tensor goes
straight between its own host buffer and its file offset, no concatenation
copy, with multi-threaded pwrite/pread underneath.  Falls back to the
safetensors library when the native runtime is unavailable.

Format (safetensors spec): ``u64 header_len | JSON header | payload``;
header maps tensor name → {dtype, shape, data_offsets=[begin,end)} with
offsets relative to payload start.
"""

from __future__ import annotations

import json
import struct
from pathlib import Path
from typing import Mapping, Optional

import numpy as np

from .. import native

# dtype <-> safetensors dtype-string (spec names)
_DTYPE_TO_STR = {
    np.dtype(np.float64): "F64", np.dtype(np.float32): "F32",
    np.dtype(np.float16): "F16", np.dtype(np.int64): "I64",
    np.dtype(np.int32): "I32", np.dtype(np.int16): "I16",
    np.dtype(np.int8): "I8", np.dtype(np.uint8): "U8",
    np.dtype(np.uint16): "U16", np.dtype(np.uint32): "U32",
    np.dtype(np.uint64): "U64", np.dtype(bool): "BOOL",
}
try:  # jax's bf16/fp8 numpy dtypes
    import ml_dtypes

    _DTYPE_TO_STR[np.dtype(ml_dtypes.bfloat16)] = "BF16"
    _DTYPE_TO_STR[np.dtype(ml_dtypes.float8_e4m3fn)] = "F8_E4M3"
    _DTYPE_TO_STR[np.dtype(ml_dtypes.float8_e5m2)] = "F8_E5M2"
except ImportError:  # pragma: no cover
    ml_dtypes = None

_STR_TO_DTYPE = {v: k for k, v in _DTYPE_TO_STR.items()}


def save_safetensors(path, tensors: Mapping[str, np.ndarray],
                     metadata: Optional[dict] = None, nthreads: Optional[int] = None) -> None:
    """Write a safetensors file via the native parallel segment writer."""
    header: dict = {}
    arrays = []
    offset = 0
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        dt = _DTYPE_TO_STR.get(arr.dtype)
        if dt is None:
            raise TypeError(f"dtype {arr.dtype} of tensor {name!r} is not safetensors-serializable")
        header[name] = {
            "dtype": dt, "shape": list(arr.shape),
            "data_offsets": [offset, offset + arr.nbytes],
        }
        arrays.append(arr)
        offset += arr.nbytes
    if metadata:
        header["__metadata__"] = {k: str(v) for k, v in metadata.items()}

    hjson = json.dumps(header, separators=(",", ":")).encode()
    hjson += b" " * (-(8 + len(hjson)) % 8)  # pad header to 8-byte multiple
    prefix = struct.pack("<Q", len(hjson)) + hjson
    base = len(prefix)

    segments = [(0, np.frombuffer(prefix, np.uint8))]
    for arr, (name, _) in zip(arrays, tensors.items()):
        if arr.nbytes:
            segments.append((base + header[name]["data_offsets"][0], arr))
    native.write_file_segments(path, segments, total_size=base + offset, nthreads=nthreads)


def read_safetensors_header(path) -> tuple[dict, int]:
    """(header dict incl. __metadata__, payload byte offset in file)."""
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen))
    return header, 8 + hlen


def load_safetensors(path, names: Optional[list[str]] = None,
                     nthreads: Optional[int] = None) -> dict[str, np.ndarray]:
    """Read tensors (all, or the given ``names``) with one parallel
    scatter-read straight into per-tensor buffers."""
    header, base = read_safetensors_header(path)
    out: dict[str, np.ndarray] = {}
    segments = []
    for name, info in header.items():
        if name == "__metadata__" or (names is not None and name not in names):
            continue
        dtype = _STR_TO_DTYPE.get(info["dtype"])
        if dtype is None:
            raise TypeError(f"unsupported safetensors dtype {info['dtype']} for {name!r}")
        arr = np.empty(info["shape"], dtype)
        out[name] = arr
        if arr.nbytes:
            segments.append((base + info["data_offsets"][0], arr))
    native.read_file_segments(path, segments, nthreads=nthreads)
    return out


class LazySafetensorsFile:
    """Per-tensor lazy reader over one file (``safe_open`` analog): holds
    only the header; each :meth:`get` is a direct offset read."""

    def __init__(self, path):
        self.path = Path(path)
        self.header, self.base = read_safetensors_header(path)
        self.header.pop("__metadata__", None)

    def keys(self):
        return self.header.keys()

    def get(self, name: str) -> np.ndarray:
        info = self.header[name]
        arr = np.empty(info["shape"], _STR_TO_DTYPE[info["dtype"]])
        if arr.nbytes:
            native.read_file_segments(self.path, [(self.base + info["data_offsets"][0], arr)])
        return arr
