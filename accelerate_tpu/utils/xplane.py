"""Minimal XSpace (``*.xplane.pb``) reader + per-op time aggregation.

The torch reference exposes ``prof.key_averages()`` — a per-op self-time
table — straight from ``torch.profiler`` (reference utils/dataclasses.py:484
ProfileKwargs → torch.profiler.profile).  On TPU the captured artifact is an
XSpace protobuf that normally needs TensorBoard's profile plugin to read;
this module decodes it directly with a hand-rolled protobuf **wire-format**
parser (no tensorflow/tensorboard dependency — only the stable public
field numbers of ``xplane.proto``), so ``TPUProfiler.key_averages()`` can
print an op-class breakdown in-process.

Wire-format subset: varint (0) and length-delimited (2) fields are enough —
every XSpace field we read is one of the two (fixed64/fixed32 are skipped
structurally).
"""

from __future__ import annotations

import glob
import os
import re
from collections import defaultdict
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Optional


@lru_cache(maxsize=16)
def _cached_planes(path: str, size: int, mtime_ns: int) -> tuple:
    """Parsed planes per file, keyed by (path, size, mtime) so one
    ``op_class_breakdown`` + ``top_ops`` pass decodes each artifact once
    (the pure-Python wire parse of a real trace costs seconds)."""
    return tuple(parse_xspace(path))


def _planes_of(path: str) -> tuple:
    st = os.stat(path)
    return _cached_planes(path, st.st_size, st.st_mtime_ns)


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message buffer.
    value is an int for varint fields, a memoryview for length-delimited."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:  # varint
            val, i = _read_varint(buf, i)
        elif wtype == 2:  # length-delimited
            ln, i = _read_varint(buf, i)
            val = memoryview(buf)[i:i + ln]
            i += ln
        elif wtype == 5:  # fixed32
            val = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        elif wtype == 1:  # fixed64
            val = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        else:  # pragma: no cover - groups are absent from xplane.proto
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


@dataclass
class Line:
    name: str = ""
    events: list = field(default_factory=list)  # (metadata_id, duration_ps)


@dataclass
class Plane:
    name: str = ""
    event_names: dict = field(default_factory=dict)  # id -> name
    lines: list = field(default_factory=list)


def _parse_event(buf) -> tuple[int, int]:
    meta_id = dur_ps = 0
    for fnum, _, val in _fields(bytes(buf)):
        if fnum == 1:
            meta_id = val
        elif fnum == 3:
            dur_ps = val
    return meta_id, dur_ps


def _parse_line(buf) -> Line:
    line = Line()
    for fnum, _, val in _fields(bytes(buf)):
        if fnum == 2:
            line.name = bytes(val).decode("utf-8", "replace")
        elif fnum == 11 and not line.name:
            line.name = bytes(val).decode("utf-8", "replace")
        elif fnum == 4:
            line.events.append(_parse_event(val))
    return line


def _parse_event_metadata_entry(buf) -> tuple[int, str]:
    """map<int64, XEventMetadata> entry: key=1, value=2 (XEventMetadata)."""
    key, name = 0, ""
    for fnum, _, val in _fields(bytes(buf)):
        if fnum == 1:
            key = val
        elif fnum == 2:
            for f2, _, v2 in _fields(bytes(val)):
                if f2 == 2:
                    name = bytes(v2).decode("utf-8", "replace")
    return key, name


def _parse_plane(buf) -> Plane:
    plane = Plane()
    for fnum, _, val in _fields(bytes(buf)):
        if fnum == 2:
            plane.name = bytes(val).decode("utf-8", "replace")
        elif fnum == 3:
            plane.lines.append(_parse_line(val))
        elif fnum == 4:
            k, name = _parse_event_metadata_entry(val)
            plane.event_names[k] = name
    return plane


def parse_xspace(path: str) -> list[Plane]:
    with open(path, "rb") as f:
        data = f.read()
    return [_parse_plane(val) for fnum, _, val in _fields(data) if fnum == 1]


def find_xplane_files(trace_dir: str) -> list[str]:
    return sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True))


def _device_planes(planes: list[Plane], device_substr: str) -> list[Plane]:
    dev = [p for p in planes if device_substr.lower() in p.name.lower()]
    if not dev:
        dev = [p for p in planes if "/device:" in p.name]
    if not dev:
        dev = [p for p in planes if p.name.startswith("/host:CPU")]
    return dev


def _line_times(trace_dir: str, device_substr: str, line_name: str,
                fallback_all: bool = False) -> dict[str, float]:
    """ms per op name, summed over lines named ``line_name``.  With
    ``fallback_all``, a plane with no such line contributes all its lines
    (backends without the TPU line naming, e.g. the CPU tests)."""
    totals: dict[str, float] = defaultdict(float)
    for path in find_xplane_files(trace_dir):
        for plane in _device_planes(list(_planes_of(path)), device_substr):
            matching = [ln for ln in plane.lines if ln.name == line_name]
            if not matching and fallback_all:
                matching = plane.lines
            for line in matching:
                for meta_id, dur_ps in line.events:
                    name = plane.event_names.get(meta_id, f"op_{meta_id}")
                    totals[name] += dur_ps / 1e9  # ps -> ms
    return dict(totals)


def device_op_times(trace_dir: str, device_substr: str = "TPU") -> dict[str, float]:
    """Total device time per HLO op (ms) from the per-op timeline only (the
    ``XLA Ops`` line).  ``Steps`` / ``XLA Modules`` are whole-program parent
    spans and ``Async XLA Ops`` are overlapped transfers — counting either
    alongside the ops would double-book the wall clock."""
    return _line_times(trace_dir, device_substr, "XLA Ops", fallback_all=True)


def async_copy_ms(trace_dir: str, device_substr: str = "TPU") -> float:
    """Total duration on the ``Async XLA Ops`` line — DMA/copy traffic that
    the scheduler overlapped with compute.  Reported separately: it costs
    bandwidth, not (necessarily) wall clock."""
    t = _line_times(trace_dir, device_substr, "Async XLA Ops")
    return round(sum(t.values()), 3)


def steps_ms(trace_dir: str, device_substr: str = "TPU") -> float:
    """Total duration of the ``Steps`` parent spans (the traced wall time)."""
    t = _line_times(trace_dir, device_substr, "Steps")
    return round(sum(t.values()), 3)


# ---------------------------------------------------------------------------
# op-class attribution
# ---------------------------------------------------------------------------

_SUFFIX_RE = re.compile(r"\.[0-9]+(\.remat)?$")


def _lhs_base(name: str) -> str:
    """`%convolution_add_fusion.82 = ...` -> `convolution_add_fusion`."""
    lhs = name.split(" = ")[0].lstrip("%").strip()
    return _SUFFIX_RE.sub("", lhs)


def classify_op(name: str) -> str:
    """Map one HLO event name to an op class.

    Heuristics tuned against real v5e train-step traces of this package
    (`bench.py --trace`): Pallas kernels surface as ``custom-call``s whose
    instruction keeps the model scope name (``self_attn`` = flash
    attention); projection/embedding matmuls are the ``convolution*``/
    ``dot*`` fusions plus XLA:TPU's *unnamed* ``fusion.N`` output fusions
    (named elementwise fusions spell their root ops instead, e.g.
    ``multiply_reduce_fusion``); the fused-CE vocab-chunk loop runs as
    ``while`` ops."""
    base = _lhs_base(name)
    low = base.lower()
    if "self_attn" in low or "flash" in low or "mha" in low:
        return "flash_attention"
    if "int8" in low or "quant" in low:
        return "int8_kernel"
    if "custom-call" in low:
        return "pallas_other"
    if any(k in low for k in ("all-reduce", "all-gather", "reduce-scatter",
                              "all-to-all", "collective-permute")):
        return "collective"
    full = name.lower()
    if low.startswith("call"):
        # XLA host-compute regions (compute_on("device_host")) surface as
        # call / call-start / call-done spans whose operand layouts carry
        # host-space markers (S(5) memory space / L(...) linear layouts) —
        # at 7B-offload these ARE the step (the chunked host optimizer
        # update + its PCIe transfers).  Device-side call subcomputations
        # (no host markers) stay out of the host bucket.
        if "s(5)" in full or "l(1024)" in full:
            return "host_compute"
        return "other"
    if low.startswith(("copy", "send", "recv", "infeed", "outfeed")):
        return "copy"
    if low.startswith("while"):
        return "while_loops"
    if "dynamic-update" in low or "dynamic-slice" in low or low.startswith(("scatter", "gather")):
        return "dynamic_slice"
    if low.startswith(("convolution", "dot", "einsum")):
        return "matmul"
    if low.startswith("fusion"):
        # unnamed output fusions: on TPU these are the matmul-rooted ones
        # (elementwise fusions carry their root-op names)
        return "matmul"
    if "fusion" in low:
        return "elementwise_fusion"
    if low.startswith("convert"):
        return "convert"
    return "other"


def op_class_breakdown(trace_dir: str, device_substr: str = "TPU") -> dict:
    """{class: {"ms": total, "share": fraction}, ...} plus ``_total_ms``,
    ``_steps_ms`` (traced wall) and ``_async_copy_ms`` (overlapped DMA) —
    the table docs/performance.md's MFU attribution is built from.
    Shares are of the op-timeline total; ``while`` spans can double-book
    their inner ops by a few percent (XLA emits both)."""
    per_op = device_op_times(trace_dir, device_substr)
    per_class: dict[str, float] = defaultdict(float)
    for name, ms in per_op.items():
        per_class[classify_op(name)] += ms
    total = sum(per_class.values())
    denom = total or 1.0  # guard only the division — _total_ms stays honest
    out = {
        cls: {"ms": round(ms, 3), "share": round(ms / denom, 4)}
        for cls, ms in sorted(per_class.items(), key=lambda kv: -kv[1])
    }
    out["_total_ms"] = round(total, 3)
    out["_steps_ms"] = steps_ms(trace_dir, device_substr)
    out["_async_copy_ms"] = async_copy_ms(trace_dir, device_substr)
    return out


def streaming_overlap_report(trace_dir: str, device_substr: str = "TPU",
                             breakdown: Optional[dict] = None) -> dict:
    """Transfer-vs-compute occupancy from a captured trace — the MEASURED
    counterpart of ``ops/streaming``'s predicted overlap accounting.

    ``overlap_frac`` is the share of DMA the latency-hiding scheduler kept
    off the critical path (async copies vs all copy traffic);
    ``transfer_occupancy``/``host_occupancy``/``compute_occupancy`` are
    shares of the op-timeline total, so a host-bound offload step shows up
    as host_occupancy ≈ 1 with its transfers hidden (overlap_frac → 1), and
    a serialized streamed decode as transfer_occupancy ≈ 1 with
    overlap_frac → 0.  Pass an already-computed ``op_class_breakdown``
    result as ``breakdown`` to skip re-aggregating the (parse-cached)
    planes."""
    br = breakdown if breakdown is not None else op_class_breakdown(trace_dir, device_substr)
    copy_inline = br.get("copy", {}).get("ms", 0.0)
    host_ms = br.get("host_compute", {}).get("ms", 0.0)
    total = br["_total_ms"]
    async_ms = br["_async_copy_ms"]
    denom = total or 1.0
    all_copy = copy_inline + async_ms
    # twin registry: MEASURED overlap (predicted side:
    # ops/streaming.offload_transfer_accounting)
    from ..telemetry import twin_registry

    twin_registry().record_measured(
        "offload_transfer.overlap_frac",
        async_ms / all_copy if all_copy else 0.0,
        source="utils/xplane.streaming_overlap_report",
    )
    return {
        "total_ms": total,
        "steps_ms": br["_steps_ms"],
        "copy_ms_inline": round(copy_inline, 3),
        "copy_ms_async": round(async_ms, 3),
        "host_compute_ms": round(host_ms, 3),
        "transfer_occupancy": round(copy_inline / denom, 4),
        "host_occupancy": round(host_ms / denom, 4),
        "compute_occupancy": round(max(0.0, total - copy_inline - host_ms) / denom, 4),
        "overlap_frac": round(async_ms / all_copy, 4) if all_copy else 0.0,
        "kind": "measured",
    }


# HLO name fragments that mark ICI collective traffic (the op classes the
# ring collective-matmul either emits — collective-permute — or replaces)
_COLLECTIVE_MARKS = ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective-permute")


def _is_collective(name: str) -> bool:
    base = _lhs_base(name).lower()
    return any(m in base for m in _COLLECTIVE_MARKS)


def async_collective_ms(trace_dir: str, device_substr: str = "TPU") -> float:
    """Collective time on the ``Async XLA Ops`` line — ICI traffic the
    latency-hiding scheduler kept off the critical path (on TPU the ring's
    ``collective-permute-start``/``done`` pairs land here when hidden)."""
    t = _line_times(trace_dir, device_substr, "Async XLA Ops")
    return round(float(sum(ms for n, ms in t.items() if _is_collective(n))), 3)


def ici_overlap_report(trace_dir: str, device_substr: str = "TPU",
                       breakdown: Optional[dict] = None) -> dict:
    """ICI comm-vs-compute occupancy from a captured trace — the MEASURED
    counterpart of ``ops/collective_matmul.tp_comm_accounting``.

    ``tp_overlap_frac`` is the share of collective time the scheduler hid
    under compute (async vs all collective traffic); ``collective_occupancy``
    is the inline (critical-path) collective share of the op timeline.  A
    well-overlapped ring shows collective_occupancy → 0 with
    tp_overlap_frac → 1; the monolithic path shows its gathers inline.
    Pass an already-computed ``op_class_breakdown`` as ``breakdown`` to skip
    re-aggregating the (parse-cached) planes."""
    br = breakdown if breakdown is not None else op_class_breakdown(trace_dir, device_substr)
    inline = br.get("collective", {}).get("ms", 0.0)
    total = br["_total_ms"]
    async_ms = async_collective_ms(trace_dir, device_substr)
    denom = total or 1.0
    all_coll = inline + async_ms
    # twin registry: MEASURED hidden fraction (predicted side:
    # ops/collective_matmul.tp_comm_accounting)
    from ..telemetry import twin_registry

    twin_registry().record_measured(
        "tp_comm.overlap_frac",
        async_ms / all_coll if all_coll else 0.0,
        source="utils/xplane.ici_overlap_report",
    )
    return {
        "total_ms": total,
        "collective_ms_inline": round(inline, 3),
        "collective_ms_async": round(async_ms, 3),
        "collective_occupancy": round(inline / denom, 4),
        "compute_occupancy": round(max(0.0, total - inline) / denom, 4),
        "tp_overlap_frac": round(async_ms / all_coll, 4) if all_coll else 0.0,
        "kind": "measured",
    }


def top_ops(trace_dir: str, n: int = 20, device_substr: str = "TPU") -> list[tuple[str, float]]:
    per_op = device_op_times(trace_dir, device_substr)
    ranked = sorted(per_op.items(), key=lambda kv: -kv[1])[:n]
    return [(name[:160], ms) for name, ms in ranked]
