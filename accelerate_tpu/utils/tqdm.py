"""Process-aware tqdm wrapper (reference utils/tqdm.py): progress bars
display on the local main process only, so an 8-host launch prints one bar,
not eight interleaved ones."""

from __future__ import annotations


def tqdm(*args, main_process_only: bool = True, **kwargs):
    """``tqdm.tqdm`` that renders only on the local main process by default.

    Pass ``main_process_only=False`` to show a bar on every process.
    """
    try:
        from tqdm.auto import tqdm as _tqdm
    except ImportError as e:  # pragma: no cover - tqdm is a torch dep in-image
        raise ImportError(
            "accelerate_tpu's tqdm wrapper requires `tqdm` to be installed."
        ) from e
    if args and isinstance(args[0], bool):
        raise ValueError(
            "Passing True/False as the first argument is unsupported; use the "
            "main_process_only keyword argument instead."
        )
    from ..state import PartialState

    disable = kwargs.pop("disable", False)
    if main_process_only and not disable:
        disable = PartialState().local_process_index != 0
    return _tqdm(*args, **kwargs, disable=disable)
