"""Memory utilities.

Port of reference ``utils/memory.py``: ``find_executable_batch_size`` (:115)
— the OOM-retry decorator that halves the batch size until the function
succeeds — and ``release_memory`` (:66).  On JAX the OOM signal is
``XlaRuntimeError: RESOURCE_EXHAUSTED`` (HBM) instead of torch's
``CUDA out of memory``; device stats come from ``Device.memory_stats()``.
"""

from __future__ import annotations

import functools
import gc
import inspect
from typing import Callable, Optional

import jax


def release_memory(*objects):
    """Drop references + free compiled executables/live buffers
    (reference memory.py:66)."""
    if len(objects) == 1 and isinstance(objects[0], (list, tuple)):
        objects = list(objects[0])
    else:
        objects = list(objects)
    for i in range(len(objects)):
        objects[i] = None
    gc.collect()
    jax.clear_caches()
    return objects


def should_reduce_batch_size(exception: Exception) -> bool:
    """True for HBM/host OOM errors (reference should_reduce_batch_size
    memory.py:84 — same role, XLA error strings)."""
    statements = (
        "RESOURCE_EXHAUSTED",
        "Out of memory",
        "out of memory",
        "Resource exhausted",
        "Allocation failure",
    )
    if isinstance(exception, MemoryError):
        return True
    return isinstance(exception, Exception) and any(s in str(exception) for s in statements)


def find_executable_batch_size(
    function: Optional[Callable] = None, starting_batch_size: int = 128
):
    """Decorator: retries ``function(batch_size, ...)`` halving
    ``batch_size`` on OOM (reference memory.py:115-176)."""
    if function is None:
        return functools.partial(find_executable_batch_size, starting_batch_size=starting_batch_size)

    batch_size_holder = [starting_batch_size]

    def decorator(*args, **kwargs):
        batch_size_holder[0] = starting_batch_size
        while True:
            if batch_size_holder[0] == 0:
                raise RuntimeError("No executable batch size found, reached zero.")
            try:
                params = list(inspect.signature(function).parameters.keys())
                if len(params) < 1 or params[0] != "batch_size":
                    raise TypeError(
                        f"Batch size was passed into `{function.__name__}` as the first argument, but its "
                        f"signature is {params} — the first argument must be `batch_size`."
                    )
                return function(batch_size_holder[0], *args, **kwargs)
            except Exception as e:
                if should_reduce_batch_size(e):
                    gc.collect()
                    jax.clear_caches()
                    batch_size_holder[0] //= 2
                else:
                    raise

    return decorator


def get_device_memory_stats(device=None) -> dict:
    """HBM stats for observability (reference device memory probes,
    SURVEY §2.9 TPU-native note)."""
    device = device or jax.local_devices()[0]
    stats = device.memory_stats() or {}
    return {
        "bytes_in_use": stats.get("bytes_in_use", 0),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
        "bytes_limit": stats.get("bytes_limit", 0),
    }
