"""Lazy, cached availability probes for optional dependencies.

TPU-native analog of the reference's ``utils/imports.py`` (~60 ``is_*_available``
probes, reference utils/imports.py:1-518).  On the JAX stack the probe list is
much shorter: the heavy engines (DeepSpeed/Megatron/TE/bnb) have no meaning
here — their *capabilities* are native to XLA — so we only probe genuinely
optional integrations (trackers, torch interop, datasets).
"""

from __future__ import annotations

import importlib.metadata
import importlib.util
from functools import lru_cache


@lru_cache
def _is_package_available(pkg_name: str, metadata_name: str | None = None) -> bool:
    exists = importlib.util.find_spec(pkg_name) is not None
    if exists and metadata_name is not None:
        try:
            importlib.metadata.version(metadata_name)
        except importlib.metadata.PackageNotFoundError:
            return False
    return exists


def is_jax_available() -> bool:
    return _is_package_available("jax")


def is_flax_available() -> bool:
    return _is_package_available("flax")


def is_optax_available() -> bool:
    return _is_package_available("optax")


def is_orbax_available() -> bool:
    return _is_package_available("orbax")


def is_chex_available() -> bool:
    return _is_package_available("chex")


def is_torch_available() -> bool:
    """Torch is only used for interop (DataLoader sources, weight import)."""
    return _is_package_available("torch")


def is_transformers_available() -> bool:
    return _is_package_available("transformers")


def is_safetensors_available() -> bool:
    return _is_package_available("safetensors")


def is_datasets_available() -> bool:
    return _is_package_available("datasets")


def is_einops_available() -> bool:
    return _is_package_available("einops")


def is_numpy_available() -> bool:
    return _is_package_available("numpy")


def is_pallas_available() -> bool:
    """Pallas ships inside jax.experimental on every supported jax."""
    return _is_package_available("jax") and importlib.util.find_spec("jax.experimental.pallas") is not None


# --------------------------------------------------------------------------
# Tracker backends (reference tracking.py registers 10; we probe the same set)
# --------------------------------------------------------------------------

def is_tensorboard_available() -> bool:
    return _is_package_available("tensorboardX") or _is_package_available("tensorboard") or _is_package_available(
        "torch.utils.tensorboard"
    )


def is_wandb_available() -> bool:
    return _is_package_available("wandb")


def is_comet_ml_available() -> bool:
    return _is_package_available("comet_ml")


def is_mlflow_available() -> bool:
    return _is_package_available("mlflow")


def is_aim_available() -> bool:
    return _is_package_available("aim")


def is_clearml_available() -> bool:
    return _is_package_available("clearml")


def is_dvclive_available() -> bool:
    return _is_package_available("dvclive")


def is_swanlab_available() -> bool:
    return _is_package_available("swanlab")


def is_trackio_available() -> bool:
    return _is_package_available("trackio")


def is_rich_available() -> bool:
    return _is_package_available("rich")


def is_tqdm_available() -> bool:
    return _is_package_available("tqdm")


def is_pynvml_available() -> bool:
    return _is_package_available("pynvml")


def is_psutil_available() -> bool:
    return _is_package_available("psutil")


def is_matplotlib_available() -> bool:
    return _is_package_available("matplotlib")


# --------------------------------------------------------------------------
# Hardware probes
# --------------------------------------------------------------------------

@lru_cache
def is_tpu_available(check_device: bool = True) -> bool:
    """True when a real TPU backend is reachable through JAX."""
    if not is_jax_available():
        return False
    if not check_device:
        return True
    try:
        import jax

        return any(d.platform.startswith(("tpu", "axon")) for d in jax.devices())
    except Exception:
        return False


@lru_cache
def is_multihost_available() -> bool:
    if not is_jax_available():
        return False
    import jax

    return jax.process_count() > 1


def is_bf16_available() -> bool:
    """bf16 is native on every TPU generation we target; always true on JAX."""
    return is_jax_available()


def is_fp8_available() -> bool:
    """float8_e4m3fn / e5m2 dtypes exist in every supported jax/ml_dtypes."""
    try:
        import jax.numpy as jnp

        jnp.float8_e4m3fn  # noqa: B018
        return True
    except (ImportError, AttributeError):
        return False
