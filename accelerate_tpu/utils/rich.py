"""Rich console helpers (reference ``utils/rich.py``: traceback install
gated on availability; opt-in via ``ACCELERATE_ENABLE_RICH``, reference
``utils/imports.py:289``).

Importing this module with rich installed activates pretty tracebacks for
the current process — launcher workers opt in by exporting
``ACCELERATE_ENABLE_RICH=true`` (see ``commands/launch.py``).
"""

from .environment import parse_flag_from_env
from .imports import is_rich_available


def rich_enabled() -> bool:
    """rich is installed *and* the user opted in via env."""
    return is_rich_available() and parse_flag_from_env("ACCELERATE_ENABLE_RICH")


def install_rich_tracebacks(show_locals: bool = False) -> bool:
    """Install rich's traceback formatter; returns whether it engaged."""
    if not is_rich_available():
        return False
    from rich.traceback import install

    install(show_locals=show_locals)
    return True


def get_console():
    """A rich Console for pretty CLI output (raises if rich is missing)."""
    if not is_rich_available():
        raise ModuleNotFoundError(
            "rich is not installed; install it or unset ACCELERATE_ENABLE_RICH"
        )
    from rich.console import Console

    return Console()


if rich_enabled():  # pragma: no cover - env-dependent side effect
    install_rich_tracebacks()
