"""Enums, plugins and kwargs handlers — the declarative config surface.

TPU-native re-design of the reference's ``utils/dataclasses.py`` (3,200+ LoC of
plugins/enums, reference utils/dataclasses.py).  The big behavioral difference:
on GSPMD every parallelism strategy is a *sharding configuration of one
mechanism*, so the DeepSpeed/Megatron/FSDP plugin zoo collapses into
``ShardingPlugin``-style dataclasses that produce :class:`jax.sharding`
annotations instead of wrapping engines.

Every plugin reads ``ACCELERATE_*`` environment defaults in ``__post_init__``,
matching the reference's env-as-config-transport contract
(reference utils/dataclasses.py:1217-1260, parallelism_config.py:274-289).
"""

from __future__ import annotations

import copy
import dataclasses
import enum
import functools
import os
import warnings
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Callable, Iterable, Optional

from .environment import parse_flag_from_env


class EnumWithContains(enum.EnumMeta):
    """Metaclass so ``"bf16" in MixedPrecisionType`` works (reference :585)."""

    def __contains__(cls, item):
        try:
            cls(item)
        except ValueError:
            return False
        return True


class BaseEnum(str, enum.Enum, metaclass=EnumWithContains):
    def __str__(self):
        return self.value

    @classmethod
    def list(cls):
        return list(map(str, cls))


class DistributedType(BaseEnum):
    """Topology of the current run (reference dataclasses.py:613-645).

    The reference enumerates one value per engine (DDP/FSDP/DeepSpeed/...);
    here strategies are sharding configs, so the enum only describes the
    *process/device topology*.
    """

    NO = "NO"                    # single device
    MULTI_DEVICE = "MULTI_DEVICE"  # one process, many local devices (single host)
    MULTI_HOST = "MULTI_HOST"    # jax.distributed world, one process per host


class MixedPrecisionType(BaseEnum):
    """reference dataclasses.py:647 — 'no'|'fp16'|'bf16'|'fp8'."""

    NO = "no"
    FP16 = "fp16"
    BF16 = "bf16"
    FP8 = "fp8"


class ShardingStrategy(BaseEnum):
    """How parameters/optimizer state are laid out across ``dp_shard``.

    Capability-parity with reference FSDP ``sharding_strategy``
    (dataclasses.py:1566) and DeepSpeed ``zero_stage`` (:1164):
    NO_SHARD≅DDP/stage-0, SHARD_GRAD_OP≅ZeRO-2, FULL_SHARD≅ZeRO-3/FSDP,
    HYBRID_SHARD≅HSDP (shard intra-slice over ICI, replicate over DCN).
    """

    NO_SHARD = "NO_SHARD"
    SHARD_GRAD_OP = "SHARD_GRAD_OP"
    FULL_SHARD = "FULL_SHARD"
    HYBRID_SHARD = "HYBRID_SHARD"


class RNGType(BaseEnum):
    """Which RNG streams to synchronize/checkpoint (reference :600)."""

    JAX = "jax"
    NUMPY = "numpy"
    PYTHON = "python"
    TORCH = "torch"
    GENERATOR = "generator"


class CheckpointFormat(BaseEnum):
    """FULL = merged single-host arrays; SHARDED = per-shard OCDBT/tensorstore
    (capability parity with reference ``StateDictType`` full/sharded,
    dataclasses.py:1601)."""

    FULL = "FULL_STATE"
    SHARDED = "SHARDED_STATE"


class LoggerType(BaseEnum):
    ALL = "all"
    TENSORBOARD = "tensorboard"
    WANDB = "wandb"
    COMETML = "comet_ml"
    MLFLOW = "mlflow"
    AIM = "aim"
    CLEARML = "clearml"
    DVCLIVE = "dvclive"
    SWANLAB = "swanlab"
    TRACKIO = "trackio"


class FP8Format(BaseEnum):
    """FP8 dtype pairing for matmul inputs (TE 'HYBRID' recipe analog,
    reference dataclasses.py:359-438)."""

    E4M3 = "E4M3"
    HYBRID = "HYBRID"  # e4m3 fwd, e5m2 bwd


# ---------------------------------------------------------------------------
# Kwargs handlers (reference dataclasses.py:68-560)
# ---------------------------------------------------------------------------


@dataclass
class KwargsHandler:
    """Base for objects that tweak a subsystem's kwargs (reference :68)."""

    def to_dict(self):
        return copy.deepcopy(self.__dict__)

    def to_kwargs(self):
        """Only the fields that differ from the default instance."""
        default_dict = self.__class__().to_dict()
        this_dict = self.to_dict()
        return {k: v for k, v in this_dict.items() if default_dict[k] != v}


@dataclass
class AutocastKwargs(KwargsHandler):
    """Compute-dtype policy knobs (reference AutocastKwargs :113)."""

    enabled: bool = True
    cache_enabled: bool = True  # kept for API parity; XLA handles caching


@dataclass
class GradSyncKwargs(KwargsHandler):
    """Analog of ``DistributedDataParallelKwargs`` (reference :155).

    On GSPMD the all-reduce is compiler-inserted; the surviving knobs control
    *how* gradients cross ``dp``: reduction dtype compression (the DDP comm
    hook analog, reference DDPCommunicationHookType :134) and bucketing hints.
    """

    comm_dtype: Optional[str] = None  # None | "bf16" | "fp16" — grads cast before psum
    # mean (DDP semantics) vs sum across dp: GSPMD's implicit reduction
    # yields the global-mean grad, so False rescales the tree by the dp
    # world size before clip/update (honored in both the dense and the
    # powersgd train-step paths)
    average_grads: bool = True
    # None: grads carry master (fp32) width through clip/update (torch-DDP
    # semantics).  "bf16": differentiate wrt the compute-width param copy so
    # the whole grad tree stays bf16 — halves grad HBM; the per-leaf optimizer
    # math still promotes against its fp32 state (MaxText-style).  Requires
    # mixed_precision="bf16" (fp16 needs fp32 unscaling, see prepare_train_step).
    grad_dtype: Optional[str] = None
    # "powersgd": error-feedback low-rank compression of the dp-axis grad
    # reduction (reference DDPCommunicationHookType.POWER_SGD analog; engine:
    # parallel/powersgd.py).  ``rank`` is the factor rank — wire bytes per
    # eligible [n, m] leaf drop from n*m to rank*(n+m) (the P psum moves
    # n*rank floats, the Q psum m*rank — matching wire_bytes_report).
    compression: Optional[str] = None
    rank: int = 4
    # Hierarchical ICI->DCN reduction (parallel/hierarchical.py) for meshes
    # with a non-trivial `dcn` (cross-slice) axis: reduce-scatter inside the
    # slice over ICI, all-reduce only the sharded slab over DCN, all-gather
    # back — replacing the flat joint-axis psum whose DCN hop would carry
    # ici_size redundant full-gradient copies.  None = auto (engage when the
    # mesh has dcn > 1 and the config is compatible: pure data parallelism
    # with replicated params, like `compression`); False = never (flat psum
    # even across slices); True = require (raise on incompatible configs
    # instead of falling back).
    hierarchical: Optional[bool] = None
    # "powersgd": compress the hierarchical path's cross-slice (DCN) hop —
    # each device's slab crosses as its rank-`rank` factors with per-device
    # error feedback.  Requires the hierarchical path (dcn axis present and
    # not disabled); ICI legs stay uncompressed (they are ~7x cheaper per
    # byte, and the EF residual would have to survive two codecs).
    dcn_compression: Optional[str] = None


@dataclass
class InitProcessGroupKwargs(KwargsHandler):
    """Coordinator init knobs (reference InitProcessGroupKwargs :273)."""

    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    timeout: timedelta = field(default_factory=lambda: timedelta(seconds=1800))
    initialization_timeout: Optional[int] = None


@dataclass
class ProfileKwargs(KwargsHandler):
    """Declarative profiler config → a step-scheduled ``jax.profiler`` trace
    (reference ProfileKwargs :484 builds torch.profiler.profile; engine:
    ``utils/profiler.py``).

    ``wait``/``warmup``/``active`` define the per-cycle step schedule —
    each cycle traces exactly steps ``[wait+warmup, wait+warmup+active)``
    as counted by ``profiler.step()`` calls; ``repeat`` bounds the number
    of cycles (0 = cycle until the block ends, each cycle under
    ``cycle_<i>/``).  When **no schedule is given** (all of
    ``wait``/``warmup``/``repeat`` at 0 and ``active`` left at ``None``)
    the whole ``with`` block is ONE continuous trace window even if
    ``profiler.step()`` is called — the reference's no-schedule
    ``torch.profiler`` behavior — instead of a start/stop pair per step.
    ``profile_memory`` reports device memory deltas over
    the active window in ``profiler.summary['memory']``; ``with_flops``
    accumulates :meth:`TPUProfiler.flops_estimate` results into
    ``summary['flops']``.  ``on_trace_ready(trace_dir)`` fires at the end
    of every cycle.
    """

    wait: int = 0
    warmup: int = 0
    # None = "no schedule declared" (continuous window); an explicit int
    # turns on the per-cycle schedule
    active: Optional[int] = None
    repeat: int = 0
    output_trace_dir: Optional[str] = None
    with_flops: bool = False
    profile_memory: bool = False
    create_perfetto_link: bool = False
    on_trace_ready: Optional[Callable] = None

    def __post_init__(self):
        if self.active is not None and self.active < 1:
            raise ValueError(
                f"ProfileKwargs.active must be >= 1 when set (got {self.active}); "
                "leave it at None for a single continuous trace window"
            )

    def has_schedule(self) -> bool:
        return bool(self.wait or self.warmup or self.repeat or self.active is not None)


@dataclass
class SeedWorkersKwargs(KwargsHandler):
    """Dataloader worker seeding (DataLoaderConfiguration companion)."""

    base_seed: int = 0


# ---------------------------------------------------------------------------
# Plugins (the strategy config surface)
# ---------------------------------------------------------------------------


@dataclass
class GradientAccumulationPlugin(KwargsHandler):
    """reference dataclasses.py:85-111 — plus TPU-native microbatch mode.

    ``in_step`` folds the accumulation loop into the jitted train step as a
    ``lax.scan`` over microbatches (TPU idiom: one compilation, compiler
    overlaps); ``across_steps`` keeps the reference's python-loop semantics
    (grad buffer carried in TrainState between step calls).
    """

    num_steps: int = 1
    adjust_scheduler: bool = True
    sync_with_dataloader: bool = True
    sync_each_batch: bool = False
    mode: str = "in_step"  # "in_step" | "across_steps"

    def __post_init__(self):
        if self.mode not in ("in_step", "across_steps"):
            raise ValueError(f"invalid gradient accumulation mode {self.mode!r}")
        if self.num_steps < 1:
            raise ValueError("gradient_accumulation num_steps must be >= 1")


@dataclass
class FullyShardedDataParallelPlugin(KwargsHandler):
    """FSDP/ZeRO-as-sharding-config (reference FSDP plugin dataclasses.py:1566,
    DeepSpeedPlugin :1113).

    Under GSPMD the whole plugin compiles down to: which mesh axes shard the
    parameter/optimizer pytrees, above what size, and what happens after
    forward.  ``state_dict_type`` controls checkpoint materialization.
    """

    sharding_strategy: Optional[ShardingStrategy] = None  # default: env or FULL_SHARD
    reshard_after_forward: bool = True      # ZeRO-3 vs ZeRO-2 behavior
    min_weight_size: int = 2**12            # auto-wrap-policy analog: don't shard tiny params
    state_dict_type: CheckpointFormat = CheckpointFormat.SHARDED
    cpu_offload: Optional[bool] = None      # ZeRO-offload: optimizer state in pinned host
                                            # memory, update as XLA host compute
    offload_params: Optional[bool] = None   # also keep the fp32 master params host-side
                                            # (default: follows cpu_offload, matching FSDP
                                            # CPUOffload(offload_params=True) semantics)
    host_update_chunk_gib: Optional[float] = None
                                            # split the host-compute optimizer update into
                                            # per-leaf-group regions of at most this many
                                            # GiB of fp32 params each, bounding the host's
                                            # transient working set (upcasts + moment temps)
                                            # — what lets adamw run at 7B on one chip.
                                            # Requires a per-leaf-independent optimizer
                                            # chain (adamw/lion/sgd/...; NOT
                                            # clip_by_global_norm inside tx — use the
                                            # train step's max_grad_norm instead).
                                            # None = one monolithic region.
    host_update_pipeline: Optional[bool] = None
                                            # 3-stage software pipeline over the chunked
                                            # host update (ops/streaming.py): chunk k+1's
                                            # grads stage D2H and chunk k-1's outputs
                                            # write back while chunk k's host region runs
                                            # (only the update regions ride the
                                            # serialization token chain).  Bitwise-
                                            # identical to the serial schedule — same
                                            # chunk boundaries, same SR hash streams
                                            # (tests/test_offload.py).  Default True; env
                                            # ACCELERATE_HOST_UPDATE_PIPELINE=false
                                            # restores the fully serialized A/B baseline.
                                            # Only consulted when host_update_chunk_gib
                                            # is set.
    int8_state_block_size: Optional[int] = None
                                            # per-block fp32-scale granularity for the
                                            # -sr8 int8 optimizer-state recipes
                                            # (ops/int8_state.py; smaller blocks = finer
                                            # scales = lower quant noise, more scale
                                            # bytes: 8/block B/param/moment of extra host
                                            # traffic).  Config transport only — the
                                            # recipes are built through
                                            # optimizer.make_optimizer(name,
                                            # block_size=...) or
                                            # Accelerator.prepare_optimizer("<name>"),
                                            # which reads this knob.  Default 128 (one
                                            # TPU lane width); env
                                            # ACCELERATE_INT8_STATE_BLOCK.
    collective_matmul: Optional[str] = None
                                            # ring collective-matmul for the TP/SP hot
                                            # path (ops/collective_matmul.py): "off"
                                            # leaves the monolithic GSPMD all-gather/
                                            # reduce-scatter, "on"/"ring" decomposes
                                            # them into ppermute ring schedules that
                                            # hide ICI hops under the partial matmuls,
                                            # "bidir" halves ring depth with opposing
                                            # half-rings.  Trace-time: the Accelerator
                                            # installs it as the ambient mode at
                                            # construction.  Default "off"; env
                                            # ACCELERATE_COLLECTIVE_MATMUL.
    activation_checkpointing: Optional[bool] = None  # jax.checkpoint on remat-policy blocks
    remat_policy: str = "nothing_saveable"  # name of a jax.checkpoint policy
    use_orig_params: bool = True            # API parity; always true under GSPMD

    def __post_init__(self):
        # Env vars supply *defaults* only — an explicit argument always wins
        # (reference plugin __post_init__ contract, dataclasses.py:1217-1260).
        env = os.environ
        if self.sharding_strategy is None:
            self.sharding_strategy = ShardingStrategy(env.get("FSDP_SHARDING_STRATEGY", "FULL_SHARD"))
        elif isinstance(self.sharding_strategy, str):
            self.sharding_strategy = ShardingStrategy(self.sharding_strategy)
        if isinstance(self.state_dict_type, str):
            self.state_dict_type = CheckpointFormat(self.state_dict_type)
        if self.cpu_offload is None:
            self.cpu_offload = parse_flag_from_env("FSDP_OFFLOAD_PARAMS")
        if self.offload_params is None:
            self.offload_params = self.cpu_offload
        if self.host_update_pipeline is None:
            self.host_update_pipeline = parse_flag_from_env(
                "ACCELERATE_HOST_UPDATE_PIPELINE", default=True
            )
        if self.int8_state_block_size is None:
            self.int8_state_block_size = int(env.get("ACCELERATE_INT8_STATE_BLOCK", 128))
        if self.int8_state_block_size < 1:
            raise ValueError(
                f"int8_state_block_size must be >= 1, got {self.int8_state_block_size}"
            )
        if self.collective_matmul is None:
            self.collective_matmul = env.get("ACCELERATE_COLLECTIVE_MATMUL", "off")
        # normalize through the engine's canonical table (raises on junk)
        from ..ops.collective_matmul import normalize_mode

        self.collective_matmul = normalize_mode(self.collective_matmul)
        if self.activation_checkpointing is None:
            self.activation_checkpointing = parse_flag_from_env("FSDP_ACTIVATION_CHECKPOINTING")


@dataclass
class ResiliencePlugin(KwargsHandler):
    """Preemption-safe training knobs (engine: ``accelerate_tpu/resilience/``;
    CheckFreq/Varuna discipline — see docs/resilience.md).

    ``ACCELERATE_RESILIENCE=1`` arms the whole layer by default (NaN guard +
    preemption handling); individual ``ACCELERATE_NAN_GUARD`` /
    ``ACCELERATE_PREEMPTION`` flags override per-feature.  Checkpoint
    verification and bounded I/O retry are on regardless — they cost nothing
    on the hot path and are what the corruption-fallback contract rests on.
    """

    nan_guard: Optional[bool] = None        # lax-select skip-step on non-finite
                                            # loss/grad-norm inside the jitted
                                            # step; counters persist in
                                            # TrainState.guard_state.  Default:
                                            # env ACCELERATE_NAN_GUARD, else
                                            # ACCELERATE_RESILIENCE.
    max_consecutive_nan_skips: int = 3      # abort (NanGuardAbort) after this
                                            # many consecutive skipped steps;
                                            # 0 disables the abort only — the
                                            # armed guard always fetches its
                                            # skip scalar per step so goodput/
                                            # bench counters stay truthful.
    handle_preemption: Optional[bool] = None  # install the SIGTERM-at-step-
                                            # boundary handler at Accelerator
                                            # construction.  Default: env
                                            # ACCELERATE_PREEMPTION, else
                                            # ACCELERATE_RESILIENCE.
    preemption_signals: tuple = ("SIGTERM",)
    preemption_check_every: int = 1         # multi-process: agree the any-rank
                                            # stop via a tiny host-blocking
                                            # all-gather every N steps.  1 =
                                            # stop at the very next boundary;
                                            # raise it on long runs to keep
                                            # the step pipeline async (the
                                            # stop then lands within N steps
                                            # of the notice — budget against
                                            # the preemption grace window).
    emergency_checkpoint: bool = True       # write a checkpoint at the stop
                                            # boundary before exiting
    resume_exit_code: int = 75              # EX_TEMPFAIL: "re-run me" — what
                                            # supervisors key restarts on
    verify_checkpoints: bool = True         # manifest (sizes+crc32) on save,
                                            # verify + valid-fallback on load
    io_retries: int = 3                     # bounded retry budget for
                                            # checkpoint I/O + host transfers
    io_backoff_s: float = 0.05              # first backoff; doubles per retry
    peer_snapshot_every: int = 0            # >0: CheckFreq-style host snapshot
                                            # of the TrainState every N steps,
                                            # replicated to the buddy rank's
                                            # host RAM (resilience/peer_ckpt) —
                                            # the fast rung of the recovery
                                            # ladder.  0 disables.
    peer_snapshot_keep: int = 2             # newest waves kept per side
                                            # (local + buddy copies)

    def __post_init__(self):
        armed = parse_flag_from_env("ACCELERATE_RESILIENCE")
        if self.nan_guard is None:
            self.nan_guard = parse_flag_from_env("ACCELERATE_NAN_GUARD", default=armed)
        if self.handle_preemption is None:
            self.handle_preemption = parse_flag_from_env(
                "ACCELERATE_PREEMPTION", default=armed
            )
        if isinstance(self.preemption_signals, str):
            self.preemption_signals = (self.preemption_signals,)
        else:
            self.preemption_signals = tuple(self.preemption_signals)
        if self.max_consecutive_nan_skips < 0:
            raise ValueError(
                "max_consecutive_nan_skips must be >= 0 (0 disables the "
                f"abort), got {self.max_consecutive_nan_skips}"
            )
        if self.io_retries < 0:
            raise ValueError(f"io_retries must be >= 0, got {self.io_retries}")
        if self.peer_snapshot_every < 0:
            raise ValueError(
                "peer_snapshot_every must be >= 0 (0 disables peer "
                f"snapshots), got {self.peer_snapshot_every}"
            )
        if self.peer_snapshot_keep < 1:
            raise ValueError(
                f"peer_snapshot_keep must be >= 1, got {self.peer_snapshot_keep}"
            )


@dataclass
class ServingPlugin(KwargsHandler):
    """Serving-core knobs (engine: ``accelerate_tpu/serving/`` — paged KV
    cache + continuous batching; see docs/serving.md).

    Geometry defaults target the CPU test mesh; production configs size the
    pool off the predicted KV-HBM ladder
    (``serving.paged_cache.kv_pool_accounting``).  Every knob reads an
    ``ACCELERATE_SERVE_*`` environment default in ``__post_init__`` (explicit
    arguments always win — the reference plugin contract).
    """

    num_slots: Optional[int] = None          # concurrent decode lanes
                                             # (env ACCELERATE_SERVE_SLOTS, default 8)
    page_size: Optional[int] = None          # tokens per KV page
                                             # (env ACCELERATE_SERVE_PAGE_SIZE, default 16)
    pages_per_slot: Optional[int] = None     # block-table width = per-sequence KV
                                             # ceiling in pages (env
                                             # ACCELERATE_SERVE_PAGES_PER_SLOT, default 8)
    num_pages: Optional[int] = None          # pool size; default provisions ~half the
                                             # worst case (num_slots * pages_per_slot
                                             # // 2) — continuous batching's bet that
                                             # sequences rarely all peak together
                                             # (env ACCELERATE_SERVE_PAGES)
    prefill_chunk: Optional[int] = None      # max prompt tokens prefilled per engine
                                             # tick (chunked prefill; env
                                             # ACCELERATE_SERVE_PREFILL_CHUNK, default 64)
    prefill_buckets: Optional[tuple] = None  # pad-to-bucket widths for the jitted
                                             # prefill step — one compile per bucket,
                                             # never a recompile mid-traffic.  Default:
                                             # powers of two from 16 up to prefill_chunk.
    decode_kernel: str = ""                  # "auto" (paged Pallas kernel on TPU,
                                             # native gather elsewhere) | "native" |
                                             # "flash" (env ACCELERATE_SERVE_KERNEL)
    speculate: str = ""                      # speculative multi-token decode:
                                             # "off" | "ngram" (prompt-lookup
                                             # self-drafting) | "draft" (small
                                             # draft model — pass draft_model/
                                             # draft_params to the engine).
                                             # env ACCELERATE_SERVE_SPECULATE
                                             # ("on"/"1" mean "ngram"), default off
    speculate_k: Optional[int] = None        # draft tokens proposed per verify
                                             # pass (env
                                             # ACCELERATE_SERVE_SPECULATE_K,
                                             # default 4)
    speculate_buckets: Optional[tuple] = None  # verify-program width ladder (the
                                             # program compiles once per bucket
                                             # at width bucket+1, never
                                             # mid-traffic).  Default:
                                             # (speculate_k,)
    speculate_draft_window: Optional[int] = None  # draft-model context window
                                             # (the fixed-shape windowed forward
                                             # the "draft" provider re-runs per
                                             # draft token; env
                                             # ACCELERATE_SERVE_SPECULATE_DRAFT,
                                             # default 32)
    prefix_cache: str = ""                   # content-addressed COW prefix
                                             # reuse (serving/prefix_cache.py):
                                             # "off" | "on" — full prompt-prefix
                                             # pages hash-match against shared
                                             # refcounted physical pages, chunked
                                             # prefill starts at the hit
                                             # boundary.  env
                                             # ACCELERATE_SERVE_PREFIX_CACHE
                                             # ("1"/"on" mean on), default off
    max_queue: Optional[int] = None          # bounded waiting line: beyond this
                                             # depth the deterministic shed
                                             # policy drops requests (0 =
                                             # unbounded; env
                                             # ACCELERATE_SERVE_MAX_QUEUE)
    kv_shed_watermark: Optional[float] = None  # predicted KV pressure (used +
                                             # queued prompt demand, as a pool
                                             # fraction) beyond which queued
                                             # requests shed (0.0 = off; env
                                             # ACCELERATE_SERVE_KV_WATERMARK)
    default_deadline_ticks: Optional[int] = None  # deadline (engine ticks from
                                             # arrival) stamped on requests that
                                             # carry none (0 = no deadline; env
                                             # ACCELERATE_SERVE_DEADLINE)
    ladder_reserve_frac: Optional[float] = None  # free-page reserve admission
                                             # must keep once the degradation
                                             # ladder tightens (fraction of the
                                             # pool; env
                                             # ACCELERATE_SERVE_LADDER_RESERVE,
                                             # default 0.125)
    kv_dtype: str = ""                       # KV page storage dtype: "bf16"
                                             # (model dtype, dense pages) |
                                             # "int8" | "fp8" — quantized pages
                                             # store 1-byte codes + per-(kv-
                                             # head, page) scales, ~1.9-2x the
                                             # token capacity per HBM byte
                                             # (serving/paged_cache.py
                                             # kv_pool_accounting ladder).  env
                                             # ACCELERATE_SERVE_KV_DTYPE,
                                             # default bf16

    def __post_init__(self):
        env = os.environ
        if self.num_slots is None:
            self.num_slots = int(env.get("ACCELERATE_SERVE_SLOTS", 8))
        if self.page_size is None:
            self.page_size = int(env.get("ACCELERATE_SERVE_PAGE_SIZE", 16))
        if self.pages_per_slot is None:
            self.pages_per_slot = int(env.get("ACCELERATE_SERVE_PAGES_PER_SLOT", 8))
        if self.num_pages is None:
            env_pages = env.get("ACCELERATE_SERVE_PAGES")
            self.num_pages = (int(env_pages) if env_pages
                              else max(self.pages_per_slot,
                                       self.num_slots * self.pages_per_slot // 2))
        if self.prefill_chunk is None:
            self.prefill_chunk = int(env.get("ACCELERATE_SERVE_PREFILL_CHUNK", 64))
        if not self.decode_kernel:
            self.decode_kernel = env.get("ACCELERATE_SERVE_KERNEL", "auto")
        if self.decode_kernel not in ("auto", "native", "flash"):
            raise ValueError(
                f"decode_kernel must be 'auto', 'native' or 'flash', got "
                f"{self.decode_kernel!r}"
            )
        if isinstance(self.speculate, bool):
            # the generate_paged(speculate=True) convention works here too
            self.speculate = "ngram" if self.speculate else "off"
        if not self.speculate:
            self.speculate = env.get("ACCELERATE_SERVE_SPECULATE", "off")
        self.speculate = {"1": "ngram", "on": "ngram", "0": "off",
                          "": "off"}.get(self.speculate.lower(),
                                         self.speculate.lower())
        if self.speculate not in ("off", "ngram", "draft"):
            raise ValueError(
                f"speculate must be 'off', 'ngram' or 'draft' (or 'on'/'1' "
                f"for ngram), got {self.speculate!r}"
            )
        if self.speculate_k is None:
            self.speculate_k = int(env.get("ACCELERATE_SERVE_SPECULATE_K", 4))
        if self.speculate_draft_window is None:
            self.speculate_draft_window = int(
                env.get("ACCELERATE_SERVE_SPECULATE_DRAFT", 32)
            )
        if self.speculate != "off" and self.speculate_k < 1:
            raise ValueError(
                f"speculate_k must be >= 1 with speculation on, got "
                f"{self.speculate_k}"
            )
        if self.speculate_buckets is None:
            self.speculate_buckets = (self.speculate_k,)
        else:
            self.speculate_buckets = tuple(
                sorted(int(b) for b in self.speculate_buckets)
            )
            if not self.speculate_buckets or \
                    self.speculate_buckets[-1] < self.speculate_k:
                raise ValueError(
                    f"speculate_buckets {self.speculate_buckets} must include "
                    f"a bucket >= speculate_k={self.speculate_k}"
                )
            if self.speculate_buckets[0] < 1:
                raise ValueError("speculate_buckets entries must be >= 1")
        if isinstance(self.prefix_cache, bool):
            self.prefix_cache = "on" if self.prefix_cache else "off"
        if not self.prefix_cache:
            self.prefix_cache = os.environ.get(
                "ACCELERATE_SERVE_PREFIX_CACHE", "off"
            )
        self.prefix_cache = {"1": "on", "true": "on", "0": "off",
                             "false": "off", "": "off"}.get(
            self.prefix_cache.lower(), self.prefix_cache.lower()
        )
        if self.prefix_cache not in ("off", "on"):
            raise ValueError(
                f"prefix_cache must be 'off' or 'on' (or '1'/'true' for on), "
                f"got {self.prefix_cache!r}"
            )
        if self.max_queue is None:
            self.max_queue = int(env.get("ACCELERATE_SERVE_MAX_QUEUE", 0))
        if self.kv_shed_watermark is None:
            self.kv_shed_watermark = float(
                env.get("ACCELERATE_SERVE_KV_WATERMARK", 0.0)
            )
        if self.default_deadline_ticks is None:
            self.default_deadline_ticks = int(env.get("ACCELERATE_SERVE_DEADLINE", 0))
        if self.ladder_reserve_frac is None:
            self.ladder_reserve_frac = float(
                env.get("ACCELERATE_SERVE_LADDER_RESERVE", 0.125)
            )
        if self.max_queue < 0:
            raise ValueError(f"max_queue must be >= 0 (0 = unbounded), got {self.max_queue}")
        if not 0.0 <= self.kv_shed_watermark <= 1.0:
            raise ValueError(
                f"kv_shed_watermark must be in [0, 1] (0 = off), got "
                f"{self.kv_shed_watermark}"
            )
        if self.default_deadline_ticks < 0:
            raise ValueError(
                f"default_deadline_ticks must be >= 0 (0 = none), got "
                f"{self.default_deadline_ticks}"
            )
        if not 0.0 < self.ladder_reserve_frac < 1.0:
            raise ValueError(
                f"ladder_reserve_frac must be in (0, 1), got "
                f"{self.ladder_reserve_frac}"
            )
        for name in ("num_slots", "page_size", "pages_per_slot", "num_pages",
                     "prefill_chunk"):
            if getattr(self, name) < 1:
                raise ValueError(f"ServingPlugin.{name} must be >= 1, got {getattr(self, name)}")
        if self.num_pages < self.pages_per_slot:
            raise ValueError(
                f"num_pages={self.num_pages} must cover at least one sequence "
                f"(pages_per_slot={self.pages_per_slot})"
            )
        if not self.kv_dtype:
            self.kv_dtype = env.get("ACCELERATE_SERVE_KV_DTYPE", "bf16")
        self.kv_dtype = self.kv_dtype.lower()
        if self.kv_dtype not in ("bf16", "int8", "fp8"):
            raise ValueError(
                f"kv_dtype must be 'bf16', 'int8' or 'fp8', got "
                f"{self.kv_dtype!r}"
            )
        if self.prefill_buckets is None:
            buckets, b = [], 16
            while b < self.prefill_chunk:
                buckets.append(b)
                b *= 2
            buckets.append(self.prefill_chunk)
            self.prefill_buckets = tuple(buckets)
        else:
            self.prefill_buckets = tuple(sorted(int(b) for b in self.prefill_buckets))
            if not self.prefill_buckets or self.prefill_buckets[-1] < self.prefill_chunk:
                raise ValueError(
                    f"prefill_buckets {self.prefill_buckets} must include a bucket "
                    f">= prefill_chunk={self.prefill_chunk}"
                )


@dataclass
class LoraPlugin(KwargsHandler):
    """Multi-tenant batched-LoRA knobs (engine:
    ``accelerate_tpu/serving/adapters.py`` + ``ops/lora.py`` — see the
    multi-tenant section of docs/serving.md).

    One base model serves/fine-tunes thousands of LoRA adapters: a
    fixed-size **device pool** holds the hot adapters as stacked A/B
    factors (slot 0 reserved for the null adapter = base model), cold
    adapters hot-swap in from host memmaps, and every batch row routes
    through its adapter by id in ONE gathered einsum (S-LoRA/BGMV
    discipline — no recompile per tenant mix).  Every knob reads an
    ``ACCELERATE_LORA_*`` environment default in ``__post_init__``
    (explicit arguments win — the plugin contract).
    """

    rank: Optional[int] = None               # LoRA rank r
                                             # (env ACCELERATE_LORA_RANK, default 8)
    alpha: Optional[float] = None            # scaling numerator; alpha/rank is folded
                                             # into stored B factors at adapter
                                             # creation (env ACCELERATE_LORA_ALPHA,
                                             # default 16.0)
    pool_slots: Optional[int] = None         # device-resident adapters (excl. the
                                             # null slot) — the hot-swap LRU pool
                                             # size (env ACCELERATE_LORA_POOL_SLOTS,
                                             # default 4)
    targets: Optional[tuple] = None          # module names that carry adapters
                                             # (env ACCELERATE_LORA_TARGETS,
                                             # comma-separated; default q_proj,v_proj)
    kernel: str = ""                         # "auto" (Pallas BGMV gather-matmul on
                                             # TPU T=1 decode, gathered einsum
                                             # elsewhere) | "native" | "bgmv"
                                             # (env ACCELERATE_LORA_KERNEL)
    max_bypass_age: Optional[int] = None     # admission fairness bound: how many
                                             # engine ticks a head-of-line request
                                             # blocked on an adapter swap tolerates
                                             # younger zero-swap requests admitting
                                             # past it before admission holds the
                                             # line (env ACCELERATE_LORA_BYPASS_AGE,
                                             # default 16; 0 = strict FIFO)
    optimizer: str = ""                      # make_optimizer recipe for per-adapter
                                             # fine-tuning state — the int8-SR
                                             # recipes keep per-tenant state tiny
                                             # (env ACCELERATE_LORA_OPTIMIZER,
                                             # default lion-sr8)

    def __post_init__(self):
        env = os.environ
        if self.rank is None:
            self.rank = int(env.get("ACCELERATE_LORA_RANK", 8))
        if self.alpha is None:
            self.alpha = float(env.get("ACCELERATE_LORA_ALPHA", 16.0))
        if self.pool_slots is None:
            self.pool_slots = int(env.get("ACCELERATE_LORA_POOL_SLOTS", 4))
        if self.targets is None:
            raw = env.get("ACCELERATE_LORA_TARGETS", "q_proj,v_proj")
            self.targets = tuple(t.strip() for t in raw.split(",") if t.strip())
        elif isinstance(self.targets, str):
            self.targets = tuple(t.strip() for t in self.targets.split(",") if t.strip())
        else:
            self.targets = tuple(self.targets)
        if not self.kernel:
            self.kernel = env.get("ACCELERATE_LORA_KERNEL", "auto")
        from ..ops.lora import normalize_lora_kernel

        self.kernel = normalize_lora_kernel(self.kernel)
        if self.max_bypass_age is None:
            self.max_bypass_age = int(env.get("ACCELERATE_LORA_BYPASS_AGE", 16))
        if not self.optimizer:
            self.optimizer = env.get("ACCELERATE_LORA_OPTIMIZER", "lion-sr8")
        if self.rank < 1:
            raise ValueError(f"LoraPlugin.rank must be >= 1, got {self.rank}")
        if self.alpha <= 0:
            raise ValueError(f"LoraPlugin.alpha must be > 0, got {self.alpha}")
        if self.pool_slots < 1:
            raise ValueError(
                f"LoraPlugin.pool_slots must be >= 1, got {self.pool_slots}"
            )
        if self.max_bypass_age < 0:
            raise ValueError(
                f"LoraPlugin.max_bypass_age must be >= 0, got {self.max_bypass_age}"
            )
        if not self.targets:
            raise ValueError("LoraPlugin.targets must name at least one module")


@dataclass
class PreflightConfig(KwargsHandler):
    """Deploy-preflight knobs (``commands/preflight.py`` — AOT-compile every
    production program and audit the executables; see the "Deploy
    preflight" section of docs/static_analysis.md).

    Every knob reads an ``ACCELERATE_PREFLIGHT_*`` environment default in
    ``__post_init__`` (explicit arguments win — the plugin contract).
    """

    hbm_gb: Optional[float] = None           # HBM budget for GL302; None = use the
                                             # backend's measured bytes_limit (CPU
                                             # reports none -> GL302 skipped)
                                             # (env ACCELERATE_PREFLIGHT_HBM_GB)
    donation_slack_bytes: int = -1           # non-aliased donated bytes tolerated
                                             # before GL301 (scalar counters XLA
                                             # reasonably declines; default 1024,
                                             # env ACCELERATE_PREFLIGHT_DONATION_SLACK)
    fail_on: str = ""                        # lowest severity that fails the run
                                             # ("error" | "warning" | "info"; env
                                             # ACCELERATE_PREFLIGHT_FAIL_ON, default
                                             # error — GL301/GL302 are errors)
    optimizer: str = ""                      # optimizer recipe for the train-step
                                             # program (env
                                             # ACCELERATE_PREFLIGHT_OPTIMIZER,
                                             # default lion)

    def __post_init__(self):
        env = os.environ
        if self.hbm_gb is None:
            raw = env.get("ACCELERATE_PREFLIGHT_HBM_GB")
            self.hbm_gb = float(raw) if raw else None
        if self.donation_slack_bytes < 0:
            self.donation_slack_bytes = int(
                env.get("ACCELERATE_PREFLIGHT_DONATION_SLACK", 1024)
            )
        if not self.fail_on:
            self.fail_on = env.get("ACCELERATE_PREFLIGHT_FAIL_ON", "error")
        if self.fail_on not in ("error", "warning", "info"):
            raise ValueError(
                f"PreflightConfig.fail_on must be 'error', 'warning' or "
                f"'info', got {self.fail_on!r}"
            )
        if not self.optimizer:
            self.optimizer = env.get("ACCELERATE_PREFLIGHT_OPTIMIZER", "lion")
        if self.hbm_gb is not None and self.hbm_gb <= 0:
            raise ValueError(f"PreflightConfig.hbm_gb must be > 0, got {self.hbm_gb}")


@dataclass
class TelemetryPlugin(KwargsHandler):
    """Unified-telemetry knobs (engine: ``accelerate_tpu/telemetry/`` —
    twin registry, request trace spans, training timeline, SLO monitors;
    see docs/observability.md).

    Telemetry is host-side only: on or off, serving tokens and training
    loss are bitwise identical and no new program compiles (pinned by
    tests and the multichip dryrun ``_telemetry_leg``); the measured
    recording cost is reported as ``telemetry_overhead_frac``.  Every knob
    reads an ``ACCELERATE_TELEMETRY*`` environment default in
    ``__post_init__`` (explicit arguments win — the plugin contract).
    """

    enabled: Optional[bool] = None           # master switch: arms timeline +
                                             # request tracing defaults (env
                                             # ACCELERATE_TELEMETRY, default off)
    trace_requests: Optional[bool] = None    # per-request lifecycle spans on the
                                             # serving engine (env
                                             # ACCELERATE_TELEMETRY_TRACE_REQUESTS,
                                             # else `enabled`)
    timeline: Optional[bool] = None          # training step timeline on the
                                             # Accelerator (env
                                             # ACCELERATE_TELEMETRY_TIMELINE,
                                             # else `enabled`)
    ring_capacity: Optional[int] = None      # span ring-buffer size per recorder
                                             # (bounded memory; env
                                             # ACCELERATE_TELEMETRY_RING, default 4096)
    slo: Optional[dict] = None               # SLOMonitor thresholds, e.g.
                                             # {"ttft_s": {"p99_warn": 0.5,
                                             #  "p99_trip": 2.0}} — None: no monitor
    export_dir: Optional[str] = None         # where end-of-run Chrome traces land
                                             # (env ACCELERATE_TELEMETRY_DIR;
                                             # None: export only on request)

    def __post_init__(self):
        env = os.environ
        if self.enabled is None:
            self.enabled = parse_flag_from_env("ACCELERATE_TELEMETRY")
        if self.trace_requests is None:
            self.trace_requests = parse_flag_from_env(
                "ACCELERATE_TELEMETRY_TRACE_REQUESTS", default=self.enabled
            )
        if self.timeline is None:
            self.timeline = parse_flag_from_env(
                "ACCELERATE_TELEMETRY_TIMELINE", default=self.enabled
            )
        if self.ring_capacity is None:
            self.ring_capacity = int(env.get("ACCELERATE_TELEMETRY_RING", 4096))
        if self.ring_capacity < 1:
            raise ValueError(
                f"TelemetryPlugin.ring_capacity must be >= 1, got "
                f"{self.ring_capacity}"
            )
        if self.export_dir is None:
            self.export_dir = env.get("ACCELERATE_TELEMETRY_DIR") or None
        if self.slo is not None and not isinstance(self.slo, dict):
            raise ValueError(
                f"TelemetryPlugin.slo must be a thresholds dict, got "
                f"{type(self.slo).__name__}"
            )


@dataclass
class TensorParallelConfig(KwargsHandler):
    """reference TorchTensorParallelConfig dataclasses.py:2264.

    ``plan`` names a sharding-rule table (models ship defaults); GSPMD makes TP
    pure annotation — no module rewrite (reference had to DTensor-ify params,
    accelerator.py:1594-1616).
    """

    tp_size: int = 1
    plan: str = "auto"
    async_matmul: bool = True  # allow XLA latency-hiding collective matmuls


@dataclass
class ContextParallelConfig(KwargsHandler):
    """reference TorchContextParallelConfig dataclasses.py:2186-2210.

    rotate_method: 'allgather' gathers all KV once; 'alltoall' (ring) streams
    KV blocks with ppermute — the ring-attention path.
    """

    cp_size: int = 1
    rotate_method: str = "allgather"  # "allgather" | "alltoall"
    load_balance: bool = True          # zigzag sequence ordering for causal masks

    def __post_init__(self):
        if self.rotate_method not in ("allgather", "alltoall"):
            raise ValueError(f"invalid cp rotate method {self.rotate_method!r}")


@dataclass
class SequenceParallelConfig(KwargsHandler):
    """Ulysses/ALST head-parallel attention (reference
    DeepSpeedSequenceParallelConfig dataclasses.py:2214-2260): two all_to_alls
    swap sharding between sequence dim and head dim around attention."""

    sp_size: int = 1
    seq_length: Optional[int] = None
    attn_implementation: str = "native"


@dataclass
class ExpertParallelConfig(KwargsHandler):
    """MoE expert sharding over an ``ep`` mesh axis (capability parity with the
    reference's DeepSpeed MoE leaf-module marking accelerator.py:2258-2259)."""

    ep_size: int = 1
    capacity_factor: float = 1.25
    drop_tokens: bool = True


@dataclass
class FP8RecipeKwargs(KwargsHandler):
    """Unified fp8 recipe (reference AO/TE/MSAMP recipes dataclasses.py:311-483).

    XLA-native: matmul inputs cast to float8 with per-tensor delayed scaling;
    amax history drives the scale like TE's DelayedScaling.
    """

    fp8_format: FP8Format = FP8Format.HYBRID
    amax_history_len: Optional[int] = None   # env ACCELERATE_FP8_AMAX_HISTORY_LEN,
                                             # default 16
    amax_compute_algo: str = "max"
    margin: Optional[int] = None             # env ACCELERATE_FP8_MARGIN, default 0
    module_filter: Optional[Callable[[str], bool]] = None

    def __post_init__(self):
        if isinstance(self.fp8_format, str):
            self.fp8_format = FP8Format(self.fp8_format.upper())
        env = os.environ
        if self.amax_history_len is None:
            self.amax_history_len = int(
                env.get("ACCELERATE_FP8_AMAX_HISTORY_LEN", 16)
            )
        if self.margin is None:
            self.margin = int(env.get("ACCELERATE_FP8_MARGIN", 0))
        if self.amax_history_len < 1:
            raise ValueError(
                f"amax_history_len must be >= 1, got {self.amax_history_len}"
            )
        if self.margin < 0:
            raise ValueError(f"margin must be >= 0, got {self.margin}")
        if self.amax_compute_algo != "max":
            raise ValueError(
                "amax_compute_algo: only 'max' is implemented "
                f"(got {self.amax_compute_algo!r})"
            )


@dataclass
class DataLoaderConfiguration(KwargsHandler):
    """reference dataclasses.py DataLoaderConfiguration (split_batches,
    dispatch_batches, even_batches, use_seedable_sampler...)."""

    split_batches: bool = False
    dispatch_batches: Optional[bool] = None
    even_batches: bool = True
    use_seedable_sampler: bool = False
    data_seed: Optional[int] = None
    non_blocking: bool = True
    use_stateful_dataloader: bool = False
    prefetch_size: int = 2


@dataclass
class ProjectConfiguration(KwargsHandler):
    """Checkpoint/project dir config (reference ProjectConfiguration
    dataclasses.py — automatic_checkpoint_naming + total_limit GC used by
    accelerator.save_state :3587-3613)."""

    project_dir: Optional[str] = None
    logging_dir: Optional[str] = None
    automatic_checkpoint_naming: bool = False
    total_limit: Optional[int] = None
    iteration: int = 0
    save_on_each_node: bool = False

    def set_directories(self, project_dir: Optional[str] = None):
        self.project_dir = project_dir
        if self.logging_dir is None:
            self.logging_dir = project_dir

    def __post_init__(self):
        self.set_directories(self.project_dir)


# ---------------------------------------------------------------------------
# Exceptions
# ---------------------------------------------------------------------------


class DistributedOperationException(Exception):
    """Raised by debug-mode collective shape verification
    (reference operations.py:364-398)."""


ALL_KWARGS_HANDLERS = (
    AutocastKwargs,
    GradSyncKwargs,
    InitProcessGroupKwargs,
    ProfileKwargs,
    FP8RecipeKwargs,
)
