"""Seeding and cross-process RNG synchronization.

TPU-native analog of reference ``utils/random.py`` (156 LoC): ``set_seed``
(:39) seeds every framework RNG; ``synchronize_rng_states`` (:154) broadcasts
RNG state from process 0 so shuffles agree across ranks.  JAX adds a twist:
its PRNG is functional (keys, not global state), so the framework keeps a
module-level *root key* that samplers/dataloaders fold per-epoch/per-step —
deterministic and sync-free by construction, which is why ``jax`` appears in
``rng_types`` but needs no cross-process traffic.
"""

from __future__ import annotations

import random
from typing import Iterable, Optional

import jax
import numpy as np

from .dataclasses import RNGType
from .imports import is_torch_available

_root_key: Optional[jax.Array] = None
_root_seed: Optional[int] = None


def set_seed(seed: int, device_specific: bool = False, deterministic: bool = False):
    """Seed python/numpy/torch and install the JAX root key
    (reference random.py:39-66).  ``device_specific`` offsets by process index
    so each host draws different data-augmentation randomness."""
    global _root_key, _root_seed
    if device_specific:
        from ..state import PartialState

        seed += PartialState().process_index
    random.seed(seed)
    np.random.seed(seed % (2**32))
    if is_torch_available():
        import torch

        torch.manual_seed(seed)
    _root_seed = seed
    _root_key = jax.random.key(seed)
    return seed


def get_rng_key(fold: Optional[int] = None) -> jax.Array:
    """The framework root PRNG key, optionally folded with ``fold``
    (epoch/step index) for a derived stream."""
    global _root_key
    if _root_key is None:
        set_seed(0)
    return jax.random.fold_in(_root_key, fold) if fold is not None else _root_key


def get_root_seed() -> int:
    global _root_seed
    if _root_seed is None:
        set_seed(0)
    return _root_seed


def synchronize_rng_state(rng_type: Optional[RNGType] = None, generator=None):
    """Broadcast one RNG stream's state from process 0
    (reference random.py:69-151)."""
    from ..ops.operations import broadcast_object_list
    from ..state import PartialState

    state = PartialState()
    if rng_type == RNGType.JAX:
        # Functional keys derived from a shared seed are already identical
        # across processes; sync the seed to be safe.
        payload = [get_root_seed()]
        broadcast_object_list(payload, from_process=0)
        if state.num_processes > 1:
            global _root_key, _root_seed
            _root_seed = payload[0]
            _root_key = jax.random.key(_root_seed)
        return
    if rng_type == RNGType.NUMPY:
        payload = [np.random.get_state()]
        broadcast_object_list(payload, from_process=0)
        np.random.set_state(payload[0])
        return
    if rng_type == RNGType.PYTHON:
        payload = [random.getstate()]
        broadcast_object_list(payload, from_process=0)
        random.setstate(payload[0])
        return
    if rng_type == RNGType.TORCH and is_torch_available():
        import torch

        payload = [torch.get_rng_state()]
        broadcast_object_list(payload, from_process=0)
        torch.set_rng_state(payload[0])
        return
    if rng_type == RNGType.GENERATOR and generator is not None:
        payload = [generator.get_state() if hasattr(generator, "get_state") else generator.bit_generator.state]
        broadcast_object_list(payload, from_process=0)
        if hasattr(generator, "set_state"):
            generator.set_state(payload[0])
        else:
            generator.bit_generator.state = payload[0]
        return


def synchronize_rng_states(rng_types: Iterable, generator=None):
    """reference random.py:154."""
    for rng_type in rng_types:
        synchronize_rng_state(RNGType(rng_type) if not isinstance(rng_type, RNGType) else rng_type, generator)
