"""Weight-only quantized loading — the bitsandbytes-capability analog.

The reference loads 4/8-bit models through bitsandbytes
(``BnbQuantizationConfig`` reference dataclasses.py:3025,
``load_and_quantize_model`` utils/bnb.py:~50): weights quantize as checkpoint
shards stream in, norm/embedding-class modules stay in high precision, and
matmuls dequantize on the fly.

TPU-native design: quantized weights are first-class **pytree leaves** — a
:class:`QuantizedTensor` node holding the packed codes + blockwise scales —
so they flow through ``jit``/sharding like any other param.  Dequantization
happens *inside* the compiled step (``dequantize`` is jit-traceable; XLA
fuses the ``codes * scale`` expand into the consuming matmul), which is the
part that matters on TPU: weight HBM traffic drops 2-4x while the MXU still
sees bf16 operands.

Schemes:
- ``int8``  — blockwise absmax: ``w ≈ scale * q`` with ``q ∈ [-127, 127]``.
- ``nf4``   — 4-bit NormalFloat (QLoRA codebook): blockwise absmax scaling to
  [-1, 1], nearest-code lookup, two codes packed per byte.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

# QLoRA NF4 codebook: the 16 quantiles of a standard normal, normalized to
# [-1, 1] (public constants from the QLoRA paper).
NF4_CODE = np.array(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
        0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
        0.7229568362236023, 1.0,
    ],
    np.float32,
)


@dataclasses.dataclass(frozen=True)
class QuantizationConfig:
    """reference BnbQuantizationConfig dataclasses.py:3025 capability surface."""

    load_in_8bit: bool = False
    load_in_4bit: bool = False
    # 128 = one TPU lane width: the Pallas int8 matmul kernel requires
    # block_size % 128 == 0 for its in-tile dequant (ops/quantized_matmul.py);
    # other sizes still work via the dequantize fallback
    block_size: int = 128
    compute_dtype: Any = jnp.bfloat16
    # leaves whose path matches any pattern stay unquantized (reference
    # keep_in_fp32_modules / skip_modules)
    skip_patterns: tuple = ("embed", "norm", "bias", "scale", "lm_head")
    # only quantize matrices at least this big (small leaves aren't worth it)
    min_size: int = 4096

    def __post_init__(self):
        if self.load_in_8bit and self.load_in_4bit:
            raise ValueError("pick one of load_in_8bit / load_in_4bit")
        if not (self.load_in_8bit or self.load_in_4bit):
            raise ValueError("one of load_in_8bit / load_in_4bit must be set")

    @property
    def scheme(self) -> str:
        return "int8" if self.load_in_8bit else "nf4"

    def should_quantize(self, path: str, arr) -> bool:
        # attribute checks only — never np.asarray here (that would force a
        # full D2H transfer per leaf just to inspect metadata)
        if getattr(arr, "ndim", 0) < 2 or getattr(arr, "size", 0) < self.min_size:
            return False
        try:
            if not jnp.issubdtype(arr.dtype, jnp.floating):
                return False
        except (TypeError, AttributeError):
            return False
        low = path.lower()
        return not any(re.search(p, low) for p in self.skip_patterns)


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Packed codes + blockwise scales; a jit-traversable pytree node.

    ``shape``/``dtype`` mimic the dequantized array so sharding planners can
    treat it like the original weight.
    """

    def __init__(self, data, scale, shape, dtype, scheme: str, block_size: int,
                 layout: str = "flat"):
        # layout "flat": data int8 [n_blocks, block] (or uint8 packed nf4),
        #   scale f32 [n_blocks, 1] — blockwise over the row-major flat array.
        # layout "k2d" (2-D int8 only): data int8 [H, F], scale f32
        #   [F/block, H] — the Pallas matmul kernel's exact operand layouts,
        #   fixed at quantize time so the decode scan body contains zero
        #   per-step reshapes/transposes (XLA does not hoist them out of the
        #   while loop; measured ~6 ms/token of glue at 1.1B).
        self.data = data
        self.scale = scale
        self.shape = tuple(shape)
        self.dtype = dtype
        self.scheme = scheme
        self.block_size = block_size
        self.layout = layout

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    def tree_flatten(self):
        return (self.data, self.scale), (
            self.shape, self.dtype, self.scheme, self.block_size, self.layout,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, scale = children
        return cls(data, scale, *aux)

    def __repr__(self):
        return f"QuantizedTensor({self.scheme}, shape={self.shape}, block={self.block_size})"


def is_quantized(x) -> bool:
    return isinstance(x, QuantizedTensor)


# ---------------------------------------------------------------------------
# quantize (host-side, numpy) — runs while checkpoint shards stream in
# ---------------------------------------------------------------------------


def _blockify(arr: np.ndarray, block: int) -> tuple[np.ndarray, int]:
    flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
    pad = -len(flat) % block
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    return flat.reshape(-1, block), pad


def _k2d_eligible(shape, block: int) -> bool:
    return len(shape) == 2 and shape[1] % block == 0


def _int8_blockwise(a, block: int, k2d: bool, xp):
    """The one int8 absmax quantization implementation, shared by the numpy
    (host/stream) and jitted (on-device) paths via the ``xp`` namespace.

    k2d: returns data [H, F] int8 + scale [F/block, H] fp32 — the Pallas
    matmul kernel's operand layouts.  flat: data [n_blocks, block] + scale
    [n_blocks, 1] over the row-major flat array (padded to whole blocks).
    """
    if k2d:
        h, f = a.shape
        blocks = xp.reshape(a.astype(xp.float32), (h, f // block, block))
    else:
        flat = xp.reshape(a.astype(xp.float32), (-1,))
        pad = -flat.shape[0] % block
        if pad:
            flat = xp.concatenate([flat, xp.zeros((pad,), xp.float32)])
        blocks = xp.reshape(flat, (-1, block))
    absmax = xp.abs(blocks).max(axis=-1, keepdims=True)
    absmax = xp.where(absmax == 0, 1.0, absmax)
    scale = (absmax / 127.0).astype(xp.float32)
    q = xp.clip(xp.round(blocks / scale), -127, 127).astype(xp.int8)
    if k2d:
        return xp.reshape(q, (h, f)), scale[..., 0].T  # [H,F], [F/block, H]
    return q, scale


@functools.partial(jax.jit, static_argnums=(1, 2))
def _int8_quantize_jit(a, block: int, k2d: bool):
    return _int8_blockwise(a, block, k2d, jnp)


def _quantize_int8_on_device(arr: jax.Array, block: int) -> QuantizedTensor:
    """int8 blockwise quantization as a jitted device computation — no
    host round trip (quantizing an already-loaded 2GB model over a slow
    link via the numpy path costs minutes; on-device it is one kernel,
    compile-cached across same-shape leaves)."""
    k2d = _k2d_eligible(arr.shape, block)
    q, scale = _int8_quantize_jit(arr, block, k2d)
    return QuantizedTensor(q, scale, arr.shape, arr.dtype, "int8", block,
                           layout="k2d" if k2d else "flat")


def _accelerator_backed(arr) -> bool:
    """True only when ``arr`` already lives in accelerator device memory —
    quantizing a host/numpy-backed leaf on device would transiently stage the
    full-precision tensor in HBM, which host-staged flows exist to avoid."""
    if not isinstance(arr, jax.Array):
        return False
    if getattr(arr.sharding, "memory_kind", None) not in (None, "device"):
        return False
    try:
        return all(d.platform != "cpu" for d in arr.devices())
    except Exception:
        return False


def quantize(arr, config: QuantizationConfig, on_device: Optional[bool] = None) -> QuantizedTensor:
    explicit = on_device is not None
    if on_device is None:
        on_device = _accelerator_backed(arr)
    if on_device and config.scheme == "int8" and jax.devices()[0].platform != "cpu":
        if not isinstance(arr, jax.Array) and explicit:
            # explicit opt-in: the caller accepts staging the leaf in HBM
            arr = jnp.asarray(arr)
        if isinstance(arr, jax.Array) and arr.is_fully_addressable:  # single-process arrays only
            return _quantize_int8_on_device(arr, config.block_size)
    if on_device and explicit:
        import warnings

        warnings.warn(
            "quantize(on_device=True) could not take the on-device path "
            f"(scheme={config.scheme!r}, platform="
            f"{jax.devices()[0].platform!r}); falling back to the host path."
        )
    np_arr = np.asarray(jax.device_get(arr) if isinstance(arr, jax.Array) else arr)
    orig_dtype = np_arr.dtype
    if config.scheme == "int8":
        k2d = _k2d_eligible(np_arr.shape, config.block_size)
        q, scale = _int8_blockwise(np_arr, config.block_size, k2d, np)
        return QuantizedTensor(q, np.ascontiguousarray(scale), np_arr.shape, orig_dtype,
                               "int8", config.block_size, layout="k2d" if k2d else "flat")
    blocks, _ = _blockify(np_arr.astype(np.float32), config.block_size)
    absmax = np.abs(blocks).max(axis=1, keepdims=True)
    absmax = np.where(absmax == 0, 1.0, absmax)
    # nf4: scale to [-1,1], nearest codebook entry, pack two per byte
    norm = blocks / absmax
    codes = np.abs(norm[..., None] - NF4_CODE).argmin(axis=-1).astype(np.uint8)
    lo, hi = codes[:, 0::2], codes[:, 1::2]
    packed = (hi << 4 | lo).astype(np.uint8)
    return QuantizedTensor(packed, absmax.astype(np.float32), np_arr.shape, orig_dtype,
                           "nf4", config.block_size)


# ---------------------------------------------------------------------------
# dequantize (jit-traceable) — fused by XLA into the consuming matmul
# ---------------------------------------------------------------------------


def dequantize(qt: QuantizedTensor, dtype=None):
    if not is_quantized(qt):
        return qt
    out_dtype = dtype or qt.dtype
    n = int(np.prod(qt.shape)) if qt.shape else 1
    if getattr(qt, "layout", "flat") == "k2d":
        h, f = qt.shape
        blocks = qt.data.astype(jnp.float32).reshape(h, f // qt.block_size, qt.block_size)
        vals = blocks * qt.scale.T[:, :, None]
        return vals.reshape(h, f).astype(out_dtype)
    if qt.scheme == "int8":
        vals = qt.data.astype(jnp.float32) * qt.scale
    else:  # nf4
        code = jnp.asarray(NF4_CODE)
        lo = code[(qt.data & 0x0F).astype(jnp.int32)]
        hi = code[(qt.data >> 4).astype(jnp.int32)]
        # interleave back: block positions 0,2,4... were lo, 1,3,5... hi
        vals = jnp.stack([lo, hi], axis=-1).reshape(qt.data.shape[0], -1) * qt.scale
    return vals.reshape(-1)[:n].reshape(qt.shape).astype(out_dtype)


def dequantize_tree(params, dtype=None):
    """Dequantize every :class:`QuantizedTensor` leaf (inside jit this is
    where XLA fuses the expansion into consumers)."""
    return jax.tree_util.tree_map(
        lambda x: dequantize(x, dtype) if is_quantized(x) else x,
        params,
        is_leaf=is_quantized,
    )


def quantized_apply(apply_fn: Callable, dtype=None) -> Callable:
    """Wrap a model ``apply`` so quantized param trees dequantize in-step:
    ``model.apply`` → ``quantized_apply(model.apply)`` is the whole
    integration (the linear-module-swap dance of the reference's bnb path
    collapses to a pytree map under jit)."""

    def wrapped(params, *args, **kwargs):
        return apply_fn(dequantize_tree(params, dtype), *args, **kwargs)

    return wrapped


# ---------------------------------------------------------------------------
# tree-level quantization + streaming loader
# ---------------------------------------------------------------------------


def quantize_params(params, config: QuantizationConfig):
    """Quantize eligible leaves of a param pytree (reference
    load_and_quantize_model's module walk, as a pytree map)."""
    leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in leaves:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        if config.should_quantize(key, leaf):
            out.append(quantize(leaf, config))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def quantized_nbytes(params) -> int:
    """Total bytes of a (possibly quantized) param tree — the memory-footprint
    estimate surfaced by ``accelerate estimate-memory``."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params, is_leaf=is_quantized):
        if is_quantized(leaf):
            total += leaf.data.size * leaf.data.dtype.itemsize + leaf.scale.size * 4
        elif hasattr(leaf, "nbytes"):
            total += leaf.nbytes
        else:
            total += np.asarray(leaf).nbytes
    return total


def load_and_quantize_model(
    abstract_params,
    checkpoint_path,
    config: QuantizationConfig,
    mesh=None,
    param_spec_fn=None,
):
    """Stream a checkpoint and quantize eligible weights as they arrive
    (reference load_and_quantize_model utils/bnb.py): unquantized leaves are
    device_put (optionally sharded via ``param_spec_fn(path) ->
    PartitionSpec`` over ``mesh``), quantized leaves stay as
    :class:`QuantizedTensor` nodes with their codes on device.
    """
    from ..big_modeling import load_checkpoint_in_model

    params, _ = load_checkpoint_in_model(abstract_params, checkpoint_path)

    leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in leaves:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        if config.should_quantize(key, leaf):
            qt = quantize(leaf, config)
            qt = QuantizedTensor(
                jax.device_put(qt.data), jax.device_put(qt.scale),
                qt.shape, qt.dtype, qt.scheme, qt.block_size, layout=qt.layout,
            )
            out.append(qt)
        else:
            if mesh is not None and param_spec_fn is not None:
                from jax.sharding import NamedSharding

                leaf = jax.device_put(leaf, NamedSharding(mesh, param_spec_fn(key)))
            else:
                leaf = jax.device_put(leaf)
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)
