"""The single registry of file names and option lists (reference
``utils/constants.py:107`` — the reference centralizes weights/index file
names, rng-state patterns, and launcher option lists; modules were carrying
their own copies here until round 4).

Checkpoint-layout names are imported by ``checkpointing.py`` /
``big_modeling.py``; option lists back CLI ``choices=`` and config
validation so the questionnaire, the launcher, and the dataclasses cannot
drift apart.
"""

from __future__ import annotations

# -- checkpoint layout (save_state/load_state, save_model) -------------------
MODEL_NAME = "model"
TRAIN_STATE_DIR = "train_state"
RNG_STATE_NAME = "random_states_{}.pkl"
CUSTOM_STATES_NAME = "custom_checkpoint_{}.pkl"
SAMPLER_STATES_NAME = "sampler_states.json"
SCHEDULER_STATES_NAME = "scheduler_states.json"
METADATA_NAME = "accelerate_metadata.json"
CHECKPOINT_DIR_PREFIX = "checkpoint"
CHECKPOINT_DIR_PATTERN = r"checkpoint_\d+"
# verified atomic checkpoints (checkpointing.py): every file stages under
# <dir>.tmp, the manifest (per-file sizes + crc32) is written last, and one
# os.replace publishes the directory — the pattern above intentionally does
# NOT match *.tmp, so scans/GC never see a half-written checkpoint
CHECKPOINT_TMP_SUFFIX = ".tmp"
CHECKPOINT_MANIFEST_NAME = "checkpoint_manifest.json"

# -- unified weights files (save_model / load_checkpoint_in_model) -----------
SAFE_WEIGHTS_NAME = "model.safetensors"
SAFE_WEIGHTS_INDEX_NAME = "model.safetensors.index.json"
SAFE_WEIGHTS_SHARD_PATTERN = "model-{:05d}-of-{:05d}.safetensors"

# -- option lists (CLI choices / config validation / plugin env parsing) -----
MIXED_PRECISION_CHOICES = ["no", "bf16", "fp16", "fp8"]
SHARDING_STRATEGY_CHOICES = ["FULL_SHARD", "SHARD_GRAD_OP", "NO_SHARD", "HYBRID_SHARD"]
REMAT_POLICY_CHOICES = ["full", "dots", "offload"]
GRAD_ACCUM_MODE_CHOICES = ["in_step", "across_steps"]
RNG_TYPES = ["python", "numpy", "jax", "torch", "generator"]
QUANTIZATION_SCHEMES = ["int8", "nf4"]
