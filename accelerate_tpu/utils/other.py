"""Grab-bag utilities (reference ``utils/other.py``, 564 LoC).

TPU-native analogs of the pieces that survive the torch→JAX redesign:

- :func:`extract_model_from_parallel` (reference :218) — unwrap framework
  wrappers back to the user's model.
- :func:`save` / :func:`load` (reference :354/:404) — pytree serialization
  to disk, main-process-gated.
- :func:`compile_regions` / :func:`aot_compile` (reference ``compile_regions``
  :102 — regional ``torch.compile`` of repeated blocks to cut compile time)
  — the XLA analog is ahead-of-time lowering: jit already caches per-shape,
  so the win is *when* compilation happens, not how often.
- :func:`check_os_kernel` (reference :501) — warn on Linux kernels with the
  MKL/OMP fork bug class.
"""

from __future__ import annotations

import logging
import os
import platform
import time
from typing import Any, Callable, Optional

import jax

logger = logging.getLogger(__name__)


def extract_model_from_parallel(model: Any, keep_fp32_wrapper: bool = True) -> Any:
    """Unwrap framework wrappers and return the underlying user model
    (reference utils/other.py:218 — DDP/FSDP/DeepSpeed/compiled unwrap).

    The TPU build has exactly one wrapping container: a pipeline-parallel
    :class:`~accelerate_tpu.parallel.pipeline_parallel.PipelinedModel`.
    Sharded training never wraps the model (GSPMD shards arrays, not
    modules), so everything else passes through unchanged.
    """
    try:
        from ..parallel.pipeline_parallel import PipelinedModel
    except ImportError:  # partial build without the pipeline module
        return model

    if isinstance(model, PipelinedModel):
        return model.model
    return model


def _flatten_for_safetensors(obj):
    import numpy as np

    flat, _ = jax.tree_util.tree_flatten_with_path(obj)
    out = {}
    for path, leaf in flat:
        key = "/".join(
            str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path
        )
        out[key] = np.asarray(leaf)
    return out


def save(obj: Any, path: os.PathLike | str, safe_serialization: bool = True) -> None:
    """Serialize a pytree of arrays to ``path``, only on the main process
    (reference utils/other.py:354).

    ``safe_serialization=True`` writes safetensors (flat ``a/b/c`` keys, the
    reference's safe format); ``False`` writes flax msgpack bytes, which
    round-trip arbitrary pytree structure without a target."""
    from ..state import PartialState

    if not PartialState().is_main_process:
        return
    if safe_serialization:
        from safetensors.numpy import save_file

        save_file(_flatten_for_safetensors(jax.device_get(obj)), str(path))
        return
    from flax import serialization

    data = serialization.to_bytes(jax.device_get(obj))
    with open(path, "wb") as f:
        f.write(data)


def load(path: os.PathLike | str, target: Optional[Any] = None) -> Any:
    """Inverse of :func:`save` (reference utils/other.py:404).  Sniffs the
    format (safetensors vs msgpack).  With ``target`` (an example pytree) a
    msgpack load keeps its exact structure and dtypes; a safetensors load
    returns the flat ``{path: array}`` dict."""
    try:
        from safetensors.numpy import load_file

        return load_file(str(path))
    except Exception:
        pass
    from flax import serialization

    with open(path, "rb") as f:
        data = f.read()
    if target is not None:
        return serialization.from_bytes(target, data)
    return serialization.msgpack_restore(data)


def aot_compile(fn: Callable, *example_args, **example_kwargs):
    """Ahead-of-time compile ``fn`` for the example arguments.

    Returns ``(compiled, seconds)``.  ``compiled`` is an executable
    accepting arrays matching the example shapes/dtypes/shardings — calling
    it never triggers tracing or compilation again.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    t0 = time.perf_counter()
    compiled = jitted.lower(*example_args, **example_kwargs).compile()
    return compiled, time.perf_counter() - t0


def compile_regions(step_fns: dict[str, Callable] | Callable, *example_args):
    """Regional pre-compilation (reference ``compile_regions``
    utils/other.py:102).

    The torch version compiles each *repeated block* separately so compile
    cost is paid once per block class instead of once per call site.  Under
    XLA, jit's trace cache already deduplicates identical block programs;
    what remains worth controlling is paying compilation up front.  Pass one
    function or a dict of named functions plus example args; each is
    AOT-compiled and returned in the same shape, with compile seconds logged.
    """
    if callable(step_fns):
        compiled, dt = aot_compile(step_fns, *example_args)
        logger.info("compile_regions: compiled in %.2fs", dt)
        return compiled
    out = {}
    for name, fn in step_fns.items():
        out[name], dt = aot_compile(fn, *example_args)
        logger.info("compile_regions[%s]: compiled in %.2fs", name, dt)
    return out


def check_os_kernel() -> None:
    """Warn about Linux kernels below 5.5 (reference utils/other.py:501 —
    a known source of hangs with heavy host threading)."""
    info = platform.uname()
    if info.system != "Linux":
        return
    try:
        release = tuple(int(p) for p in info.release.split(".")[:2])
    except ValueError:
        return
    if release < (5, 5):
        logger.warning(
            "Detected Linux kernel %s < 5.5; host-side data loading may hang "
            "under heavy threading. Consider upgrading.", info.release
        )
