"""Scoped JAX persistent compilation-cache management.

The suite and the bench are compile-dominated on CPU hosts, so both lean on
``jax_compilation_cache_dir`` — but one flat ``/tmp`` directory shared by
every process proved fragile: **concurrent jax processes corrupt the shared
cache** (documented segfault/garbage flakes on this rig), and entries from a
different jax build are dead weight at best.  This module gives every run a
**scoped** cache directory instead (the first slice of ROADMAP item 4's
compilation-cache management):

- keyed by ``jax``/Python version, so an upgraded toolchain never reads a
  stale cache;
- keyed by a **tag** per harness (``tests``, ``bench``, ...), so the suite
  and bench subprocesses never share a directory;
- optionally keyed by a **scope** for concurrent runs: the
  ``ACCELERATE_JAX_CACHE_SCOPE`` env var, or — automatically — the
  pytest-xdist worker id, so parallel test workers each get a private cache
  (the exact shape of the documented corruption).

``ACCELERATE_JAX_CACHE_ROOT`` moves the whole tree off ``/tmp``.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path
from typing import Optional


def scoped_cache_dir(tag: str = "run", root: Optional[str] = None) -> str:
    """The scoped cache directory for this (toolchain, tag, scope) — created
    if missing, returned as a string path."""
    import jax

    root = root or os.environ.get(
        "ACCELERATE_JAX_CACHE_ROOT", "/tmp/accelerate_tpu_jax_cache"
    )
    version_key = (
        f"jax{jax.__version__}-py{sys.version_info.major}.{sys.version_info.minor}"
    )
    scope = os.environ.get("ACCELERATE_JAX_CACHE_SCOPE") or os.environ.get(
        "PYTEST_XDIST_WORKER", ""
    )
    leaf = f"{tag}-{scope}" if scope else tag
    path = Path(root) / version_key / leaf
    path.mkdir(parents=True, exist_ok=True)
    return str(path)


def enable_scoped_compilation_cache(
    tag: str = "run",
    *,
    root: Optional[str] = None,
    min_compile_time_secs: float = 0.5,
    min_entry_size_bytes: int = 0,
) -> Optional[str]:
    """Point jax's persistent compilation cache at the scoped directory.
    Returns the directory, or ``None`` when this jax build lacks the knobs
    (older releases — the run proceeds uncached, never fails)."""
    import jax

    try:
        d = scoped_cache_dir(tag, root)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_compile_time_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", min_entry_size_bytes)
        return d
    except Exception:  # pragma: no cover - older jax without the knobs
        return None
