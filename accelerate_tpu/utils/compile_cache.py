"""Scoped JAX persistent compilation-cache management.

The suite and the bench are compile-dominated on CPU hosts, so both lean on
``jax_compilation_cache_dir`` — but one flat ``/tmp`` directory shared by
every process proved fragile: **concurrent jax processes corrupt the shared
cache** (documented segfault/garbage flakes on this rig), and entries from a
different jax build are dead weight at best.  This module gives every run a
**scoped** cache directory instead (the first slice of ROADMAP item 4's
compilation-cache management):

- keyed by ``jax``/Python version, so an upgraded toolchain never reads a
  stale cache;
- keyed by a **tag** per harness (``tests``, ``bench``, ...), so the suite
  and bench subprocesses never share a directory;
- optionally keyed by a **scope** for concurrent runs: the
  ``ACCELERATE_JAX_CACHE_SCOPE`` env var, or — automatically — the
  pytest-xdist worker id, so parallel test workers each get a private cache
  (the exact shape of the documented corruption).

``ACCELERATE_JAX_CACHE_ROOT`` moves the whole tree off ``/tmp``.

**Prewarm distribution** (the remaining slice of ROADMAP item 4):
:func:`export_prewarm` packs a warmed scoped cache into one
toolchain-keyed archive, and :func:`load_prewarm` unpacks it on a deploy
host BEFORE the preflight/warmup — so production startup pays zero cold
compiles even on a fresh machine.  Loads are **version-keyed**: a pack
from a different jax/Python build is refused (its entries could never
hit), and every stale-version directory under the cache root is swept on
load, so upgraded toolchains never accumulate dead weight.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tarfile
from pathlib import Path
from typing import Optional

PREWARM_MANIFEST = "prewarm_manifest.json"


def toolchain_version_key() -> str:
    """The cache-keying toolchain tag: jax + Python version.  An entry
    compiled by one toolchain is dead weight (at best) to another."""
    import jax

    return f"jax{jax.__version__}-py{sys.version_info.major}.{sys.version_info.minor}"


def _cache_root(root: Optional[str] = None) -> Path:
    return Path(root or os.environ.get(
        "ACCELERATE_JAX_CACHE_ROOT", "/tmp/accelerate_tpu_jax_cache"
    ))


def _process_scope() -> str:
    """The multi-process scope component: launched workers never share a
    cache directory (concurrent jax processes corrupt a shared cache — the
    documented flake the scoped dirs retired, which a 2-process
    ``accelerate_tpu launch`` would otherwise reintroduce).

    Keyed by the launcher's ``ACCELERATE_PROCESS_ID`` env when present —
    reading ``jax.process_index()`` here would *initialize* the backend and
    make the worker's later ``jax.distributed.initialize`` impossible, so
    jax is only consulted when the distributed runtime is already up
    (state.py has initialized it)."""
    pid = os.environ.get("ACCELERATE_PROCESS_ID")
    if pid is not None:
        return f"proc{pid}"
    from ..state import _jax_distributed_initialized

    if _jax_distributed_initialized:
        import jax

        if jax.process_count() > 1:
            return f"proc{jax.process_index()}"
    return ""


def scoped_cache_dir(tag: str = "run", root: Optional[str] = None) -> str:
    """The scoped cache directory for this (toolchain, tag, scope, process)
    — created if missing, returned as a string path."""
    scope = os.environ.get("ACCELERATE_JAX_CACHE_SCOPE") or os.environ.get(
        "PYTEST_XDIST_WORKER", ""
    )
    proc = _process_scope()
    leaf = "-".join(part for part in (tag, scope, proc) if part)
    path = _cache_root(root) / toolchain_version_key() / leaf
    path.mkdir(parents=True, exist_ok=True)
    return str(path)


def export_prewarm(dest: str, tag: str = "run", *, root: Optional[str] = None) -> str:
    """Pack the scoped compilation cache into one distributable archive.

    The archive carries a manifest keyed by :func:`toolchain_version_key`
    and ``tag``; ship it to deploy hosts and :func:`load_prewarm` it before
    ``preflight``/``warmup`` — the whole bucket ladder then compiles from
    cache hits.  Returns the archive path."""
    src = Path(scoped_cache_dir(tag, root))
    dest_path = Path(dest)
    dest_path.parent.mkdir(parents=True, exist_ok=True)
    entries = sorted(p.name for p in src.iterdir() if p.is_file())
    manifest = {
        "version_key": toolchain_version_key(),
        "tag": tag,
        "entries": entries,
    }
    manifest_file = src / PREWARM_MANIFEST
    manifest_file.write_text(json.dumps(manifest, indent=1))
    try:
        with tarfile.open(dest_path, "w") as tar:
            tar.add(manifest_file, arcname=PREWARM_MANIFEST)
            for name in entries:
                tar.add(src / name, arcname=f"cache/{name}")
    finally:
        manifest_file.unlink(missing_ok=True)
    return str(dest_path)


def sweep_stale_versions(root: Optional[str] = None) -> list[str]:
    """Remove every cache-root subdirectory keyed by a DIFFERENT toolchain
    than the current one (the version-keyed eviction: an upgraded jax or
    Python never reads — or pays disk for — a stale cache).  Returns the
    swept directory names."""
    root_path = _cache_root(root)
    current = toolchain_version_key()
    swept = []
    if not root_path.is_dir():
        return swept
    for child in sorted(root_path.iterdir()):
        if child.is_dir() and child.name != current:
            shutil.rmtree(child, ignore_errors=True)
            swept.append(child.name)
    return swept


def load_prewarm(archive: str, tag: str = "run", *,
                 root: Optional[str] = None) -> dict:
    """Unpack a prewarm archive into this host's scoped cache directory.

    Version-keyed: an archive built by a different toolchain is REFUSED
    (``{"loaded": 0, "stale": True}`` — its entries could never hit and a
    deserialized foreign executable is exactly the corruption class the
    scoped dirs retired).  Either way, stale-version directories under the
    cache root are swept.  Never raises on a bad archive — a broken
    prewarm pack degrades to a cold start, not a failed deploy."""
    report = {"loaded": 0, "stale": False, "swept": [], "version_key": toolchain_version_key()}
    try:
        with tarfile.open(archive, "r") as tar:
            try:
                member = tar.extractfile(PREWARM_MANIFEST)
            except KeyError:  # no manifest member at all (foreign tar)
                member = None
            manifest = json.loads(member.read().decode()) if member else {}
            if manifest.get("version_key") != toolchain_version_key():
                report["stale"] = True
            else:
                dest = Path(scoped_cache_dir(tag, root))
                for m in tar.getmembers():
                    name = m.name
                    if not (m.isfile() and name.startswith("cache/")):
                        continue
                    leaf = Path(name).name  # flatten: no traversal, ever
                    src = tar.extractfile(m)
                    if src is None:  # pragma: no cover - malformed member
                        continue
                    (dest / leaf).write_bytes(src.read())
                    report["loaded"] += 1
    except (OSError, tarfile.TarError, json.JSONDecodeError) as e:
        report["stale"] = True
        report["error"] = str(e)
    report["swept"] = sweep_stale_versions(root)
    return report


def enable_scoped_compilation_cache(
    tag: str = "run",
    *,
    root: Optional[str] = None,
    min_compile_time_secs: float = 0.5,
    min_entry_size_bytes: int = 0,
) -> Optional[str]:
    """Point jax's persistent compilation cache at the scoped directory.
    Returns the directory, or ``None`` when this jax build lacks the knobs
    (older releases — the run proceeds uncached, never fails)."""
    import jax

    try:
        d = scoped_cache_dir(tag, root)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_compile_time_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", min_entry_size_bytes)
        return d
    except Exception:  # pragma: no cover - older jax without the knobs
        return None
