"""Step-scheduled profiling (the engine behind ``Accelerator.profile``).

Fills the role of the reference's ``ProfileKwargs``-built
``torch.profiler.profile`` (reference utils/dataclasses.py:484-560 +
accelerator.py profile):  a ``wait/warmup/active`` step schedule with
``repeat`` cycles, optional memory capture and FLOPs accounting — mapped to
TPU-native mechanisms:

- the **trace window** is ``jax.profiler.start_trace``/``stop_trace`` around
  exactly the ``active`` steps of each cycle (steps
  ``[wait+warmup, wait+warmup+active)``).  ``warmup`` steps run untraced:
  their torch purpose (letting kernels/caches settle, then discarding the
  samples) maps to letting XLA's compile+autotune settle before the window
  opens — JAX traces cannot discard a prefix after the fact.
- ``profile_memory`` snapshots ``Device.memory_stats()`` at the window edges
  and reports deltas + peak (there is no per-op allocator hook on TPU; HBM
  attribution lives in the captured trace's memory viewer).
- ``with_flops`` exposes compiled-executable cost analysis
  (:meth:`TPUProfiler.flops_estimate`) and accumulates it into the summary.

Multi-cycle runs write each cycle to ``<dir>/cycle_<i>`` and invoke
``on_trace_ready(trace_dir)`` per cycle like torch's per-cycle handler.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax

from .memory import get_device_memory_stats


@dataclass
class _Schedule:
    """Position arithmetic for the wait/warmup/active/repeat cycle."""

    wait: int
    warmup: int
    active: int
    repeat: int  # 0 = cycle forever

    @property
    def cycle_len(self) -> int:
        return max(1, self.wait + self.warmup + self.active)

    def locate(self, step: int) -> tuple[int, str]:
        """(cycle index, phase) for a global step; phase in
        {'wait', 'warmup', 'active', 'done'}."""
        cycle, pos = divmod(step, self.cycle_len)
        if self.repeat and cycle >= self.repeat:
            return cycle, "done"
        if pos < self.wait:
            return cycle, "wait"
        if pos < self.wait + self.warmup:
            return cycle, "warmup"
        return cycle, "active"


class TPUProfiler:
    """Yielded by ``Accelerator.profile``; call :meth:`step` once per
    training step, mirroring ``torch.profiler.profile.step()``.

    Without any ``step()`` calls the whole ``with`` block is one active
    window (the pre-schedule behavior of a bare ``output_trace_dir``).
    """

    def __init__(self, handler, state=None):
        self._handler = handler
        # An all-defaults handler declared no schedule: the whole block is
        # one continuous window even when step() is called each iteration
        # (the reference's no-schedule torch.profiler pattern) — otherwise a
        # naive per-step step() would open/close a trace per training step.
        self._no_schedule = not handler.has_schedule()
        self._schedule = _Schedule(
            wait=handler.wait, warmup=handler.warmup,
            active=max(1, handler.active or 1), repeat=handler.repeat,
        )
        self._state = state
        self.step_num = 0
        self._tracing_cycle: Optional[int] = None
        self._mem_at_start: Optional[dict] = None
        self.summary: dict[str, Any] = {"traced_steps": [], "cycles": 0}
        if handler.with_flops:
            self.summary["flops"] = 0.0
        self._stepped = False

    # -- trace-dir naming ---------------------------------------------------

    def _cycle_dir(self, cycle: int) -> Optional[str]:
        base = self._handler.output_trace_dir
        if base is None:
            return None
        # cycle 0 writes to the configured dir itself — bare-block profiles
        # and single-cycle schedules keep the pre-schedule layout (tooling
        # points TensorBoard at output_trace_dir); later cycles nest
        return base if cycle == 0 else os.path.join(base, f"cycle_{cycle}")

    # -- window transitions -------------------------------------------------

    def _open_window(self, cycle: int) -> None:
        trace_dir = self._cycle_dir(cycle)
        if trace_dir is not None:
            jax.profiler.start_trace(
                trace_dir, create_perfetto_link=self._handler.create_perfetto_link
            )
        if self._handler.profile_memory:
            self._mem_at_start = self._capture_memory()
        self._tracing_cycle = cycle

    def _close_window(self) -> None:
        cycle, self._tracing_cycle = self._tracing_cycle, None
        trace_dir = self._cycle_dir(cycle)
        if trace_dir is not None:
            jax.profiler.stop_trace()
        if self._handler.profile_memory:
            end = self._capture_memory()
            start = self._mem_at_start or {}
            self.summary["memory"] = {
                "bytes_in_use": end.get("bytes_in_use", 0),
                "bytes_delta": end.get("bytes_in_use", 0) - start.get("bytes_in_use", 0),
                "peak_bytes_in_use": end.get("peak_bytes_in_use", 0),
                "bytes_limit": end.get("bytes_limit", 0),
            }
        self.summary["cycles"] += 1
        if self._handler.on_trace_ready is not None and trace_dir is not None:
            # no trace dir = memory/flops-only profiling: there is no trace
            # for the callback to consume (pre-schedule behavior)
            self._handler.on_trace_ready(trace_dir)

    @staticmethod
    def _capture_memory() -> dict:
        try:
            return get_device_memory_stats()
        except Exception:  # platforms without memory_stats
            return {}

    # -- public surface -----------------------------------------------------

    def step(self) -> None:
        """Advance the schedule by one training step, opening/closing the
        trace window at the phase boundaries."""
        self._stepped = True
        in_active = self._tracing_cycle is not None
        if in_active:
            self.summary["traced_steps"].append(self.step_num)
        self.step_num += 1
        if self._no_schedule:
            # continuous-window mode: the block edges own the trace window;
            # step() only records which steps fell inside it
            return
        cycle, phase = self._schedule.locate(self.step_num)
        if in_active and (phase != "active" or cycle != self._tracing_cycle):
            self._close_window()
            in_active = False
        if not in_active and phase == "active":
            self._open_window(cycle)

    def key_averages(self, device_substr: str = "TPU") -> dict:
        """Per-op-class device-time shares from the captured trace — the
        ``torch.profiler`` ``key_averages()`` table analog, decoded from the
        xplane artifact in-process (``utils/xplane.py``).  Call after the
        trace window has closed (outside the ``with`` block or after the
        cycle ended)."""
        from .xplane import op_class_breakdown

        base = self._handler.output_trace_dir
        if base is None:
            raise ValueError("key_averages needs output_trace_dir (no trace was captured)")
        return op_class_breakdown(base, device_substr)

    def streaming_overlap(self, device_substr: str = "TPU") -> dict:
        """Measured transfer-vs-compute occupancy + achieved overlap from
        the captured trace (``utils/xplane.streaming_overlap_report``) — the
        profiler-side view of the ``ops/streaming`` pipelines' accounting.
        Call after the trace window has closed, like :meth:`key_averages`."""
        from .xplane import streaming_overlap_report

        base = self._handler.output_trace_dir
        if base is None:
            raise ValueError(
                "streaming_overlap needs output_trace_dir (no trace was captured)"
            )
        return streaming_overlap_report(base, device_substr)

    def ici_overlap(self, device_substr: str = "TPU") -> dict:
        """Measured ICI collective-vs-compute occupancy from the captured
        trace (``utils/xplane.ici_overlap_report``) — the profiler-side view
        of the ring collective-matmul's ``tp_overlap_frac`` (predicted twin:
        ``ops/collective_matmul.tp_comm_accounting``).  Call after the trace
        window has closed, like :meth:`key_averages`."""
        from .xplane import ici_overlap_report

        base = self._handler.output_trace_dir
        if base is None:
            raise ValueError(
                "ici_overlap needs output_trace_dir (no trace was captured)"
            )
        return ici_overlap_report(base, device_substr)

    def flops_estimate(self, fn, *args, **kwargs) -> float:
        """FLOPs of one call of a jittable ``fn`` at these arguments, from
        XLA's compiled-executable cost analysis; accumulates into
        ``summary['flops']`` when ``with_flops`` is set."""
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # some backends wrap per-device
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        if "flops" in self.summary:
            self.summary["flops"] += flops
        return flops

    # -- context plumbing (driven by Accelerator.profile) -------------------

    def _enter(self):
        cycle, phase = self._schedule.locate(0)
        if phase == "active":
            self._open_window(cycle)
        return self

    def _exit(self):
        if self._tracing_cycle is not None:
            if not self._stepped:
                # bare-block mode: the whole region was one active window
                self.summary["traced_steps"].append(self.step_num)
            self._close_window()
