"""Launch environment construction — config crosses the process boundary
exclusively as ``ACCELERATE_*`` / ``PARALLELISM_CONFIG_*`` / ``FSDP_*`` env
vars, the reference's transport contract (utils/launch.py:99-423; SURVEY §3.1
"Config crosses the boundary only as env vars").

TPU-native differences from the reference:
- no torchrun/elastic layer — workers are plain processes; the collective
  runtime comes up inside the worker via ``jax.distributed.initialize``
  (state.py), keyed off ``ACCELERATE_COORDINATOR_ADDRESS`` /
  ``ACCELERATE_NUM_PROCESSES`` / ``ACCELERATE_PROCESS_ID``;
- TPU pod topology is auto-derived from the TPU metadata env
  (``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES``) when present, mirroring
  reference ``prepare_tpu`` (utils/launch.py:586).
"""

from __future__ import annotations

import os
import sys
from typing import Optional

from .environment import get_free_port


def _script_cmd(args) -> list[str]:
    """Build the worker command line (reference
    ``prepare_simple_launcher_cmd_env`` utils/launch.py:99-150)."""
    cmd = []
    if not getattr(args, "no_python", False):
        cmd.append(sys.executable)
        if getattr(args, "module", False):
            cmd.append("-m")
    cmd.append(args.training_script)
    cmd.extend(args.training_script_args or [])
    return cmd


def config_env(config) -> dict[str, str]:
    """The framework env transport derived from ``config`` ALONE — no ambient
    environ mixed in (cloud manifests must not inherit the operator shell's
    ACCELERATE_* residue)."""
    env = {str(k): str(v) for k, v in (config.env or {}).items()}
    env["ACCELERATE_MIXED_PRECISION"] = str(config.mixed_precision)
    env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] = str(config.gradient_accumulation_steps)
    if config.use_cpu:
        env["ACCELERATE_USE_CPU"] = "true"
    if config.debug:
        env["ACCELERATE_DEBUG_MODE"] = "true"
    if config.use_fsdp:
        env["ACCELERATE_USE_FSDP"] = "true"
        env["FSDP_SHARDING_STRATEGY"] = config.fsdp_sharding_strategy
        env["FSDP_OFFLOAD_PARAMS"] = str(config.fsdp_offload_params).lower()
        env["FSDP_ACTIVATION_CHECKPOINTING"] = str(config.fsdp_activation_checkpointing).lower()
    # Parallelism axes — PARALLELISM_CONFIG_* transport
    # (reference parallelism_config.py:274-289 / utils/launch.py:397).
    from ..parallelism_config import AXIS_SIZE_FIELDS

    for field in AXIS_SIZE_FIELDS:
        env[f"PARALLELISM_CONFIG_{field.upper()}"] = str(getattr(config, field))
    return env


def _base_env(args, config) -> dict[str, str]:
    """Env vars common to every launch mode.  ``config`` is a
    :class:`~accelerate_tpu.commands.config.LaunchConfig` already merged with
    CLI flags (flag > file > default)."""
    env = os.environ.copy()
    # An uninstalled source checkout must stay importable in workers: the
    # child runs the user script by path (sys.path[0] = script dir), so the
    # package root rides PYTHONPATH (reference installs; we may not be).
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH")) if p
    )
    env.update(config_env(config))
    return env


def prepare_simple_launcher_cmd_env(args, config) -> tuple[list[str], dict[str, str]]:
    """Single-process launch (reference utils/launch.py:99)."""
    return _script_cmd(args), _base_env(args, config)


def prepare_multiprocess_env(args, config, process_id: int) -> dict[str, str]:
    """Env for worker ``process_id`` of a multi-process launch.

    The worker's ``PartialState`` reads the three ``ACCELERATE_*`` coordinator
    vars and calls ``jax.distributed.initialize`` (state.py:47) — the analog of
    torchrun's ``RANK``/``WORLD_SIZE``/``MASTER_ADDR`` contract
    (reference utils/launch.py:198 ``prepare_multi_gpu_env``).
    """
    env = _base_env(args, config)
    ip = config.main_process_ip or "127.0.0.1"
    port = config.main_process_port or get_free_port()
    config.main_process_port = port  # pin so every worker agrees
    env["ACCELERATE_COORDINATOR_ADDRESS"] = f"{ip}:{port}"
    env["ACCELERATE_NUM_PROCESSES"] = str(config.num_processes)
    env["ACCELERATE_PROCESS_ID"] = str(process_id)
    return env


def discover_slice_topology() -> Optional[dict[str, int]]:
    """Slice-level topology from the multi-slice runtime metadata env, if
    present: ``{"num_slices": N, "slice_id": i}``.

    On Cloud TPU multislice the MegaScale runtime exports
    ``MEGASCALE_NUM_SLICES`` / ``MEGASCALE_SLICE_ID`` on every host; a
    single-slice pod (or a laptop) has neither and returns ``None``.  The
    launcher uses this to auto-fill ``ParallelismConfig.dcn_size`` — the
    explicit cross-slice mesh axis the hierarchical gradient-sync path keys
    off — when the operator left it unspecified."""
    num = os.environ.get("MEGASCALE_NUM_SLICES")
    if num is None:
        return None
    try:
        num_slices = int(num)
    except ValueError:
        return None
    if num_slices < 2:
        return None
    slice_id = os.environ.get("MEGASCALE_SLICE_ID")
    return {
        "num_slices": num_slices,
        "slice_id": int(slice_id) if slice_id is not None else 0,
    }


def topology_summary(config) -> str:
    """One-line slice×host topology description for launch-time logging."""
    hosts = config.num_processes
    slices = getattr(config, "dcn_size", 1) or 1
    if slices > 1:
        return (
            f"{slices} slices x {max(hosts // slices, 1)} hosts/slice "
            f"({hosts} processes; dcn axis size {slices})"
        )
    return f"1 slice x {hosts} host{'s' if hosts != 1 else ''}"


def prepare_tpu_pod_env(args, config) -> Optional[dict[str, str]]:
    """Auto-derive multi-host topology from TPU pod metadata env, if present
    (reference ``prepare_tpu`` utils/launch.py:586 — but env-derived rather
    than gcloud-SSH-orchestrated; on Cloud TPU each host's runtime already
    exports its identity)."""
    worker_id = os.environ.get("TPU_WORKER_ID") or os.environ.get("CLOUD_TPU_TASK_ID")
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if worker_id is None or not hostnames:
        return None
    hosts = [h.strip() for h in hostnames.split(",") if h.strip()]
    config.num_processes = len(hosts)
    config.machine_rank = int(worker_id)
    config.main_process_ip = hosts[0]
    config.main_process_port = config.main_process_port or 8476  # TPU runtime default port range
    # Multi-slice metadata fills the dcn axis the operator left unspecified:
    # the worker's ParallelismConfig.from_env then builds the mesh with the
    # explicit cross-slice outer axis (flag > file > metadata precedence —
    # an explicit dcn_size is never overwritten).
    slices = discover_slice_topology()
    if slices is not None and getattr(config, "dcn_size", 1) == 1:
        config.dcn_size = slices["num_slices"]
    env = _base_env(args, config)
    env["ACCELERATE_COORDINATOR_ADDRESS"] = f"{config.main_process_ip}:{config.main_process_port}"
    env["ACCELERATE_NUM_PROCESSES"] = str(config.num_processes)
    env["ACCELERATE_PROCESS_ID"] = str(config.machine_rank)
    return env


def apply_cpu_device_flags(env: dict[str, str], num_cpu_devices: Optional[int]) -> None:
    """Append the virtual-device XLA flag for CPU fake-mesh workers."""
    if num_cpu_devices:
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = f"{flags} --xla_force_host_platform_device_count={num_cpu_devices}".strip()


class PrepareForLaunch:
    """Picklable callable handed to ``multiprocessing`` start methods
    (reference utils/launch.py:776) — sets per-process env then calls the
    user function."""

    def __init__(self, launcher, env: dict[str, str], process_id: int):
        self.launcher = launcher
        self.env = env
        self.process_id = process_id

    def __call__(self, *args):
        os.environ.update(self.env)
        os.environ["ACCELERATE_PROCESS_ID"] = str(self.process_id)
        os.environ["FORK_LAUNCHED"] = "1"
        self.launcher(*args)
        # Synchronized teardown: without a barrier, the first worker to exit
        # tears the coordination service down while peers still heartbeat,
        # turning a clean run into a fatal "Socket closed" on the laggard.
        try:
            import jax

            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices("accelerate_tpu.worker_exit")
                jax.distributed.shutdown()
        except Exception:  # teardown must never mask the user function's success
            pass
