"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` mesh axis.

Capability parity with the reference's two PP paths (SURVEY §2.4 P7):
``prepare_pippy`` (reference inference.py:126-186 — stage split + GPipe
microbatch forward via torch.distributed.pipelining) and Megatron training PP
(reference utils/megatron_lm.py ``pp_degree``).  The TPU-native design is a
single SPMD program instead of per-stage processes:

- The model's homogeneous decoder blocks are **stacked** along a leading
  layer dim and sharded over the ``pp`` mesh axis — each stage holds
  ``num_layers/pp`` consecutive blocks and runs them with ``lax.scan``.
- The GPipe schedule is a ``lax.scan`` over ``num_microbatches + pp - 1``
  clock ticks inside ``jax.shard_map`` (manual over ``pp`` only; dp/tp/sp
  axes stay under GSPMD auto sharding, so PP composes with FSDP/TP by
  construction).  Stage hand-off is a single ``lax.ppermute`` per tick —
  point-to-point neighbor traffic that can ride DCN.
- Embedding and LM head run *outside* the pipeline loop on every stage
  (they are cheap relative to the blocks and keeping them out makes the
  pipelined activation buffer shape-homogeneous).
- The whole schedule is built from ``scan``/``ppermute``/``where`` — all
  reverse-differentiable — so ``jax.grad`` through a pipelined forward yields
  the pipelined backward schedule automatically: this gives *training* PP,
  which the reference only reaches via Megatron.

Bubble fraction is the classic ``(pp-1)/(mb+pp-1)``; pick
``num_microbatches >= 4*pp`` to keep it small.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .collectives import axis_size, partial_manual_kwargs


# ---------------------------------------------------------------------------
# Stage-parameter surgery
# ---------------------------------------------------------------------------


def stack_layer_params(params: dict, num_layers: int, prefix: str = "layers_"):
    """Split a flax param dict into (stacked block params, non-block rest).

    ``params`` is the inner ``{"params": ...}`` dict of a model whose decoder
    blocks live under ``{prefix}{i}`` keys (models/llama.py:228).  The stacked
    tree has a new leading layer dim of size ``num_layers``.
    """
    layers = []
    rest = {}
    for key, sub in params.items():
        if key.startswith(prefix) and key[len(prefix):].isdigit():
            layers.append((int(key[len(prefix):]), sub))
        else:
            rest[key] = sub
    if len(layers) != num_layers:
        raise ValueError(
            f"expected {num_layers} '{prefix}*' block subtrees, found {len(layers)}"
        )
    layers = [sub for _, sub in sorted(layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    return stacked, rest


def unstack_layer_params(stacked, rest: dict, prefix: str = "layers_") -> dict:
    """Inverse of :func:`stack_layer_params` (checkpoint interchange)."""
    num_layers = jax.tree.leaves(stacked)[0].shape[0]
    out = dict(rest)
    for i in range(num_layers):
        out[f"{prefix}{i}"] = jax.tree.map(lambda x, i=i: x[i], stacked)
    return out


def stage_sharding(mesh: Mesh, axis_name: str = "pp"):
    """NamedSharding pinning the leading (layer) dim to pipeline stages."""
    return lambda leaf: NamedSharding(mesh, P(axis_name, *([None] * (leaf.ndim - 1))))


# ---------------------------------------------------------------------------
# The GPipe schedule (shard_map body, manual over the pp axis only)
# ---------------------------------------------------------------------------


def _gpipe_body(
    stage_params,
    x_mbs,
    block_fn: Callable,
    axis_name: str,
    num_microbatches: int,
):
    """Per-stage program.  ``stage_params``: this stage's stacked block params
    ``[layers_per_stage, ...]``; ``x_mbs``: ALL microbatch activations
    ``[num_mb, mb, T, H]`` (replicated over pp — only stage 0 reads them).

    Clock tick ``t``: stage ``s`` works on microbatch ``t - s`` (GPipe fill/
    steady/drain); the result is ppermute'd to stage ``s+1``.  The last stage
    records finished microbatches; a masked psum replicates them to every
    stage at the end.
    """
    pp = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    total_ticks = num_microbatches + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def stage_forward(x):
        def layer(h, layer_params):
            return block_fn(layer_params, h), None

        h, _ = lax.scan(layer, x, stage_params)
        return h

    def tick(carry, t):
        buf, outs = carry
        in_idx = jnp.clip(t, 0, num_microbatches - 1)
        feed = lax.dynamic_index_in_dim(x_mbs, in_idx, 0, keepdims=False)
        x = jnp.where(rank == 0, feed, buf)
        y = stage_forward(x)
        out_idx = jnp.clip(t - (pp - 1), 0, num_microbatches - 1)
        cur = lax.dynamic_index_in_dim(outs, out_idx, 0, keepdims=False)
        write = jnp.logical_and(rank == pp - 1, t >= pp - 1)
        outs = lax.dynamic_update_index_in_dim(outs, jnp.where(write, y, cur), out_idx, 0)
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, outs), None

    carry0 = (jnp.zeros_like(x_mbs[0]), jnp.zeros_like(x_mbs))
    (buf, outs), _ = lax.scan(tick, carry0, jnp.arange(total_ticks))
    # Replicate the last stage's collected outputs to every stage so the
    # (replicated) head can run everywhere — one masked all-reduce.
    outs = jnp.where(rank == pp - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis_name)


def pipeline_blocks(
    stacked_params,
    x,
    block_fn: Callable,
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis_name: str = "pp",
    remat: bool = False,
):
    """Run stacked decoder blocks as a ``pp``-stage GPipe pipeline.

    ``stacked_params``: block params with leading layer dim ``[L, ...]``
    (shard over ``axis_name``); ``x``: activations ``[B, T, H]``;
    ``block_fn(layer_params, h) -> h``.  Returns ``[B, T, H]``.
    Differentiable — grad gives the pipelined backward pass.
    """
    pp = mesh.shape[axis_name]
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % pp:
        raise ValueError(f"num_layers {num_layers} not divisible by pp {pp}")
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by num_microbatches {num_microbatches}")
    if remat:
        block_fn = jax.checkpoint(block_fn)

    # XLA CPU-backend workaround: bf16 schedule buffers crossing the
    # partial-manual shard_map boundary (select/ppermute/psum) hit an XLA
    # check failure ("Invalid binary instruction opcode copy") on multi-axis
    # meshes.  Keep the *schedule* buffers fp32 on CPU — the block still
    # computes in its own dtype, so the unit-test numerics match TPU.
    orig_dtype = x.dtype
    cpu_bf16 = jax.default_backend() == "cpu" and orig_dtype == jnp.bfloat16
    if cpu_bf16:
        inner_fn = block_fn
        block_fn = lambda p, h: inner_fn(p, h.astype(orig_dtype)).astype(jnp.float32)  # noqa: E731
        x = x.astype(jnp.float32)

    # [pp, layers_per_stage, ...] so the pp axis is the leading dim shard.
    staged = jax.tree.map(
        lambda p: p.reshape((pp, num_layers // pp) + p.shape[1:]), stacked_params
    )
    x_mbs = x.reshape((num_microbatches, batch // num_microbatches) + x.shape[1:])

    body = functools.partial(
        _gpipe_body, block_fn=block_fn, axis_name=axis_name,
        num_microbatches=num_microbatches,
    )
    param_specs = jax.tree.map(lambda p: P(axis_name, *([None] * (p.ndim - 1))), staged)
    out = shard_map(
        lambda sp, xs: body(jax.tree.map(lambda a: a[0], sp), xs),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        **partial_manual_kwargs({axis_name}),
    )(staged, x_mbs)
    if cpu_bf16:
        out = out.astype(orig_dtype)
    return out.reshape((batch,) + x.shape[1:])


# ---------------------------------------------------------------------------
# prepare_pipeline — the user-facing one-call API (reference prepare_pippy,
# inference.py:126)
# ---------------------------------------------------------------------------


class PipelinedModel:
    """A causal-LM wrapped for pipeline-parallel execution.

    Mirrors the contract of reference ``prepare_pippy`` (inference.py:126):
    hand in a model + params, get back a callable that runs a microbatched
    pipelined forward.  Works for any model following the
    ``LlamaForCausalLM`` skeleton (embed → homogeneous ``layers_i`` blocks →
    final norm → lm_head; models/llama.py:205).
    """

    def __init__(
        self,
        model,
        params,
        mesh: Mesh,
        *,
        num_microbatches: int = 8,
        axis_name: str = "pp",
        remat: Optional[bool] = None,
    ):
        cfg = model.config
        self.model = model
        self.config = cfg
        self.mesh = mesh
        self.axis_name = axis_name
        self.num_microbatches = num_microbatches
        # Honor the model config's activation-checkpointing flag unless
        # explicitly overridden — a config that fit in HBM un-pipelined must
        # not silently lose remat when switched to PP.
        self.remat = getattr(cfg, "remat", False) if remat is None else remat

        inner = params["params"] if "params" in params else params
        stacked, rest = stack_layer_params(dict(inner), cfg.num_hidden_layers)
        # Pin stage params to their pipeline ranks; everything else stays
        # under whatever sharding it already has (GSPMD auto axes).
        pin = stage_sharding(mesh, axis_name)
        self.stacked = jax.tree.map(lambda p: jax.device_put(p, pin(p)), stacked)
        self.rest = rest
        self._block = type(model).block_cls(cfg)
        self._fwd = jax.jit(self._forward)

    # -- pieces ------------------------------------------------------------

    def _block_fn(self, positions):
        block = self._block

        def fn(layer_params, h):
            return block.apply({"params": layer_params}, h, positions)

        return fn

    def _forward(self, stacked, rest, input_ids):
        cfg = self.config
        b, t = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(t), (b // self.num_microbatches, t))
        emb = rest["embed_tokens"]["embedding"]
        x = emb[input_ids].astype(cfg.dtype)
        x = pipeline_blocks(
            stacked, x, self._block_fn(positions), self.mesh,
            num_microbatches=self.num_microbatches, axis_name=self.axis_name,
            remat=self.remat,
        )
        from ..models.llama import RMSNorm

        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype).apply({"params": rest["norm"]}, x)
        if cfg.tie_word_embeddings:
            return x @ emb.astype(jnp.float32).T
        return x.astype(jnp.float32) @ rest["lm_head"]["kernel"].astype(jnp.float32)

    def __call__(self, input_ids):
        return self._fwd(self.stacked, self.rest, input_ids)

    # -- interchange -------------------------------------------------------

    def merged_params(self) -> dict:
        """Reassemble the original (non-stacked) param dict."""
        return {"params": unstack_layer_params(jax.device_get(self.stacked), self.rest)}


def prepare_pipeline(
    model,
    params,
    mesh: Optional[Mesh] = None,
    *,
    num_microbatches: int = 8,
    axis_name: str = "pp",
    remat: Optional[bool] = None,
) -> PipelinedModel:
    """One-call pipeline-parallel wrap (reference prepare_pippy inference.py:126).

    ``mesh`` defaults to the ambient :class:`AcceleratorState` mesh.
    """
    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh
    return PipelinedModel(
        model, params, mesh,
        num_microbatches=num_microbatches, axis_name=axis_name, remat=remat,
    )
