"""Context parallelism: ring attention over the ``cp`` mesh axis.

TPU-native re-design of reference CP (P8, accelerator.py:1641-1654 +
maybe_context_parallel :4076-4140): the sequence dimension is sharded over
``cp`` and attention runs blockwise while KV shards rotate around the ring.

Two rotate methods, matching the reference's ``set_rotate_method``:
- ``allgather``: gather all KV once, one local attention (cheap at moderate
  seq, one collective);
- ``alltoall`` (ring): KV streams neighbor-to-neighbor via ``ppermute`` over
  ICI; memory O(T/cp), comm overlapped with compute by XLA's latency-hiding
  scheduler — this is ring attention proper.

Causal masking across shards uses **zigzag load balancing** (reference CP
docs' load-balanced ordering): shard i holds chunks (i, 2cp-1-i) so every
rank does equal causal work.  Helpers ``zigzag_shard``/``zigzag_unshard``
reorder the sequence on the host before sharding.

Numerics: blockwise online-softmax combine across ring steps (same math as
flash attention's running max/denom, applied shard-to-shard in fp32).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .collectives import axis_size, partial_manual_kwargs

NEG_INF = -1e30


def _block_attend(q, k, v, scores_mask, sm_scale):
    """One (q-shard, kv-shard) block: returns (numerator, denom, max) in fp32.

    q: [B, Tq, H, D]; k/v: [B, Tk, Hkv, D] (GQA broadcast here); scores_mask:
    [Tq, Tk] or [B, Tq, Tk] bool, or None.
    """
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * sm_scale
    if scores_mask is not None:
        if scores_mask.ndim == 2:
            scores_mask = scores_mask[None]
        scores = jnp.where(scores_mask[:, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)  # [B,H,Tq,1]
    # fully-masked rows: exp(NEG_INF - NEG_INF) would be 1 — zero them
    row_valid = m > NEG_INF / 2
    p = jnp.where(row_valid, jnp.exp(scores - m), 0.0)
    num = jnp.einsum("bhts,bshd->bthd", p.astype(v.dtype), v).astype(jnp.float32)
    denom = jnp.sum(p, axis=-1)[..., None].transpose(0, 2, 1, 3)  # [B,Tq,H,1]
    m = m[..., 0].transpose(0, 2, 1)[..., None]  # [B,Tq,H,1]
    return num, denom, m


def _combine(acc, new):
    """Online-softmax combine of two partial attentions."""
    num_a, den_a, m_a = acc
    num_n, den_n, m_n = new
    m = jnp.maximum(m_a, m_n)
    alpha = jnp.exp(m_a - m)
    beta = jnp.exp(m_n - m)
    return (num_a * alpha + num_n * beta, den_a * alpha + den_n * beta, m)


def _chunk_index_map(cp: int):
    """Zigzag layout: rank i holds global chunks (i, 2cp-1-i)."""
    return [(i, 2 * cp - 1 - i) for i in range(cp)]


def zigzag_shard(x, cp: int, axis: int = 1):
    """Reorder a [B, T, ...] array so contiguous per-rank shards carry zigzag
    chunk pairs.  Apply on host before forming the global array."""
    t = x.shape[axis]
    assert t % (2 * cp) == 0, f"seq len {t} must divide 2*cp={2*cp}"
    chunks = np.split(np.asarray(x), 2 * cp, axis=axis)
    order = [c for pair in _chunk_index_map(cp) for c in pair]
    return np.concatenate([chunks[i] for i in order], axis=axis)


def zigzag_unshard(x, cp: int, axis: int = 1):
    t = x.shape[axis]
    chunks = np.split(np.asarray(x), 2 * cp, axis=axis)
    order = [c for pair in _chunk_index_map(cp) for c in pair]
    inverse = np.argsort(order)
    return np.concatenate([chunks[i] for i in inverse], axis=axis)


def _zigzag_positions(t_local: int, t_global: int, cp_rank, cp: int):
    """Global token positions held by ``cp_rank`` under zigzag layout."""
    chunk = t_global // (2 * cp)
    first = cp_rank * chunk
    second = (2 * cp - 1 - cp_rank) * chunk
    return jnp.concatenate([first + jnp.arange(chunk), second + jnp.arange(chunk)])


def _combine_lse(a, b):
    """Combine two (out, lse) partial attentions (out [B,T,H,D], lse
    [B,T,H]) — the flash-kernel-block path; fully differentiable."""
    out_a, lse_a = a
    out_b, lse_b = b
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp(lse_a - m)
    wb = jnp.exp(lse_b - m)
    den = jnp.maximum(wa + wb, 1e-30)
    out = out_a * (wa / den)[..., None] + out_b * (wb / den)[..., None]
    return out, m + jnp.log(den)


def ring_attention_sharded(
    q, k, v, seg=None, *, axis_name: str = "cp", causal: bool = True,
    sm_scale: Optional[float] = None, rotate_method: str = "alltoall",
    zigzag: bool = True, use_flash: Optional[bool] = None,
):
    """The shard_map body: q/k/v are LOCAL shards [B, T/cp, H, D] / [B, T/cp,
    Hkv, D] (GQA: kv heads stay un-repeated — the flash kernel maps q heads
    to their group's kv head, the XLA path broadcasts per block — so ppermute
    moves only Hkv-sized tensors over ICI).

    With ``alltoall`` KV rotates ``cp`` times around the ring (ppermute);
    with ``allgather`` KV is gathered once and attention is a single local
    block.  Causal masks are built from global zigzag positions.

    ``seg`` [B, T/cp] are local segment ids (packed sequences): the query
    side stays put while the KV side travels with K/V around the ring, and
    cross-segment pairs are masked in-kernel.

    ``use_flash`` (default: on TPU) computes each (q-shard, kv-shard) block
    with the Pallas flash kernel — global zigzag positions feed the kernel's
    position-based causal mask, and blocks combine via the kernel's
    differentiable logsumexp output.  Off-TPU the XLA blockwise path runs.
    """
    cp = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    t_global = t_local * cp
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    # Zigzag needs 2 chunks per rank; indivisible lengths (e.g. a short
    # model.init trace) cannot have been zigzag_shard-ed by the caller, so
    # they are contiguous — use contiguous positions.
    if t_global % (2 * cp) != 0:
        zigzag = False
    if use_flash is None:
        # fallback when called directly as a shard_map body; make_ring_attention
        # resolves this from the mesh's own devices instead
        from ..ops.flash_attention import _on_tpu

        use_flash = _on_tpu()

    if zigzag and causal:
        q_pos = _zigzag_positions(t_local, t_global, rank, cp)
    else:
        q_pos = rank * t_local + jnp.arange(t_local)

    def pos_for(kv_rank):
        if zigzag and causal:
            return _zigzag_positions(t_local, t_global, kv_rank, cp)
        return kv_rank * t_local + jnp.arange(t_local)

    def mask_for(kv_rank, kv_seg=None):
        """[Tq, Tk] or [B, Tq, Tk] mask for the XLA path (causal ∧ segment)."""
        mask = None
        if causal:
            mask = q_pos[:, None] >= pos_for(kv_rank)[None, :]
        if kv_seg is not None:
            seg_mask = seg[:, :, None] == kv_seg[:, None, :]
            mask = seg_mask if mask is None else mask[None] & seg_mask
        return mask

    if use_flash:
        from ..ops.flash_attention import flash_attention

        pos_q_b = jnp.broadcast_to(q_pos, (b, t_local))

        def attend(kv_pos, k_blk, v_blk, kv_seg=None):
            out, lse = flash_attention(
                q, k_blk, v_blk, causal=causal, sm_scale=sm_scale,
                segment_ids=seg, kv_segment_ids=kv_seg,
                positions=pos_q_b if causal else None,
                kv_positions=jnp.broadcast_to(kv_pos, (b, t_local)) if causal else None,
                return_lse=True,
            )
            return out.astype(jnp.float32), lse

        zero = (
            jnp.zeros((b, t_local, h, d), jnp.float32),
            jnp.full((b, t_local, h), NEG_INF, jnp.float32),
        )
        combine = _combine_lse
    else:
        zero = (
            jnp.zeros((b, t_local, h, d), jnp.float32),
            jnp.zeros((b, t_local, h, 1), jnp.float32),
            jnp.full((b, t_local, h, 1), NEG_INF, jnp.float32),
        )
        combine = _combine

    if rotate_method == "allgather":
        k_all = lax.all_gather(k, axis_name, axis=0, tiled=False)  # [cp, B, T/cp, Hkv, D]
        v_all = lax.all_gather(v, axis_name, axis=0, tiled=False)
        seg_all = lax.all_gather(seg, axis_name, axis=0, tiled=False) if seg is not None else None
        acc = zero
        for kv_rank in range(cp):
            kv_seg = seg_all[kv_rank] if seg is not None else None
            if use_flash:
                part = attend(pos_for(kv_rank), k_all[kv_rank], v_all[kv_rank], kv_seg)
            else:
                part = _block_attend(
                    q, k_all[kv_rank], v_all[kv_rank], mask_for(kv_rank, kv_seg), sm_scale
                )
            acc = combine(acc, part)
    else:
        # ring: step s sees KV originally from rank (rank - s) mod cp
        perm = [(i, (i + 1) % cp) for i in range(cp)]

        def ring_step(s, carry):
            k_cur, v_cur, seg_cur, acc = carry
            kv_rank = (rank - s) % cp
            if use_flash:
                part = attend(pos_for(kv_rank), k_cur, v_cur, seg_cur)
            else:
                mask = None
                if causal:
                    # select the right causal mask for this step's kv source rank
                    mask = jnp.stack([mask_for(r) for r in range(cp)])[kv_rank]
                    if seg_cur is not None:
                        mask = mask[None] & (seg[:, :, None] == seg_cur[:, None, :])
                elif seg_cur is not None:
                    mask = seg[:, :, None] == seg_cur[:, None, :]
                part = _block_attend(q, k_cur, v_cur, mask, sm_scale)
            acc = combine(acc, part)
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
            seg_nxt = lax.ppermute(seg_cur, axis_name, perm) if seg_cur is not None else None
            return (k_nxt, v_nxt, seg_nxt, acc)

        carry = (k, v, seg, zero)
        for s in range(cp):  # unrolled: cp is small; lets XLA overlap ppermute+compute
            carry = ring_step(s, carry)
        acc = carry[3]

    if use_flash:
        out, _ = acc
        return out.astype(q.dtype)
    num, den, _ = acc
    return (num / jnp.maximum(den, 1e-30)).astype(q.dtype)


@functools.lru_cache(maxsize=None)
def make_ring_attention(mesh: Mesh, axis_name: str = "cp", rotate_method: str = "alltoall",
                        zigzag: bool = True, use_flash: Optional[bool] = None):
    """Build the mesh-bound ring attention usable inside a jitted model.

    Returns ``attn(q, k, v, causal=True, segment_ids=None)`` operating on
    GLOBAL arrays whose sequence dim is sharded over ``axis_name``.
    """
    if use_flash is None:
        # decide from the mesh's own devices, not the process default backend
        # (a CPU debug mesh on a TPU-attached host must take the XLA path)
        use_flash = mesh.devices.flat[0].platform == "tpu"

    # Partial-manual: only the ring axis is manualized; every other mesh
    # axis stays under GSPMD inside the body, so a tp-sharded head dim or a
    # dp-sharded batch dim keeps its sharding through the ring (a
    # full-manual region would all-gather them per step — cp×tp and cp×dp
    # compositions rely on this).  jax 0.9's eager partial-manual validator
    # rejects multi-axis meshes spuriously, so the shard_map runs under a
    # cached jit (inlined when the caller is itself jitted).
    @functools.lru_cache(maxsize=None)
    def _build(causal: bool, with_seg: bool):
        spec = P(None, axis_name, None, None)
        body = functools.partial(
            ring_attention_sharded, axis_name=axis_name, causal=causal,
            rotate_method=rotate_method, zigzag=zigzag, use_flash=use_flash,
        )
        in_specs = (spec, spec, spec) + ((P(None, axis_name),) if with_seg else ())
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=spec,
            **partial_manual_kwargs({axis_name}),
        ))

    def attn(q, k, v, *, causal: bool = True, segment_ids=None):
        if segment_ids is None:
            return _build(causal, False)(q, k, v)
        # NOTE: under zigzag layout the caller shards segment_ids with the
        # same zigzag_shard reorder as the tokens
        # (Accelerator.maybe_context_parallel does this for step buffers)
        # so local ids line up with local tokens.
        return _build(causal, True)(q, k, v, jnp.asarray(segment_ids, jnp.int32))

    return attn


def ring_attention(q, k, v, *, causal: bool = True, segment_ids=None):
    """Config-name entry (models.llama attn_implementation='ring'): resolves
    the mesh from the ambient AcceleratorState."""
    from ..state import AcceleratorState

    state = AcceleratorState()
    cfg = state.parallelism_config
    rotate = "alltoall"
    return make_ring_attention(state.mesh, rotate_method=rotate)(
        q, k, v, causal=causal, segment_ids=segment_ids
    )
