"""Parameter-sharding planner: FSDP/ZeRO/TP/HSDP as PartitionSpec assignment.

This is the heart of the strategy layer (SURVEY §2.4): where the reference
wraps models in engines (torch FSDP accelerator.py:1885, DTensor
``fully_shard`` fsdp_utils.py:621, DeepSpeed zero-stage engines), the
TPU-native design assigns a :class:`NamedSharding` to every parameter — XLA's
GSPMD partitioner then *is* the runtime.  FSDP ≅ shard params/grads/optimizer
state over ``dp_shard`` (+``cp`` under the flattened ``dp_shard_cp`` joint dim,
reference parallelism_config.py:157-164); TP = rule-matched specs on attention
/MLP matrices; HSDP = replicate over ``dp_replicate`` (DCN) while sharding
over ``dp_shard`` (ICI).

The "auto wrap policy" analog (reference fsdp auto_wrap_policy
accelerator.py:1909-1937) is ``min_weight_size``: parameters smaller than it
stay replicated — sharding tiny tensors costs more in collective latency than
it saves in HBM.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallelism_config import ParallelismConfig
from ..utils.dataclasses import FullyShardedDataParallelPlugin, ShardingStrategy

logger = logging.getLogger(__name__)


def path_str(path) -> str:
    """Render a jax key path as 'a/b/0/c' for regex rule matching."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _axis_size(mesh: Mesh, axes) -> int:
    if axes is None:
        return 1
    if isinstance(axes, str):
        axes = (axes,)
    size = 1
    for a in axes:
        size *= mesh.shape[a]
    return size


def _spec_for_leaf(
    path: str,
    shape: tuple[int, ...],
    mesh: Mesh,
    fsdp_axes: tuple[str, ...],
    min_weight_size: int,
    tp_rules: Sequence[tuple[str, PartitionSpec]],
) -> PartitionSpec:
    ndim = len(shape)
    spec: list = [None] * ndim

    # Scanned layer stacks (models' scan_layers=True) carry a leading
    # num_layers dim under the "layers_scan" module — TP rules written for
    # the per-layer shapes shift right by one
    offset = 1 if "layers_scan" in path else 0

    # 1. TP rules first (they own specific dims)
    for pattern, rule_spec in tp_rules:
        if re.search(pattern, path):
            for d, entry in enumerate(rule_spec):
                d += offset
                if d >= ndim or entry is None:
                    continue
                size = _axis_size(mesh, entry)
                if size > 1 and shape[d] % size == 0:
                    spec[d] = entry
                elif size > 1:
                    logger.warning(
                        "TP rule %r wants to shard dim %d of %s %s but %d %% %d != 0; replicating",
                        pattern, d, path, shape, shape[d], size,
                    )
            break

    # 2. FSDP: shard the largest still-free, divisible dim — but never below
    # the TPU tile (8 sublanes x 128 lanes): a shard extent smaller than the
    # tile forces the partitioner into replicate-then-reshard churn
    # ("involuntary full rematerialization") every time the param crosses a
    # differently-sharded region (e.g. the cp ring shard_map), costing ICI
    # traffic each step.  Small params replicate instead — the same trade
    # min_weight_size makes, applied per-dim.
    fsdp_size = _axis_size(mesh, fsdp_axes)
    if fsdp_size > 1 and int(np.prod(shape)) >= min_weight_size:
        def _tile_ok(d: int) -> bool:
            extent = shape[d] // fsdp_size
            return extent >= (128 if d == ndim - 1 else 8)

        candidates = sorted(
            (
                d for d in range(ndim)
                if spec[d] is None and shape[d] % fsdp_size == 0 and _tile_ok(d)
            ),
            key=lambda d: shape[d],
            reverse=True,
        )
        if candidates:
            spec[candidates[0]] = fsdp_axes if len(fsdp_axes) > 1 else fsdp_axes[0]

    return PartitionSpec(*spec)


def resolve_sharding_strategy(
    fsdp_plugin: Optional[FullyShardedDataParallelPlugin],
    parallelism_config: Optional[ParallelismConfig],
) -> ShardingStrategy:
    """The effective strategy a config resolves to: an explicit plugin wins;
    otherwise a non-trivial ``dp_shard`` axis implies FULL_SHARD (ZeRO-3 is
    the point of asking for that axis) and anything else is NO_SHARD."""
    if fsdp_plugin is not None:
        return fsdp_plugin.sharding_strategy
    cfg = parallelism_config or ParallelismConfig()
    return ShardingStrategy.FULL_SHARD if cfg.dp_shard_size > 1 else ShardingStrategy.NO_SHARD


def param_fsdp_axes(mesh: Mesh, cfg: ParallelismConfig, strategy: ShardingStrategy) -> tuple:
    """Mesh axes *parameters* actually shard over under ``strategy``.

    Empty means replicated params.  Under FULL_SHARD/HYBRID the axes come
    from ``fsdp_dim_names`` (default ``dp_shard`` when non-trivial), minus
    ``cp``: params consumed inside the cp ring shard_map (a *manual* region
    over cp) must be cp-replicated there; sharding them over the joint
    (dp_shard, cp) axes makes the partitioner replicate-then-reshard every
    layer every step ("involuntary full rematerialization" — wasted ICI).
    The optimizer state keeps the full joint ZeRO sharding (it never crosses
    the shard_map) — see make_opt_state_sharding_plan.  NO_SHARD /
    SHARD_GRAD_OP replicate parameters across dp (grad/optimizer sharding
    for SHARD_GRAD_OP is applied to opt_state only)."""
    if strategy not in (ShardingStrategy.FULL_SHARD, ShardingStrategy.HYBRID_SHARD):
        return ()
    fsdp_axes = cfg.fsdp_dim_names or (("dp_shard",) if mesh.shape.get("dp_shard", 1) > 1 else ())
    return tuple(a for a in fsdp_axes if a != "cp" and mesh.shape.get(a, 1) > 1)


def make_sharding_plan(
    params,
    mesh: Mesh,
    parallelism_config: Optional[ParallelismConfig] = None,
    fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
    tp_rules: Optional[Sequence[tuple[str, PartitionSpec]]] = None,
):
    """Assign a NamedSharding to every parameter leaf.

    ``params`` may be a real pytree or a tree of ``jax.ShapeDtypeStruct``
    (abstract planning — the big-model path, no materialization needed).
    Returns a pytree of :class:`NamedSharding` with the same structure.
    """
    cfg = parallelism_config or ParallelismConfig()
    tp_rules = list(tp_rules or [])

    strategy = resolve_sharding_strategy(fsdp_plugin, cfg)
    min_size = fsdp_plugin.min_weight_size if fsdp_plugin is not None else 2**12
    fsdp_axes = param_fsdp_axes(mesh, cfg, strategy)

    def _leaf(path, leaf):
        shape = tuple(leaf.shape) if hasattr(leaf, "shape") else ()
        if not shape:
            return NamedSharding(mesh, PartitionSpec())
        return NamedSharding(
            mesh, _spec_for_leaf(path_str(path), shape, mesh, tuple(fsdp_axes), min_size, tp_rules)
        )

    return jax.tree_util.tree_map_with_path(_leaf, params)


def make_opt_state_sharding_plan(
    opt_state_shapes,
    params_plan,
    mesh: Mesh,
    parallelism_config: Optional[ParallelismConfig] = None,
    fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
):
    """Sharding plan for optimizer state (the ZeRO-1/2 axis of the design).

    Moment tensors that mirror a parameter inherit that parameter's sharding;
    under SHARD_GRAD_OP (ZeRO-2 analog) mirrors are *additionally* sharded
    even though params are replicated.  Scalar counts replicate.
    """
    cfg = parallelism_config or ParallelismConfig()
    plugin = fsdp_plugin
    shard_opt = plugin is None or plugin.sharding_strategy != ShardingStrategy.NO_SHARD

    # index param shardings by path for mirror matching (optax moment trees
    # embed the param tree, so param paths appear as suffixes)
    flat_plan = {path_str(p): s for p, s in jax.tree_util.tree_flatten_with_path(
        params_plan, is_leaf=lambda x: isinstance(x, NamedSharding))[0]}

    min_size = plugin.min_weight_size if plugin is not None else 2**12
    if shard_opt:
        fsdp_axes = cfg.fsdp_dim_names or (("dp_shard",) if mesh.shape.get("dp_shard", 1) > 1 else ())
    else:
        fsdp_axes = ()
    # the entry shape the *params* plan uses for its (cp-excluded) fsdp axes,
    # so mirrors can be recognized and upgraded to the joint ZeRO sharding
    param_axes = tuple(a for a in fsdp_axes if a != "cp")
    param_entry = (param_axes if len(param_axes) > 1 else param_axes[0]) if param_axes else None
    joint_entry = (tuple(fsdp_axes) if len(fsdp_axes) > 1 else fsdp_axes[0]) if fsdp_axes else None
    joint_size = _axis_size(mesh, fsdp_axes)

    def _leaf(path, leaf):
        shape = tuple(leaf.shape) if hasattr(leaf, "shape") else ()
        if not shape:
            return NamedSharding(mesh, PartitionSpec())
        p = path_str(path)
        # moment tensors under optax appear with the param path as suffix
        for param_path, sharding in flat_plan.items():
            if p.endswith(param_path) and len(sharding.spec) <= len(shape):
                if sharding.spec and any(s is not None for s in sharding.spec):
                    spec = list(sharding.spec)
                    if joint_entry is not None and joint_entry != param_entry:
                        # moments never enter the cp shard_map: upgrade the
                        # param's fsdp entry to the joint (dp_shard, cp)
                        # sharding for the full ZeRO memory saving
                        for d, entry in enumerate(spec):
                            if entry == param_entry and shape[d] % joint_size == 0:
                                spec[d] = joint_entry
                    return NamedSharding(mesh, PartitionSpec(*spec))
                break
        return NamedSharding(mesh, _spec_for_leaf(p, shape, mesh, tuple(fsdp_axes), min_size, []))

    return jax.tree_util.tree_map_with_path(_leaf, opt_state_shapes)


# ---------------------------------------------------------------------------
# Built-in TP rule tables (the transformers tp_plan="auto" analog,
# reference accelerator.py:1870-1879)
# ---------------------------------------------------------------------------

# Megatron-style column/row parallel layout for transformer blocks:
# qkv/up projections column-parallel (shard output dim), out/down projections
# row-parallel (shard input dim), embeddings shard vocab, norms replicate.
TRANSFORMER_TP_RULES: list[tuple[str, PartitionSpec]] = [
    (r"(embed_tokens|embedding|wte|word_embeddings)/embedding$", PartitionSpec("tp", None)),
    (r"(q_proj|k_proj|v_proj|query|key|value|wq|wk|wv|in_proj|qkv)/kernel$", PartitionSpec(None, "tp")),
    (r"(o_proj|out_proj|wo|dense(?!_4h)|attn_out)/kernel$", PartitionSpec("tp", None)),
    (r"(gate_proj|up_proj|wi|wi_gate|wi_up|w1|w3|fc1|dense_h_to_4h|c_fc)/kernel$", PartitionSpec(None, "tp")),
    (r"(down_proj|wo_mlp|w2|fc2|dense_4h_to_h|c_proj)/kernel$", PartitionSpec("tp", None)),
    (r"(lm_head|output|score)/kernel$", PartitionSpec(None, "tp")),
]


def get_tp_rules(plan: str = "auto"):
    """Rule table lookup (models may register their own)."""
    if plan in ("auto", "transformer"):
        return TRANSFORMER_TP_RULES
    if plan in ("moe", "mixtral"):
        from .expert_parallel import get_moe_rules

        return get_moe_rules()
    if plan in ("none", None):
        return []
    raise ValueError(f"unknown tp plan {plan!r}")


def shard_params(params, plan):
    """device_put a real param pytree onto its plan (initial placement)."""
    return jax.tree_util.tree_map(lambda p, s: jax.device_put(p, s), params, plan)


def replicated_plan(params, mesh: Mesh):
    return jax.tree_util.tree_map(lambda _: NamedSharding(mesh, PartitionSpec()), params)


# ---------------------------------------------------------------------------
# Host (CPU-memory) offload of training state — the ZeRO-offload analog
# (reference DeepSpeedPlugin offload_optimizer_device/offload_param_device,
# dataclasses.py:1172-1187; FSDP CPUOffload).  On TPU, "offload" means the
# pytree lives in ``pinned_host`` memory and the optimizer update runs as XLA
# host compute — grads stream D2H, the update executes on the host CPU, and
# only the refreshed params return over PCIe.
# ---------------------------------------------------------------------------


def plan_bytes_per_device(abstract_tree, plan) -> int:
    """Per-device bytes of a pytree under a sharding plan (abstract: pure
    arithmetic over specs — works with :class:`jax.sharding.AbstractMesh`,
    no real devices needed).  Used by ``bench.py --plan`` and the memory
    estimator to report multi-chip footprints from one host."""
    total = 0
    leaves = jax.tree_util.tree_leaves(
        abstract_tree, is_leaf=lambda x: hasattr(x, "shape") and hasattr(x, "dtype")
    )
    plans = jax.tree_util.tree_leaves(plan, is_leaf=lambda x: isinstance(x, NamedSharding))
    for leaf, sh in zip(leaves, plans):
        n = int(np.prod(leaf.shape)) * jax.dtypes.canonicalize_dtype(leaf.dtype).itemsize
        div = 1
        if isinstance(sh, NamedSharding):
            for entry in sh.spec:
                if entry is not None:
                    div *= _axis_size(sh.mesh, entry)
        total += -(-n // div)
    return total


def host_offload_supported() -> bool:
    """Whether in-``jit`` memory-kind placement works on this backend.

    The TPU runtime implements ``annotate_device_placement`` for
    ``pinned_host`` buffers; XLA:CPU rejects it (side-effecting custom call
    cannot be sharded), so on the CPU test mesh offload degrades to regular
    device placement while the host-compute update path is still exercised.
    """
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:  # pragma: no cover - no backend at all
        return False


def with_memory_kind(sharding: NamedSharding, kind: str) -> NamedSharding:
    return NamedSharding(sharding.mesh, sharding.spec, memory_kind=kind)


def single_device_sharding(memory_kind: str = "device") -> NamedSharding:
    """Replicated sharding over the first local device, in the given memory
    kind — the placement handle for single-chip host-offload tiers."""
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("d",))
    return NamedSharding(mesh, PartitionSpec(), memory_kind=memory_kind)


def host_plan(plan):
    """Map a sharding plan into ``pinned_host`` memory (same mesh/specs)."""
    return jax.tree_util.tree_map(
        lambda s: with_memory_kind(s, "pinned_host") if isinstance(s, NamedSharding) else s,
        plan,
        is_leaf=lambda x: isinstance(x, NamedSharding),
    )


def device_plan(plan):
    """Strip memory kinds from a plan (back to default device/HBM)."""
    return jax.tree_util.tree_map(
        lambda s: with_memory_kind(s, "device") if isinstance(s, NamedSharding) else s,
        plan,
        is_leaf=lambda x: isinstance(x, NamedSharding),
    )
