"""In-jit collectives over named mesh axes — the GSPMD hot-path plane.

These are the explicit collectives used inside ``shard_map`` bodies (ring
attention KV rotation, Ulysses all-to-alls, MoE dispatch).  Everything else in
the framework relies on *implicit* collectives: XLA derives psum/all-gather/
reduce-scatter from sharding annotations on jitted computations — the
TPU-native replacement for the reference's NCCL calls (SURVEY §2.5).

Axis-name arguments accept a single name or a tuple (joint dims like
``("dp_replicate", "dp_shard")`` — the reference's flattened mesh dims,
parallelism_config.py:157-164).
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisNames = Union[str, Sequence[str]]


def _normalize(axis_names: AxisNames):
    if isinstance(axis_names, str):
        return axis_names
    return tuple(axis_names)


def psum(x, axis_names: AxisNames):
    """All-reduce sum across mesh axes (NCCL all_reduce analog)."""
    return lax.psum(x, _normalize(axis_names))


def pmean(x, axis_names: AxisNames):
    return lax.pmean(x, _normalize(axis_names))


def pmax(x, axis_names: AxisNames):
    return lax.pmax(x, _normalize(axis_names))


def pmin(x, axis_names: AxisNames):
    return lax.pmin(x, _normalize(axis_names))


def all_gather(x, axis_names: AxisNames, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` (NCCL all_gather analog)."""
    return lax.all_gather(x, _normalize(axis_names), axis=axis, tiled=tiled)


def reduce_scatter(x, axis_names: AxisNames, axis: int = 0):
    """Sum-reduce then scatter along ``axis`` (NCCL reduce_scatter analog)."""
    return lax.psum_scatter(x, _normalize(axis_names), scatter_dimension=axis, tiled=True)


def ppermute(x, axis_name: str, perm: Sequence[tuple[int, int]]):
    """Point-to-point ring permutation — the KV-rotation primitive for ring
    attention (reference CP 'alltoall' rotate, accelerator.py:1641-1654)."""
    return lax.ppermute(x, axis_name, perm)


def _axis_size(axis_name: str):
    """lax.axis_size across jax versions (older jax spells it psum(1, axis))."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def partial_manual_kwargs(axis_names) -> dict:
    """shard_map kwargs for a region manual over only ``axis_names`` with
    the replication check off, across the jax API generations.  New jax
    (jax.shard_map) takes ``axis_names``/``check_vma``; old jax
    (jax.experimental.shard_map) has neither — there the region degrades to
    fully-manual over the whole mesh with ``check_rep`` off, which is
    equivalent whenever the remaining mesh axes are trivial (the CPU test
    meshes) and best-effort otherwise."""
    import jax as _jax

    if hasattr(_jax, "shard_map"):
        return {"axis_names": set(axis_names), "check_vma": False}
    return {"check_rep": False}


def ring_permute(x, axis_name: str, shift: int = 1):
    """Rotate shards around the ring by ``shift`` (ICI-neighbor traffic)."""
    n = _axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int, tiled: bool = True):
    """All-to-all resharding — the Ulysses heads<->sequence swap primitive
    (reference UlyssesSPAttentionHF, accelerator.py:2370-2394)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return _axis_size(axis_name)


def broadcast_from(x, axis_name: str, src: int = 0):
    """Broadcast the ``src`` shard to all members of the axis.

    One-hot mask + psum: every rank contributes zeros except ``src``, so the
    sum IS the source shard — O(n) wire/memory per rank.  (The previous
    implementation all-gathered the full [devices, ...] stack just to index
    one row: O(n * devices) memory on every rank.)  ``where`` rather than
    multiply-by-mask so non-finite values on non-source ranks cannot poison
    the sum; bools ride as int32 through the reduction.
    """
    n = _axis_size(axis_name)  # static int (axis extents are trace-time)
    if isinstance(n, int) and not 0 <= src < n:
        # the old gather-then-index form raised at trace time on a bad src;
        # an unmatched one-hot would instead psum to silent zeros
        raise ValueError(f"broadcast_from src={src} out of range for axis "
                         f"{axis_name!r} of size {n}")
    idx = lax.axis_index(axis_name)
    as_bool = x.dtype == jnp.bool_
    payload = x.astype(jnp.int32) if as_bool else x
    masked = jnp.where(idx == src, payload, jnp.zeros_like(payload))
    out = lax.psum(masked, axis_name)
    return out.astype(jnp.bool_) if as_bool else out
