from . import collectives, expert_parallel, pipeline_parallel
