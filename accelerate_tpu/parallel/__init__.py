from . import collectives
