from . import collectives, expert_parallel
