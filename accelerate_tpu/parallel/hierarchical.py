"""Hierarchical ICI→DCN gradient synchronization for multi-slice data
parallelism.

A multi-slice launch (``accelerate_tpu launch`` over ``jax.distributed``)
builds its mesh with an explicit outermost ``dcn`` axis
(parallelism_config.py): devices that differ only in their dcn coordinate
live in different slices, and traffic across that axis rides the datacenter
network at a small fraction of ICI bandwidth.  A *flat* data-parallel psum
over the joint ``(dcn, dp_*)`` axes is therefore the wrong shape: after the
intra-slice reduction every one of the slice's ``p`` devices holds the full
reduced gradient, so the cross-slice hop moves ``p`` redundant full-size
copies over the slow link.

The hierarchical schedule (the standard multi-slice discipline) replaces it
with three phases, each on the network tier it belongs to:

1. **reduce-scatter over ICI** — each of the slice's ``p`` devices ends up
   owning the intra-slice *sum* of a disjoint ``1/p`` slab of the gradient;
2. **all-reduce over DCN** — each device all-reduces only its slab across
   slices: the DCN cut carries ``1/p`` of the flat schedule's bytes, and the
   ``p`` slab streams ride in parallel.  Optionally the slab crosses DCN
   PowerSGD-compressed (``parallel/powersgd.py`` — rank-``r`` factors with
   per-device error feedback), dropping the DCN bytes further to
   ``~r*(rows+cols)/(rows*cols)`` of the slab;
3. **all-gather over ICI** — the globally reduced slabs reassemble into the
   full gradient inside each slice.

Everything here runs *inside* a ``shard_map`` over the data-parallel axes
(the accelerator's train step wires it, mirroring the PowerSGD comm-hook
path).  The accounting twins follow the ``tp_comm_accounting`` pattern:
:func:`dcn_comm_accounting` predicts per-device DCN bytes for the
hierarchical and flat schedules from the parameter tree alone, and
:func:`measure_dcn_bytes` reads the *actual* DCN traffic off a traced
program's jaxpr — the clean-run contract is that the two agree.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .powersgd import compress_decompress

# One ring all-reduce of ``b`` bytes over ``d`` members moves
# ``2 * b * (d-1)/d`` per member (reduce-scatter + all-gather halves).
def ring_reduce_factor(d: int) -> float:
    d = max(1, int(d))
    return 2.0 * (d - 1) / d if d > 1 else 0.0


# ---------------------------------------------------------------------------
# slab geometry — how a leaf lays out across the intra-slice ring
# ---------------------------------------------------------------------------


def slab_geometry(leaf_size: int, ici_size: int) -> dict:
    """Deterministic slab layout for a leaf of ``leaf_size`` elements
    reduce-scattered over an intra-slice ring of ``ici_size``.

    ``chunk`` is the per-device slab length (leaf zero-padded so the ring
    divides it); ``rows``/``cols`` is the near-square matrix view the
    PowerSGD codec compresses the slab through (the slab zero-pads again to
    ``rows*cols`` — zero padding is exact under sum-reductions and lands in
    the error-feedback residual like any other coordinate)."""
    p = max(1, int(ici_size))
    chunk = -(-int(leaf_size) // p)
    rows = max(1, int(math.isqrt(chunk)))
    cols = -(-chunk // rows)
    return {"size": int(leaf_size), "ici_size": p, "chunk": chunk,
            "padded": chunk * p, "rows": rows, "cols": cols}


def slab_eligible(leaf, ici_size: int, rank: int) -> bool:
    """PowerSGD eligibility of a leaf's *slab*: floating dtype and factor
    traffic that beats the dense slab (``rank*(rows+cols) < rows*cols``)."""
    if not hasattr(leaf, "shape"):
        return False
    if not jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
        return False
    g = slab_geometry(int(np.prod(leaf.shape)) if leaf.shape else 1, ici_size)
    return rank * (g["rows"] + g["cols"]) < g["rows"] * g["cols"]


def init_dcn_powersgd_state(params, rank: int, dp_world: int, ici_size: int,
                            seed: int = 0):
    """``(qs, errs)`` pytrees congruent with ``params`` for the DCN codec:
    a warm-start Q ``[cols, rank]`` per eligible leaf (replicated — identical
    on every rank by construction) and a zero error buffer
    ``[dp_world, rows, cols]`` whose leading axis the caller shards over the
    joint data-parallel axes, so each rank owns its own slab residual.
    Ineligible leaves carry ``None`` in both trees."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    qs, errs = [], []
    for i, leaf in enumerate(leaves):
        if slab_eligible(leaf, ici_size, rank):
            g = slab_geometry(int(np.prod(leaf.shape)), ici_size)
            q = jax.random.normal(jax.random.key(seed + i), (g["cols"], rank),
                                  jnp.float32)
            qs.append(q)
            errs.append(jnp.zeros((dp_world, g["rows"], g["cols"]), jnp.float32))
        else:
            qs.append(None)
            errs.append(None)
    return (
        jax.tree_util.tree_unflatten(treedef, qs),
        jax.tree_util.tree_unflatten(treedef, errs),
    )


# ---------------------------------------------------------------------------
# the in-shard_map schedule
# ---------------------------------------------------------------------------


def _axis_size(axis_names) -> int:
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    size = 1
    for name in axis_names:
        size *= (lax.axis_size(name) if hasattr(lax, "axis_size")
                 else lax.psum(1, name))
    return size


def hierarchical_sync(grads, ici_axes: Sequence[str], dcn_axis: str = "dcn",
                      *, qs: Any = None, errs: Any = None, rank: int = 4):
    """Globally *mean*-reduce per-rank gradients with the ICI→DCN schedule.

    Must run inside a ``shard_map`` manual over ``(dcn_axis, *ici_axes)``.
    ``grads`` are this rank's local gradients; returns
    ``(mean_grads, new_qs, new_errs)`` where the mean is over the full
    data-parallel world (``dcn * ici`` ranks) — the same semantics as the
    flat ``lax.pmean`` it replaces.  With ``qs``/``errs`` ``None`` the DCN
    hop is a dense slab psum; per-leaf PowerSGD state (from
    :func:`init_dcn_powersgd_state`, already indexed down to this rank's
    ``[rows, cols]`` residual) routes that leaf's slab through the
    compressed codec instead, with error feedback carried across steps."""
    ici_axes = tuple(ici_axes)
    p = _axis_size(ici_axes) if ici_axes else 1
    d = _axis_size(dcn_axis)
    world = p * d

    def one(g, q, e):
        shape, dtype = g.shape, g.dtype
        size = int(np.prod(shape)) if shape else 1
        geo = slab_geometry(size, p)
        flat = g.astype(jnp.float32).reshape(-1)
        if geo["padded"] != size:
            flat = jnp.pad(flat, (0, geo["padded"] - size))
        if p > 1:
            # phase 1 — intra-slice sum, each device keeps its 1/p slab
            slab = lax.psum_scatter(flat, ici_axes, scatter_dimension=0,
                                    tiled=True)
        else:
            slab = flat
        new_q = new_e = None
        if q is not None:
            # phase 2 (compressed) — only the rank-r factors cross DCN;
            # the pmean inside compress_decompress averages over slices and
            # the residual (what the factors lost of THIS rank's slab)
            # feeds back next step
            mtx = slab
            mat_len = geo["rows"] * geo["cols"]
            if mat_len != geo["chunk"]:
                mtx = jnp.pad(mtx, (0, mat_len - geo["chunk"]))
            mtx = mtx.reshape(geo["rows"], geo["cols"])
            hat, new_q, new_e = (
                t["s"] for t in compress_decompress(
                    {"s": mtx}, {"s": q}, {"s": e}, (dcn_axis,), rank
                )
            )
            slab = hat.reshape(-1)[: geo["chunk"]] / p  # pmean'd over dcn; /p → world mean
        else:
            # phase 2 (dense) — the slab, not the full gradient, crosses DCN
            slab = lax.psum(slab, dcn_axis) / world
        if p > 1:
            # phase 3 — reassemble inside the slice over ICI
            full = lax.all_gather(slab, ici_axes, axis=0, tiled=True)
        else:
            full = slab
        return full[:size].reshape(shape).astype(dtype), new_q, new_e

    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_q = (treedef.flatten_up_to(qs) if qs is not None
              else [None] * len(flat_g))
    flat_e = (treedef.flatten_up_to(errs) if errs is not None
              else [None] * len(flat_g))
    out = [one(g, q, e) for g, q, e in zip(flat_g, flat_q, flat_e)]
    unf = lambda xs: jax.tree_util.tree_unflatten(treedef, xs)
    return unf([o[0] for o in out]), unf([o[1] for o in out]), unf([o[2] for o in out])


# ---------------------------------------------------------------------------
# predicted / measured accounting twins (the tp_comm_accounting pattern)
# ---------------------------------------------------------------------------

# One DCN link direction between v5e slices measures ~6.25 GiB/s/host
# (50 Gbps NICs) vs ~45 GiB/s per ICI link direction — the ~7x gap that
# makes the slab schedule (and the PowerSGD codec on top) worth its QR.
DCN_GIBS_DEFAULT = 6.25


def dcn_comm_accounting(
    params,
    *,
    ici_size: int,
    dcn_size: int,
    compression: Optional[str] = None,
    rank: int = 4,
    dtype_bytes: int = 4,
    dcn_gibs: float = DCN_GIBS_DEFAULT,
    step_compute_s: Optional[float] = None,
) -> dict:
    """Predicted per-device DCN bytes per step: hierarchical vs flat.

    Model (per device, ring all-reduce factor ``2*(d-1)/d`` over ``d``
    slices): the *flat* schedule all-reduces the full gradient tree across
    ``dcn`` on every device; the *hierarchical* schedule all-reduces only
    this device's ``1/ici_size`` slab (zero-pad included), and with
    ``compression='powersgd'`` an eligible leaf's slab crosses as its
    rank-``r`` factors (``rank*(rows+cols)`` fp32 per device — the P and Q
    psums of ``parallel/powersgd.py``) instead.  ``dcn_overlap_frac`` is
    the hideable fraction of the DCN hop under ``step_compute_s`` of
    per-step compute (1.0 = fully hideable behind the backward pass).
    ``dcn_size <= 1`` returns the zeros-clean shape (no DCN axis, no DCN
    bytes) so the always-emitted bench fields stay truthful."""
    d = max(1, int(dcn_size))
    p = max(1, int(ici_size))
    factor = ring_reduce_factor(d)
    total_bytes = hier_bytes = 0
    n_eligible = n_dense = 0
    for leaf in jax.tree_util.tree_leaves(params):
        if not hasattr(leaf, "shape"):
            continue
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        total_bytes += size * dtype_bytes
        geo = slab_geometry(size, p)
        if compression == "powersgd" and slab_eligible(leaf, p, rank):
            hier_bytes += rank * (geo["rows"] + geo["cols"]) * dtype_bytes
            n_eligible += 1
        else:
            hier_bytes += geo["chunk"] * dtype_bytes
            n_dense += 1
    dcn_bytes = int(factor * hier_bytes)
    dcn_bytes_flat = int(factor * total_bytes)
    dcn_s = dcn_bytes / (dcn_gibs * 2**30) if d > 1 else 0.0
    if d <= 1:
        overlap = 0.0
    elif step_compute_s is None or dcn_s <= 0:
        overlap = 1.0 if dcn_s <= 0 else 0.0
    else:
        overlap = min(1.0, step_compute_s / dcn_s)
    # twin registry: PREDICTED per-device DCN bytes of the hierarchical
    # schedule; measured side is measure_dcn_bytes off the traced program
    from ..telemetry import twin_registry

    twin_registry().record_predicted(
        "dcn_comm.dcn_bytes", dcn_bytes,
        source="parallel/hierarchical.dcn_comm_accounting",
    )
    return {
        "dcn_size": d,
        "ici_size": p,
        "compression": compression,
        "rank": rank if compression == "powersgd" else None,
        "dcn_bytes": dcn_bytes,
        "dcn_bytes_flat": dcn_bytes_flat,
        "ratio": dcn_bytes / max(dcn_bytes_flat, 1),
        "eligible_leaves": n_eligible,
        "dense_leaves": n_dense,
        "dcn_s_per_step": round(dcn_s, 9),
        "dcn_overlap_frac": round(overlap, 4),
        "kind": "predicted",
    }


def collective_axes(eqn) -> tuple:
    """The named mesh axes a jaxpr collective equation reduces/moves over
    (``()`` for non-collectives)."""
    axes = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def measure_dcn_bytes(closed, *, dcn_axis: str = "dcn",
                      dcn_size: int) -> dict:
    """Measured twin: per-device DCN bytes read off a traced program.

    Walks every equation of ``closed`` (a ``ClosedJaxpr`` from
    ``jax.jit(fn).trace(...).jaxpr`` — CPU-safe, nothing executes) and sums
    the cross-slice cost of each collective whose axes include ``dcn_axis``:
    a psum costs the ring factor ``2*(d-1)/d`` of its operand bytes, an
    all-gather ``(d-1)`` incoming shards, a reduce-scatter ``(d-1)/d``.
    Operand avals inside ``shard_map`` are per-device, so the sum is the
    per-device wire cost — directly comparable to
    :func:`dcn_comm_accounting`'s predicted ``dcn_bytes``."""
    from ..analysis import iter_eqns

    d = max(1, int(dcn_size))
    total = 0.0
    rows = []
    for eqn in iter_eqns(closed):
        axes = collective_axes(eqn)
        if dcn_axis not in axes:
            continue
        op = eqn.invars[0].aval
        nbytes = int(np.prod(op.shape)) * op.dtype.itemsize if op.shape else op.dtype.itemsize
        name = eqn.primitive.name
        if name == "all_gather":
            cost = (d - 1) * nbytes
        elif name == "reduce_scatter":
            cost = (d - 1) / d * nbytes
        else:  # psum / all_reduce family
            cost = ring_reduce_factor(d) * nbytes
        total += cost
        rows.append({"primitive": name, "axes": axes, "operand_bytes": nbytes,
                     "dcn_bytes": int(cost)})
    from ..telemetry import twin_registry

    twin_registry().record_measured(
        "dcn_comm.dcn_bytes", int(total),
        source="parallel/hierarchical.measure_dcn_bytes",
    )
    return {"dcn_bytes": int(total), "dcn_size": d, "collectives": rows,
            "kind": "measured"}
