"""Ulysses/ALST sequence parallelism over the ``sp`` mesh axis.

TPU-native re-design of reference P9 (DeepSpeed ``UlyssesSPAttentionHF``
head-scatter all-to-all + ``UlyssesSPDataLoaderAdapter`` sequence sharding,
reference accelerator.py:2370-2409): activations are sharded along the
*sequence* dim everywhere except inside attention, where two ``all_to_all``s
re-shard to the *head* dim so every rank computes full-sequence attention for
its subset of heads — 'two all_to_alls around attention', the natural
``shard_map`` over ICI (SURVEY §2.4 P9).

Requires num_heads % sp == 0 and seq_len % sp == 0.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .collectives import axis_size, partial_manual_kwargs


def ulysses_attention_sharded(q, k, v, seg=None, *, axis_name: str = "sp", causal: bool = True,
                              inner_attn: Optional[Callable] = None,
                              heads_sharded: bool = False):
    """shard_map body.  q/k/v local: [B, T/sp, H, D] → out [B, T/sp, H, D].

    all_to_all #1: seq-sharded → head-sharded ([B, T, H/sp, D]);
    full-sequence attention on local heads;
    all_to_all #2: back to seq-sharded.

    GQA runs at kv-head width through the all_to_alls when ``Hkv % sp == 0``
    (head-group alignment is preserved per rank: q heads [r·H/sp, …) map to
    kv heads [r·Hkv/sp, …)).  ``seg`` [B, T/sp] local segment ids are
    all-gathered to the full sequence each rank attends over (packed
    sequences; int16-sized traffic, negligible next to KV).

    ``heads_sharded``: the collective-matmul boundary contract
    (``ops/collective_matmul.ulysses_sp_boundary``) — q/k/v arrive already
    full-sequence head-sharded ([B, T, H/sp, D], the ring all-gather→matmul
    q/k/v projections absorbed all_to_all #1) and the output leaves
    head-sharded (the o_proj ring matmul→reduce-scatter absorbs all_to_all
    #2); ``seg`` then arrives full-sequence too.  Both monolithic
    all_to_alls disappear from this body.
    """
    sp = axis_size(axis_name)

    def seq2head(x):
        # split heads across ranks, concat sequence: [B, T/sp, H, D] -> [B, T, H/sp, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    if heads_sharded:
        q_h, k_h, v_h = q, k, v
        seg_full = seg
    else:
        q_h, k_h, v_h = seq2head(q), seq2head(k), seq2head(v)
        seg_full = None
        if seg is not None:
            seg_full = lax.all_gather(seg, axis_name, axis=1, tiled=True)  # [B, T]
    if inner_attn is None:
        from ..models.llama import native_attention

        inner_attn = native_attention
    # keyword only when present: custom inner_attn callables without a
    # segment_ids parameter stay compatible
    kwargs = {"segment_ids": seg_full} if seg_full is not None else {}
    out_h = inner_attn(q_h, k_h, v_h, causal=causal, **kwargs)
    return out_h if heads_sharded else head2seq(out_h)


@functools.lru_cache(maxsize=None)
def make_ulysses_attention(mesh: Mesh, axis_name: str = "sp", inner_attn: Optional[Callable] = None):
    """Mesh-bound Ulysses attention on GLOBAL arrays (seq dim sharded over
    ``axis_name``)."""
    if inner_attn is None and mesh.devices.flat[0].platform == "tpu":
        # post-all_to_all attention is plain full-sequence attention over the
        # local heads — the Pallas flash kernel applies directly.  Decided
        # from the mesh's own devices (not the process default backend) so a
        # CPU debug mesh on a TPU-attached host still gets the native path.
        from ..ops.flash_attention import flash_attention

        inner_attn = flash_attention

    # Partial-manual: only sp is manualized — the head dim may itself be
    # tp-sharded outside and keeps that sharding through the all_to_alls
    # (sp splits the LOCAL tp head shard; sp×tp needs H/tp % sp == 0), and a
    # dp-sharded batch is not gathered into the body.  jax 0.9's eager
    # partial-manual validator rejects multi-axis meshes spuriously, so the
    # shard_map runs under a cached jit (inlined under an outer jit).
    @functools.lru_cache(maxsize=None)
    def _build(causal: bool, with_seg: bool, heads_sharded: bool = False):
        # heads_sharded (the collective-matmul sp boundary): q/k/v enter
        # full-sequence with the HEAD dim manual over sp, and leave the same
        # way — the surrounding ring matmuls own the sequence resharding
        spec = (P(None, None, axis_name, None) if heads_sharded
                else P(None, axis_name, None, None))
        seg_spec = P(None, None) if heads_sharded else P(None, axis_name)
        body = functools.partial(ulysses_attention_sharded, axis_name=axis_name, causal=causal,
                                 inner_attn=inner_attn, heads_sharded=heads_sharded)
        in_specs = (spec, spec, spec) + ((seg_spec,) if with_seg else ())
        return jax.jit(shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=spec,
                                 **partial_manual_kwargs({axis_name})))

    def attn(q, k, v, *, causal: bool = True, segment_ids=None, heads_sharded: bool = False):
        h_q, h_kv = q.shape[2], k.shape[2]
        sp = mesh.shape[axis_name]
        if h_kv != h_q and h_kv % sp != 0:
            if heads_sharded:
                raise ValueError(
                    f"heads_sharded ulysses needs kv heads {h_kv} divisible by sp={sp}"
                )
            # kv heads don't split across sp — broadcast to q width (the
            # aligned case keeps kv at Hkv width through the all_to_alls)
            rep = h_q // h_kv
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if h_q % sp != 0:
            raise ValueError(f"num_heads {h_q} must be divisible by sp={sp}")
        if segment_ids is None:
            return _build(causal, False, heads_sharded)(q, k, v)
        return _build(causal, True, heads_sharded)(q, k, v, jnp.asarray(segment_ids, jnp.int32))

    return attn


def ulysses_attention(q, k, v, *, causal: bool = True, segment_ids=None,
                      heads_sharded: bool = False):
    """Config-name entry resolving the ambient mesh."""
    from ..state import AcceleratorState

    state = AcceleratorState()
    return make_ulysses_attention(state.mesh)(
        q, k, v, causal=causal, segment_ids=segment_ids, heads_sharded=heads_sharded
    )


# ---------------------------------------------------------------------------
# Sequence-sharding dataloader adapter
# (reference UlyssesSPDataLoaderAdapter accelerator.py:2396-2409)
# ---------------------------------------------------------------------------


def shard_batch_along_sequence(batch, mesh: Mesh, axis_name: str = "sp", seq_axis: int = 1,
                               batch_axes=("dp_replicate", "dp_shard")):
    """Re-spec a global batch so its sequence dim is sharded over sp/cp.

    The loss must then be averaged with the sequence shards in the
    denominator — use ``cross_rank_token_mean`` below (the reference's
    dp_cp loss-averaging dims, parallelism_config.py:146-155)."""
    from jax.sharding import NamedSharding

    def _respec(x):
        if np.ndim(x) <= seq_axis:
            return x
        entries: list = [tuple(a for a in batch_axes if mesh.shape[a] > 1) or None]
        entries += [None] * (np.ndim(x) - 1)
        entries[seq_axis] = axis_name
        return jax.device_put(x, NamedSharding(mesh, P(*entries)))

    return jax.tree_util.tree_map(_respec, batch)


def cross_rank_token_mean(per_token_loss, mask, axis_names):
    """Differentiable cross-rank loss aggregation (reference Ulysses loss
    helper): sum(loss*mask)/sum(mask) with both sums psum'd over the sequence
    (and dp) axes — call inside shard_map or rely on GSPMD reductions."""
    num = jnp.sum(per_token_loss * mask)
    den = jnp.sum(mask)
    num = lax.psum(num, axis_names)
    den = lax.psum(den, axis_names)
    return num / jnp.maximum(den, 1.0)
