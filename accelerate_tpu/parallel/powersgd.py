"""PowerSGD low-rank gradient compression with error feedback.

TPU-native analog of the reference's DDP PowerSGD communication hook
(reference ``DDPCommunicationHookType.POWER_SGD``, utils/dataclasses.py:134,
wired at accelerator.py:1865): instead of all-reducing the dense gradient
over the data-parallel axis, each rank compresses its *local* gradient into
rank-``r`` factors, all-reduces only the factors, and decompresses — with a
per-rank error buffer feeding the compression residual back into the next
step (Vogels et al., NeurIPS 2019).

Under GSPMD the dense gradient all-reduce is implicit (XLA inserts the psum
from shardings), so there is no hook point to intercept — the compressed
path instead runs the loss/grad inside a ``shard_map`` over the dp axes
where per-rank gradients exist, and the only cross-device traffic for
eligible leaves is the two factor ``psum``s (rides ICI exactly like the
dense psum, at ``r*(n+m)/(n*m)`` of the bytes).

Eligibility: floating leaves with ndim >= 2 whose factor traffic beats the
dense leaf (``r*(n+m) < n*m``); everything else (biases, norm scales,
scalars) all-reduces dense.  All math in fp32; Gram–Schmidt via QR.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _matrix_view(shape) -> tuple[int, int]:
    """[n, m] view a leaf compresses through: dim 0 stays, the rest fold."""
    return shape[0], int(np.prod(shape[1:]))


def eligible(leaf, rank: int) -> bool:
    if not hasattr(leaf, "shape") or len(leaf.shape) < 2:
        return False
    if not jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
        return False
    n, m = _matrix_view(leaf.shape)
    return rank * (n + m) < n * m


def init_powersgd_state(params, rank: int, dp_size: int, seed: int = 0):
    """``(qs, errs)`` pytrees congruent with ``params``: a warm-start Q
    [m, r] for eligible leaves (replicated; identical on every rank by
    construction) and a zero error buffer [dp_size, *leaf.shape] whose
    leading axis the caller shards over the dp axes — each rank owns its
    own residual.  Ineligible leaves carry ``None`` in both trees."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    qs, errs = [], []
    for i, leaf in enumerate(leaves):
        if eligible(leaf, rank):
            _, m = _matrix_view(leaf.shape)
            q = jax.random.normal(jax.random.key(seed + i), (m, rank), jnp.float32)
            qs.append(q)
            errs.append(jnp.zeros((dp_size, *leaf.shape), jnp.float32))
        else:
            qs.append(None)
            errs.append(None)
    return (
        jax.tree_util.tree_unflatten(treedef, qs),
        jax.tree_util.tree_unflatten(treedef, errs),
    )


def _orthonormalize(p):
    # reduced QR: the factor psum sums rank-r spans; orthonormal P keeps the
    # projection well-conditioned across steps (plain Gram–Schmidt in the
    # paper; QR is the batched XLA-native spelling)
    q, _ = jnp.linalg.qr(p)
    return q


def compress_decompress(grads, qs, errs, axis_names, rank: int):
    """Inside ``shard_map``: per-rank grads -> globally averaged low-rank
    approximations.  Returns ``(grads_hat, new_qs, new_errs)``; ineligible
    leaves are dense-``pmean``ed with ``None`` state."""

    def one(g, q, e):
        if q is None:
            return jax.lax.pmean(g, axis_names), None, None
        shape = g.shape
        n, m = _matrix_view(shape)
        mtx = g.astype(jnp.float32).reshape(n, m) + e.reshape(n, m)
        p = jax.lax.pmean(mtx @ q, axis_names)       # [n, r]
        p = _orthonormalize(p)
        q_local = mtx.T @ p                          # [m, r] this rank's factor
        new_q = jax.lax.pmean(q_local, axis_names)
        g_hat = p @ new_q.T                          # [n, m], already averaged
        # the residual is vs this rank's own approximation — what the factor
        # psum lost of *our* gradient comes back next step
        new_e = mtx - p @ q_local.T
        return g_hat.reshape(shape).astype(g.dtype), new_q, new_e.reshape(shape)

    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_q = treedef.flatten_up_to(qs)
    flat_e = treedef.flatten_up_to(errs)
    out = [one(g, q, e) for g, q, e in zip(flat_g, flat_q, flat_e)]
    unf = lambda xs: jax.tree_util.tree_unflatten(treedef, xs)
    return unf([o[0] for o in out]), unf([o[1] for o in out]), unf([o[2] for o in out])


def wire_bytes_report(params, rank: int) -> dict:
    """Per-step all-reduce traffic accounting: dense psum vs the PowerSGD
    factor psums (the convergence-parity test pins this, and it is the
    number to quote when sizing DCN-bound multi-slice dp)."""
    dense = compressed = 0
    n_eligible = n_dense = 0
    for leaf in jax.tree_util.tree_leaves(params):
        if not hasattr(leaf, "shape"):
            continue
        size = int(np.prod(leaf.shape)) * 4
        dense += size
        if eligible(leaf, rank):
            n, m = _matrix_view(leaf.shape)
            # per step: the P psum moves n*r floats, the Q psum m*r
            compressed += rank * (n + m) * 4
            n_eligible += 1
        else:
            compressed += size
            n_dense += 1
    return {
        "dense_bytes_per_step": dense,
        "compressed_bytes_per_step": compressed,
        "ratio": compressed / max(dense, 1),
        "eligible_leaves": n_eligible,
        "dense_leaves": n_dense,
        "rank": rank,
    }
