"""Expert parallelism (MoE) over the ``ep`` mesh axis.

TPU-native design for SURVEY §2.4 P10.  The reference has no first-class EP —
MoE support there is DeepSpeed ZeRO-3 leaf-module marking
(``deepspeed_plugin.set_moe_leaf_modules``, reference accelerator.py:2258-2259,
``transformer_moe_cls_names`` dataclasses.py:1199-1205) plus Megatron
``num_experts`` plumbing (reference utils/megatron_lm.py).  Capability parity
= "MoE models train under sharding without materializing all experts per
device", which on TPU is an ``ep`` mesh axis plus token dispatch.

Two complementary mechanisms, both MXU-friendly:

1. **GSPMD einsum dispatch** (GShard-style): routing produces dense
   ``dispatch``/``combine`` tensors ``[tokens, experts, capacity]``; expert
   compute is a batched einsum with the expert dim sharded over ``ep`` —
   XLA's partitioner inserts the all_to_alls.  This is the default path used
   by :class:`~accelerate_tpu.models.mixtral.MixtralForCausalLM`.
2. **Explicit shard_map dispatch** (:func:`expert_parallel_apply`): manual
   ``all_to_all`` that re-shards grouped tokens from capacity-sharded to
   expert-sharded, for expert bodies that cannot be expressed as one einsum
   (the "ragged all-to-all" capability named in SURVEY §2.4 P10).

Routing follows Switch/Mixtral: top-k softmax gating with a load-balancing
auxiliary loss and an optional router z-loss.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .collectives import partial_manual_kwargs


class RoutingResult(NamedTuple):
    """Dense dispatch/combine tensors plus router diagnostics.

    dispatch: [S, E, C] bool — token s goes to expert e at capacity slot c.
    combine:  [S, E, C] f32  — gating weight for the dispatched slot.
    aux_loss: scalar — Switch load-balancing loss (1.0 when perfectly uniform).
    z_loss:   scalar — router logit magnitude regularizer.
    """

    dispatch: jax.Array
    combine: jax.Array
    aux_loss: jax.Array
    z_loss: jax.Array


def expert_capacity(num_tokens: int, num_experts: int, top_k: int, capacity_factor: float) -> int:
    """Per-expert token capacity C = ceil(S * k / E * factor), padded to a
    multiple of 8 so the [E, C, D] expert batches tile onto the MXU."""
    raw = int(np.ceil(num_tokens * top_k / num_experts * capacity_factor))
    return max(8, int(np.ceil(raw / 8)) * 8)


def top_k_routing(
    router_logits: jax.Array,
    top_k: int,
    capacity: int,
    *,
    normalize_weights: bool = True,
) -> RoutingResult:
    """Capacity-constrained top-k routing (Switch Transformer §2.2 semantics,
    Mixtral-style top-k weight normalization).

    router_logits: [S, E].  Tokens beyond an expert's capacity are dropped
    (their combine weight is zero → residual connection passes them through).
    """
    s, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [S, E]
    gate_vals, gate_idx = lax.top_k(probs, top_k)  # [S, K]
    if normalize_weights:
        gate_vals = gate_vals / jnp.maximum(jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # One-hot expert assignment per k-slot: [K, S, E].  Priority is slot-major
    # (all tokens' 1st choices before any 2nd choices — Switch behavior).
    assign = jax.nn.one_hot(gate_idx.T, e, dtype=jnp.int32)  # [K, S, E]
    flat_assign = assign.reshape(top_k * s, e)
    # Position of each (slot, token) within its expert's queue.
    position = jnp.cumsum(flat_assign, axis=0) - flat_assign  # [K*S, E]
    position = jnp.sum(position * flat_assign, axis=-1).reshape(top_k, s)  # [K, S]
    kept = position < capacity

    # dispatch[s, e, c]: OR over k-slots of (token s → expert e at slot c)
    pos_oh = jax.nn.one_hot(jnp.where(kept, position, capacity), capacity, dtype=jnp.float32)
    dispatch_k = assign.astype(jnp.float32)[..., None] * pos_oh[:, :, None, :]  # [K, S, E, C]
    dispatch = jnp.sum(dispatch_k, axis=0)  # [S, E, C]
    combine = jnp.sum(dispatch_k * gate_vals.T[:, :, None, None], axis=0)  # [S, E, C]

    # Switch load-balancing loss: E * sum_e f_e * p_e where f_e is the
    # fraction of tokens whose FIRST choice is e and p_e the mean router prob.
    first_choice = jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32)
    f = jnp.mean(first_choice, axis=0)
    p = jnp.mean(probs, axis=0)
    aux_loss = e * jnp.sum(f * p)
    z_loss = jnp.mean(jnp.square(jax.nn.logsumexp(router_logits.astype(jnp.float32), axis=-1)))

    return RoutingResult(dispatch > 0, combine, aux_loss, z_loss)


def moe_dispatch(x: jax.Array, routing: RoutingResult) -> jax.Array:
    """Gather tokens into per-expert batches: [S, D] → [E, C, D].

    With expert-dim outputs sharded over ``ep`` this einsum IS the all_to_all
    (GSPMD inserts it)."""
    return jnp.einsum("sec,sd->ecd", routing.dispatch.astype(x.dtype), x)


def moe_combine(expert_out: jax.Array, routing: RoutingResult) -> jax.Array:
    """Weighted scatter back: [E, C, D] → [S, D]."""
    return jnp.einsum("sec,ecd->sd", routing.combine.astype(expert_out.dtype), expert_out)


# ---------------------------------------------------------------------------
# Explicit shard_map dispatch (ragged all-to-all capability)
# ---------------------------------------------------------------------------


def _ep_body(x_grouped, axis_name: str, expert_fn: Callable):
    """shard_map body.  Local block: [E, C/ep, D] (capacity-sharded).

    all_to_all #1 re-shards experts→local, capacities→global:
    [E, C/ep, D] → [E/ep, C, D]; apply the local experts; all_to_all #2
    restores the original layout.  ``expert_fn(local_idx, batch)`` computes
    one expert's forward, vmapped over the local expert dim by the caller.
    """
    local = lax.all_to_all(x_grouped, axis_name, split_axis=0, concat_axis=1, tiled=True)
    ep_rank = lax.axis_index(axis_name)
    e_local = local.shape[0]
    out = expert_fn(ep_rank * e_local + jnp.arange(e_local), local)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0, tiled=True)


def expert_parallel_apply(
    mesh: Mesh,
    expert_fn: Callable,
    x_grouped: jax.Array,
    *,
    axis_name: str = "ep",
):
    """Apply per-expert compute to grouped tokens with explicit all_to_all.

    x_grouped: GLOBAL [E, C, D], capacity dim sharded over ``axis_name``.
    expert_fn: ``(global_expert_indices [E/ep], batch [E/ep, C, D]) → [E/ep, C, D]``.
    Returns [E, C, D] with the input's sharding.

    Use when the expert body is not expressible as a single einsum over a
    sharded expert dim (e.g. per-expert quantized weights, ragged kernels).
    """
    if mesh.shape.get(axis_name, 1) == 1:
        e = x_grouped.shape[0]
        return expert_fn(jnp.arange(e), x_grouped)
    spec = P(None, axis_name, None)
    fn = shard_map(
        functools.partial(_ep_body, axis_name=axis_name, expert_fn=expert_fn),
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
        **partial_manual_kwargs({axis_name}),
    )
    return fn(x_grouped)


# ---------------------------------------------------------------------------
# Sharding rules for expert parameters
# ---------------------------------------------------------------------------

# Expert weight tensors carry a leading num_experts dim → shard it over "ep";
# the contraction dims follow the usual Megatron column/row TP layout.
MOE_EP_RULES: list[tuple[str, P]] = [
    (r"experts/(gate_proj|up_proj)$", P("ep", None, "tp")),
    (r"experts/down_proj$", P("ep", "tp", None)),
    (r"router/kernel$", P()),  # router stays replicated — it is tiny
]


def get_moe_rules():
    """EP+TP rule table for MoE transformer blocks (prepend to the dense
    TRANSFORMER_TP_RULES so expert patterns win)."""
    from .sharding import TRANSFORMER_TP_RULES

    return MOE_EP_RULES + TRANSFORMER_TP_RULES
