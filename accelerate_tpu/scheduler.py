"""LR scheduler wrapper.

TPU-native re-design of reference ``scheduler.py`` (98 LoC,
``AcceleratedScheduler`` :25).  optax schedules are pure functions of the
step count, so the scheduler does not need to be 'stepped' inside the hot
loop — the step count in the optimizer state drives it.  This wrapper keeps
the reference's semantics for code that reads the LR or steps manually:

- steps only count when the optimizer actually stepped (accumulation
  boundary / no fp16 overflow — reference :54-68);
- ``step_with_optimizer`` + ``split_batches=False`` advances
  ``num_processes`` steps per call so per-process schedules line up with the
  global-batch schedule (reference :69-82).
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import optax

from .state import AcceleratorState, GradientState


class AcceleratedScheduler:
    """Wraps an optax schedule (``Callable[[int], float]``)."""

    def __init__(
        self,
        schedule: Union[Callable[[int], float], optax.Schedule],
        optimizer=None,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
    ):
        if not callable(schedule):
            raise TypeError(f"AcceleratedScheduler expects an optax schedule callable, got {type(schedule)}")
        self.schedule = schedule
        self.optimizer = optimizer
        self.step_with_optimizer = step_with_optimizer
        self.split_batches = split_batches
        self.gradient_state = GradientState()
        self._step_count = 0

    def step(self, *args, **kwargs):
        if not self.step_with_optimizer:
            self._step_count += 1
            return
        if not self.gradient_state.sync_gradients:
            # mid-accumulation: schedule holds (but count bumps if the plugin
            # asks schedules to track every batch — reference :62-64)
            if self.gradient_state.plugin.adjust_scheduler:
                return
        if self.split_batches:
            self._step_count += 1
        else:
            self._step_count += AcceleratorState().num_processes

    def get_last_lr(self):
        return [float(self.schedule(max(self._step_count - 1, 0)))]

    def get_lr(self):
        return [float(self.schedule(self._step_count))]

    def state_dict(self):
        return {"step_count": self._step_count}

    def load_state_dict(self, state_dict):
        self._step_count = state_dict.get("step_count", 0)

    def __repr__(self):
        return f"AcceleratedScheduler(schedule={self.schedule}, step={self._step_count})"
