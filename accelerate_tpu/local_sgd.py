"""Local SGD — communication-reducing periodic parameter averaging.

Capability parity with reference ``local_sgd.py:19-106`` (``LocalSGD`` ctx
manager whose ``step()`` all-reduces parameters every ``local_sgd_steps``
optimizer steps, P13 in SURVEY §2.4).  The torch version suppresses DDP's
per-step gradient all-reduce via ``no_sync`` and averages model parameters in
place; the TPU-native contract is functional: each process trains an
*independent* local train state (no cross-process grad sync — exactly what a
per-process mesh gives), and ``step(state)`` returns the state with
parameters averaged across processes at the synchronization cadence.

On a single process this degenerates to a no-op (the reference behaves the
same: ``enabled`` requires a distributed world), so the class is cheap to
leave in scripts unconditionally.
"""

from __future__ import annotations

from typing import Optional

import jax

from .ops import operations as ops
from .state import PartialState


class LocalSGD:
    """Context manager for Local SGD training (reference local_sgd.py:19).

    Usage::

        with LocalSGD(accelerator, local_sgd_steps=8) as local_sgd:
            for batch in loader:
                state, metrics = train_step(state, batch)
                state = local_sgd.step(state)
            state = local_sgd.sync(state)  # final average if the loop ended mid-cadence

    ``step`` counts optimizer steps and every ``local_sgd_steps`` averages
    ``state.params`` (or a raw param pytree) across processes with the pytree
    collective :func:`ops.reduce` — one all-reduce per cadence instead of one
    per step, the whole point of Local SGD.
    """

    def __init__(
        self,
        accelerator=None,
        local_sgd_steps: int = 8,
        enabled: bool = True,
    ):
        if local_sgd_steps < 1:
            raise ValueError(f"local_sgd_steps must be >= 1, got {local_sgd_steps}")
        self.accelerator = accelerator
        self.local_sgd_steps = local_sgd_steps
        self.num_steps = 0
        # PartialState, not AcceleratorState: only the world size is needed,
        # and eagerly building AcceleratorState here would freeze its config
        # before the user constructs their Accelerator.
        self._num_processes = (
            accelerator.num_processes if accelerator is not None else PartialState().num_processes
        )
        self.enabled = enabled and self._num_processes > 1
        self._last_synced_step = 0

    def __enter__(self) -> "LocalSGD":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # The torch reference syncs in-place on exit; with a functional train
        # state the final average must flow through a return value, so warn
        # when the loop ended mid-cadence without a trailing sync().
        if exc_type is None and self.enabled and self.num_steps != self._last_synced_step:
            import warnings

            warnings.warn(
                f"LocalSGD exited {self.num_steps - self._last_synced_step} step(s) "
                "after the last parameter average; ranks may hold divergent "
                "params. Call `state = local_sgd.sync(state)` after the loop.",
                stacklevel=2,
            )
        return None

    def step(self, state):
        """Count one optimizer step; average params at the cadence boundary.

        ``state`` is a train state with a ``.params`` attribute (the
        Accelerator's TrainState) or a bare param pytree.  Returns the same
        structure, parameters averaged across processes every
        ``local_sgd_steps``-th call.
        """
        self.num_steps += 1
        if not self.enabled or self.num_steps % self.local_sgd_steps:
            return state
        return self.sync(state)

    def sync(self, state):
        """Unconditional cross-process parameter average."""
        self._last_synced_step = self.num_steps
        if not self.enabled:
            return state
        params = state.params if hasattr(state, "params") else state
        averaged = ops.reduce(params, reduction="mean")
        # ops.reduce returns host numpy arrays; re-commit to the original
        # shardings so the next jitted step sees device-resident params.
        averaged = jax.tree.map(
            lambda avg, old: jax.device_put(
                avg, old.sharding if hasattr(old, "sharding") else None
            ),
            averaged,
            params,
        )
        if hasattr(state, "replace"):
            return state.replace(params=averaged)
        if hasattr(state, "params"):
            state.params = averaged
            return state
        return averaged
