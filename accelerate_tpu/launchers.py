"""Notebook / debug launchers (reference launchers.py:41 ``notebook_launcher``,
:276 ``debug_launcher``).

On TPU a notebook process already owns every local chip, so ``num_processes``
means *hosts*: in-notebook multi-process only makes sense on the CPU platform
(fake mesh testing), where N spawned processes form a real collective world
over a local coordinator — the analog of the reference's fork/spawn +
``PrepareForLaunch`` dance (launchers.py:160-236).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .utils.environment import get_free_port, patch_environment
from .utils.launch import PrepareForLaunch


def notebook_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: Optional[int] = None,
    mixed_precision: str = "no",
    use_port: Optional[int] = None,
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
) -> Any:
    """Launch ``function(*args)`` for (notebook) training.

    - On TPU (or any accelerator platform): run in-process — the process
      already addresses all local devices, GSPMD handles the rest (the
      reference instead needed ``xmp.spawn`` per-core, launchers.py:112-133).
    - ``num_processes > 1``: spawn that many CPU processes forming a real
      collective world (reference multi-GPU fork path :160).
    """
    in_colab_or_single = num_processes in (None, 0, 1)
    if in_colab_or_single:
        with patch_environment(ACCELERATE_MIXED_PRECISION=mixed_precision):
            return function(*args)

    # num_processes > 1: workers always form a CPU collective world
    # (ACCELERATE_USE_CPU in their env) — a TPU process already owns all local
    # chips, so in-notebook multi-process is a CPU-testing feature by design.
    # Don't probe jax for the platform here: any backend query would
    # initialize XLA in the parent and make forking unsafe.
    import multiprocessing

    # Multi-node notebooks (reference launchers.py:41 node_rank/num_nodes):
    # ``num_processes`` is the per-node count; the world is num_nodes as big
    # and this node owns the contiguous rank block starting at its offset.
    if not (0 <= node_rank < num_nodes):
        raise ValueError(f"node_rank {node_rank} must be in [0, {num_nodes})")
    if num_nodes > 1 and use_port is None:
        raise ValueError("multi-node notebook launch needs an explicit use_port every node agrees on")
    world_size = num_processes * num_nodes
    rank_offset = node_rank * num_processes
    port = use_port or get_free_port()
    env = {
        "ACCELERATE_USE_CPU": "true",
        "ACCELERATE_MIXED_PRECISION": mixed_precision,
        "ACCELERATE_COORDINATOR_ADDRESS": f"{master_addr}:{port}",
        "ACCELERATE_NUM_PROCESSES": str(world_size),
    }
    # Fork so functions defined in a notebook cell survive into workers (the
    # reference forks for the same reason, launchers.py:160-236) — but only
    # while the parent hasn't initialized an XLA backend, which fork would
    # duplicate into a broken state (the reference's CUDA-initialized check).
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        raise RuntimeError(
            "notebook_launcher needs a JAX-untouched process to fork workers "
            "from; restart the notebook and call it before any jax operation "
            "(the analog of the reference's 'CUDA already initialized' guard)."
        )
    ctx = multiprocessing.get_context("fork")
    procs = []
    for pid in range(rank_offset, rank_offset + num_processes):
        p = ctx.Process(target=PrepareForLaunch(function, env, pid), args=args)
        p.start()
        procs.append(p)
    # Poll ALL workers so a crash in worker k>0 surfaces immediately instead
    # of blocking in join() on worker 0 through its distributed-init timeout
    # (same pattern as commands/launch.py _spawn_local_workers).
    import time

    live = dict(enumerate(procs))
    failed: Optional[tuple[int, int]] = None
    while live:
        for pid in list(live):
            p = live[pid]
            if p.is_alive():
                continue
            p.join()
            del live[pid]
            if p.exitcode != 0 and failed is None:
                failed = (pid, p.exitcode)
                for other in live.values():
                    other.terminate()
        if live:
            time.sleep(0.2)
    if failed is not None:
        raise RuntimeError(f"process {failed[0]} exited with code {failed[1]}")


def debug_launcher(function: Callable, args: tuple = (), num_processes: int = 2) -> Any:
    """2-process CPU launch for CI debugging (reference launchers.py:276)."""
    return notebook_launcher(function, args, num_processes=num_processes)
