"""Host memory bandwidth on THIS TPU-VM host — the denominator of the
7B-offload accounting (docs/performance.md).  The offloaded lion update is
host-side streaming arithmetic over the fp32 masters + bf16 momentum/grads;
its floor is host DRAM bandwidth, measured here STREAM-style with numpy
(copy and triad over 1 GiB operands), plus a pinned-host<->device move is
measured separately by pcie_probe.py."""

import json
import time

import numpy as np


def _bw(fn, bytes_moved, iters=6):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    dt = time.perf_counter() - t0
    return bytes_moved * iters / dt / 2**30


def main():
    n = 256 * 1024 * 1024  # 1 GiB fp32
    a = np.ones(n, np.float32)
    b = np.ones(n, np.float32)
    c = np.empty(n, np.float32)
    out = {
        # copy: read 4B + write 4B per element
        "copy_gib_s": round(_bw(lambda: np.copyto(c, a), 8 * n), 2),
        # triad a = b + 0.5*c: read 8B + write 4B
        "triad_gib_s": round(_bw(lambda: np.add(b, 0.5 * c, out=a), 16 * n), 2),
    }
    # the lion-shaped op: sign(momentum-combined) applied to fp32 master
    m = np.ones(n // 2, np.float16)  # stand-in for bf16 momentum width

    def lion_like():
        np.subtract(a[: n // 2], 1e-4 * np.sign(m, dtype=np.float16).astype(np.float32),
                    out=a[: n // 2])

    out["lion_like_gib_s"] = round(_bw(lion_like, (4 + 2 + 4) * (n // 2)), 2)
    print(json.dumps({"metric": "host_memory_bandwidth", "unit": "GiB/s", **out}))


if __name__ == "__main__":
    main()
