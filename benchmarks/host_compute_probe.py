"""Measured throughput of an XLA host-compute region on the REAL worker
host — the denominator of the 7B-offload accounting.

The 7B step's host region performs a lion-shaped streaming update over the
pinned-host masters/momentum; this probe times the same op shape (read
fp32 master + bf16 momentum + bf16 grad, write fp32 master + bf16
momentum) over a 1 GiB master tree as a whole program, giving effective
GiB/s of the worker host's memory system under XLA host compute.  (A numpy
STREAM on the *operator* box measures the wrong machine — under axon the
host regions execute on the remote TPU-VM host.)

The measurement kernel itself is
``accelerate_tpu.utils.environment.calibrate_host_compute`` — the SAME
function the quiet-box gate's 1-s calibration chain runs, just at 1-GiB
granularity and ``--streams`` independent regions, so the calibration and
the baseline it is compared against can never drift onto different
kernels.

The probe ENFORCES the quiet-box precondition (VERDICT r5 weak #7: the
same binary measured 0.35-1.61 GiB/s depending on operator-box load):
a loadavg gate plus the calibration chain compared against the
documented 1.71 GiB/s quiet baseline run first, and the probe refuses on
a loaded/degraded box unless ``--force`` is passed.  The gate report is
always included in the output JSON so every archived number carries its
own validity evidence."""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", type=int, default=1,
                    help="number of INDEPENDENT host regions in one program "
                         "(disjoint trees, no data deps): measures whether the "
                         "worker host's bandwidth scales with host-region "
                         "concurrency — the 7B chunked update currently "
                         "token-serializes into one chain")
    ap.add_argument("--gib", type=float, default=1.0,
                    help="fp32 master GiB per stream")
    ap.add_argument("--force", action="store_true",
                    help="measure anyway on a loaded/degraded box (the gate "
                         "report still lands in the output JSON)")
    args = ap.parse_args()

    from accelerate_tpu.utils.environment import calibrate_host_compute, quiet_box_gate

    gate = quiet_box_gate()
    if not gate["ok"]:
        for w in gate["warnings"]:
            print(f"host_compute_probe: {w}", file=sys.stderr)
        if not args.force:
            print(json.dumps({
                "metric": "worker_host_compute_bandwidth",
                "unit": "GiB/s",
                "refused": True,
                "quiet_box": gate,
            }))
            sys.exit(2)

    rep = calibrate_host_compute(gib=args.gib, iters=4, streams=args.streams)
    print(json.dumps({
        "metric": "worker_host_compute_bandwidth",
        "unit": "GiB/s",
        "streams": rep["streams"],
        "gib_per_stream": args.gib,
        "aggregate_gib_s": rep["gibs"],
        "secs_per_iter": rep["secs_per_iter"],
        "backend": jax.default_backend(),
        "quiet_box": gate,
    }))


if __name__ == "__main__":
    main()
