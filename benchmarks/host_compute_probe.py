"""Measured throughput of an XLA host-compute region on the REAL worker
host — the denominator of the 7B-offload accounting.

The 7B step's host region performs a lion-shaped streaming update over the
pinned-host masters/momentum; this probe times the same op shape (read
fp32 master + bf16 momentum + bf16 grad, write fp32 master + bf16
momentum) over a 1 GiB master tree as a whole program, giving effective
GiB/s of the worker host's memory system under XLA host compute.  (A numpy
STREAM on the *operator* box measures the wrong machine — under axon the
host regions execute on the remote TPU-VM host.)"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.compute_on import compute_on
from jax.sharding import NamedSharding, PartitionSpec as P


def main():
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("d",))
    host = NamedSharding(mesh, P(), memory_kind="pinned_host")
    dev = NamedSharding(mesh, P(), memory_kind="device")
    n = 256 * 1024 * 1024  # 1 GiB fp32 master
    master = jax.device_put(jnp.zeros((n,), jnp.float32), host)
    mom = jax.device_put(jnp.zeros((n,), jnp.bfloat16), host)
    grad = jax.device_put(jnp.ones((n,), jnp.bfloat16), host)

    @jax.jit
    def host_lion(master, mom, grad, salt):
        with compute_on("device_host"):
            g = grad.astype(jnp.float32) + salt  # varying input defeats caching
            m = mom.astype(jnp.float32)
            new_master = master - 1e-4 * jnp.sign(0.9 * m + 0.1 * g)
            new_mom = (0.99 * m + 0.01 * g).astype(jnp.bfloat16)
            checksum = new_master[0] + new_master[-1]
        return (
            jax.device_put(new_master, host),
            jax.device_put(new_mom, host),
            jax.device_put(checksum, dev),
        )

    salt0 = jax.device_put(jnp.float32(0.0), host)
    master, mom, cs = host_lion(master, mom, grad, salt0)  # compile + warm
    float(cs)
    iters = 4
    t0 = time.perf_counter()
    for i in range(iters):
        salt = jax.device_put(jnp.float32(i + 1.0), host)
        master, mom, cs = host_lion(master, mom, grad, salt)
        float(cs)  # scalar fetch sync
    dt = time.perf_counter() - t0
    bytes_per = n * (4 + 2 + 2 + 4 + 2)  # r master+mom+grad, w master+mom
    print(json.dumps({
        "metric": "worker_host_compute_bandwidth",
        "unit": "GiB/s",
        "lion_like_gib_s": round(bytes_per * iters / dt / 2**30, 2),
        "secs_per_gib_master": round(dt / iters, 3),
    }))


if __name__ == "__main__":
    main()
