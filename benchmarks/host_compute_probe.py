"""Measured throughput of an XLA host-compute region on the REAL worker
host — the denominator of the 7B-offload accounting.

The 7B step's host region performs a lion-shaped streaming update over the
pinned-host masters/momentum; this probe times the same op shape (read
fp32 master + bf16 momentum + bf16 grad, write fp32 master + bf16
momentum) over a 1 GiB master tree as a whole program, giving effective
GiB/s of the worker host's memory system under XLA host compute.  (A numpy
STREAM on the *operator* box measures the wrong machine — under axon the
host regions execute on the remote TPU-VM host.)"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.compute_on import compute_on
from jax.sharding import NamedSharding, PartitionSpec as P


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", type=int, default=1,
                    help="number of INDEPENDENT host regions in one program "
                         "(disjoint trees, no data deps): measures whether the "
                         "worker host's bandwidth scales with host-region "
                         "concurrency — the 7B chunked update currently "
                         "token-serializes into one chain")
    ap.add_argument("--gib", type=float, default=1.0,
                    help="fp32 master GiB per stream")
    args = ap.parse_args()

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("d",))
    host = NamedSharding(mesh, P(), memory_kind="pinned_host")
    dev = NamedSharding(mesh, P(), memory_kind="device")
    S = args.streams
    n = int(args.gib * 256 * 1024 * 1024)  # fp32 elements per stream
    masters = [jax.device_put(jnp.zeros((n,), jnp.float32), host) for _ in range(S)]
    moms = [jax.device_put(jnp.zeros((n,), jnp.bfloat16), host) for _ in range(S)]
    grads = [jax.device_put(jnp.ones((n,), jnp.bfloat16), host) for _ in range(S)]

    def one_stream(master, mom, grad, salt):
        with compute_on("device_host"):
            g = grad.astype(jnp.float32) + salt  # varying input defeats caching
            m = mom.astype(jnp.float32)
            new_master = master - 1e-4 * jnp.sign(0.9 * m + 0.1 * g)
            new_mom = (0.99 * m + 0.01 * g).astype(jnp.bfloat16)
            checksum = new_master[0] + new_master[-1]
        return new_master, new_mom, checksum

    @jax.jit
    def host_lion(masters, moms, grads, salt):
        outs = [one_stream(ma, mo, g, salt) for ma, mo, g in zip(masters, moms, grads)]
        return (
            [jax.device_put(o[0], host) for o in outs],
            [jax.device_put(o[1], host) for o in outs],
            jax.device_put(sum(o[2] for o in outs), dev),
        )

    salt0 = jax.device_put(jnp.float32(0.0), host)
    masters, moms, cs = host_lion(masters, moms, grads, salt0)  # compile + warm
    float(cs)
    iters = 4
    t0 = time.perf_counter()
    for i in range(iters):
        salt = jax.device_put(jnp.float32(i + 1.0), host)
        masters, moms, cs = host_lion(masters, moms, grads, salt)
        float(cs)  # scalar fetch sync
    dt = time.perf_counter() - t0
    bytes_per = n * (4 + 2 + 2 + 4 + 2) * S  # r master+mom+grad, w master+mom
    print(json.dumps({
        "metric": "worker_host_compute_bandwidth",
        "unit": "GiB/s",
        "streams": S,
        "gib_per_stream": args.gib,
        "aggregate_gib_s": round(bytes_per * iters / dt / 2**30, 2),
        "secs_per_iter": round(dt / iters, 3),
    }))


if __name__ == "__main__":
    main()
