"""Quality validation for the fp8 train recipe on a shuffled stream.

Fixed-batch bench losses are throughput probes, not quality metrics — the
same discipline as ``sr_quality.py``: train on a stream of DISTINCT
Zipf-distributed batches (identical stream for both runs), track a
held-out batch, and compare ``mixed_precision="fp8"`` (delayed scaling:
e4m3 forward / e5m2 backward, per-tensor amax history riding
``TrainState.fp8_state``) against the bf16 reference at the same
hyperparameters.  Two envelopes come out:

- ``train_envelope_max_pct`` — the worst per-step train-loss divergence
  over the run (fp8 quantization noise is per-step, so this is the noisy
  bound);
- ``final_held_out_gap_pct`` — the held-out gap at the horizon (the
  number docs/performance.md's "validated envelopes" table pins; like SR,
  the per-step noise should average out rather than accumulate).

  python benchmarks/fp8_quality.py --steps 240
  python benchmarks/fp8_quality.py --steps 240 --current-scaling
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=["600m", "1b"], default="600m")
    ap.add_argument("--steps", type=int, default=240)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--eval-every", type=int, default=20)
    ap.add_argument("--lr", type=float, default=1e-4)
    ap.add_argument("--optimizer", default="lion-sr")
    ap.add_argument("--current-scaling", action="store_true",
                    help="disable the delayed-scaling amax history "
                         "(ACCELERATE_FP8_DELAYED=0): per-step current "
                         "scaling, the A/B for the history's contribution")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (smoke mode; the axon "
                         "sitecustomize preempts JAX_PLATFORMS env vars)")
    args = ap.parse_args()

    if args.current_scaling:
        os.environ["ACCELERATE_FP8_DELAYED"] = "0"

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn
    from accelerate_tpu.state import AcceleratorState, GradientState

    on_tpu = jax.default_backend() == "tpu"
    seq = args.seq_len if on_tpu else 128
    if args.model == "1b" and on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=24, num_attention_heads=16, num_key_value_heads=8,
            max_position_embeddings=seq, attn_implementation="flash",
            dtype=jnp.bfloat16,
        )
        batch = args.batch or 4
    elif on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=16, num_attention_heads=16, num_key_value_heads=8,
            max_position_embeddings=seq, attn_implementation="flash",
            dtype=jnp.bfloat16,
        )
        batch = args.batch or 8
    else:
        cfg = LlamaConfig.tiny()
        batch = args.batch or 4

    # identical data stream for every run: distinct Zipf-distributed batches
    # (long-tail token stats like real text) + one held-out batch
    rng = np.random.default_rng(0)
    zipf = lambda n: np.minimum(
        rng.zipf(1.2, (n, seq)).astype(np.int64), cfg.vocab_size - 1
    ).astype(np.int32)
    stream = [zipf(batch) for _ in range(args.steps)]
    held_out = zipf(batch)

    def run(precision):
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        from accelerate_tpu.optimizer import make_optimizer

        acc = Accelerator(
            parallelism_config=ParallelismConfig(dp_shard_size=jax.device_count()),
            mixed_precision=precision,
        )
        model = LlamaForCausalLM(cfg)
        ids = jnp.ones((batch, 8), jnp.int32)
        params = acc.init_params(model, jax.random.key(0), ids)
        tx = make_optimizer(args.optimizer, args.lr, weight_decay=0.0)
        state = acc.create_train_state(params, tx, apply_fn=model.apply)
        loss_fn = make_llama_loss_fn(model, fused_vocab_chunks=4 if on_tpu else None)
        step = acc.prepare_train_step(loss_fn, max_grad_norm=None)
        eval_loss = jax.jit(lambda p, b: loss_fn(p, b))
        curve, evals = [], []
        for i, tokens in enumerate(stream):
            b = {"input_ids": jnp.asarray(tokens), "labels": jnp.asarray(tokens)}
            state, m = step(state, b)
            curve.append(round(float(m["loss"]), 4))
            if (i + 1) % args.eval_every == 0:
                h = {"input_ids": jnp.asarray(held_out), "labels": jnp.asarray(held_out)}
                evals.append(round(float(eval_loss(state.params, h)), 4))
        return curve, evals

    fp8_curve, fp8_evals = run("fp8")
    ref_curve, ref_evals = run("bf16")
    train_env = max(
        abs(a - b) / max(abs(b), 1e-9) for a, b in zip(fp8_curve, ref_curve)
    )
    print(json.dumps({
        "metric": "fp8_quality_shuffled_stream",
        # report the EFFECTIVE config: off-TPU the harness substitutes the
        # tiny CPU model, so labeling the output with the requested TPU
        # model name would misattribute smoke numbers
        "model": args.model if on_tpu else "tiny-cpu",
        "backend": jax.default_backend(),
        "scaling": "current" if args.current_scaling else "delayed",
        "steps": args.steps, "batch": batch, "seq_len": seq, "lr": args.lr,
        "optimizer": args.optimizer,
        "fp8": {"train_every10": fp8_curve[9::10], "held_out": fp8_evals},
        "ref": {"train_every10": ref_curve[9::10], "held_out": ref_evals},
        "train_envelope_max_pct": round(100.0 * train_env, 3),
        "final_held_out_gap_pct": round(
            100.0 * abs(fp8_evals[-1] - ref_evals[-1]) / max(abs(ref_evals[-1]), 1e-9), 3
        ) if fp8_evals and ref_evals else None,
    }))


if __name__ == "__main__":
    main()
