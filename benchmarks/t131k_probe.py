"""Isolation probe for the T>=2^17 single-chip crash (docs/long_context.md).

Runs ONE suspect component at a given sequence length in a fresh process so
the crashing component can be bisected out of the full train step:

  --component flash      Pallas flash attention fwd+bwd at [1, T, 16, 96]
  --component matmul     plain [T, H] @ [H, H] chain fwd+bwd (control)
  --component offload    the scan+boundary-offload skeleton, identity math,
                         no attention (the D2H/H2D path alone)
  --component scanflash  scan+boundary-offload WITH flash attention in the
                         body (--layers to vary depth; --splits to divide
                         the stack into consecutive independent scans —
                         probes whether 2x8 dodges the >=16-layer bug cell)

Outcome (2026-08-01, this rig, v5e tunnel): every component PASSES
standalone at T=131,072, which ruled a per-component dimension limit OUT.
The full-step crash set (capacity-fitting configs only) is the exact shape
cell {T >= 2^17, scanned layers >= 16, hidden 1536}; neighboring cells
(15L, 17L at shorter T, hidden 1024) run, and every capacity metric is
non-monotone with crashing — a shape-conditioned runtime bug.  The
complete run matrix lives in docs/long_context.md "Where the single-chip
ceiling actually is".

The reproducer is NOT minimal: `--component scanflash --layers 16` (a
16-iteration scan whose body runs real flash attention with the boundary
offloaded) PASSES at T=131,072, so the trigger needs still more of the
full step (MLP/RMSNorm/fused-CE/optimizer/donation) — left for an
upstream report rather than further bisection here.
"""

import argparse
import json


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, required=True)
    ap.add_argument("--component",
                    choices=["flash", "matmul", "offload", "scanflash"],
                    default="flash")
    ap.add_argument("--block-q", type=int, default=None)
    ap.add_argument("--block-k", type=int, default=None)
    ap.add_argument("--layers", type=int, default=16)
    ap.add_argument("--splits", type=int, default=1,
                    help="scanflash only: number of consecutive independent "
                         "scans the layer stack is divided into")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (smoke mode; flash/offload "
                         "components need the TPU for their real form)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    T = args.seq_len
    out = {"metric": "t131k_probe", "component": args.component, "seq_len": T}

    if args.component == "flash":
        from accelerate_tpu.ops.flash_attention import flash_attention

        B, H, Hkv, D = 1, 16, 8, 96
        key = jax.random.key(0)
        q = jax.random.normal(key, (B, T, H, D), jnp.bfloat16)
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, T, Hkv, D), jnp.bfloat16)
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, T, Hkv, D), jnp.bfloat16)
        kw = {}
        if args.block_q:
            kw["block_q"] = args.block_q
        if args.block_k:
            kw["block_k"] = args.block_k

        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True, **kw).astype(jnp.float32).sum()

        val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(q, k, v)
        out["value"] = float(val)
        out["grad_norm"] = float(
            sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in grads) ** 0.5
        )
    elif args.component == "matmul":
        Hd = 1536
        key = jax.random.key(0)
        x = jax.random.normal(key, (T, Hd), jnp.bfloat16)
        w = jax.random.normal(jax.random.fold_in(key, 1), (Hd, Hd), jnp.bfloat16)

        def loss(x, w):
            y = x
            for _ in range(4):
                y = jnp.tanh(y @ w)
            return y.astype(jnp.float32).sum()

        val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))(x, w)
        out["value"] = float(val)
    elif args.component == "scanflash":
        from jax.ad_checkpoint import checkpoint_name

        from accelerate_tpu.ops.flash_attention import flash_attention

        Hd, Hq, Hkv, D = 1536, 16, 8, 96
        L, S = args.layers, args.splits
        assert L % S == 0, "--layers must divide by --splits"
        policy = jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["boundary"],
            offload_src="device", offload_dst="pinned_host",
        )

        def body(x, w):
            # one flash-attention "layer": qkv projections off a shared
            # weight, flash over the full sequence, out-projection residual
            x = checkpoint_name(x, "boundary")
            q = (x @ w).reshape(1, T, Hq, D)
            kv = (x @ w[:, : Hkv * D * 2]).reshape(1, T, Hkv, 2 * D)
            k, v = kv[..., :D], kv[..., D:]
            o = flash_attention(q, k, v, causal=True).reshape(T, Hq * D)
            return (x + o @ w.T).astype(jnp.bfloat16), None

        def loss(x, ws_list):
            for ws in ws_list:  # S consecutive, independent scans
                x, _ = jax.lax.scan(
                    jax.checkpoint(body, policy=policy, prevent_cse=False), x, ws
                )
            return x.astype(jnp.float32).sum()

        key = jax.random.key(0)
        x = jax.random.normal(key, (T, Hd), jnp.bfloat16) * 0.02
        ws_list = [
            jax.random.normal(jax.random.fold_in(key, i), (L // S, Hd, Hq * D),
                              jnp.bfloat16) * 0.02
            for i in range(S)
        ]
        val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0,)))(x, ws_list)
        out["value"] = float(val)
        out["layers"], out["splits"] = L, S
    else:  # offload skeleton: scan with boundary offload, elementwise body
        from jax.ad_checkpoint import checkpoint_name

        Hd, L = 1536, 16
        policy = jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["boundary"],
            offload_src="device", offload_dst="pinned_host",
        )

        def body(x, w):
            x = checkpoint_name(x, "boundary")
            return jnp.tanh(x @ w), None

        def loss(x, ws):
            y, _ = jax.lax.scan(
                jax.checkpoint(body, policy=policy, prevent_cse=False), x, ws
            )
            return y.astype(jnp.float32).sum()

        key = jax.random.key(0)
        x = jax.random.normal(key, (T, Hd), jnp.bfloat16)
        ws = jax.random.normal(jax.random.fold_in(key, 1), (L, Hd, Hd), jnp.bfloat16)
        val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0,)))(x, ws)
        out["value"] = float(val)

    print(json.dumps(out))


if __name__ == "__main__":
    main()


