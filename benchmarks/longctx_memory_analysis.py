"""Compile-only HBM accounting for the long-context configs: lowers the full
train step at a given sequence length and prints XLA's compiled memory
analysis (temp/argument/output bytes).  This is the arithmetic behind the
112k-works / 131k-crashes cliff in docs/long_context.md — no execution, so
it is safe at lengths that crash the worker at run time."""

import argparse
import json

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, required=True)
    ap.add_argument("--scan-block", type=int, default=None)
    ap.add_argument("--optimizer", choices=["adamw", "lion-sr"], default="adamw")
    ap.add_argument("--boundary-frac", type=float, default=1.0,
                    help="boundary_offload_fraction: <1 keeps the tail slice of "
                         "each scan boundary in device HBM instead of pinned host")
    ap.add_argument("--layers", type=int, default=16,
                    help="num_hidden_layers (bisecting the T>=2^17 crash: fewer "
                         "layers = fewer in-flight boundaries at identical T)")
    ap.add_argument("--hidden", type=int, default=1536,
                    help="hidden_size (the byte-size-vs-shape discriminator for "
                         "the T>=2^17 crash: smaller hidden = smaller boundary "
                         "bytes at identical (T, L) shape)")
    ap.add_argument("--execute", action="store_true",
                    help="actually run 2 steps after compiling (default: "
                         "compile-only, safe at crash-prone lengths)")
    ap.add_argument("--compiler-opt", action="append", default=[],
                    metavar="K=V", help="extra XLA compiler option(s) for the "
                    "step compile, e.g. xla_tpu_enable_latency_hiding_scheduler=false")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn

    seq = args.seq_len
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=args.hidden, intermediate_size=4096,
        num_hidden_layers=args.layers, num_attention_heads=16, num_key_value_heads=8,
        max_position_embeddings=seq, attn_implementation="flash",
        remat=True, dtype=jnp.bfloat16,
        remat_policy="offload" if seq > 98304 else "full",
        scan_layers=seq > 98304,
        scan_block_size=(
            args.scan_block
            or (2 if seq > 114688 and args.layers % 2 == 0 else 1)
        ) if seq > 98304 else 1,
        boundary_offload_fraction=args.boundary_frac,
    )
    model = LlamaForCausalLM(cfg)
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=jax.device_count()),
                      mixed_precision="bf16")
    ids = jnp.ones((1, seq), jnp.int32)
    params = acc.init_params(model, jax.random.key(0), ids[:, :8])
    if args.optimizer == "lion-sr":
        from accelerate_tpu.ops.stochastic_rounding import lion_bf16_sr

        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        tx = lion_bf16_sr(1e-4, b1=0.9, b2=0.99)
    else:
        tx = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    state = acc.create_train_state(params, tx, apply_fn=model.apply)
    chunks = max(16, seq // 2048)
    step = acc.prepare_train_step(make_llama_loss_fn(model, fused_vocab_chunks=chunks))
    batch = {"input_ids": ids, "labels": ids}
    # prepare_train_step exposes its jitted core as step._jitted
    copts = {}
    for kv in args.compiler_opt:
        k, _, v = kv.partition("=")
        copts[k] = {"true": True, "false": False}.get(v.lower(), v)
    compiled = step._jitted.lower(state, batch).compile(
        compiler_options=copts or None
    )
    ma = compiled.memory_analysis()
    fields = {}
    for k in ("temp_size_in_bytes", "argument_size_in_bytes", "output_size_in_bytes",
              "alias_size_in_bytes", "generated_code_size_in_bytes", "host_temp_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            fields[k] = int(v)
    live = fields.get("temp_size_in_bytes", 0) + fields.get("argument_size_in_bytes", 0) \
        + fields.get("output_size_in_bytes", 0) - fields.get("alias_size_in_bytes", 0)
    report = {
        "metric": "longctx_compiled_memory", "seq_len": seq, "optimizer": args.optimizer,
        "scan_block": cfg.scan_block_size, "layers": args.layers,
        "hidden": args.hidden, **fields,
        "peak_estimate_gib": round(live / 2**30, 2),
        "hbm_gib": round((jax.devices()[0].memory_stats() or {}).get("bytes_limit", 0) / 2**30, 2)
        if getattr(jax.devices()[0], "memory_stats", lambda: None)() else None,
    }
    if args.compiler_opt:
        report["compiler_options"] = copts
    if args.execute:
        import time
        for i in range(2):
            t0 = time.perf_counter()
            state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])  # scalar fetch = sync
            report[f"step{i}_s"] = round(time.perf_counter() - t0, 2)
            report[f"step{i}_loss"] = round(loss, 4)
        report["executed"] = True
    print(json.dumps(report))


if __name__ == "__main__":
    main()
