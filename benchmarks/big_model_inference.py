"""Big-model inference benchmark (reference
benchmarks/big_model_inference/README.md: GPT-J/NeoX/OPT load time +
per-token generation latency on consumer GPUs).

TPU-native equivalents of the same three numbers:
- **load**: write a sharded safetensors checkpoint to disk once, then time
  ``load_checkpoint_and_dispatch`` streaming it into device placement
  (abstract init -> plan -> shard-stream; no full-model host copy);
- **prefill latency**: one jitted forward over the prompt writing the KV
  cache;
- **per-token latency**: steady-state decode step (the number the reference
  reports as "generate time per token").

Prints one JSON line per metric, bench.py-style.  Model: ~1.1B Llama
(``llama2_1b``) in bf16 — sized to one v5e chip like the reference's
GPT-J-6B was sized to its 2x Titan RTX.

Run: ``python benchmarks/big_model_inference.py [--layers N]``
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.big_modeling import load_checkpoint_and_dispatch
    from accelerate_tpu.checkpointing import save_model
    from accelerate_tpu.generation import GenerationConfig, generate
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig.llama2_1b(num_hidden_layers=args.layers or 22)
    else:  # CPU smoke
        cfg = LlamaConfig.tiny(num_hidden_layers=args.layers or 2)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)

    with tempfile.TemporaryDirectory() as ckpt_dir:
        # one-time checkpoint authoring (not timed — the reference times the
        # *load*, the checkpoint already exists on disk)
        params = jax.jit(
            lambda: model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))
        )()
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
        save_model(None, params, ckpt_dir)
        del params

        t0 = time.perf_counter()
        loaded, _ = load_checkpoint_and_dispatch(
            model, ckpt_dir, sample_args=(jnp.ones((1, 8), jnp.int32),),
            device_map=None, dtype=jnp.bfloat16,
        )
        jax.block_until_ready(loaded)
        load_s = time.perf_counter() - t0

    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (args.batch, args.prompt_len)), jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=args.new_tokens)
    wrapped = {"params": loaded["params"]} if "params" in loaded else loaded

    if args.load_in_8bit:
        # int8 weight-only decode (reference bnb path): decode reads ~half
        # the weight bytes per step, and decode is HBM-bound.  QuantizedTensor
        # kernels route natively through the Pallas in-tile-dequant matmul in
        # QuantizableDense — no apply wrapper needed.
        from accelerate_tpu.utils.quantization import QuantizationConfig, quantize_params

        wrapped = quantize_params(wrapped, QuantizationConfig(load_in_8bit=True))

    t0 = time.perf_counter()
    out = generate(model, wrapped, prompt, gen_cfg)
    out.block_until_ready()
    first_s = time.perf_counter() - t0  # includes compile

    t0 = time.perf_counter()
    out = generate(model, wrapped, jnp.asarray(
        rng.integers(0, cfg.vocab_size, prompt.shape), jnp.int32), gen_cfg)
    out.block_until_ready()
    steady_s = time.perf_counter() - t0
    per_token = steady_s / args.new_tokens

    meta = {"params": n_params, "batch": args.batch, "prompt_len": args.prompt_len,
            "new_tokens": args.new_tokens, "backend": jax.default_backend(),
            "int8": bool(args.load_in_8bit),
            "compile_s": round(first_s - steady_s, 2)}
    print(json.dumps({"metric": "big_model_load_seconds", "value": round(load_s, 2),
                      "unit": "s", "extra": meta}))
    print(json.dumps({"metric": "big_model_decode_seconds_per_token",
                      "value": round(per_token, 4), "unit": "s/token", "extra": meta}))


if __name__ == "__main__":
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--layers", type=int, default=None)
    p.add_argument("--load_in_8bit", action="store_true")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--prompt_len", type=int, default=128)
    p.add_argument("--new_tokens", type=int, default=64)
    main(p.parse_args())
