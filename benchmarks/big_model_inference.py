"""Big-model inference benchmark (reference
benchmarks/big_model_inference/README.md: GPT-J/NeoX/OPT load time +
per-token generation latency on consumer GPUs).

TPU-native equivalents of the same three numbers:
- **load**: write a sharded safetensors checkpoint to disk once, then time
  ``load_checkpoint_and_dispatch`` streaming it into device placement
  (abstract init -> plan -> shard-stream; no full-model host copy);
- **prefill latency**: one jitted forward over the prompt writing the KV
  cache;
- **per-token latency**: steady-state decode step (the number the reference
  reports as "generate time per token").

Prints one JSON line per metric, bench.py-style.  Model: ~1.1B Llama
(``llama2_1b``) in bf16 — sized to one v5e chip like the reference's
GPT-J-6B was sized to its 2x Titan RTX.

Run: ``python benchmarks/big_model_inference.py [--layers N]``
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def over_hbm_main(args):
    """A model ~1.7x the chip's HBM decodes via layer-streamed generation
    (reference rows: OPT-30B fp16 CPU-offload at 2.37 s/token on a 24GB
    card, benchmarks/big_model_inference/README.md:36).  ~26B int8 weights
    live in pinned host memory (~26GiB); HBM holds one layer + the KV
    cache; every token sweeps the weights over PCIe."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.generation import GenerationConfig, generate_streamed
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.parallel.sharding import (
        host_offload_supported, single_device_sharding,
    )
    from accelerate_tpu.utils.quantization import _quantize_int8_on_device

    assert jax.default_backend() == "tpu" and host_offload_supported(), \
        "--over_hbm needs a real TPU (pinned host memory)"
    host = single_device_sharding("pinned_host")

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=7168, intermediate_size=19456,
        num_hidden_layers=args.layers or 48, num_attention_heads=56,
        num_key_value_heads=8, max_position_embeddings=64,
        attn_implementation="native", dtype=jnp.bfloat16,
    )
    model = LlamaForCausalLM(cfg)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))
    )

    t0 = time.perf_counter()
    gen_jits: dict = {}

    def _gen(shape, dtype, key):
        k = (shape, str(dtype))
        if k not in gen_jits:
            gen_jits[k] = jax.jit(
                lambda kk: (jax.random.normal(kk, shape, jnp.float32) * 0.02).astype(dtype)
            )
        return gen_jits[k](key)

    flat, treedef = jax.tree_util.tree_flatten_with_path(abstract)
    leaves, n_bytes = [], 0
    for i, (path, sds) in enumerate(flat):
        name = "/".join(str(getattr(p, "key", p)) for p in path).lower()
        if sds.ndim == 2 and not any(s in name for s in ("embed", "lm_head", "norm")):
            w = _gen(sds.shape, jnp.bfloat16, jax.random.key(i))
            qt = _quantize_int8_on_device(w, 128)
            qt.data = jax.device_put(qt.data, host)
            qt.scale = jax.device_put(qt.scale, host)
            n_bytes += qt.data.nbytes + qt.scale.nbytes
            leaves.append(qt)
        elif "norm" in name or "scale" in name:
            leaves.append(jax.device_put(jnp.ones(sds.shape, jnp.bfloat16), host))
            n_bytes += int(np.prod(sds.shape)) * 2
        else:
            w = _gen(sds.shape, jnp.bfloat16, jax.random.key(i))
            leaves.append(jax.device_put(w, host))
            n_bytes += w.nbytes
    host_params = jax.tree_util.tree_unflatten(treedef, leaves)
    n_params = sum(
        int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(abstract)
    )
    build_s = time.perf_counter() - t0
    print(f"built {n_params/1e9:.1f}B params, {n_bytes/2**30:.1f} GiB in host memory, "
          f"{build_s:.0f}s", flush=True)

    from accelerate_tpu.ops.streaming import StreamStats

    prefetch = not args.no_prefetch
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, args.prompt_len)), jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=args.new_tokens)
    t0 = time.perf_counter()
    # warmup/compile run with stats ON: stats routes fetches through the
    # prefetcher even when disabled, so every timed run below (all
    # stats-on) sees the same device-resident jit signature — otherwise a
    # --no-prefetch warmup would pass host-resident trees and the serial
    # baseline's timed window would absorb n_layers recompiles, inflating
    # speedup_vs_serial
    out = generate_streamed(model, host_params, prompt, gen_cfg,
                            prefetch=prefetch, stream_stats=StreamStats())
    np.asarray(out)
    first_s = time.perf_counter() - t0
    # Serial-transfer baseline for the achieved-overlap number: one timed
    # run with prefetch OFF, stats on — its blocking fetches measure the
    # un-hidden per-token PCIe sweep the double buffer exists to hide.
    serial_stats = StreamStats()
    t0 = time.perf_counter()
    out = generate_streamed(
        model, host_params,
        jnp.asarray(rng.integers(0, cfg.vocab_size, prompt.shape), jnp.int32),
        gen_cfg, prefetch=False, stream_stats=serial_stats,
    )
    np.asarray(out)
    serial_per_token = (time.perf_counter() - t0) / args.new_tokens
    stats = StreamStats()
    t0 = time.perf_counter()
    out = generate_streamed(
        model, host_params,
        jnp.asarray(rng.integers(0, cfg.vocab_size, prompt.shape), jnp.int32),
        gen_cfg, prefetch=prefetch, stream_stats=stats,
    )
    np.asarray(out)
    per_token = (time.perf_counter() - t0) / args.new_tokens
    overlap = stats.overlap_report(serial_transfer_s=serial_stats.fetch_wait_s)
    overlap["serial_s_per_token"] = round(serial_per_token, 3)
    overlap["speedup_vs_serial"] = round(serial_per_token / max(per_token, 1e-9), 3)
    print(json.dumps({
        "metric": "over_hbm_decode_seconds_per_token", "value": round(per_token, 3),
        "unit": "s/token",
        "extra": {"params": n_params, "host_GiB": round(n_bytes / 2**30, 2),
                  "hbm_GiB": 16, "layers": cfg.num_hidden_layers,
                  "compile_s": round(first_s - per_token * args.new_tokens, 1),
                  "prompt_len": args.prompt_len, "new_tokens": args.new_tokens,
                  "prefetch": prefetch,
                  "overlap_frac": overlap.get("overlap_frac", 0.0),
                  "h2d_bytes": overlap["h2d_bytes"],
                  "d2h_bytes": overlap["d2h_bytes"],
                  "streaming": overlap},
    }))


def main(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.big_modeling import load_checkpoint_and_dispatch
    from accelerate_tpu.checkpointing import save_model
    from accelerate_tpu.generation import GenerationConfig, generate
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig.llama2_1b(num_hidden_layers=args.layers or 22)
    else:  # CPU smoke
        cfg = LlamaConfig.tiny(num_hidden_layers=args.layers or 2)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)

    with tempfile.TemporaryDirectory() as ckpt_dir:
        # one-time checkpoint authoring (not timed — the reference times the
        # *load*, the checkpoint already exists on disk)
        params = jax.jit(
            lambda: model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))
        )()
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
        save_model(None, params, ckpt_dir)
        del params

        t0 = time.perf_counter()
        loaded, _ = load_checkpoint_and_dispatch(
            model, ckpt_dir, sample_args=(jnp.ones((1, 8), jnp.int32),),
            device_map=None, dtype=jnp.bfloat16,
        )
        jax.block_until_ready(loaded)
        load_s = time.perf_counter() - t0

    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (args.batch, args.prompt_len)), jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=args.new_tokens)
    wrapped = {"params": loaded["params"]} if "params" in loaded else loaded

    if args.load_in_8bit:
        # int8 weight-only decode (reference bnb path): decode reads ~half
        # the weight bytes per step, and decode is HBM-bound.  QuantizedTensor
        # kernels route natively through the Pallas in-tile-dequant matmul in
        # QuantizableDense — no apply wrapper needed.
        from accelerate_tpu.utils.quantization import QuantizationConfig, quantize_params

        wrapped = quantize_params(wrapped, QuantizationConfig(load_in_8bit=True))

    def time_decode(params, reps=1):
        # sync via a scalar fetch, NOT block_until_ready: the axon tunnel's
        # block_until_ready returns before results land (measured 0.0s runs);
        # inputs vary per rep so the tunnel's identical-dispatch cache can't
        # serve a replay
        t0 = time.perf_counter()
        out = generate(model, params, prompt, gen_cfg)
        float(out[0, -1])
        first_s = time.perf_counter() - t0  # includes compile
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            out = generate(model, params, jnp.asarray(
                rng.integers(0, cfg.vocab_size, prompt.shape), jnp.int32), gen_cfg)
            float(out[0, -1])
            best = min(best or 1e9, time.perf_counter() - t0)
        return best / args.new_tokens, first_s - best

    per_token, compile_s = time_decode(wrapped, reps=args.reps)

    meta = {"params": n_params, "batch": args.batch, "prompt_len": args.prompt_len,
            "new_tokens": args.new_tokens, "backend": jax.default_backend(),
            "int8": bool(args.load_in_8bit),
            "compile_s": round(compile_s, 2)}
    print(json.dumps({"metric": "big_model_load_seconds", "value": round(load_s, 2),
                      "unit": "s", "extra": meta}))
    print(json.dumps({"metric": "big_model_decode_seconds_per_token",
                      "value": round(per_token, 4), "unit": "s/token", "extra": meta}))

    if args.ab:
        # same-process A/B: quantize the SAME loaded weights and re-measure,
        # so bf16 and int8 see identical chip/tunnel state
        from accelerate_tpu.utils.quantization import QuantizationConfig, quantize_params

        q = quantize_params(wrapped, QuantizationConfig(load_in_8bit=True))
        q_per_token, _ = time_decode(q, reps=args.reps)
        print(json.dumps({"metric": "int8_vs_bf16_decode_ratio",
                          "value": round(q_per_token / per_token, 3),
                          "unit": "x (lower is better)",
                          "extra": {"bf16_s_per_tok": round(per_token, 4),
                                    "int8_s_per_tok": round(q_per_token, 4)}}))


if __name__ == "__main__":
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--layers", type=int, default=None)
    p.add_argument("--load_in_8bit", action="store_true")
    p.add_argument("--ab", action="store_true",
                   help="measure bf16 then int8 on the same weights in one process")
    p.add_argument("--reps", type=lambda v: max(1, int(v)), default=3,
                   help="steady-state repetitions (min 1); best is reported")
    p.add_argument("--over_hbm", action="store_true",
                   help="~26B int8 model in host memory, layer-streamed decode")
    p.add_argument("--no-prefetch", action="store_true",
                   help="--over_hbm only: disable the layer double buffer "
                        "(ops/streaming.LayerPrefetcher) — the serialized "
                        "fetch-then-compute baseline")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--prompt_len", type=int, default=None,
                   help="default: 128 (32 with --over_hbm)")
    p.add_argument("--new_tokens", type=int, default=None,
                   help="default: 64 (4 with --over_hbm)")
    _args = p.parse_args()
    if _args.ab and _args.load_in_8bit:
        p.error("--ab measures bf16-then-int8 itself; drop --load_in_8bit "
                "(combining them would compare int8 against int8)")
    if _args.ab and _args.over_hbm:
        p.error("--ab has no effect with --over_hbm (the layer-streamed path "
                "has its own quantization scheme); drop one of them")
    if _args.over_hbm:
        _args.prompt_len = _args.prompt_len or 32
        _args.new_tokens = _args.new_tokens or 4
        over_hbm_main(_args)
    else:
        _args.prompt_len = _args.prompt_len or 128
        _args.new_tokens = _args.new_tokens or 64
        main(_args)
