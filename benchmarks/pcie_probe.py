"""Measured host<->device bandwidth on THIS chip environment — the number the
7B offload accounting multiplies bytes by (docs/performance.md).  Whole-
program measurement per the microbench rules (vary inputs, scalar-fetch
sync); prints one JSON line."""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def main():
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("d",))
    host = NamedSharding(mesh, P(), memory_kind="pinned_host")
    dev = NamedSharding(mesh, P(), memory_kind="device")
    n = 512 * 1024 * 1024  # 1 GiB of bf16
    out = {}

    @jax.jit
    def bump(x):
        return x + jnp.bfloat16(1.0)

    for name, src_sh, dst_sh in (("h2d", host, dev), ("d2h", dev, host)):
        x = jax.device_put(jnp.zeros((n,), jnp.bfloat16), src_sh)

        @jax.jit
        def move(v):
            return jax.device_put(v, dst_sh)

        move(x)  # compile + warm
        iters = 8
        t0 = time.perf_counter()
        for i in range(iters):
            x = jax.device_put(bump(x), src_sh) if name == "h2d" else x
            y = move(x)
            jax.block_until_ready(y)
        dt = time.perf_counter() - t0
        gib = 2 * n / 2**30
        out[name + "_gib_s"] = round(gib * iters / dt, 2)
    print(json.dumps({"metric": "pcie_bandwidth", "unit": "GiB/s", **out}))


if __name__ == "__main__":
    main()
