"""Measured host<->device bandwidth on THIS chip environment — the bus-rate
bound in the 7B offload accounting (docs/performance.md).

Measurement rules (ROADMAP environment quirks): inputs vary per iteration
(the axon tunnel caches identical dispatches) and completion is forced by a
scalar fetch, not ``block_until_ready``.  Each timed iteration performs
exactly ONE counted transfer; the input variation happens on the source
side before the clock starts for that leg.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def main():
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("d",))
    host = NamedSharding(mesh, P(), memory_kind="pinned_host")
    dev = NamedSharding(mesh, P(), memory_kind="device")
    n = 512 * 1024 * 1024  # 1 GiB of bf16
    iters = 6
    out = {}

    for name, src_sh, dst_sh in (("h2d", host, dev), ("d2h", dev, host)):
        # pre-build `iters` DISTINCT source arrays on the source side so the
        # timed loop contains only the measured move
        sources = [
            jax.device_put(jnp.full((n,), jnp.bfloat16(i + 1)), src_sh)
            for i in range(iters)
        ]

        @jax.jit
        def move(v):
            moved = jax.device_put(v, dst_sh)
            return moved, moved[0]  # scalar rides along for the sync fetch

        move(sources[0])  # compile + warm
        t0 = time.perf_counter()
        for i in range(iters):
            moved, probe = move(sources[i])
            float(probe)  # scalar fetch: the transfer has completed
        dt = time.perf_counter() - t0
        out[name + "_gib_s"] = round((2 * n / 2**30) * iters / dt, 2)
    print(json.dumps({"metric": "pcie_bandwidth", "unit": "GiB/s", **out}))


if __name__ == "__main__":
    main()
