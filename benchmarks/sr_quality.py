"""Quality validation for the bf16-master SR recipes on a shuffled stream.

Fixed-batch bench losses are throughput probes, not quality metrics (SR
realizes full-ulp moves on an lr/ulp-probability subset each step, so it
memorizes a repeated batch faster — docs/performance.md).  This harness is
the quality measurement: train on a stream of DISTINCT Zipf-distributed
batches (identical stream for both runs), track a held-out batch, and
compare the SR recipe against its fp32-master reference at the same
hyperparameters.  Measured (r5, one v5e chip): 1.35B lion-sr over 80
steps — held-out 4.6262 vs 4.6244 (0.04%); 600m over 200 steps —
lion-sr 0.035%, adamw-sr 0.002% (5.0849 vs 5.0848), with the gaps
SHRINKING from the 60-step points (0.047% adamw-sr) — the SR noise
averages out with horizon rather than accumulating.

  python benchmarks/sr_quality.py --optimizer adamw-sr --steps 80
  python benchmarks/sr_quality.py --optimizer lion-sr --model 1b
"""

import argparse
import json


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--optimizer",
                    choices=["lion-sr", "adamw-sr", "lion-sr8", "adamw-sr8"],
                    default="adamw-sr")
    ap.add_argument("--model", choices=["600m", "1b"], default="600m")
    ap.add_argument("--steps", type=int, default=80)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--eval-every", type=int, default=10)
    ap.add_argument("--lr", type=float, default=None)
    ap.add_argument("--int8-block", type=int, default=None,
                    help="per-block scale granularity for the -sr8 recipes "
                         "(default 128)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (smoke mode; the axon "
                         "sitecustomize preempts JAX_PLATFORMS env vars)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn
    from accelerate_tpu.state import AcceleratorState, GradientState

    on_tpu = jax.default_backend() == "tpu"
    seq = args.seq_len if on_tpu else 128
    if args.model == "1b" and on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=24, num_attention_heads=16, num_key_value_heads=8,
            max_position_embeddings=seq, attn_implementation="flash",
            dtype=jnp.bfloat16,
        )
        batch = args.batch or 2  # both recipes must fit: fp32 masters cap here
    elif on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=16, num_attention_heads=16, num_key_value_heads=8,
            max_position_embeddings=seq, attn_implementation="flash",
            dtype=jnp.bfloat16,
        )
        batch = args.batch or 8
    else:
        cfg = LlamaConfig.tiny()
        batch = args.batch or 4

    # identical data stream for every run: distinct Zipf-distributed batches
    # (long-tail token stats like real text) + one held-out batch
    rng = np.random.default_rng(0)
    zipf = lambda n: np.minimum(
        rng.zipf(1.2, (n, seq)).astype(np.int64), cfg.vocab_size - 1
    ).astype(np.int32)
    stream = [zipf(batch) for _ in range(args.steps)]
    held_out = zipf(batch)

    lr = args.lr or (1e-4 if "lion" in args.optimizer else 3e-4)

    def make_tx(kind):
        # the recipe registry passes weight_decay explicitly to EVERY recipe
        # (including the optax references, whose own defaults are non-zero:
        # adamw 1e-4, lion 1e-3) — the SR-vs-reference comparison really
        # runs at the same hyperparameters
        from accelerate_tpu.optimizer import make_optimizer

        return make_optimizer(
            kind, lr, weight_decay=0.0,
            block_size=args.int8_block if kind.endswith("-sr8") else None,
        )

    def run(kind):
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        acc = Accelerator(
            parallelism_config=ParallelismConfig(dp_shard_size=jax.device_count()),
            mixed_precision="bf16",
        )
        model = LlamaForCausalLM(cfg)
        ids = jnp.ones((batch, 8), jnp.int32)
        params = acc.init_params(model, jax.random.key(0), ids)
        if kind.endswith(("-sr", "-sr8")):
            params = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.bfloat16)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        state = acc.create_train_state(params, make_tx(kind), apply_fn=model.apply)
        loss_fn = make_llama_loss_fn(model, fused_vocab_chunks=4 if on_tpu else None)
        step = acc.prepare_train_step(loss_fn, max_grad_norm=None)
        eval_loss = jax.jit(lambda p, b: loss_fn(p, b))
        curve, evals = [], []
        for i, tokens in enumerate(stream):
            b = {"input_ids": jnp.asarray(tokens), "labels": jnp.asarray(tokens)}
            state, m = step(state, b)
            curve.append(round(float(m["loss"]), 4))
            if (i + 1) % args.eval_every == 0:
                h = {"input_ids": jnp.asarray(held_out), "labels": jnp.asarray(held_out)}
                evals.append(round(float(eval_loss(state.params, h)), 4))
        return curve, evals

    sr_kind = args.optimizer
    from accelerate_tpu.optimizer import reference_recipe

    ref_kind = reference_recipe(sr_kind)
    sr_curve, sr_evals = run(sr_kind)
    ref_curve, ref_evals = run(ref_kind)
    print(json.dumps({
        "metric": "sr_quality_shuffled_stream",
        # report the EFFECTIVE config: off-TPU the harness substitutes the
        # tiny CPU model, so labeling the output with the requested TPU
        # model name would misattribute smoke numbers
        "model": args.model if on_tpu else "tiny-cpu",
        "backend": jax.default_backend(),
        "steps": args.steps, "batch": batch, "seq_len": seq, "lr": lr,
        "sr": {"optimizer": sr_kind, "train_every10": sr_curve[9::10],
               "held_out": sr_evals},
        "ref": {"optimizer": ref_kind, "train_every10": ref_curve[9::10],
                "held_out": ref_evals},
        "final_held_out_gap_pct": round(
            100.0 * abs(sr_evals[-1] - ref_evals[-1]) / max(abs(ref_evals[-1]), 1e-9), 3
        ) if sr_evals and ref_evals else None,
    }))


if __name__ == "__main__":
    main()
