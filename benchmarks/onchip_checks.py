"""On-chip verification probes that the CPU suite cannot exercise
(memory-kind placement is TPU-only).  Each probe prints one JSON line.

Run on a real chip:  python benchmarks/onchip_checks.py [--probe NAME]

Probes:
  adafactor_offload  — optax.adafactor under the ZeRO-offload host-compute
                       update (VERDICT r2 weak #7: its trace-time constant
                       arrays used to lower into the host region in device
                       memory space and fail; _host_constant_hoist pins
                       them to pinned_host).
  scan_offload       — scan_layers=True + remat_policy="offload" trains a
                       small stack with finite loss (the 131k enabler).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def probe_adafactor_offload():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=1),
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size=0, cpu_offload=True),
    )
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        "dense": {"kernel": jax.random.normal(k1, (256, 512)) * 0.05,
                  "bias": jnp.zeros((512,))},
        "out": {"kernel": jax.random.normal(k2, (512, 8)) * 0.05,
                "bias": jnp.zeros((8,))},
    }

    def loss_fn(p, batch):
        h = jnp.tanh(batch["x"] @ p["dense"]["kernel"] + p["dense"]["bias"])
        pred = h @ p["out"]["kernel"] + p["out"]["bias"]
        return jnp.mean((pred - batch["y"]) ** 2)

    tx = acc.prepare(optax.adafactor(1e-3))
    state = acc.create_train_state(params, tx)
    step = acc.prepare_train_step(loss_fn, max_grad_norm=None)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(4):
        batch = {"x": jnp.asarray(rng.normal(size=(16, 256)), jnp.float32),
                 "y": jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)}
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    ok = all(np.isfinite(losses)) and losses[-1] < losses[0]
    print(json.dumps({"probe": "adafactor_offload", "ok": bool(ok),
                      "losses": [round(l, 5) for l in losses]}))
    return ok


def probe_scan_offload():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn

    cfg = LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=512,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, remat=True, remat_policy="offload",
        scan_layers=True, attn_implementation="flash",
    )
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, (2, 512)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    loss_fn = make_llama_loss_fn(model)
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(p, o, batch):
        loss, grads = jax.value_and_grad(loss_fn)(p, batch)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    t0, losses = time.perf_counter(), []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, {"input_ids": ids, "labels": ids})
        losses.append(float(loss))
    ok = all(np.isfinite(losses)) and losses[-1] < losses[0]
    print(json.dumps({"probe": "scan_offload", "ok": bool(ok),
                      "losses": [round(l, 4) for l in losses],
                      "wall_s": round(time.perf_counter() - t0, 1)}))
    return ok


PROBES = {
    "adafactor_offload": probe_adafactor_offload,
    "scan_offload": probe_scan_offload,
}

if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe", choices=sorted(PROBES), default=None)
    args = ap.parse_args()
    names = [args.probe] if args.probe else sorted(PROBES)
    results = [PROBES[n]() for n in names]  # run ALL probes; no short-circuit
    raise SystemExit(0 if all(results) else 1)
