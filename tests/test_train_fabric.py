"""2-process training recovery-ladder fabric (slow tier; `make chaos`).

Drives ``test_utils/scripts/train_fabric.py`` through REAL
``accelerate_tpu launch`` subprocess gangs (2 procs x 1 CPU device, mesh
dcn=2) and pins the recovery acceptance criteria from docs/resilience.md:

- peer-RAM rung beats the disk rung (fewer steps replayed) when a fresh
  buddy wave exists; a ``partial_ckpt`` torn wave is dropped by the crc
  gate and the gang agrees one wave back; with snapshots disarmed the
  verified-disk rung catches;
- every recovered pass continues bitwise = the uninterrupted reference,
  with zero new compiles once warmed;
- ``recovery.peer_snapshot_bytes`` matches the ``peer_ckpt_accounting``
  model exactly (twin tolerance 0);
- a straggler/SIGTERM mismatch at the same nominal step drains to ONE
  agreed boundary, one consistent emergency checkpoint, exit 75, and a
  ``launch --resume`` picks it up bitwise with restored goodput counters.

The single-process flavors of these pins live in tests/test_resilience.py;
this module is the only place the cross-rank legs (buddy exchange, agreed
stop at mismatched boundaries, re-send on rank loss) actually execute.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from accelerate_tpu.checkpointing import METADATA_NAME, list_checkpoints
from accelerate_tpu.test_utils import train_fabric_script_path

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[1]


def _launch(mode, work, resume=False, expect_code=0):
    cmd = [sys.executable, "-m", "accelerate_tpu.commands.launch", "--cpu",
           "--num_processes", "2", "--num_cpu_devices", "1"]
    if resume:
        cmd.append("--resume")
    cmd.append(str(train_fabric_script_path()))
    # scrub inherited gang/fault env so nested launches start clean
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_"))}
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (str(REPO), env.get("PYTHONPATH")) if p
    )
    env.update({"TRAIN_FABRIC_MODE": mode, "TRAIN_FABRIC_DIR": str(work)})
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                       env=env)
    assert r.returncode == expect_code, (
        f"{mode} exited {r.returncode} (want {expect_code})\n"
        f"--- stdout ---\n{r.stdout[-3000:]}\n--- stderr ---\n{r.stderr[-3000:]}"
    )
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    return json.loads(lines[-1]) if lines else None


def test_chaos_recovery_ladder_two_process(tmp_path):
    """rank_loss x {fresh peer wave, torn wave, disarmed} → peer, peer-1,
    disk rungs; bitwise parity; zero warm compiles; exact bytes twin."""
    chaos = _launch("chaos", tmp_path / "chaos")
    assert chaos["num_processes"] == 2
    assert chaos["predicted_bytes"] == chaos["measured_bytes"], (
        "recovery.peer_snapshot_bytes twin drifted (tolerance 0)")

    a, b, c = chaos["pass_a"], chaos["pass_b"], chaos["pass_c"]
    # fresh wave: peer rung restores NEWER state than the step-4 disk ckpt
    assert a["restore_path"] == "peer"
    assert a["restored_step"] > chaos["disk_step_a"]
    assert a["steps_recomputed"] == 0
    # torn wave dropped by crc → gang agrees one wave back, still peer
    assert b["restore_path"] == "peer"
    assert b["restored_step"] < a["restored_step"]
    # snapshots disarmed → verified disk checkpoint catches
    assert c["restore_path"] == "disk"
    assert all(p["parity"] for p in (a, b, c)), chaos
    assert chaos["compiles_passes_bc"] == 0


def test_agreed_preemption_then_resume_bitwise(tmp_path):
    """Straggler on rank 0 vs SIGTERM on rank 1 at the same nominal step:
    one agreed boundary, one emergency checkpoint, exit 75; --resume
    continues bitwise with zero post-warmup compiles."""
    work = tmp_path / "preempt"
    _launch("preempt", work, expect_code=75)

    ckpts = list_checkpoints(str(work))
    assert len(ckpts) == 1, "exactly one agreed emergency checkpoint"
    meta = json.loads((Path(ckpts[0]) / METADATA_NAME).read_text())
    assert meta["step_count"] == 5
    assert meta["goodput"]["preemptions"] == 1  # satellite: counters persist
    rng_shards = sorted(Path(ckpts[0]).glob("random_states_*.pkl"))
    assert [p.name for p in rng_shards] == [
        "random_states_0.pkl", "random_states_1.pkl"]

    resumed = _launch("resume", work, resume=True)
    assert resumed["start"] == 5
    # the resumed tail is bitwise = the uninterrupted reference tail
    assert resumed["losses"] == resumed["ref_losses"][5:]
    assert resumed["compiles_after_first"] == 0
    assert resumed["goodput_restarts"] == 1
