"""Unified telemetry tests (accelerate_tpu/telemetry/): twin registry +
drift report, request-level trace spans (bitwise-invisible contract),
training timeline, streaming-quantile SLO monitors, Prometheus exposition,
TelemetryPlugin knobs.

The two load-bearing contracts pinned here:

- tracing/telemetry on vs off is BITWISE identical (serving tokens and
  training loss) and compiles no new program (``strict_compiles`` holds
  with tracing armed);
- every one of the canonical seven predicted/measured twins registers in
  the central :class:`TwinRegistry`, and a deliberately mis-predicted twin
  trips the drift report.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.state import AcceleratorState, GradientState
from accelerate_tpu.telemetry import (
    STANDARD_TWINS,
    RequestTracer,
    SLOMonitor,
    SpanRecorder,
    StreamingQuantile,
    TrainTimeline,
    TwinRegistry,
    VirtualClock,
    prometheus_text,
    twin_registry,
    validate_chrome_trace,
)
from accelerate_tpu.test_utils.training import (
    make_regression_loader,
    regression_init_params,
    regression_loss_fn,
)
from accelerate_tpu.utils.dataclasses import ServingPlugin, TelemetryPlugin


# ---------------------------------------------------------------------------
# twin registry
# ---------------------------------------------------------------------------


def test_twin_registry_rel_err_and_status():
    reg = TwinRegistry()
    t = reg.record("kv_pool.utilization", predicted=0.5, measured=0.55)
    assert t.rel_err == pytest.approx(0.05 / 0.55)
    assert t.status == "ok"
    # beyond tolerance -> warn; beyond 4x tolerance -> error
    reg.record("kv_pool.utilization", measured=0.8)
    assert reg.get("kv_pool.utilization").status == "warn"
    reg.record("kv_pool.utilization", predicted=0.01, measured=0.8)
    assert reg.get("kv_pool.utilization").status == "error"


def test_twin_registry_idle_and_zeros_clean():
    reg = TwinRegistry()
    reg.declare_standard_twins()
    rep = reg.drift_report()
    assert set(rep) == set(STANDARD_TWINS)
    for row in rep.values():
        assert row["status"] == "idle"
        assert row["predicted"] == row["measured"] == row["rel_err"] == 0.0
    # both sides recorded as zero: exact agreement, not a division blowup
    reg.record("compiles.steady_state", predicted=0, measured=0)
    assert reg.get("compiles.steady_state").status == "ok"
    assert reg.get("compiles.steady_state").rel_err == 0.0


def test_twin_registry_compiles_zero_tolerance():
    # tolerance 0.0: ANY disagreement on the compiles twin is an error
    reg = TwinRegistry()
    reg.declare_standard_twins()
    reg.record("compiles.steady_state", predicted=0, measured=1)
    assert reg.get("compiles.steady_state").status == "error"


def test_twin_registry_register_idempotent_metadata_first_wins():
    reg = TwinRegistry()
    reg.register("x.y", units="bytes", tolerance=0.5)
    reg.register("x.y", units="frac", tolerance=0.1)  # ignored
    t = reg.get("x.y")
    assert t.units == "bytes" and t.tolerance == 0.5


def test_twin_registry_drifting_ranked_worst_first():
    reg = TwinRegistry()
    reg.record("a.one", predicted=1.0, measured=1.15, tolerance=0.1)
    reg.record("b.two", predicted=1.0, measured=4.0, tolerance=0.1)
    reg.record("c.ok", predicted=1.0, measured=1.01, tolerance=0.1)
    names = [t.name for t in reg.drifting()]
    assert names == ["b.two", "a.one"]
    assert [t.name for t in reg.drifting("error")] == ["b.two"]


def test_twin_registry_flat_metrics_tracker_shape():
    reg = TwinRegistry()
    reg.record("a.one", predicted=2.0, measured=2.0)
    flat = reg.flat_metrics()
    assert flat["twins/a.one/predicted"] == 2.0
    assert flat["twins/a.one/rel_err"] == 0.0


def test_mis_predicted_twin_trips_drift_report():
    """The acceptance pin: a deliberately mis-predicted twin is flagged by
    drift_report() beyond its tolerance."""
    reg = twin_registry()
    reg.declare_standard_twins()
    # deliberately wrong model: predicted 10% utilization, measured 90%
    reg.record("kv_pool.utilization", predicted=0.1, measured=0.9)
    row = reg.drift_report()["kv_pool.utilization"]
    assert row["status"] == "error" and row["rel_err"] > 0.8
    assert reg.drifting("error")[0].name == "kv_pool.utilization"


def test_all_standard_twins_register_from_their_accounting_sites():
    """Every existing predicted/measured accounting site records into the
    ONE registry — the migration the autotuner substrate needs."""
    reg = twin_registry()
    reg.reset()

    # 1. offload_transfer (ops/streaming)
    from accelerate_tpu.ops.streaming import offload_transfer_accounting

    offload_transfer_accounting(1_000_000, optimizer="lion-sr")

    # 2. tp_comm (ops/collective_matmul)
    from accelerate_tpu.ops.collective_matmul import tp_comm_accounting

    tp_comm_accounting(4096, 1024, 4096, 4)

    # 3. dcn_comm, both sides (parallel/hierarchical)
    from accelerate_tpu.parallel.hierarchical import (
        dcn_comm_accounting,
        measure_dcn_bytes,
    )

    params = {"w": np.ones((8, 8), np.float32)}
    dcn_comm_accounting(params, ici_size=2, dcn_size=2)
    # measured side via a tiny traced psum over a dcn mesh axis
    from tests.shard_map_compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("dcn",))

    def fn(x):
        return shard_map(
            lambda v: jax.lax.psum(v, "dcn"),
            mesh=mesh, in_specs=P("dcn"), out_specs=P(),
        )(x)

    measure_dcn_bytes(jax.jit(fn).trace(jnp.ones((4,), jnp.float32)).jaxpr,
                      dcn_size=2)

    # 4 + 5 + 7. kv_pool / adapter_pool / compiles (serving/harness)
    from accelerate_tpu.serving.harness import _adapter_fields

    class _Plugin:
        pool_slots, rank = 2, 4

    class _Store:
        plugin = _Plugin()
        swaps, swap_bytes = 3, 1024

        def hit_rate(self):
            return 0.5

    class _Eng:
        adapters = _Store()

    from accelerate_tpu.serving.scheduler import Request

    _adapter_fields(_Eng(), [Request(uid=0, prompt=(1,), max_new_tokens=1,
                                     adapter_id=1)])
    reg.record("kv_pool.utilization", predicted=0.3, measured=0.3)
    reg.record("compiles.steady_state", predicted=0, measured=0)

    # 6. goodput (resilience/goodput) — both sides
    from accelerate_tpu.resilience.goodput import (
        GoodputTracker,
        goodput_accounting,
    )

    goodput_accounting(0.1, 100)
    GoodputTracker().report()

    # 8 + 9. speculate accept-rate / tokens-per-step (serving/harness)
    from accelerate_tpu.serving.harness import _speculate_fields
    from accelerate_tpu.serving.speculate import NgramDraft, Speculator

    class _SpecEng:
        metrics = {"decode_lane_passes": 4, "decode_emitted_tokens": 6,
                   "draft_tokens": 4, "accepted_draft_tokens": 2,
                   "speculative_rollbacks": 1, "verify_steps": 4}
        speculator = Speculator(NgramDraft(), 2, (2,))
        speculate_mode = "ngram"

    _speculate_fields(
        _SpecEng(),
        [Request(uid=0, prompt=(1, 2, 1, 2), max_new_tokens=4)],
        {0: [5, 6, 7]}, wall_s=1.0,
    )

    # 10-14. serving overload block (serving/harness._overload_fields):
    # measured from the scheduler counters, predicted from the clean-run
    # model (no FaultPlan active here)
    from accelerate_tpu.serving.harness import _overload_fields

    class _OverloadSched:
        requests_shed = 0
        deadline_misses = 0
        cancelled = 0
        pages_reclaimed_on_cancel = 0
        retired_uids: set = set()
        max_queue = 0
        kv_shed_watermark = 0.0
        default_deadline_ticks = 0
        shed_armed = False

    class _OverloadLadder:
        stage = "normal"
        engagements = 0

    class _OverloadEng:
        sched = _OverloadSched()
        results = {0: [1, 2]}
        adapters = None
        ladder = _OverloadLadder()

    _overload_fields(_OverloadEng(),
                     [Request(uid=0, prompt=(1,), max_new_tokens=2)])

    # 15-17. prefix cache hit rate (serving/harness._prefix_fields), the
    # bench ttft with/without-reuse baseline, and the disaggregation
    # transfer accounting (serving/transfer)
    from accelerate_tpu.serving.harness import _prefix_fields
    from accelerate_tpu.serving.prefix_cache import PrefixCache
    from accelerate_tpu.serving.transfer import transfer_accounting

    class _PrefixPlugin:
        num_slots, num_pages, page_size = 2, 8, 4
        pages_per_slot, prefill_chunk = 4, 4

    class _PrefixEng:
        metrics = {"page_transfers": 0, "page_transfer_pages": 0,
                   "page_transfer_bytes": 0}
        prefix = PrefixCache(4)
        plugin = _PrefixPlugin()

    _prefix_fields(_PrefixEng(),
                   [Request(uid=0, prompt=(1, 2, 3, 4, 5), max_new_tokens=2)])
    # the bench --prefix-share baseline records the ttft pair; the
    # transport records the measured transfer bytes — stand in for both
    reg.record("prefix_cache.ttft_ticks", predicted=4.0, measured=3.0,
               source="bench.serve prefix baseline")

    class _Cfg:
        num_hidden_layers, num_key_value_heads, head_dim = 2, 2, 4

    transfer_accounting(
        _Cfg(), [Request(uid=0, prompt=(1, 2, 3, 4, 5), max_new_tokens=2)], 4
    )
    reg.record_measured("transfer.page_bytes", 256,
                        source="serving/transfer.PagedKVTransport")

    # 18. quantized KV page bytes (serving/paged_cache + engine): the
    # accounting records the predicted codes+scales arithmetic; the
    # engine's allocated-pool nbytes stands in for the measured side
    from accelerate_tpu.serving.paged_cache import (
        kv_page_bytes,
        kv_pool_accounting,
    )

    kv_pool_accounting(_Cfg(), 8, 4, 2, kv_dtype="int8")
    reg.record_measured("kv_quant.page_bytes",
                        kv_page_bytes(_Cfg(), 4, 2, "int8"),
                        source="serving/engine.ServingEngine")

    # 19. distributed wire unit (analysis/distributed_audit.pair_preflight
    # vs serving/transfer.PagedKVTransport): the pair gate records the
    # GL403 schema's page_bytes as predicted; the constructed transport's
    # _page_bytes — the same wire_schema() derivation — is the measured
    # side, so the row agrees exactly
    from accelerate_tpu.analysis.distributed_audit import wire_schema
    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    schema = wire_schema(LlamaConfig.tiny(), ServingPlugin(
        num_slots=4, page_size=4, pages_per_slot=16, num_pages=40))
    reg.record_predicted("distributed.wire_bytes_per_page",
                         schema["page_bytes"],
                         source="analysis/distributed_audit.pair_preflight")
    reg.record_measured("distributed.wire_bytes_per_page",
                        schema["page_bytes"],
                        source="serving/transfer.PagedKVTransport")

    # 20-22. fleet rows (serving/router.fleet_replay): goodput measured vs
    # the clean-run model, fleet-aggregate prefix/adapter hit rates vs the
    # single-cache/-pool trace models (tests/test_router.py drives the real
    # site end-to-end; the stand-ins here pin registry membership)
    reg.record("fleet.request_goodput", predicted=1.0, measured=1.0,
               source="serving/router.fleet_replay")
    reg.record("fleet.prefix_hit_rate", predicted=0.5, measured=0.4,
               source="serving/router.fleet_replay")
    reg.record("fleet.adapter_pool_hit_rate", predicted=0.75, measured=0.5,
               source="serving/router.fleet_replay")

    # 23-24. recovery rows (resilience/peer_ckpt + Accelerator.recover):
    # the accounting model records the predicted wave bytes; the
    # snapshotter's capture and the ladder walk record the measured sides
    # (tests/test_resilience.py + the 2-proc fabric drive the real sites)
    from accelerate_tpu.resilience.peer_ckpt import peer_ckpt_accounting

    acct = peer_ckpt_accounting({"w": np.ones((4, 4), np.float32)})
    reg.record_measured("recovery.peer_snapshot_bytes",
                        float(acct["snapshot_bytes"]),
                        source="resilience/peer_ckpt.PeerSnapshotter")
    reg.record_measured("recovery.restore_time_s", 0.01,
                        source="Accelerator.recover")

    rows = reg.drift_report()
    for name in STANDARD_TWINS:
        assert name in rows, name
    # capture measures exactly what the model predicts (tolerance 0.0)
    assert rows["recovery.peer_snapshot_bytes"]["status"] == "ok"
    # pairs that recorded both sides carry a real rel_err status
    for paired in ("dcn_comm.dcn_bytes", "kv_pool.utilization",
                   "adapter_pool.hit_rate", "goodput.goodput_frac",
                   "compiles.steady_state", "speculate.accept_rate",
                   "speculate.tokens_per_step", "kv_quant.page_bytes",
                   "distributed.wire_bytes_per_page"):
        assert rows[paired]["status"] != "idle", (paired, rows[paired])
    # predicted and measured route through the same kv_page_bytes
    # arithmetic — exact by construction (tolerance 0.0)
    assert rows["kv_quant.page_bytes"]["status"] == "ok"
    # dcn predicted (psum slab model) vs the traced psum agree exactly:
    # 4 fp32 = 16 bytes * ring factor 1.0 on both sides of a 2-slice tree
    # of 64 fp32... the MODELS differ (tree vs traced fn) so only pairing,
    # not equality, is pinned here — exact agreement lives in
    # tests/test_hierarchical.py
    assert rows["tp_comm.overlap_frac"]["predicted"] > 0


# ---------------------------------------------------------------------------
# span recorder + chrome export
# ---------------------------------------------------------------------------


def test_span_recorder_ring_is_bounded():
    rec = SpanRecorder(capacity=8, clock=VirtualClock(1.0))
    for i in range(20):
        rec.instant(f"e{i}", "t")
    assert len(rec) == 8
    assert rec.dropped == 12 and rec.recorded == 20
    names = [e[1] for e in rec.events()]
    assert names == [f"e{i}" for i in range(12, 20)]  # oldest dropped


def test_span_recorder_disabled_records_nothing():
    rec = SpanRecorder(clock=VirtualClock(1.0), enabled=False)
    rec.instant("x", "t")
    with rec.span("y", "t"):
        pass
    rec.complete("z", "t", rec.stamp())
    assert len(rec) == 0 and rec.overhead_s == 0.0
    assert rec.stamp() == 0.0


def test_virtual_clock_traces_are_deterministic():
    def run():
        rec = SpanRecorder(clock=VirtualClock(1.0))
        with rec.span("outer", "engine", step=0):
            rec.instant("mark", "req 1", step=0)
        rec.complete("tail", "req 1", rec.stamp(), cat="request")
        return json.dumps(rec.to_chrome_trace(), sort_keys=True)

    assert run() == run()


def test_chrome_trace_schema_and_track_metadata():
    rec = SpanRecorder(clock=VirtualClock(1.0))
    rec.complete("a", "engine", rec.stamp(), cat="step", k=1)
    rec.instant("b", "req 7")
    trace = rec.to_chrome_trace()
    assert validate_chrome_trace(trace) == []
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    thread_names = {e["args"]["name"] for e in meta if e["name"] == "thread_name"}
    assert thread_names == {"engine", "req 7"}
    x = next(e for e in trace["traceEvents"] if e["ph"] == "X")
    assert x["dur"] >= 0 and x["args"] == {"k": 1}


def test_validate_chrome_trace_rejects_malformed():
    assert validate_chrome_trace({}) != []
    assert validate_chrome_trace({"traceEvents": "nope"}) != []
    bad_phase = {"traceEvents": [{"ph": "Q", "name": "x", "pid": 0, "tid": 0, "ts": 0}]}
    assert any("phase" in p for p in validate_chrome_trace(bad_phase))
    no_dur = {"traceEvents": [{"ph": "X", "name": "x", "pid": 0, "tid": 0, "ts": 0}]}
    assert any("dur" in p for p in validate_chrome_trace(no_dur))
    torn_args = {"traceEvents": [{"ph": "i", "name": "x", "pid": 0, "tid": 0,
                                  "ts": 0, "args": {"f": object()}}]}
    assert any("args" in p for p in validate_chrome_trace(torn_args))


def test_jsonl_export_round_trips(tmp_path):
    rec = SpanRecorder(clock=VirtualClock(1.0))
    rec.complete("a", "t", rec.stamp(), k=2)
    p = tmp_path / "spans.jsonl"
    rec.write_jsonl(p)
    rows = [json.loads(l) for l in p.read_text().splitlines()]
    assert rows[0]["name"] == "a" and rows[0]["args"] == {"k": 2}


# ---------------------------------------------------------------------------
# serving engine tracing (the bitwise-invisible contract)
# ---------------------------------------------------------------------------


def _serve_setup(num_pages=40):
    from accelerate_tpu.generation import GenerationConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))
    plugin = ServingPlugin(num_slots=4, page_size=4, pages_per_slot=16,
                           num_pages=num_pages, prefill_chunk=16,
                           decode_kernel="native")
    return model, params, plugin, GenerationConfig(max_new_tokens=24)


def test_engine_tracing_tokens_bitwise_and_strict_compiles():
    """THE acceptance pin: same seeded trace, tracing on vs off — token
    streams identical, replay's strict_compiles passes with tracing on
    (telemetry compiles no program)."""
    from accelerate_tpu.serving import ServingEngine, replay, synthesize_trace

    model, params, plugin, gen = _serve_setup()
    trace = synthesize_trace(3, 10, vocab_size=model.config.vocab_size,
                             mean_interarrival_steps=0.5,
                             prompt_len_range=(4, 24), new_tokens_range=(4, 24))

    off = ServingEngine(model, params, plugin, gen)
    rep_off = replay(off, trace)  # strict_compiles default True
    res_off = rep_off.pop("results")

    on = ServingEngine(model, params, plugin, gen)
    on.enable_tracing(clock=VirtualClock(1e-6))
    rep_on = replay(on, trace)
    res_on = rep_on.pop("results")

    assert res_on == res_off
    assert rep_on["compiles_measured"] == 0
    assert rep_on["trace_spans"] > 0 and rep_off["trace_spans"] == 0
    assert rep_off["telemetry_overhead_frac"] == 0.0
    # the scheduler made the same decisions (telemetry sees, never steers)
    for field in ("engine_steps", "decode_steps", "prefill_steps",
                  "evictions", "generated_tokens"):
        assert rep_on[field] == rep_off[field], field


def test_engine_trace_lifecycle_taxonomy():
    from accelerate_tpu.serving import ServingEngine, replay, synthesize_trace

    model, params, plugin, gen = _serve_setup()
    trace = synthesize_trace(5, 8, vocab_size=model.config.vocab_size,
                             mean_interarrival_steps=0.5,
                             prompt_len_range=(4, 24), new_tokens_range=(4, 24))
    eng = ServingEngine(model, params, plugin, gen)
    tracer = eng.enable_tracing(clock=VirtualClock(1.0))
    replay(eng, trace)
    chrome = tracer.to_chrome_trace()
    assert validate_chrome_trace(chrome) == []
    events = [e for e in chrome["traceEvents"] if e["ph"] != "M"]
    by_name: dict = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    for name in ("submit", "queued", "admit", "prefill_chunk", "decode",
                 "retire", "schedule", "host_sync"):
        assert name in by_name, name
    assert any(n.startswith("dispatch:") for n in by_name)
    # one queued span and one retire instant per completed request
    assert len(by_name["retire"]) == len(trace)
    assert len(by_name["queued"]) >= len(trace)
    # spans are well-formed on the virtual clock: integer-microsecond ts
    for e in by_name["queued"]:
        assert e["ts"] == int(e["ts"]) and e["dur"] >= 0


def test_engine_trace_evict_and_readmit_spans():
    """Pool pressure: the evicted request carries an `evict` instant and a
    SECOND `queued` span (the readmit wait), and still retires."""
    from accelerate_tpu.serving import ServingEngine, replay, synthesize_trace

    # tiny pool: two long sequences cannot coexist
    model, params, plugin, gen = _serve_setup(num_pages=16)
    trace = synthesize_trace(7, 6, vocab_size=model.config.vocab_size,
                             mean_interarrival_steps=0.3,
                             prompt_len_range=(12, 24),
                             new_tokens_range=(12, 24))
    eng = ServingEngine(model, params, plugin, gen)
    tracer = eng.enable_tracing(clock=VirtualClock(1.0))
    rep = replay(eng, trace)
    assert rep["evictions"] > 0, "scenario failed to evict — shrink the pool"
    events = [e for e in tracer.to_chrome_trace()["traceEvents"]
              if e["ph"] != "M"]
    evicted_tracks = {e["tid"] for e in events if e["name"] == "evict"}
    assert evicted_tracks
    for tid in evicted_tracks:
        track_events = [e for e in events if e["tid"] == tid]
        queued = [e for e in track_events if e["name"] == "queued"]
        assert len(queued) >= 2  # original wait + readmit wait
        assert any(e["name"] == "retire" for e in track_events)


def test_engine_trace_ring_bound_under_load():
    from accelerate_tpu.serving import ServingEngine, replay, synthesize_trace

    model, params, plugin, gen = _serve_setup()
    trace = synthesize_trace(9, 8, vocab_size=model.config.vocab_size,
                             mean_interarrival_steps=0.5,
                             prompt_len_range=(4, 24), new_tokens_range=(4, 24))
    eng = ServingEngine(model, params, plugin, gen)
    tracer = eng.enable_tracing(clock=VirtualClock(1.0), capacity=32)
    replay(eng, trace)
    assert len(tracer.recorder) == 32
    assert tracer.recorder.dropped > 0
    assert validate_chrome_trace(tracer.to_chrome_trace()) == []


def test_engine_telemetry_plugin_arms_tracing(monkeypatch):
    from accelerate_tpu.serving import ServingEngine

    model, params, plugin, gen = _serve_setup()
    eng = ServingEngine(model, params, plugin, gen,
                        telemetry=TelemetryPlugin(trace_requests=True,
                                                  ring_capacity=64))
    assert eng.trace is not None
    assert eng.trace.recorder.capacity == 64
    monkeypatch.setenv("ACCELERATE_TELEMETRY", "1")
    eng2 = ServingEngine(model, params, plugin, gen)
    assert eng2.trace is not None  # env default armed it
    eng2.disable_tracing()
    assert eng2.trace is None


# ---------------------------------------------------------------------------
# training timeline + accelerator integration
# ---------------------------------------------------------------------------


def test_train_timeline_phases_and_summary():
    tl = TrainTimeline(clock=VirtualClock(1.0))
    for _ in range(3):
        with tl.phase("step_dispatch"):
            pass
    with tl.phase("data_wait"):
        pass
    s = tl.summary()
    assert s["step_dispatch"]["count"] == 3
    assert s["data_wait"]["count"] == 1
    assert s["step_dispatch"]["total_s"] > 0
    assert validate_chrome_trace(tl.to_chrome_trace()) == []


def test_timeline_nested_phases_report_exclusive_time():
    """A phase nested inside another (the prefetch path's h2d_staging
    inside data_wait) attributes its time to itself only — phase totals
    never sum past the wall clock; the exported spans keep full
    (inclusive) durations."""
    clk = VirtualClock(1.0)
    tl = TrainTimeline(clock=clk)
    with tl.phase("data_wait"):
        clk.now += 10.0          # 10s of pure waiting
        with tl.phase("h2d_staging"):
            clk.now += 5.0       # 5s of staging INSIDE the wait bracket
    s = tl.summary()
    assert s["h2d_staging"]["total_s"] == pytest.approx(6.0)   # 5 + clock ticks
    # data_wait excludes the nested staging time (inclusive would be ~17)
    assert s["data_wait"]["total_s"] == pytest.approx(12.0, abs=1.0)
    # the exported span keeps the inclusive duration for Perfetto nesting
    spans = {e[1]: e[5] for e in tl.recorder.events()}
    assert spans["data_wait"] > spans["h2d_staging"] > 5.0


def test_timeline_summary_survives_ring_wrap():
    tl = TrainTimeline(capacity=4, clock=VirtualClock(1.0))
    for _ in range(10):
        with tl.phase("step_dispatch"):
            pass
    assert tl.summary()["step_dispatch"]["count"] == 10
    assert len(tl.recorder) == 4


def _train_losses(telemetry_plugin, n_epochs=2):
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(telemetry_plugin=telemetry_plugin)
    dl = acc.prepare(make_regression_loader(batch_size=16))
    state = acc.create_train_state(regression_init_params(), optax.sgd(0.1))
    step = acc.prepare_train_step(regression_loss_fn, max_grad_norm=1.0)
    losses = []
    for _ in range(n_epochs):
        for batch in dl:
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    return acc, losses


def test_accelerator_timeline_bitwise_loss_and_phases():
    """Telemetry on vs off: the loss trajectory is BITWISE identical (the
    acceptance pin for training), and the armed timeline carries the
    data_wait / h2d_staging / step_dispatch phases from the real loop."""
    acc_off, losses_off = _train_losses(TelemetryPlugin(enabled=False))
    assert acc_off.timeline is None

    acc_on, losses_on = _train_losses(
        TelemetryPlugin(enabled=True, trace_requests=False)
    )
    assert losses_on == losses_off
    s = acc_on.timeline.summary()
    assert s["step_dispatch"]["count"] == len(losses_on)
    assert "data_wait" in s and "h2d_staging" in s
    assert acc_on.timeline.overhead_frac(10.0) >= 0.0


def test_accelerator_slo_monitor_observes_steps():
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    trips = []
    acc = Accelerator(telemetry_plugin=TelemetryPlugin(
        enabled=False,
        slo={"step_time_s": {"p99_warn": 1e9}},  # never breached
    ))
    assert acc.slo_monitor is not None
    dl = acc.prepare(make_regression_loader(batch_size=16))
    state = acc.create_train_state(regression_init_params(), optax.sgd(0.1))
    step = acc.prepare_train_step(regression_loss_fn)
    for batch in dl:
        state, _ = step(state, batch)
    rep = acc.slo_monitor.report()
    # step_time_s is the inter-step cadence: n-1 gaps for n steps (a delta
    # around the async jitted dispatch would measure enqueue, not compute —
    # the GL109 hazard)
    assert rep["step_time_s"]["n"] == 3
    assert rep["step_time_s"]["status"] == "ok"
    assert rep["goodput_frac"]["p50"] > 0.99
    assert not trips


# ---------------------------------------------------------------------------
# streaming quantiles + SLO monitor
# ---------------------------------------------------------------------------


def test_streaming_quantile_exact_small_n():
    """Documented small-n contract: exact (numpy-convention) for n <= 5."""
    rng = np.random.default_rng(42)
    for n in (1, 2, 3, 4, 5):
        xs = rng.exponential(1.0, n)
        for q in (0.5, 0.99):
            est = StreamingQuantile(q)
            for x in xs:
                est.observe(x)
            assert est.value() == pytest.approx(
                float(np.percentile(xs, q * 100)), rel=1e-12
            ), (n, q)


@pytest.mark.parametrize("dist", ["exponential", "lognormal", "uniform"])
def test_streaming_quantile_error_bounds_large_n(dist):
    """The documented error bounds on seeded traffic-shaped traces
    (docs/observability.md): p50 within ~8 % from n >= 500; p99 within
    ~10 % in the steady regime (n >= 5000) and within ~25 % at n = 500 on
    heavy-tailed traffic (five markers converge slower on the tail)."""
    bounds = {  # (q, n) -> relative-error bound
        (0.5, 500): 0.08, (0.5, 5000): 0.05,
        (0.99, 500): 0.25, (0.99, 5000): 0.10,
    }
    rng = np.random.default_rng(0)
    draw = {
        "exponential": lambda n: rng.exponential(0.01, n),
        "lognormal": lambda n: rng.lognormal(-3, 0.8, n),
        "uniform": lambda n: rng.uniform(0.0, 1.0, n),
    }[dist]
    for n in (500, 5000):
        xs = draw(n)
        for q in (0.5, 0.99):
            est = StreamingQuantile(q)
            for x in xs:
                est.observe(x)
            exact = float(np.percentile(xs, q * 100))
            rel = abs(est.value() - exact) / abs(exact)
            assert rel < bounds[(q, n)], (dist, n, q, rel)


def test_streaming_quantile_rejects_bad_q():
    with pytest.raises(ValueError):
        StreamingQuantile(0.0)
    with pytest.raises(ValueError):
        StreamingQuantile(1.0)


def test_slo_monitor_warn_trip_transitions_fire_once():
    events = []
    mon = SLOMonitor(
        {"ttft_s": {"p99_warn": 0.5, "p99_trip": 2.0}},
        on_warn=lambda m, q, v: events.append(("warn", m, q)),
        on_trip=lambda m, q, v: events.append(("trip", m, q)),
    )
    for _ in range(10):
        mon.observe("ttft_s", 0.1)
    assert events == [] and mon.status("ttft_s").status == "ok"
    for _ in range(50):
        mon.observe("ttft_s", 1.0)  # p99 crosses warn once
    assert events == [("warn", "ttft_s", "p99")]
    assert mon.status("ttft_s").status == "warn"
    for _ in range(200):
        mon.observe("ttft_s", 10.0)
    assert events[-1] == ("trip", "ttft_s", "p99")
    assert mon.trip_count == 1 and mon.warn_count == 1
    # a sustained breach fires no further events
    for _ in range(50):
        mon.observe("ttft_s", 10.0)
    assert mon.trip_count == 1


def test_slo_monitor_goodput_breaches_downward():
    events = []
    mon = SLOMonitor({"goodput_frac": {"p50_warn": 0.9}},
                     on_warn=lambda m, q, v: events.append((m, q, v)))
    for _ in range(10):
        mon.observe("goodput_frac", 1.0)
    assert not events
    for _ in range(20):
        mon.observe("goodput_frac", 0.2)
    assert events and events[0][0] == "goodput_frac"


def test_slo_monitor_recovery_rearms():
    events = []
    mon = SLOMonitor({"x": {"p50_warn": 1.0}},
                     on_warn=lambda m, q, v: events.append("warn"))
    for _ in range(8):
        mon.observe("x", 5.0)
    assert events == ["warn"]
    for _ in range(100):
        mon.observe("x", 0.01)  # p50 recovers under the threshold
    assert mon.status("x").status == "ok"
    for _ in range(200):
        mon.observe("x", 50.0)
    assert events == ["warn", "warn"]  # re-armed, fires again


def test_slo_monitor_report_and_untracked_metric_queryable():
    mon = SLOMonitor()
    mon.observe("token_latency_s", 0.01)
    rep = mon.report()
    assert rep["token_latency_s"]["n"] == 1
    assert rep["_counters"] == {"warns": 0, "trips": 0}
    assert mon.status("never_seen").status == "idle"
    flat = mon.flat_metrics()
    assert "slo/token_latency_s/p50" in flat


def test_replay_overhead_is_per_replay_not_engine_lifetime():
    """telemetry_overhead_frac is THIS replay's recording cost over THIS
    replay's wall: pre-replay overhead on a reused traced engine is
    excluded (pinned by poisoning the cumulative counter up front)."""
    from accelerate_tpu.serving import ServingEngine, replay, synthesize_trace

    model, params, plugin, gen = _serve_setup()
    trace = synthesize_trace(2, 6, vocab_size=model.config.vocab_size,
                             mean_interarrival_steps=0.5,
                             prompt_len_range=(4, 16), new_tokens_range=(4, 16))
    eng = ServingEngine(model, params, plugin, gen)
    tracer = eng.enable_tracing()
    tracer.recorder.overhead_s = 1e6  # engine-lifetime junk to exclude
    rep = replay(eng, trace)
    assert rep["telemetry_overhead_frac"] < 0.5  # delta, not cumulative


def test_accelerator_reset_step_cadence():
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(telemetry_plugin=TelemetryPlugin(
        enabled=False, slo={"step_time_s": {"p99_trip": 1e9}}))
    state = acc.create_train_state(regression_init_params(), optax.sgd(0.1))
    step = acc.prepare_train_step(regression_loss_fn)
    x = jnp.ones((16, 1))
    batch = {"x": x, "y": 2 * x[:, 0] + 3}
    state, _ = step(state, batch)
    assert acc._slo_prev_step_t is not None
    # a legitimate pause (eval loop / drain) re-anchors: the next step
    # starts a fresh gap instead of observing the pause as one giant step
    acc.reset_step_cadence()
    assert acc._slo_prev_step_t is None
    state, _ = step(state, batch)
    assert acc.slo_monitor.report()["step_time_s"]["n"] == 0  # both anchors


def test_harness_replay_feeds_slo_monitor():
    from accelerate_tpu.serving import ServingEngine, replay, synthesize_trace

    model, params, plugin, gen = _serve_setup()
    trace = synthesize_trace(1, 6, vocab_size=model.config.vocab_size,
                             mean_interarrival_steps=0.5,
                             prompt_len_range=(4, 16), new_tokens_range=(4, 16))
    mon = SLOMonitor({"ttft_s": {"p99_warn": 1e9}})
    eng = ServingEngine(model, params, plugin, gen)
    replay(eng, trace, slo_monitor=mon)
    rep = mon.report()
    assert rep["ttft_s"]["n"] == len(trace)
    assert rep["token_latency_s"]["n"] > 0


# ---------------------------------------------------------------------------
# prometheus exposition + plugin knobs
# ---------------------------------------------------------------------------


def test_prometheus_text_exposition_shape():
    reg = twin_registry()
    reg.declare_standard_twins()
    reg.record("kv_pool.utilization", predicted=0.4, measured=0.5)
    mon = SLOMonitor({"ttft_s": {"p99_warn": 0.5}})
    mon.observe("ttft_s", 0.1)
    text = prometheus_text(monitors={"serve": mon})
    lines = text.splitlines()
    assert "# TYPE accelerate_twin_rel_err gauge" in lines
    assert any(l.startswith('accelerate_twin_measured{twin="kv_pool.utilization"} 0.5')
               for l in lines)
    assert any(l.startswith('accelerate_slo_quantile{job="serve",metric="ttft_s",q="p99"}')
               for l in lines)
    assert 'accelerate_slo_events_total{job="serve",level="trip"} 0' in lines
    # every sample line is `name{labels} value` with a float-parseable value
    for l in lines:
        if l.startswith("#"):
            continue
        float(l.rsplit(" ", 1)[1])


def test_telemetry_plugin_env_defaults(monkeypatch):
    p = TelemetryPlugin()
    assert p.enabled is False and p.trace_requests is False \
        and p.timeline is False
    assert p.ring_capacity == 4096
    monkeypatch.setenv("ACCELERATE_TELEMETRY", "1")
    monkeypatch.setenv("ACCELERATE_TELEMETRY_RING", "128")
    p2 = TelemetryPlugin()
    assert p2.enabled and p2.trace_requests and p2.timeline
    assert p2.ring_capacity == 128
    # per-feature env overrides the master switch
    monkeypatch.setenv("ACCELERATE_TELEMETRY_TRACE_REQUESTS", "0")
    p3 = TelemetryPlugin()
    assert p3.enabled and not p3.trace_requests and p3.timeline
    # explicit arguments always win
    p4 = TelemetryPlugin(enabled=False, ring_capacity=16)
    assert not p4.enabled and p4.ring_capacity == 16


def test_telemetry_plugin_validation():
    with pytest.raises(ValueError, match="ring_capacity"):
        TelemetryPlugin(ring_capacity=0)
    with pytest.raises(ValueError, match="slo"):
        TelemetryPlugin(slo="p99<0.5")


def test_accelerator_exports_timeline_at_end_training(tmp_path):
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(telemetry_plugin=TelemetryPlugin(
        enabled=True, trace_requests=False, export_dir=str(tmp_path / "tele"),
    ))
    state = acc.create_train_state(regression_init_params(), optax.sgd(0.1))
    step = acc.prepare_train_step(regression_loss_fn)
    x = jnp.ones((16, 1))
    state, _ = step(state, {"x": x, "y": 2 * x[:, 0] + 3})
    acc.end_training()
    trace = json.loads((tmp_path / "tele" / "train_timeline.json").read_text())
    assert validate_chrome_trace(trace) == []
    assert any(e.get("name") == "step_dispatch" for e in trace["traceEvents"])


def test_twin_metrics_flow_through_jsonl_tracker(tmp_path):
    """The always-available JSONL sink: twin + SLO tables land through
    Accelerator.log with no extra dependency."""
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    reg = twin_registry()
    reg.record("kv_pool.utilization", predicted=0.4, measured=0.42)
    acc = Accelerator(log_with="jsonl", project_dir=str(tmp_path))
    acc.init_trackers("proj")
    acc.log(reg.flat_metrics(), step=0)
    acc.end_training()
    rows = [json.loads(l) for l in
            (tmp_path / "proj" / "metrics.jsonl").read_text().splitlines()]
    assert rows[0]["twins/kv_pool.utilization/measured"] == 0.42
