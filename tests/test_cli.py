"""CLI-layer tests (reference tests/test_cli.py:643 — config round-trip,
flag>file>default precedence, estimator output, env transport)."""

import argparse
import os
import subprocess
import sys

import pytest
import yaml

from accelerate_tpu.commands.config import LaunchConfig, load_config_or_default
from accelerate_tpu.commands.estimate import abstract_param_sizes
from accelerate_tpu.commands.launch import (
    _merge_args_into_config,
    _validate,
    launch_command_parser,
)
from accelerate_tpu.utils.launch import (
    prepare_multiprocess_env,
    prepare_simple_launcher_cmd_env,
)


def _parse_launch(argv):
    return launch_command_parser().parse_args(argv)


def test_config_roundtrip(tmp_path):
    cfg = LaunchConfig(num_processes=4, mixed_precision="bf16", tp_size=2, use_fsdp=True)
    path = cfg.save(tmp_path / "cfg.yaml")
    loaded = LaunchConfig.load(path)
    assert loaded == cfg


def test_config_templates_load_validate_and_roundtrip(tmp_path):
    """Every checked-in template must load with NO unknown keys, pass launch
    validation, and survive a save/load round trip (VERDICT r4 missing #1;
    reference examples/config_yaml_templates/)."""
    import pathlib

    from accelerate_tpu.commands.launch import _validate

    tpl_dir = pathlib.Path(__file__).parent.parent / "examples" / "config_templates"
    templates = sorted(tpl_dir.glob("*.yaml"))
    assert len(templates) >= 6
    for tpl in templates:
        cfg = LaunchConfig.load(tpl)
        # unknown keys land in env passthrough — a template must have none
        assert not cfg.env, f"{tpl.name}: unrecognized keys {sorted(cfg.env)}"
        _validate(cfg)
        # multi-host templates must NOT pin a machine rank into the file
        if cfg.num_machines > 1:
            assert cfg.machine_rank is None, f"{tpl.name} stores machine_rank"
        reloaded = LaunchConfig.load(cfg.save(tmp_path / tpl.name))
        assert reloaded == cfg, tpl.name
    # the cloud templates carry usable cloud-launch defaults
    gke = LaunchConfig.load(tpl_dir / "cloud_gke.yaml")
    assert gke.cloud_backend == "gke" and gke.cloud_image and gke.cloud_tpu_topology
    # the topology must actually hold the declared gang: chips in the
    # topology product == hosts x chips-per-host (a 2x4 slice can never
    # schedule 4 indexed pods of 4 chips)
    topo_chips = 1
    for d in gke.cloud_tpu_topology.split("x"):
        topo_chips *= int(d)
    assert topo_chips == gke.num_machines * gke.cloud_chips_per_host
    qr = LaunchConfig.load(tpl_dir / "cloud_queued_resources.yaml")
    assert qr.cloud_backend == "queued-resources" and qr.cloud_tpu_type


def test_config_forward_compat_unknown_keys(tmp_path):
    path = tmp_path / "cfg.yaml"
    path.write_text(yaml.safe_dump({"num_processes": 2, "some_future_key": "x"}))
    loaded = LaunchConfig.load(path)
    assert loaded.num_processes == 2
    assert loaded.env["some_future_key"] == "x"


def test_load_config_or_default_missing_file(tmp_path):
    assert load_config_or_default(str(tmp_path / "nope.yaml")) == LaunchConfig()


def test_flag_beats_file(tmp_path):
    cfg_path = tmp_path / "cfg.yaml"
    LaunchConfig(mixed_precision="fp16", tp_size=4).save(cfg_path)
    args = _parse_launch(["--config_file", str(cfg_path), "--mixed_precision", "bf16", "script.py"])
    merged = _merge_args_into_config(args, LaunchConfig.load(cfg_path))
    assert merged.mixed_precision == "bf16"  # flag wins
    assert merged.tp_size == 4  # file survives where no flag given


def test_multi_host_requires_rank_and_port():
    from accelerate_tpu.commands.launch import launch_command

    with pytest.raises(ValueError, match="machine_rank"):
        launch_command(_parse_launch(["--multi_host", "--main_process_ip", "1.2.3.4",
                                      "--main_process_port", "29500", "script.py"]))
    with pytest.raises(ValueError, match="main_process_port"):
        launch_command(_parse_launch(["--machine_rank", "0", "--main_process_ip", "1.2.3.4",
                                      "--num_processes", "2", "script.py"]))


def test_local_spawn_despite_stored_coordinator_ip(tmp_path, monkeypatch):
    """A local multi-process config that carries a coordinator address (as the
    questionnaire used to store) must still spawn workers locally."""
    from accelerate_tpu.commands import launch as launch_mod

    cfg_path = tmp_path / "local.yaml"
    LaunchConfig(num_processes=4, main_process_ip="127.0.0.1", main_process_port=29500).save(cfg_path)
    called = {}
    def fake_spawn(cmd, args, config):
        called["n"] = config.num_processes
        return 0

    monkeypatch.setattr(launch_mod, "_spawn_local_workers", fake_spawn)
    with pytest.raises(SystemExit) as exc:
        launch_mod.launch_command(_parse_launch(["--config_file", str(cfg_path), "script.py"]))
    assert exc.value.code == 0
    assert called["n"] == 4


def test_multi_host_config_without_rank_raises(tmp_path):
    """num_machines>1 from a config file must not silently default every host
    to machine_rank 0."""
    from accelerate_tpu.commands.launch import launch_command

    cfg_path = tmp_path / "cluster.yaml"
    LaunchConfig(num_processes=2, num_machines=2, main_process_ip="10.0.0.1",
                 main_process_port=29500).save(cfg_path)
    with pytest.raises(ValueError, match="machine_rank"):
        launch_command(_parse_launch(["--config_file", str(cfg_path), "script.py"]))


def test_validate_rejects_topology_mismatch():
    with pytest.raises(ValueError, match="num_machines"):
        _validate(LaunchConfig(num_processes=4, num_machines=2))
    with pytest.raises(ValueError, match="machine_rank"):
        _validate(LaunchConfig(num_processes=2, num_machines=2, machine_rank=5))


def test_pre_num_machines_config_rejected(tmp_path):
    """Old-style multi-host YAML (ip stored, no num_machines key) must not be
    silently reinterpreted as a local spawn."""
    cfg_path = tmp_path / "old.yaml"
    cfg_path.write_text("num_processes: 2\nmain_process_ip: 10.0.0.1\nmain_process_port: 29500\n")
    with pytest.raises(ValueError, match="num_machines"):
        LaunchConfig.load(cfg_path)


def test_explicit_topology_beats_pod_metadata(monkeypatch):
    """Explicit flags must win over pod metadata (flag > file > default)."""
    from accelerate_tpu.commands import launch as launch_mod

    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
    captured = {}

    def fake_popen(cmd, env=None):
        captured["env"] = env

        class _P:
            def wait(self):
                return 0

        return _P()

    monkeypatch.setattr(launch_mod.subprocess, "Popen", fake_popen)
    monkeypatch.setattr(launch_mod.sys, "exit", lambda code=0: None)
    launch_mod.launch_command(_parse_launch(["--num_processes", "1", "script.py"]))
    # pod metadata would have set ACCELERATE_NUM_PROCESSES=2
    assert "ACCELERATE_NUM_PROCESSES" not in captured["env"]


def test_compute_module_sizes_counts_list_subtrees():
    import numpy as np

    from accelerate_tpu.big_modeling import compute_module_sizes

    params = {"layers": [{"w": np.zeros((4, 4), np.float32)}, {"w": np.zeros((8,), np.float32)}]}
    sizes = compute_module_sizes(params)
    assert sizes[""] == 4 * 4 * 4 + 8 * 4
    assert sizes["layers.0"] == 64
    assert sizes["layers.1.w"] == 32


def test_validate_rejects_bad_sizes():
    cfg = LaunchConfig(tp_size=0)
    with pytest.raises(ValueError):
        _validate(cfg)
    cfg = LaunchConfig(tp_size=-1, dp_shard_size=-1)
    with pytest.raises(ValueError):
        _validate(cfg)


def test_env_transport_simple():
    args = _parse_launch(["--mixed_precision", "bf16", "--tp_size", "2", "--use_fsdp", "script.py", "--lr", "3"])
    config = _merge_args_into_config(args, LaunchConfig())
    cmd, env = prepare_simple_launcher_cmd_env(args, config)
    assert cmd[-3:] == ["script.py", "--lr", "3"]
    assert env["ACCELERATE_MIXED_PRECISION"] == "bf16"
    assert env["PARALLELISM_CONFIG_TP_SIZE"] == "2"
    assert env["ACCELERATE_USE_FSDP"] == "true"
    # every axis crosses the process boundary, including the pp axis
    assert env["PARALLELISM_CONFIG_PP_SIZE"] == "1"
    assert env["FSDP_SHARDING_STRATEGY"] == "FULL_SHARD"


def test_env_transport_pp_size():
    args = _parse_launch(["--pp_size", "2", "script.py"])
    config = _merge_args_into_config(args, LaunchConfig())
    _, env = prepare_simple_launcher_cmd_env(args, config)
    assert env["PARALLELISM_CONFIG_PP_SIZE"] == "2"


def test_env_transport_multiprocess():
    args = _parse_launch(["--num_processes", "2", "script.py"])
    config = _merge_args_into_config(args, LaunchConfig())
    env0 = prepare_multiprocess_env(args, config, 0)
    env1 = prepare_multiprocess_env(args, config, 1)
    assert env0["ACCELERATE_NUM_PROCESSES"] == "2"
    assert env0["ACCELERATE_PROCESS_ID"] == "0"
    assert env1["ACCELERATE_PROCESS_ID"] == "1"
    # every worker must agree on the coordinator
    assert env0["ACCELERATE_COORDINATOR_ADDRESS"] == env1["ACCELERATE_COORDINATOR_ADDRESS"]


def test_tpu_pod_env_autodetect(monkeypatch):
    from accelerate_tpu.utils.launch import prepare_tpu_pod_env

    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1,host2,host3")
    args = _parse_launch(["script.py"])
    config = _merge_args_into_config(args, LaunchConfig())
    env = prepare_tpu_pod_env(args, config)
    assert env is not None
    assert env["ACCELERATE_NUM_PROCESSES"] == "4"
    assert env["ACCELERATE_PROCESS_ID"] == "1"
    assert env["ACCELERATE_COORDINATOR_ADDRESS"].startswith("host0:")


def test_estimate_param_sizes():
    total, largest, per_module = abstract_param_sizes(
        "llama",
        {"hidden_size": 64, "intermediate_size": 128, "num_hidden_layers": 2,
         "num_attention_heads": 4, "num_key_value_heads": 2, "vocab_size": 256},
    )
    assert total > 0 and largest > 0
    assert largest <= total
    assert sum(per_module.values()) == total


def test_interactive_config_full_flow(monkeypatch, capsys):
    """The questionnaire covers every launcher-transported field with
    validation: bad answers re-prompt, cp+sp conflict is rejected inline,
    and the produced config is one the launcher accepts (VERDICT r1 #10)."""
    from accelerate_tpu.commands.config import interactive_config
    from accelerate_tpu.utils.launch import _base_env

    answers = iter([
        "4",          # num_processes
        "2",          # num_machines
        "10.0.0.1",   # coordinator ip
        "",           # port (default)
        "2",          # slices (dcn cross-slice axis)
        "",           # use_cpu
        "y",          # debug
        "fp4",        # invalid precision -> re-prompt
        "fp8",        # precision
        "2",          # grad accum
        "2",          # tp
        "2",          # cp
        "2",          # sp  -> cp+sp conflict, cp/sp re-prompt
        "2",          # cp
        "1",          # sp
        "1",          # ep
        "1",          # pp
        "1",          # dp_replicate
        "y",          # use_fsdp
        "ZERO3",      # invalid strategy -> re-prompt
        "FULL_SHARD", # strategy
        "y",          # offload
        "y",          # activation ckpt
        "y",          # configure cloud defaults
        "gke",        # backend
        "",           # tpu type (default)
        "eu.gcr.io/x/train:1",  # image
        "4x4",        # topology
        "4",          # chips per host
    ])
    monkeypatch.setattr("builtins.input", lambda prompt="": next(answers))
    cfg = interactive_config()
    out = capsys.readouterr().out
    assert "not one of" in out          # invalid answers were rejected
    assert "pick one" in out            # cp+sp conflict surfaced
    assert "Mesh:" in out
    assert cfg.mixed_precision == "fp8"
    assert cfg.tp_size == 2 and cfg.cp_size == 2 and cfg.sp_size == 1
    assert cfg.fsdp_offload_params and cfg.fsdp_activation_checkpointing
    assert cfg.debug and cfg.num_machines == 2
    assert cfg.main_process_ip == "10.0.0.1" and cfg.main_process_port == 29500
    assert cfg.dcn_size == 2
    assert cfg.cloud_backend == "gke" and cfg.cloud_tpu_type == "tpu-v5-lite-podslice"
    assert cfg.cloud_image == "eu.gcr.io/x/train:1"
    assert cfg.cloud_tpu_topology == "4x4" and cfg.cloud_chips_per_host == 4

    class _Args:
        num_cpu_devices = None

    env = _base_env(_Args(), cfg)
    assert env["ACCELERATE_MIXED_PRECISION"] == "fp8"
    assert env["FSDP_OFFLOAD_PARAMS"] == "true"
    assert env["PARALLELISM_CONFIG_TP_SIZE"] == "2"
    assert env["ACCELERATE_DEBUG_MODE"] == "true"


def test_estimate_arbitrary_checkpoint(tmp_path, capsys):
    """estimate-memory accepts any safetensors checkpoint path and reports
    from headers only (reference estimate.py:318 meta-loads any hub model;
    VERDICT r1 missing #5)."""
    import numpy as np

    from accelerate_tpu.commands.estimate import (
        checkpoint_param_sizes,
        estimate_command,
        estimate_command_parser,
    )
    from accelerate_tpu.utils.serialization import save_safetensors

    save_safetensors(
        str(tmp_path / "model-00001-of-00002.safetensors"),
        {"model.layers.0.mlp.w": np.zeros((32, 64), np.float32),
         "model.layers.0.norm.scale": np.zeros((64,), np.float16)},
    )
    save_safetensors(
        str(tmp_path / "model-00002-of-00002.safetensors"),
        {"model.layers.1.mlp.w": np.zeros((32, 64), np.float32)},
    )
    total, largest, per_module, per_dtype = checkpoint_param_sizes(str(tmp_path))
    assert total == 32 * 64 * 2 + 64
    assert per_dtype["F32"] == 32 * 64 * 2 and per_dtype["F16"] == 64
    assert largest == max(per_module.values())

    args = estimate_command_parser().parse_args([str(tmp_path), "--num_chips", "4"])
    estimate_command(args)
    out = capsys.readouterr().out
    assert "Checkpoint:" in out and "F32: 4,096" in out and "bfloat16" in out

    with pytest.raises(SystemExit, match="neither"):
        estimate_command(estimate_command_parser().parse_args(["no-such-model"]))


def test_cli_help_lists_subcommands():
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu", "--help"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert result.returncode == 0
    for sub in ("config", "env", "launch", "test", "estimate-memory", "merge-weights",
                "tpu-config", "from-accelerate", "lint", "preflight"):
        assert sub in result.stdout


# ---------------------------------------------------------------------------
# from-accelerate importer (migration path from reference configs)
# ---------------------------------------------------------------------------


def test_from_accelerate_fsdp_config():
    from accelerate_tpu.commands.from_accelerate import convert

    raw = {
        "compute_environment": "LOCAL_MACHINE",
        "distributed_type": "FSDP",
        "mixed_precision": "bf16",
        "num_machines": 1,
        "num_processes": 8,
        "machine_rank": 0,
        "use_cpu": False,
        "debug": False,
        "fsdp_config": {
            "fsdp_sharding_strategy": "FULL_SHARD",
            "fsdp_offload_params": True,
            "fsdp_activation_checkpointing": True,
            "fsdp_auto_wrap_policy": "TRANSFORMER_BASED_WRAP",
            "fsdp_transformer_layer_cls_to_wrap": "BertLayer",
        },
    }
    cfg, notes = convert(raw)
    assert cfg.use_fsdp and cfg.fsdp_sharding_strategy == "FULL_SHARD"
    assert cfg.fsdp_offload_params and cfg.fsdp_activation_checkpointing
    assert cfg.num_processes == 8
    assert cfg.machine_rank is None  # single machine: rank not meaningful
    assert any("wrap" in n for n in notes)  # wrap-policy drop explained


def test_from_accelerate_deepspeed_zero3():
    from accelerate_tpu.commands.from_accelerate import convert

    raw = {
        "distributed_type": "DEEPSPEED",
        "deepspeed_config": {
            "zero_stage": 3,
            "offload_optimizer_device": "cpu",
            "gradient_accumulation_steps": 4,
        },
        "mixed_precision": "fp16",
    }
    cfg, notes = convert(raw)
    assert cfg.use_fsdp and cfg.fsdp_sharding_strategy == "FULL_SHARD"
    assert cfg.fsdp_offload_params
    assert cfg.gradient_accumulation_steps == 4
    assert cfg.mixed_precision == "bf16"  # fp16 -> bf16 on TPU
    assert any("zero_stage 3" in n for n in notes)


def test_from_accelerate_deepspeed_config_file_refused():
    """Delegating to an unread DeepSpeed JSON must hard-fail, not silently
    convert with assumed stage/offload."""
    from accelerate_tpu.commands.from_accelerate import convert

    with pytest.raises(ValueError, match="DeepSpeed JSON"):
        convert({"distributed_type": "DEEPSPEED",
                 "deepspeed_config": {"deepspeed_config_file": "ds3.json"}})


def test_from_accelerate_nested_keys_reported():
    from accelerate_tpu.commands.from_accelerate import convert

    _, notes = convert({
        "distributed_type": "FSDP",
        "fsdp_config": {"fsdp_sharding_strategy": "FULL_SHARD",
                        "fsdp_backward_prefetch": "BACKWARD_PRE"},
        "parallelism_config": {"parallelism_config_cp_size": 2,
                               "parallelism_config_cp_comm_strategy": "alltoall"},
    })
    assert any("fsdp_config.fsdp_backward_prefetch" in n for n in notes)
    assert any("parallelism_config.parallelism_config_cp_comm_strategy" in n for n in notes)


def test_from_accelerate_parallelism_axes():
    from accelerate_tpu.commands.from_accelerate import convert

    raw = {
        "distributed_type": "MULTI_GPU",
        "parallelism_config": {
            "parallelism_config_dp_replicate_size": 2,
            "parallelism_config_dp_shard_size": 4,
            "parallelism_config_tp_size": 2,
            "parallelism_config_cp_size": 1,
        },
    }
    cfg, _ = convert(raw)
    assert (cfg.dp_replicate_size, cfg.dp_shard_size, cfg.tp_size) == (2, 4, 2)


def test_from_accelerate_cli_end_to_end(tmp_path):
    src = tmp_path / "ref.yaml"
    out = tmp_path / "tpu.yaml"
    yaml.safe_dump(
        {"distributed_type": "FSDP", "num_processes": 4, "mixed_precision": "no",
         "fsdp_config": {"fsdp_sharding_strategy": "FULL_SHARD"},
         "tpu_use_cluster": False, "gpu_ids": "all"},
        open(src, "w"),
    )
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu", "from-accelerate", str(src),
         "--output", str(out)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert result.returncode == 0, result.stderr
    assert "dropped gpu_ids" in result.stdout
    cfg = LaunchConfig.load(out)
    assert cfg.use_fsdp and cfg.num_processes == 4


def test_menu_select_fallback_paths(monkeypatch):
    """Non-TTY select(): accepts a name, an index, empty (default), and
    re-prompts on junk (the menu UI degrades to this in pipes/CI)."""
    from accelerate_tpu.commands import menu

    answers = iter(["", "bf16", "3", "junk", "1"])
    monkeypatch.setattr("builtins.input", lambda prompt="": next(answers))
    choices = ("no", "bf16", "fp16", "fp8")
    assert menu.select("precision", choices, "bf16") == "bf16"   # default
    assert menu.select("precision", choices, "no") == "bf16"     # by name
    assert menu.select("precision", choices, "no") == "fp8"      # by index
    assert menu.select("precision", choices, "no") == "bf16"     # junk -> re-ask


def test_menu_tty_select_keys(monkeypatch):
    """Arrow-key path: down/up/jk wrap, digits jump, enter confirms."""
    from accelerate_tpu.commands import menu

    keys = iter(["\x1b[B", "\x1b[B", "\x1b[A", "\r"])  # down down up enter
    monkeypatch.setattr(menu, "_read_key", lambda: next(keys))
    out = menu._tty_select("pick", ["a", "b", "c"], 0)
    assert out == "b"
    keys = iter(["2", "\n"])
    monkeypatch.setattr(menu, "_read_key", lambda: next(keys))
    assert menu._tty_select("pick", ["a", "b", "c"], 0) == "c"
    keys = iter(["k", "\r"])  # wrap upward from 0
    monkeypatch.setattr(menu, "_read_key", lambda: next(keys))
    assert menu._tty_select("pick", ["a", "b", "c"], 0) == "c"


def test_cloud_launch_renders_jobset(tmp_path, capsys, monkeypatch):
    """cloud-launch (the managed-cloud job surface; reference SageMaker
    launcher analog, launch.py:1176): renders a GKE JobSet with the full env
    transport, indexed completions as machine rank, and the worker command."""
    for k in list(__import__("os").environ):
        if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_")):
            monkeypatch.delenv(k, raising=False)
    from accelerate_tpu.commands.cloud import cloud_command_parser

    parser = cloud_command_parser()
    args = parser.parse_args([
        "--backend", "gke", "--num_machines", "4", "--mixed_precision", "bf16",
        "--tpu_type", "tpu-v5-lite-podslice", "--image", "my/image:1",
        "train.py", "--lr", "3e-4",
    ])
    from accelerate_tpu.commands.cloud import cloud_launch_command

    cloud_launch_command(args)
    out = capsys.readouterr().out
    assert "kind: JobSet" in out
    assert "parallelism: 4" in out and "completions: 4" in out
    assert "completionMode: Indexed" in out
    assert "ACCELERATE_MIXED_PRECISION" in out and "'bf16'" in out
    assert "PARALLELISM_CONFIG_TP_SIZE" in out
    assert "job-completion-index" in out          # rank from the index
    assert "'python', 'train.py', '--lr', '3e-4'" in out
    assert "google.com/tpu: 4" in out
    assert "gke-tpu-topology: 2x4" in out      # a real topology label, never 'auto'
    assert "maxRestarts" in out                # whole-gang JobSet failurePolicy
    # the operator shell's residue must never leak into a manifest
    assert "ACCELERATE_USE_CPU" not in out


def test_cloud_launch_renders_queued_resource(capsys, monkeypatch):
    for k in list(__import__("os").environ):
        if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_")):
            monkeypatch.delenv(k, raising=False)
    from accelerate_tpu.commands.cloud import cloud_command_parser, cloud_launch_command

    parser = cloud_command_parser()
    args = parser.parse_args([
        "--backend", "queued-resources", "--tpu_type", "v5litepod-16",
        "--zone", "us-west4-a", "train.py",
    ])
    cloud_launch_command(args)
    out = capsys.readouterr().out
    assert "gcloud compute tpus queued-resources create" in out
    assert "--accelerator-type=v5litepod-16" in out
    assert "--zone=us-west4-a" in out
    assert "ACCELERATE_MIXED_PRECISION" in out and "python train.py" in out


def test_cloud_launch_submit_dry_run_gke(tmp_path, capsys, monkeypatch):
    """--submit --dry-run hands kubectl a client-side validation run (or
    prints the exact line when kubectl is absent) — nothing reaches any
    cluster, which is what lets CI assert the submission path."""
    for k in list(__import__("os").environ):
        if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_")):
            monkeypatch.delenv(k, raising=False)
    from accelerate_tpu.commands import cloud as cloud_mod

    calls = []
    monkeypatch.setattr(cloud_mod.shutil, "which", lambda name: f"/usr/bin/{name}")
    monkeypatch.setattr(
        cloud_mod.subprocess, "run",
        lambda cmd, **kw: calls.append((cmd, kw.get("input"))) or
        __import__("types").SimpleNamespace(returncode=0),
    )
    out_file = tmp_path / "jobset.yaml"
    args = cloud_mod.cloud_command_parser().parse_args([
        "--backend", "gke", "--num_machines", "2", "--submit", "--dry-run",
        "-o", str(out_file), "train.py",
    ])
    cloud_mod.cloud_launch_command(args)
    assert len(calls) == 1
    cmd, _stdin = calls[0]
    assert cmd == ["kubectl", "apply", "-f", str(out_file), "--dry-run=client"]
    assert "kind: JobSet" in out_file.read_text()
    # without kubectl the dry run degrades to printing the exact line
    calls.clear()
    monkeypatch.setattr(cloud_mod.shutil, "which", lambda name: None)
    cloud_mod.cloud_launch_command(args)
    out = capsys.readouterr().out
    assert "DRY RUN" in out and "--dry-run=client" in out
    assert not calls


def test_cloud_launch_submit_dry_run_queued(capsys, monkeypatch):
    for k in list(__import__("os").environ):
        if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_")):
            monkeypatch.delenv(k, raising=False)
    from accelerate_tpu.commands import cloud as cloud_mod

    monkeypatch.setattr(
        cloud_mod.subprocess, "run",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError("must not execute")),
    )
    args = cloud_mod.cloud_command_parser().parse_args([
        "--backend", "queued-resources", "--tpu_type", "v5litepod-16",
        "--submit", "--dry-run", "train.py",
    ])
    cloud_mod.cloud_launch_command(args)
    out = capsys.readouterr().out
    assert "DRY RUN: gcloud compute tpus queued-resources create" in out


def test_cloud_launch_reads_questionnaire_defaults(tmp_path, capsys, monkeypatch):
    """cloud_* answers stored by the config questionnaire (the reference
    SageMakerConfig flow) become the submission defaults — flags still win."""
    for k in list(__import__("os").environ):
        if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_")):
            monkeypatch.delenv(k, raising=False)
    from accelerate_tpu.commands import cloud as cloud_mod
    from accelerate_tpu.commands.config import LaunchConfig

    cfg = LaunchConfig(
        cloud_backend="queued-resources", cloud_tpu_type="v5litepod-16",
        cloud_zone="europe-west4-b", cloud_project="my-proj",
    )
    path = cfg.save(tmp_path / "config.yaml")
    args = cloud_mod.cloud_command_parser().parse_args(
        ["--config_file", str(path), "train.py"]
    )
    cloud_mod.cloud_launch_command(args)
    out = capsys.readouterr().out
    assert "queued-resources create" in out
    assert "--accelerator-type=v5litepod-16" in out
    assert "--zone=europe-west4-b" in out and "--project=my-proj" in out
    # an explicit flag overrides the stored answer
    args = cloud_mod.cloud_command_parser().parse_args(
        ["--config_file", str(path), "--tpu_type", "v5litepod-32", "train.py"]
    )
    cloud_mod.cloud_launch_command(args)
    assert "--accelerator-type=v5litepod-32" in capsys.readouterr().out


def test_cloud_launch_rejects_non_python_script():
    from accelerate_tpu.commands.cloud import cloud_command_parser, cloud_launch_command

    parser = cloud_command_parser()
    args = parser.parse_args(["run.sh"])
    import pytest

    with pytest.raises(ValueError, match="python training script"):
        cloud_launch_command(args)
