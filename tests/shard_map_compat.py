"""One place for the jax.shard_map import fallback the test suite uses
(mirrors parallel/collectives.partial_manual_kwargs for the package side):
new jax exports ``jax.shard_map`` and spells the replication-check knob
``check_vma``; old jax has only ``jax.experimental.shard_map`` with
``check_rep``.  Tests that need the check off unpack ``**NO_CHECK``."""

try:
    from jax import shard_map

    NO_CHECK = {"check_vma": False}
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map  # noqa: F401

    NO_CHECK = {"check_rep": False}
