"""Model-family tests: forward shapes, loss decrease, TP-rule alignment."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import (
    BertConfig,
    BertForSequenceClassification,
    LlamaConfig,
    LlamaForCausalLM,
    ResNet,
    ResNetConfig,
    causal_lm_loss,
    make_bert_loss_fn,
    make_llama_loss_fn,
)


def test_llama_forward_shapes():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_llama_gqa_and_causality():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.randint(0, 255, (1, 12)), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    logits_full = model.apply(params, ids)
    # causality: changing a future token must not change past logits
    ids2 = ids.at[0, 8].set((ids[0, 8] + 1) % 255)
    logits_mod = model.apply(params, ids2)
    np.testing.assert_allclose(
        np.asarray(logits_full[0, :8]), np.asarray(logits_mod[0, :8]), rtol=2e-2, atol=2e-3
    )
    assert not np.allclose(np.asarray(logits_full[0, 8:]), np.asarray(logits_mod[0, 8:]), atol=1e-3)


def test_llama_trains_under_accelerator():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    ids = jnp.ones((8, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    state = acc.create_train_state(params, optax.adamw(1e-3), apply_fn=model.apply)
    step = acc.prepare_train_step(make_llama_loss_fn(model), max_grad_norm=1.0)
    rng = np.random.default_rng(0)
    batch_np = rng.integers(0, 255, (8, 16))
    from accelerate_tpu.ops import host_local_to_global
    from jax.sharding import PartitionSpec as P

    batch = host_local_to_global(
        {"input_ids": batch_np.astype(np.int32), "labels": batch_np.astype(np.int32)},
        acc.mesh, P(("dp_shard",), None),
    )
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_llama_tp_sharding_applied():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2))
    ids = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    state = acc.create_train_state(params, optax.sgd(1e-3))
    q_kernel = state.params["params"]["layers_0"]["self_attn"]["q_proj"]["kernel"]
    assert "tp" in str(q_kernel.sharding.spec)
    logits = model.apply(state.params, ids)  # still computes correctly sharded
    assert logits.shape == (4, 16, cfg.vocab_size)


def test_causal_lm_loss_ignore_index():
    logits = jnp.zeros((1, 4, 8))
    labels = jnp.asarray([[1, 2, -100, 3]])
    loss = causal_lm_loss(logits, labels)
    assert np.isclose(float(loss), np.log(8), rtol=1e-5)


def test_bert_forward_and_train():
    cfg = BertConfig.tiny()
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((4, 16), jnp.int32)
    mask = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids, mask)
    logits = model.apply(params, ids, mask)
    assert logits.shape == (4, cfg.num_labels)

    acc = Accelerator()
    state = acc.create_train_state(params, optax.adamw(1e-3))
    step = acc.prepare_train_step(make_bert_loss_fn(model))
    batch = {
        "input_ids": jnp.asarray(np.random.randint(0, 500, (8, 16)), jnp.int32),
        "attention_mask": jnp.ones((8, 16), jnp.int32),
        "labels": jnp.asarray(np.random.randint(0, 2, (8,)), jnp.int32),
    }
    losses = []
    for _ in range(5):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_resnet_forward():
    cfg = ResNetConfig.tiny()
    model = ResNet(cfg)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    logits, updates = model.apply(variables, x, mutable=["batch_stats"])
    assert logits.shape == (2, 10)
    assert "batch_stats" in updates


def test_flops_per_token_positive():
    from accelerate_tpu.models import flops_per_token

    cfg = LlamaConfig.llama2_7b()
    f = flops_per_token(cfg, 4096)
    # 6*6.7e9 ~ 4e10 plus attention term
    assert 3.5e10 < f < 6e10
